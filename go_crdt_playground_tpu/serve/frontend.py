"""The op-ingest serving frontend: listener + admission + batcher + node.

``ServeFrontend`` is the subsystem the ROADMAP's "serves heavy traffic"
north star plugs into: clients dial a TCP port and submit add/del ops
against a keyed AWSet replica (serve/protocol.py); connection reader
threads admit them into the bounded ``AdmissionQueue`` (full queue ⇒
typed ``Overloaded`` shed, never a silent drop); the ``MicroBatcher``
coalesces admitted ops into packed ``(B, E)`` tensor applies through
the kernel path and acks only after the WAL group commit
(``Node.ingest_batch``); and the merged state disseminates through the
EXISTING anti-entropy machinery — the frontend's ``Node`` is an
ordinary ``net/peer.py`` replica, optionally driven against a peer set
by a ``SyncSupervisor`` on the §14 durability regime.

Shutdown is a drain, not a drop (``close()``): stop accepting dials,
flip draining (in-flight connections get typed ``Draining`` rejects for
NEW ops), flush the batcher (every admitted op acks or typed-rejects),
take a final durable checkpoint (seals + retires the WAL segments the
dump covers), then close sessions and the node.

SLO accounting rides the shared ``obs.Recorder`` (names in DESIGN.md
§16): listener-side counters ``serve.ops.admitted``,
``serve.shed.overload``, ``serve.shed.draining``,
``serve.rejects.invalid``, ``serve.queries``, ``serve.connections``;
the batcher adds the latency/occupancy streams.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence, Tuple

from go_crdt_playground_tpu.net import framing
from go_crdt_playground_tpu.net.peer import Node
from go_crdt_playground_tpu.serve import protocol
from go_crdt_playground_tpu.serve.admission import AdmissionQueue, OpRequest
from go_crdt_playground_tpu.serve.batcher import MicroBatcher
from go_crdt_playground_tpu.serve.host import ConnHost
from go_crdt_playground_tpu.serve.session import Session

Addr = Tuple[str, int]

# reshard-soak crash hook: "pull" SIGKILLs the process on the next
# SLICE_PULL (the donor dying mid-handoff), "push" on the next
# SLICE_PUSH before it applies (the recipient dying mid-handoff) — the
# two windows the fleet soak's kill-mid-handoff leg adjudicates (a
# failed handoff must leave the OLD ring fully serving)
_SLICE_CRASH_ENV = "CRDT_SERVE_CRASH_ON_SLICE"


class ServeFrontend:
    """TCP op-ingest frontend over one durable AWSet replica."""

    def __init__(self, num_elements: int, num_actors: int, *,
                 actor: int = 0, durable_dir: Optional[str] = None,
                 peers: Sequence[Addr] = (), queue_depth: int = 256,
                 max_batch: int = 32, flush_ms: float = 2.0,
                 checkpoint_every: int = 0, sync_interval_s: float = 0.05,
                 wal_fsync: bool = True, recorder=None, seed: int = 0,
                 max_conns: Optional[int] = None,
                 ingest_fused: bool = True,
                 wal_compact_records: bool = True,
                 compact_interval_s: float = 0.0,
                 compact_p99_budget_s: float = 0.25,
                 gc_participants: Optional[Sequence[int]] = None,
                 sync_mode: str = "delta",
                 mesh_devices: Optional[int] = None,
                 shard_id: Optional[str] = None,
                 shard_epoch: int = 0,
                 announce_to=None,
                 repl_ack_timeout_ms: float = 250.0,
                 sched: str = "auto"):
        from go_crdt_playground_tpu.obs import Recorder

        self.recorder = recorder if recorder is not None else Recorder()
        self.durable_dir = durable_dir
        # the replica flavor: a plain single-device Node, the 1-D
        # device-mesh target (parallel/meshtarget.py, DESIGN.md §20),
        # or the 2-D dp×mp replicated-ingest mesh
        # (parallel/meshtarget2d.py, §24) — all with the SAME
        # durability/dissemination surface; everything below this
        # constructor line is flavor-agnostic.  ``mesh_devices``
        # accepts an int N (1-D), an "N"/"DPxMP" string, or a
        # (dp, mp) tuple.
        node_cls = Node
        node_kwargs: dict = {}
        if mesh_devices is not None:
            from go_crdt_playground_tpu.parallel.meshtarget2d import \
                parse_mesh_spec

            spec = parse_mesh_spec(mesh_devices)
            if isinstance(spec, tuple):
                from go_crdt_playground_tpu.parallel.meshtarget2d import \
                    Mesh2DApplyTarget

                node_cls = Mesh2DApplyTarget
                node_kwargs = {"mesh_shape": spec}
            else:
                from go_crdt_playground_tpu.parallel.meshtarget import \
                    MeshApplyTarget

                node_cls = MeshApplyTarget
                node_kwargs = {"mesh_devices": spec}
        # the flavor seam, kept for every later scratch construction
        # (_warmup must build the SAME class with the SAME kwargs or
        # it warms a program the serving node never runs)
        self._node_kwargs = node_kwargs
        if durable_dir is not None:
            os.makedirs(durable_dir, exist_ok=True)
            self.node = node_cls.restore_durable(
                durable_dir, recorder=self.recorder,
                node_kwargs=node_kwargs,
                fallback_init=lambda: node_cls(
                    actor, num_elements, num_actors,
                    recorder=self.recorder, **node_kwargs))
        else:
            # non-durable regime (benchmarks/tests): acks are NOT backed
            # by an fsync — production serving always passes durable_dir
            self.node = node_cls(actor, num_elements, num_actors,
                                 recorder=self.recorder, **node_kwargs)
        # serve-ladder knobs (plain config attrs — restore_durable
        # rebuilds the node from checkpoint metadata, which does not
        # carry them): fused one-dispatch ingest+δ and compact WAL
        # records default ON; the soak's seed-comparison leg turns them
        # off to measure the two-dispatch/dense-record baseline
        self.node.ingest_fused = ingest_fused
        self.node.wal_compact_records = wal_compact_records
        self.queue = AdmissionQueue(queue_depth)
        # shard replication (DESIGN.md §23): the publisher tracks
        # tailing standbys' durable cursors and gates the batcher's
        # acks semi-synchronously on them (degrading typed to async
        # when the standby is dead/slow — a standby can never take
        # this primary's availability down).  Dormant until the first
        # WAL_SYNC poll registers a standby.
        from go_crdt_playground_tpu.shard.replica import \
            ReplicationPublisher

        self.repl = ReplicationPublisher(
            self.recorder, ack_timeout_s=repl_ack_timeout_ms / 1e3)
        # conflict-aware admission scheduling (serve/scheduler.py,
        # DESIGN.md §25): "auto" turns it on exactly when the replica
        # serves >1 ingest stripe (the 2-D dp×mp mesh — the only
        # flavor where cross-key reordering buys throughput), "on"
        # forces it (a dp=1 scheduler still coalesces, useful for
        # parity tests), "off" keeps the byte-identical FIFO path.
        if sched not in ("auto", "on", "off"):
            raise ValueError(
                f"sched must be auto/on/off, got {sched!r}")
        stripes = max(1, int(getattr(self.node, "ingest_stripes", 1)))
        self.scheduler = None
        if sched == "on" or (sched == "auto" and stripes > 1):
            from go_crdt_playground_tpu.serve.scheduler import \
                ConflictScheduler

            self.scheduler = ConflictScheduler(
                stripes, recorder=self.recorder)
        self.batcher = MicroBatcher(
            self.node, self.queue, max_batch=max_batch,
            flush_s=flush_ms / 1000.0, recorder=self.recorder,
            repl=self.repl, scheduler=self.scheduler)
        # the dissemination half rides the EXISTING supervisor; it also
        # owns the durable checkpoint cadence (and attaches a WAL to a
        # fresh non-restored node when durable_dir is set)
        self.supervisor = None
        self.sync_mode = sync_mode
        if peers or durable_dir is not None:
            from go_crdt_playground_tpu.net.antientropy import SyncSupervisor

            self.supervisor = SyncSupervisor(
                self.node, peers, durable_dir=durable_dir,
                checkpoint_every=checkpoint_every,
                interval_s=sync_interval_s, wal_fsync=wal_fsync,
                sync_mode=sync_mode,
                recorder=self.recorder, seed=seed)
        # SLO-aware background compaction (serve/compaction.py):
        # deletion-record GC + WAL-driven checkpoint rotation, run only
        # when the serve gauges show ingest-latency headroom
        self.compactor = None
        if compact_interval_s > 0:
            from go_crdt_playground_tpu.serve.compaction import \
                CompactionScheduler

            ckpt = (self.supervisor.checkpoint
                    if self.supervisor is not None
                    and durable_dir is not None else None)
            self.compactor = CompactionScheduler(
                self.node, self.recorder, checkpoint=ckpt,
                interval_s=compact_interval_s,
                p99_budget_s=compact_p99_budget_s,
                gc_participants=gc_participants)
        # the listener/reader/conn-slot plumbing is the shared host
        # (serve/host.py) — the router tier runs the identical stack,
        # so accept-path fixes land once.  Frame caps are PER VERB: the
        # keyspace-handoff verbs scale with the universe (a SLICE_PUSH
        # body is two dense E-lane sections + ~6 bytes per entry, a
        # SLICE_PULL request one varint per moved element) — without
        # that a large-keyspace reshard could never transfer — while
        # every other frame keeps the tiny cap that bounds what an
        # untrusted length header can make one connection buffer.
        slice_cap = max(ConnHost.MAX_FRAME_BODY,
                        16 * num_elements + 4096)
        # WAL_SYNC requests carry a digest summary in the catch-up form
        # (O(E/16) bytes) — same universe-scaled treatment
        slice_verbs = (protocol.MSG_SLICE_PUSH, protocol.MSG_SLICE_PULL,
                       protocol.MSG_WAL_SYNC)
        self.host = ConnHost(
            self._dispatch, recorder=self.recorder,
            counter_prefix="serve", thread_name="serve",
            max_conns=max_conns,
            max_frame_body=lambda t: (slice_cap if t in slice_verbs
                                      else ConnHost.MAX_FRAME_BODY))
        self._has_peers = bool(peers)
        # the GC membership declaration as CONFIGURED; serve() resolves
        # it (deriving None-vs-() from the peer config when unset) into
        # _gc_declared, which the compactor AND the fleet-GC verbs
        # (FRONTIER/GC — the router's evidence channel) share
        self.gc_participants = gc_participants
        self._gc_declared = gc_participants
        self._closed = threading.Event()
        # race-ok: serve() owner thread sets it before any reader runs
        self.addr: Optional[Addr] = None
        # race-ok: read-only after __init__ (reshard-soak crash hook)
        self._slice_crash = os.environ.get(_SLICE_CRASH_ENV) or None
        # router-epoch fence (DESIGN.md §22): the highest router epoch
        # this shard has ever ADJUDICATED, persisted under durable_dir
        # (fsync-then-rename) so a restart cannot forget that a
        # primary was deposed.  Admin-plane verbs (SLICE_PULL/PUSH,
        # FRONTIER, GC) reject typed StaleRouterEpoch for any
        # connection that announced a lower epoch — or, once a fence
        # exists, never announced at all.
        from go_crdt_playground_tpu.shard.handoff import \
            load_router_epoch

        self._epoch_lock = threading.Lock()
        self._router_epoch = load_router_epoch(
            durable_dir)  # guarded-by: _epoch_lock
        # SHARD-epoch fence (DESIGN.md §23): this member's own claim to
        # its keyspace and the highest epoch it has ever adjudicated
        # (a standby's deposition notice, or the router's typed verdict
        # on the serve()-time announce probe).  seen > own = deposed:
        # a standby promoted past this member — writes shed typed
        # StaleShardEpoch, reads keep serving (CRDT lower bound).
        from go_crdt_playground_tpu.shard.replica import (
            load_shard_epoch, load_shard_epoch_seen, persist_shard_epoch)

        self.shard_id = shard_id
        self.announce_to = announce_to
        self._shard_epoch = max(int(shard_epoch), load_shard_epoch(
            durable_dir))  # guarded-by: _epoch_lock
        self._shard_epoch_seen = max(
            self._shard_epoch,
            load_shard_epoch_seen(durable_dir))  # guarded-by: _epoch_lock
        if (durable_dir is not None and shard_epoch > 0
                and self._shard_epoch == int(shard_epoch)):
            # a flag-raised epoch persists before it is acted on, the
            # router-epoch discipline
            persist_shard_epoch(durable_dir, self._shard_epoch,
                                shard_id or "?",
                                seen=self._shard_epoch_seen)
        # WAL-instance nonce: record seqs are only meaningful within
        # one DeltaWal lifetime; a restart renumbers, and the nonce in
        # every WAL_SYNC reply is how standbys find out (typed cursor
        # reset, never a silent gap).  race-ok: read-only after init
        self._wal_nonce = os.urandom(8).hex()
        # race-ok: serve()/warmup() owner thread only
        self._warmed = False

    # -- lifecycle ----------------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0,
              peer_port: Optional[int] = None) -> Addr:
        """Start serving client ops; returns the bound (host, port).
        With ``peer_port`` (or any registered peers) the node also
        starts its anti-entropy server / supervisor loop."""
        if self.host.listening:
            raise RuntimeError("already serving")
        self.warmup()
        if port != 0:
            # announce BEFORE the listener opens when the serving
            # address is declared: a deposed member must learn its
            # verdict before the first direct write can reach it
            self._announce_shard((host, port))
        self.addr = self.host.listen(host, port)
        if port == 0:
            self._announce_shard(self.addr)
        self.batcher.start()
        if peer_port is not None:
            self.node.serve(host, peer_port)
        if self.supervisor is not None and (self.supervisor.peers
                                            or self.supervisor.
                                            checkpoint_every > 0):
            self.supervisor.start()
        if self._gc_declared is None:
            # derive the GC membership declaration from the peer
            # CONFIG (restart-stable, unlike any heard-traffic
            # heuristic): no peer set and no anti-entropy listener
            # means this replica IS the deployment (the isolated
            # declaration, ``()``); any peer surface without an
            # explicit --gc-participants keeps GC disabled
            self._gc_declared = (
                None if (self._has_peers or peer_port is not None)
                else ())
        if self.compactor is not None:
            if self.compactor.gc_participants is None:
                self.compactor.gc_participants = self._gc_declared
            self.compactor.start()
        return self.addr

    def warmup(self) -> None:
        """Idempotent public warmup: a shard STANDBY (shard/replica.py)
        compiles the whole serving path at ENGAGE time so its
        promotion pays a bind + announce, not a first-batch
        trace+compile inside the failover budget; ``serve()`` calls
        this too and skips the second run."""
        if not self._warmed:
            self._warmup()
            self._warmed = True

    def _announce_shard(self, addr: Addr) -> None:
        """The serve()-time keyspace announce / resurrection probe
        (DESIGN.md §23): tell the router which member serves
        ``shard_id`` under which shard epoch.  Idempotent for the
        active member; a RESURRECTED deposed primary gets the typed
        ``StaleShardEpoch`` verdict here — the router's per-sid fence
        is durable — and boots self-fenced.  Best-effort beyond that:
        an unreachable router never blocks serving (pre-HA deployments
        configure no ``announce_to`` at all)."""
        if self.announce_to is None or self.shard_id is None:
            return
        from go_crdt_playground_tpu.serve.client import ServeClient
        from go_crdt_playground_tpu.shard.replica import \
            persist_shard_epoch as _persist

        bump = False
        with self._epoch_lock:
            if self._shard_epoch < 1:
                # an announce-configured member IS a replication-group
                # member: adopt epoch 1 as our OWN claim (persisted)
                # rather than claiming an epoch the WAL_SYNC replies
                # would then contradict — a standby tailing the raw 0
                # would promote at 0+1=1 and COLLIDE with this very
                # claim at the router (equal epoch, different address
                # = typed-stale: the failover could never swap)
                self._shard_epoch = 1
                self._shard_epoch_seen = max(self._shard_epoch_seen, 1)
                bump = True
            epoch = self._shard_epoch
            seen = self._shard_epoch_seen
        if bump:
            _persist(self.durable_dir, epoch, self.shard_id, seen=seen)
        try:
            with ServeClient(self.announce_to, timeout=5.0,
                             connect_timeout=2.0) as c:
                c.shard_failover(epoch, self.shard_id,
                                 f"serve-{os.getpid()}", addr)
            self._count("serve.shard.announces")
        except protocol.StaleShardEpoch:
            # the adjudicated epoch is higher: a standby promoted past
            # this member while it was down.  Self-fence (exact value
            # immaterial — deposed is a comparison) and persist the
            # adjudication so a re-restart boots fenced even if the
            # router is unreachable then
            from go_crdt_playground_tpu.shard.replica import \
                persist_shard_epoch

            with self._epoch_lock:
                self._shard_epoch_seen = max(self._shard_epoch_seen,
                                             self._shard_epoch + 1)
                own, seen = self._shard_epoch, self._shard_epoch_seen
            persist_shard_epoch(self.durable_dir, own,
                                self.shard_id, seen=seen)
            self._count("serve.shard.deposed_boot")
        except Exception:  # noqa: BLE001 — transport failure or an
            # unexpected router reply: the router may be mid-failover
            # itself; its link-level ordered-address redial finds us
            # regardless, so serving never blocks on the probe
            self._count("serve.shard.announce_failures")

    def claim_shard_epoch(self, epoch: int) -> None:
        """Adopt a promotion-claimed shard epoch (the standby persists
        it BEFORE calling this — shard/replica.py step 1)."""
        with self._epoch_lock:
            self._shard_epoch = max(self._shard_epoch, int(epoch))
            self._shard_epoch_seen = max(self._shard_epoch_seen,
                                         self._shard_epoch)

    @property
    def shard_deposed(self) -> bool:
        """True once a HIGHER shard epoch than our own has been
        adjudicated: a standby owns this keyspace now.  Writes shed
        typed; reads keep serving."""
        with self._epoch_lock:
            return self._shard_epoch_seen > self._shard_epoch

    def _warmup(self) -> None:
        """Run one full throwaway ingest (batch apply + δ extraction +
        wire encode + WAL append) on a scratch node of the serving
        shapes BEFORE the listener opens: the first client batch must
        pay the flush watermark, not a multi-second trace+compile (the
        un-warmed stall measured ~600ms-4s on CPU — at 200 ops/s that
        alone fills a 128-deep admission queue and sheds a burst).  The
        REAL node is untouched; compile caches are shape-keyed, so the
        scratch run warms the serving programs exactly."""
        import tempfile

        import numpy as np

        from go_crdt_playground_tpu.utils.wal import DeltaWal

        # the batcher's EFFECTIVE width: a striped 2-D replica serves
        # super-batches of ingest_stripes x max_batch rows — warming
        # the bare max_batch shape would leave the real serving shape
        # to compile on the first live super-batch
        B, E = self.batcher.width, self.node.num_elements
        with tempfile.TemporaryDirectory(prefix="serve-warmup-") as d:
            # same ingest regime as the REAL node: a --no-fused-ingest
            # worker must warm the seed two-dispatch programs, not the
            # fused one it will never run (the first batch would
            # otherwise pay the compile stall the warmup exists to
            # prevent — and skew any seed-vs-fused comparison).  Same
            # CLASS + flavor kwargs too: a mesh-sharded replica must
            # warm the shard_map programs on its own mesh shape
            scratch = type(self.node)(
                self.node.actor, E, self.node.num_actors,
                ingest_fused=self.node.ingest_fused,
                wal_compact_records=self.node.wal_compact_records,
                wal=DeltaWal(os.path.join(d, "wal"), fsync=False),
                **self._node_kwargs)
            add = np.zeros((B, E), bool)
            add[0, 0] = True  # one live lane: the δ-extract path runs
            scratch.ingest_batch(add, np.zeros((B, E), bool),
                                 np.asarray([True] + [False] * (B - 1)))
            # warm the keyspace-handoff transfer path too (slice
            # extract + payload apply): the fence window of a live
            # reshard must pay the flush-scale transfer, not a
            # multi-second first-compile of delta_apply
            mask = np.zeros(E, bool)
            mask[0] = True
            scratch.apply_payload_body(scratch.extract_slice(mask))
            if self.sync_mode == "digest":
                # the supervisor's first digest round must pay a
                # socket round-trip, not a trace+compile
                from go_crdt_playground_tpu.net import digestsync

                digestsync.warm(scratch)
            with scratch._lock:
                scratch.wal.close()

    def close(self, drain_timeout_s: float = 30.0) -> None:
        """Graceful drain (module docstring): admitted ops ack before
        the process lets go of them."""
        if self._closed.is_set():
            return
        # stop accepting dials FIRST (the host does the shutdown-
        # before-close listener dance); in-flight connections get typed
        # Draining rejects for new ops from here on
        self.host.stop_accepting()
        if self.compactor is not None:
            # before the drain: a background checkpoint racing the
            # final drain checkpoint would double-write the store
            self.compactor.stop()
        self.batcher.drain(timeout=drain_timeout_s)
        if self.supervisor is not None:
            self.supervisor.stop()
            if self.supervisor.durable_dir is not None:
                # final checkpoint: seals the WAL and retires the
                # segments the dump covers (Node.save_durable two-phase)
                try:
                    self.supervisor.checkpoint()
                except Exception:  # noqa: BLE001 — drain must finish;
                    # the WAL already holds everything the dump would
                    self._count("serve.final_checkpoint_failures")
        # node BEFORE wal: the node's peer-sync server logs every
        # applied payload, so the WAL must outlive the listener (an
        # inbound exchange against a closed WAL is a served error, not
        # a crashed handler — net/peer.py catches it — but not serving
        # it at all is better)
        self.node.close()
        with self.node._lock:
            wal = self.node.wal
        if wal is not None:
            wal.close()
        # flush: the batcher's final acks are in per-session writer
        # queues (serve/session.py); the host gives the writers ONE
        # shared bounded window to get them onto the wire
        self.host.close_sessions(flush_timeout_s=2.0)
        self._closed.set()

    def __enter__(self) -> "ServeFrontend":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request dispatch (runs on the host's reader threads) ---------------

    def _dispatch(self, session: Session, msg_type: int,
                  body: bytes) -> bool:
        if msg_type == protocol.MSG_OP:
            return self._handle_op(session, body)
        if msg_type == protocol.MSG_QUERY:
            self._handle_query(session, body)
            return True
        if msg_type == protocol.MSG_STATS:
            self._handle_stats(session, body)
            return True
        if msg_type == protocol.MSG_SLICE_PULL:
            return self._handle_slice_pull(session, body)
        if msg_type == protocol.MSG_SLICE_PUSH:
            return self._handle_slice_push(session, body)
        if msg_type == protocol.MSG_FRONTIER:
            return self._handle_frontier(session, body)
        if msg_type == protocol.MSG_GC:
            return self._handle_gc(session, body)
        if msg_type == protocol.MSG_DSUM:
            return self._handle_dsum(session, body)
        if msg_type == protocol.MSG_RING_SYNC:
            return self._handle_ring_sync(session, body)
        if msg_type == protocol.MSG_WAL_SYNC:
            return self._handle_wal_sync(session, body)
        # protocol-ignore: MSG_RESHARD — router-only admin verb; a
        # frontend answers it with the typed unknown-frame error below
        # protocol-ignore: MSG_SHARD_FAILOVER — router-only failover
        # adjudication verb; same typed unknown-frame answer
        session.send(framing.MSG_ERROR,
                     f"unexpected frame type {msg_type}".encode())
        return False

    def _handle_op(self, session: Session, body: bytes) -> bool:
        """Admit one OP frame; False ends the connection (undecodable
        frame — the stream may be out of sync)."""
        try:
            req_id, kind, elements, deadline_us = protocol.decode_op(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        E = self.node.num_elements
        if any(not 0 <= e < E for e in elements):
            self._count("serve.rejects.invalid")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_INVALID,
                f"element id outside universe E={E}"))
            return True
        if len(set(elements)) != len(elements):
            # key-SET contract (serve/protocol.py): duplicates would
            # apply set-wise here but per-argument on the reference host
            # path — refuse rather than silently diverge by ingress
            self._count("serve.rejects.invalid")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_INVALID,
                "duplicate element ids in one op"))
            return True
        if self.host.draining:
            self._count("serve.shed.draining")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_DRAINING, "frontend draining"))
            return True
        if self.shard_deposed:
            # shard-epoch self-fence (DESIGN.md §23): a standby owns
            # this keyspace — a write applied here would be acked by a
            # member the router never reads again (acked-but-invisible,
            # the one thing zero-acked-op-loss can never tolerate).
            # Reads below keep serving: a stale member's state is a
            # correct CRDT lower bound.
            self._count("serve.shed.shard_deposed")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_STALE_SHARD_EPOCH,
                "shard member deposed (stale shard epoch) — a standby "
                "was promoted for this keyspace; dial the router"))
            return True
        if self.batcher.storage_degraded():
            # disk-full graceful degrade (DESIGN.md §16 tail): the WAL
            # append/fsync path failed recently — shed WRITES typed at
            # admission (reads keep serving) until the batcher's next
            # probe window lets one batch test the disk again
            self._count("serve.shed.storage")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_STORAGE,
                "durable WAL append failing (storage degraded; "
                "reads still served — retry with backoff)"))
            return True
        now = time.monotonic()
        deadline = (now + deadline_us / 1e6) if deadline_us > 0 else None
        req = OpRequest(req_id, kind, elements, deadline, session, now)
        if self.queue.offer(req):
            self._count("serve.ops.admitted")
        else:
            # admission limit: shed with the TYPED reply — under
            # saturation offered load converts to Overloaded replies,
            # not queue growth (bounded p99, SERVE_CURVE.json)
            self._count("serve.shed.overload")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_OVERLOADED,
                f"admission queue full (depth {self.queue.maxdepth})"))
        return True

    def _handle_query(self, session: Session, body: bytes) -> None:
        try:
            req_id = protocol.decode_query(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return
        self._count("serve.queries")
        # ONE lock hold for membership + vv (separate members()/vv()
        # calls could interleave with a batch commit and reply with a
        # vv covering an add the membership doesn't show — a state no
        # replica ever held), pulling ONLY the present mask + vv: on a
        # mesh-sharded replica the dot/deletion lanes stay on-device
        members, vv = self.node.members_vv()
        session.send(protocol.MSG_MEMBERS, protocol.encode_members(
            req_id, [int(e) for e in members], vv))

    def _handle_stats(self, session: Session, body: bytes) -> None:
        """The SLO read-out: the recorder snapshot (ingest latency
        p50/p95/p99, batch occupancy, shed counters, queue depth) over
        the wire — operators and the serve soak read the same numbers."""
        try:
            req_id = protocol.decode_stats(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return
        session.send(protocol.MSG_STATS_REPLY, protocol.encode_stats_reply(
            req_id, self.recorder.snapshot()))

    def _handle_dsum(self, session: Session, body: bytes) -> bool:
        """The digest-summary read (protocol.MSG_DSUM): this replica's
        ``net/digestsync`` summary body — the O(E/16)-byte freshness
        key the router's member cache compares instead of re-pulling
        O(membership) MEMBERS replies.  On a mesh-sharded replica the
        digests come off the collective kernel; either way no state
        lane crosses to the host for this read."""
        from go_crdt_playground_tpu.net import digestsync

        try:
            req_id = protocol.decode_dsum(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        self._count("serve.digest_reads")
        session.send(protocol.MSG_DSUM_REPLY, protocol.encode_dsum_reply(
            req_id, digestsync.node_summary(self.node)))
        return True

    # -- router-epoch fence (router HA, DESIGN.md §22) ----------------------

    # fence-ok: this verb IS the router-epoch fence mechanism — it
    # adjudicates claims persist-then-adopt and must answer on a
    # deposed member so the member can learn its own deposition
    def _handle_ring_sync(self, session: Session, body: bytes) -> bool:
        """Adjudicate a router-epoch announcement (or serve a pure
        read).  A claim ABOVE the recorded maximum is adopted and
        persisted BEFORE it is acknowledged — from that fsync on, no
        older router can drive an admin verb here.  A claim BELOW it
        is the deposed router itself: typed ``StaleRouterEpoch``."""
        from go_crdt_playground_tpu.shard.handoff import \
            persist_router_epoch

        try:
            req_id, epoch, router_id = protocol.decode_ring_sync(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        with self._epoch_lock:
            current = self._router_epoch
            if epoch > current:
                # persist-then-adopt under the lock: two racing
                # announcements serialize here, and the on-disk record
                # is monotone because only the winner of the compare
                # ever writes
                persist_router_epoch(self.durable_dir, epoch, router_id)
                self._router_epoch = epoch
                current = epoch
                self._count("serve.router_epoch.adopted")
        if 0 < epoch < current:
            self._count("serve.rejects.stale_epoch")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_STALE_EPOCH,
                f"router epoch {epoch} is stale: epoch {current} "
                "already adjudicated (a standby promoted past you)"))
            return True
        if epoch > 0:
            # the fence stamp the admin verbs below adjudicate against
            session.router_epoch = epoch
        session.send(protocol.MSG_RING_SYNC_REPLY,
                     protocol.encode_ring_sync_reply(
                         req_id, {"router_epoch": current,
                                  "role": "shard"}))
        return True

    def _epoch_fenced(self, session: Session, req_id: int) -> bool:
        """The admin-plane fence check: True (and a typed reject sent)
        when this connection's announced router epoch is older than the
        highest adjudicated one — including the never-announced case
        once any fence exists, so a deposed pre-announce code path can
        never slip an admin write through.  With no epoch ever seen
        (non-HA deployments) the fence is dormant and every existing
        caller is untouched."""
        with self._epoch_lock:
            current = self._router_epoch
        if current > 0 and session.router_epoch < current:
            self._count("serve.rejects.stale_epoch")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_STALE_EPOCH,
                f"admin verb under router epoch "
                f"{session.router_epoch or 'none'}: epoch {current} "
                "already adjudicated (announce via RING_SYNC)"))
            return True
        return False

    # -- shard replication: the WAL_SYNC serve verb (DESIGN.md §23) ---------

    # reply-batch bounds: a tail reply never exceeds either, so one
    # poll can neither blow the standby's frame cap nor hold the
    # session writer behind a megarecord burst
    WAL_SYNC_MAX_RECORDS = 256
    WAL_SYNC_MAX_BYTES = 1 << 20

    # fence-ok: this verb IS the shard-epoch fence mechanism — it
    # adjudicates standby claims persist-before-ack, and the tail read
    # must keep serving on a deposed member so a lagging standby can
    # finish catching up before arbitration
    def _handle_wal_sync(self, session: Session, body: bytes) -> bool:
        """Serve one standby tail poll / catch-up / epoch claim
        (serve/protocol.py MSG_WAL_SYNC).  The ``from_seq`` cursor is
        the standby's durable ack — it feeds the semi-sync publisher
        BEFORE the records are read, so the batcher's gate wakes the
        moment the ack lands.  An epoch claim above everything seen is
        the promoting standby's deposition notice: adopted, persisted,
        and from then on this member's writes shed typed."""
        from go_crdt_playground_tpu.utils.wal import WalTruncated

        try:
            (req_id, epoch, standby_id, from_seq, wait_ms, max_records,
             summary) = protocol.decode_wal_sync(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        # -- shard-epoch adjudication (the deposition notice path) ----------
        if epoch > 0:
            from go_crdt_playground_tpu.shard.replica import \
                persist_shard_epoch

            persist = None
            with self._epoch_lock:
                if epoch > self._shard_epoch_seen:
                    self._shard_epoch_seen = epoch
                    persist = (self._shard_epoch, epoch)
                seen = self._shard_epoch_seen
            if persist is not None:
                # durable BEFORE the ack: a restart cannot forget that
                # this keyspace was claimed past us
                persist_shard_epoch(self.durable_dir, persist[0],
                                    self.shard_id or "?",
                                    seen=persist[1])
                self._count("serve.shard_epoch.adopted")
            if epoch < seen:
                self._count("serve.rejects.stale_shard_epoch")
                session.send(protocol.MSG_REJECT, protocol.encode_reject(
                    req_id, protocol.REJECT_STALE_SHARD_EPOCH,
                    f"shard epoch {epoch} is stale: epoch {seen} "
                    "already adjudicated"))
                return True
        with self._epoch_lock:
            own_epoch = self._shard_epoch
        node = self.node
        with node._lock:
            wal = node.wal
        # -- catch-up: reply the O(diff) digest payload ---------------------
        if summary is not None:
            from go_crdt_playground_tpu.net import digestsync

            try:
                _actor, group_size, vv, _proc, digests = \
                    digestsync.decode_summary(summary, node.num_elements,
                                              node.num_actors)
            except framing.ProtocolError as e:
                session.send(framing.MSG_ERROR, str(e).encode())
                return False
            try:
                with node._lock:
                    # cursor read under the SAME lock hold as the
                    # payload build: every record below next_seq is in
                    # the payload's state, so resuming the tail there
                    # can never skip one (appends take this lock)
                    next_seq = wal.next_seq() if wal is not None else 1
                    _mode, payload, _lanes, _gm = \
                        digestsync.build_reply_payload(
                            node, vv, digests, group_size)
            except Exception as e:  # noqa: BLE001 — a failed extract
                # must reply typed, not kill the reader thread
                self._count("repl.ship_errors")
                session.send(protocol.MSG_REJECT, protocol.encode_reject(
                    req_id, protocol.REJECT_OVERLOADED,
                    f"catch-up extract failed (retry): {e}"))
                return True
            self._count("repl.catchups_served")
            session.send(protocol.MSG_WAL_SYNC_REPLY,
                         protocol.encode_wal_sync_reply(
                             req_id, 0, own_epoch, self.shard_id or "?",
                             self._wal_nonce,
                             wal.min_seq() if wal is not None else 1,
                             next_seq, next_seq, (), payload))
            return True
        # -- tail poll: the ack, then a bounded record batch ----------------
        self.repl.note_poll(standby_id, from_seq)
        flags = 0
        records: list = []
        first_seq = from_seq
        if wal is None:
            min_seq = next_seq = 1
        else:
            self.repl.refresh_gauges(wal.next_seq())
            if from_seq > wal.next_seq():
                # a cursor beyond this WAL instance's tail is from a
                # previous numbering (the nonce catches the common
                # case; this guard catches a standby that missed it):
                # typed reset, never a silent forever-spin
                session.send(protocol.MSG_WAL_SYNC_REPLY,
                             protocol.encode_wal_sync_reply(
                                 req_id, protocol.WAL_TRUNCATED,
                                 own_epoch, self.shard_id or "?",
                                 self._wal_nonce, wal.min_seq(),
                                 wal.next_seq(), from_seq, ()))
                return True
            cap = min(max_records or self.WAL_SYNC_MAX_RECORDS,
                      self.WAL_SYNC_MAX_RECORDS)
            deadline = (time.monotonic() + min(wait_ms, 5000) / 1e3
                        if wait_ms > 0 else None)
            while True:
                try:
                    total = 0
                    for seq, rec in wal.stream_from(from_seq):
                        if not records:
                            first_seq = seq
                        records.append(rec)
                        total += len(rec)
                        if (len(records) >= cap
                                or total >= self.WAL_SYNC_MAX_BYTES):
                            break
                except WalTruncated:
                    # typed, never a silent gap: the standby must
                    # digest-catch-up and resume at next_seq
                    flags |= protocol.WAL_TRUNCATED
                    records = []
                except OSError:
                    self._count("repl.ship_errors")
                    records = []
                if records or flags or deadline is None \
                        or time.monotonic() >= deadline \
                        or self.host.draining:
                    break
                # long-poll: the standby parks here between batches so
                # a fresh record ships within ~one tick of its fsync
                time.sleep(0.005)
            min_seq = wal.min_seq()
            next_seq = (first_seq + len(records) if records
                        else wal.next_seq() if flags else from_seq)
            if records:
                self._count("repl.records_shipped", len(records))
        session.send(protocol.MSG_WAL_SYNC_REPLY,
                     protocol.encode_wal_sync_reply(
                         req_id, flags, own_epoch, self.shard_id or "?",
                         self._wal_nonce, min_seq, next_seq, first_seq,
                         records))
        return True

    # -- keyspace handoff (live resharding, DESIGN.md §18) ------------------

    def _crash_if_armed(self, which: str) -> None:
        """The reshard soak's kill-mid-handoff hook: SIGKILL the whole
        process at the named slice verb — donor death ("pull") before
        any state leaves, recipient death ("push") before any state
        lands, so the aborted handoff provably transferred nothing."""
        if self._slice_crash == which:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)

    def _handle_slice_pull(self, session: Session, body: bytes) -> bool:
        """Serve the donor half of a keyspace handoff: the complete
        slice state as an anti-entropy payload body (opaque bytes the
        router shuttles to the new owner)."""
        try:
            req_id, elements = protocol.decode_slice_pull(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        E = self.node.num_elements
        if any(not 0 <= e < E for e in elements):
            self._count("serve.rejects.invalid")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_INVALID,
                f"slice element outside universe E={E}"))
            return True
        if self.host.draining:
            self._count("serve.shed.draining")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_DRAINING, "frontend draining"))
            return True
        if self._epoch_fenced(session, req_id):
            return True
        self._crash_if_armed("pull")
        import numpy as np

        mask = np.zeros(E, bool)
        mask[elements] = True
        payload = self.node.extract_slice(mask)
        self._count("serve.slice.pulls")
        session.send(protocol.MSG_SLICE_STATE,
                     protocol.encode_slice_state(req_id, payload))
        return True

    def _handle_slice_push(self, session: Session, body: bytes) -> bool:
        """Serve the recipient half: apply the pushed slice through the
        WAL-logged payload path and ack only once it is durable — the
        ring swap that follows this ack trusts it exactly like a client
        trusts an op ack."""
        try:
            req_id, payload = protocol.decode_slice_push(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        if self.host.draining:
            self._count("serve.shed.draining")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_DRAINING, "frontend draining"))
            return True
        if self._epoch_fenced(session, req_id):
            return True
        self._crash_if_armed("push")
        try:
            self.node.apply_payload_body(payload)
        except framing.ProtocolError as e:
            # malformed/incompatible payload: deterministic — the
            # router must abort the handoff, not retry the same bytes
            self._count("serve.rejects.invalid")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_INVALID,
                f"slice payload refused: {e}"))
            return True
        except ValueError as e:
            # transient server trouble (e.g. a closing WAL refusing the
            # append): retryable, like a poison batch
            self._count("serve.slice.push_failures")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_OVERLOADED,
                f"slice apply failed (retry): {e}"))
            return True
        self._count("serve.slice.pushes")
        session.send(protocol.MSG_ACK, protocol.encode_ack(req_id))
        return True

    # -- fleet-aware deletion-record GC (router aggregation, §17) -----------

    def _handle_frontier(self, session: Session, body: bytes) -> bool:
        """Report this shard's GC evidence for the router's fleet
        aggregation: local provable frontier + raw processed vv +
        whether the membership declaration is the explicit isolated
        one (serve/protocol.encode_frontier_reply documents why all
        three travel together).  A non-v2 or mid-heal shard reports a
        zero frontier — it can prove nothing stable, and the zeros
        block fleet GC for every lane it holds state in."""
        import numpy as np

        try:
            req_id = protocol.decode_frontier(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        if self._epoch_fenced(session, req_id):
            return True
        node = self.node
        declared = self._gc_declared
        with node._lock:
            processed = np.asarray(node._state.processed[0],
                                   np.uint32).copy()
        if (node.delta_semantics != "v2"
                or node.full_resync_is_pending()):
            frontier = np.zeros(node.num_actors, np.uint32)
        else:
            frontier = node.deletion_frontier(declared)
        isolated = declared is not None and len(tuple(declared)) == 0
        self._count("serve.fleet_gc.frontier_reads")
        session.send(protocol.MSG_FRONTIER_REPLY,
                     protocol.encode_frontier_reply(
                         req_id, frontier, processed, isolated))
        return True

    def _handle_gc(self, session: Session, body: bytes) -> bool:
        """Apply a router-pushed fleet frontier, CLAMPED lane-wise to
        what this shard can prove locally — conservative on both hops:
        a buggy or hostile router can never make a shard drop a record
        its own evidence does not already cover (so an undeclared shard
        clamps everything to zero and never GCs)."""
        import numpy as np

        try:
            req_id, fleet = protocol.decode_gc(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        if self._epoch_fenced(session, req_id):
            return True
        node = self.node
        dropped = 0
        if (node.delta_semantics == "v2"
                and not node.full_resync_is_pending()):
            own = node.deletion_frontier(self._gc_declared)
            eff = np.zeros(node.num_actors, np.uint32)
            n = min(own.shape[0], fleet.shape[0])
            eff[:n] = np.minimum(own[:n], fleet[:n])
            if eff.any():
                out = node.gc_deletions(frontier=eff)
                dropped = out["dropped"]
                remaining = out["remaining"]
                self._count("serve.fleet_gc.runs")
                if dropped:
                    self._count("serve.fleet_gc.dropped_lanes", dropped)
            else:
                with node._lock:
                    remaining = int(
                        np.asarray(node._state.deleted[0]).sum())
        else:
            with node._lock:
                remaining = int(np.asarray(node._state.deleted[0]).sum())
        session.send(protocol.MSG_GC_REPLY,
                     protocol.encode_gc_reply(req_id, dropped, remaining))
        return True

    def _count(self, name: str, n: int = 1) -> None:
        if self.recorder is not None:
            self.recorder.count(name, n)
