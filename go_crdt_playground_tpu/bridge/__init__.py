"""Merger bridge: the framework's merge kernels as a service.

The reference's only harness is in-process ``go test`` (README.md:1);
this package is the attach point it would use from outside — proto
schema in ``merger.proto``, always-available TCP transport and optional
gRPC serving in ``service``.
"""

from go_crdt_playground_tpu.bridge.service import (  # noqa: F401
    MergerClient,
    MergerServer,
    execute_merge,
    serve_grpc,
)
