"""Proto <-> spec-model conversions for the Merger bridge.

The proto shapes (bridge/merger.proto) mirror the reference structs:
ReplicaState is an AWSet snapshot (awset.go:55-59) plus the δ Deleted log
(awset-delta_test.go:9-12) and the v2 processed vector.  Wire counters are
uint64 like Go's uint; the packed kernels are uint32, so `check_uint32`
rejects what cannot be represented instead of silently truncating
(SURVEY §7.5.5).
"""

from __future__ import annotations

from typing import Union

from go_crdt_playground_tpu.bridge import merger_pb2 as pb
from go_crdt_playground_tpu.models.spec import (AWSet, AWSetDelta, Dot,
                                                VersionVector)
from go_crdt_playground_tpu.utils.guards import UINT32_MAX


def check_uint32(state: pb.ReplicaState, label: str) -> None:
    too_big = [c for c in state.version_vector if c > UINT32_MAX]
    too_big += [e.dot.counter for e in state.entries
                if e.dot.counter > UINT32_MAX]
    too_big += [e.dot.counter for e in state.deleted
                if e.dot.counter > UINT32_MAX]
    too_big += [c for c in state.processed if c > UINT32_MAX]
    if too_big:
        raise OverflowError(
            f"{label}: counter {max(too_big)} exceeds the packed kernels' "
            f"uint32 range ({UINT32_MAX})")


def replica_from_proto(state: pb.ReplicaState,
                       delta: bool = False,
                       delta_semantics: str = "reference",
                       strict_reference_semantics: bool = True,
                       ) -> Union[AWSet, AWSetDelta]:
    vv = VersionVector(list(state.version_vector))
    if delta:
        rep: Union[AWSet, AWSetDelta] = AWSetDelta(
            actor=int(state.actor), version_vector=vv,
            delta_semantics=delta_semantics,
            strict_reference_semantics=strict_reference_semantics,
        )
        for e in state.deleted:
            rep.deleted[e.key] = Dot(int(e.dot.actor), int(e.dot.counter))
        for a, c in enumerate(state.processed):
            if c:
                rep.processed[a] = int(c)
    else:
        rep = AWSet(actor=int(state.actor), version_vector=vv)
    for e in state.entries:
        rep.entries[e.key] = Dot(int(e.dot.actor), int(e.dot.counter))
    return rep


def replica_to_proto(rep: Union[AWSet, AWSetDelta]) -> pb.ReplicaState:
    out = pb.ReplicaState(
        actor=rep.actor,
        version_vector=list(rep.version_vector.v),
    )
    for key in sorted(rep.entries):
        d = rep.entries[key]
        out.entries.append(pb.Entry(key=key, dot=pb.Dot(
            actor=d.actor, counter=d.counter)))
    if isinstance(rep, AWSetDelta):
        for key in sorted(rep.deleted):
            d = rep.deleted[key]
            out.deleted.append(pb.Entry(key=key, dot=pb.Dot(
                actor=d.actor, counter=d.counter)))
        if rep.processed:
            width = max(rep.processed) + 1
            out.processed.extend(
                rep.processed.get(a, 0) for a in range(width))
    return out
