// Command crdtbridge-client drives the go_crdt_playground_tpu Merger
// bridge from Go, replaying the reference repository's full-state AWSet
// scenarios (/root/reference/awset_test.go:10-122 — TestAWSetXXX,
// TestAWSet, TestAWSetConcurrentAddWinsOverDelete) and the δ-state
// scenario (/root/reference/awset-delta_test.go:168-189 — TestAWSetDelta)
// with EVERY dst.Merge(src) executed by the framework's packed TPU merge
// kernel, reached over the plain-TCP framing of bridge/service.py:
//
//	frame = method(1 byte) | length(uint32 big-endian) | proto body
//	merge = method 0x01, body crdtbridge.MergeRequest
//	ping  = method 0x02, empty body, echoed
//
// Local ops (Add/Del/Clone) run client-side exactly as the reference
// fixture does (awset_test.go:156-174); the merge decision logic never
// runs here — the point is that the framework, not this client, computes
// every merge, and this program checks memberships and the canonical
// rendering against the reference tests' expectations.
//
// The proto bytes are emitted DETERMINISTICALLY so that
// tests/test_bridge_client.py can replay the byte-identical stream from
// Python against a live MergerServer:
//   - fields in ascending tag order;
//   - map entries sorted by key before encoding;
//   - proto3 zero values omitted; repeated uint64 packed.
//
// No Go toolchain exists in the build image (SURVEY preamble), so CI
// exercises this byte stream via tests/test_bridge_client.py; run it for
// real with:
//
//	python -m go_crdt_playground_tpu serve   # prints host:port
//	cd go_crdt_playground_tpu/bridge/client && go run . -addr HOST:PORT
package main

import (
	"bytes"
	"encoding/binary"
	"flag"
	"fmt"
	"net"
	"os"
	"sort"
	"strconv"
)

const (
	methodMerge = 0x01
	methodPing  = 0x02
)

// ---------------------------------------------------------------------------
// Client-side replica model: local ops only (awset.go:89-101 semantics).
// ---------------------------------------------------------------------------

type dot struct {
	Actor   uint32
	Counter uint64
}

type replica struct {
	Actor   uint32
	VV      []uint64
	Entries map[string]dot
}

func newReplica(actor uint32, actors int) *replica {
	return &replica{
		Actor:   actor,
		VV:      make([]uint64, actors),
		Entries: map[string]dot{},
	}
}

// add ticks the clock once per key and stamps the birth dot
// (awset.go:89-94; re-add overwrites the dot).
func (r *replica) add(keys ...string) {
	for _, k := range keys {
		r.VV[r.Actor]++
		r.Entries[k] = dot{r.Actor, r.VV[r.Actor]}
	}
}

// del removes without ticking the clock (awset.go:96-101: the increment
// is commented out in the reference; causality rides on the VV).
func (r *replica) del(keys ...string) {
	for _, k := range keys {
		delete(r.Entries, k)
	}
}

func (r *replica) clone() *replica {
	c := newReplica(r.Actor, len(r.VV))
	copy(c.VV, r.VV)
	for k, d := range r.Entries {
		c.Entries[k] = d
	}
	return c
}

func (r *replica) sortedValues() []string {
	vals := make([]string, 0, len(r.Entries))
	for k := range r.Entries {
		vals = append(vals, k)
	}
	sort.Strings(vals)
	return vals
}

// String reproduces the canonical rendering (awset.go:163-171,
// crdt-misc.go:17-19,57-68):  [(A 1), (B 2)]\n  (A 1)  "Alice"\n  ...
func (r *replica) String() string {
	var b bytes.Buffer
	b.WriteByte('[')
	for i, n := range r.VV {
		if i > 0 {
			b.WriteString(", ")
		}
		fmt.Fprintf(&b, "(%c %d)", rune('A'+i), n)
	}
	b.WriteByte(']')
	for _, k := range r.sortedValues() {
		d := r.Entries[k]
		fmt.Fprintf(&b, "\n  (%c %d)  %s",
			rune('A'+d.Actor), d.Counter, strconv.Quote(k))
	}
	return b.String()
}

// ---------------------------------------------------------------------------
// δ-state replica model (awset-delta_test.go:9-49 semantics).
// ---------------------------------------------------------------------------

type deltaReplica struct {
	replica
	Deleted map[string]dot
}

func newDeltaReplica(actor uint32, actors int) *deltaReplica {
	return &deltaReplica{replica: *newReplica(actor, actors)}
}

// del ticks the clock ONCE per call (even when no key matches) and stamps
// every removed key with that one shared deletion dot, recording it in the
// Deleted log (awset-delta_test.go:14-33) — unlike AWSet.Del, which never
// ticks (awset.go:96-101).
func (r *deltaReplica) del(keys ...string) {
	r.VV[r.Actor]++
	d := dot{r.Actor, r.VV[r.Actor]}
	for _, k := range keys {
		if _, ok := r.Entries[k]; ok {
			if r.Deleted == nil {
				r.Deleted = map[string]dot{}
			}
			r.Deleted[k] = d
			delete(r.Entries, k)
		}
	}
}

// ---------------------------------------------------------------------------
// Minimal deterministic proto3 wire encoding (merger.proto messages only).
// ---------------------------------------------------------------------------

func putVarint(b *bytes.Buffer, v uint64) {
	var tmp [binary.MaxVarintLen64]byte
	b.Write(tmp[:binary.PutUvarint(tmp[:], v)])
}

func putTag(b *bytes.Buffer, field, wire uint64) {
	putVarint(b, field<<3|wire)
}

func putLenField(b *bytes.Buffer, field uint64, payload []byte) {
	putTag(b, field, 2)
	putVarint(b, uint64(len(payload)))
	b.Write(payload)
}

func encodeDot(d dot) []byte {
	var b bytes.Buffer
	if d.Actor != 0 {
		putTag(&b, 1, 0)
		putVarint(&b, uint64(d.Actor))
	}
	if d.Counter != 0 {
		putTag(&b, 2, 0)
		putVarint(&b, d.Counter)
	}
	return b.Bytes()
}

func encodeEntry(key string, d dot) []byte {
	var b bytes.Buffer
	putLenField(&b, 1, []byte(key))
	putLenField(&b, 2, encodeDot(d))
	return b.Bytes()
}

func encodeReplica(r *replica) []byte {
	var b bytes.Buffer
	if r.Actor != 0 {
		putTag(&b, 1, 0)
		putVarint(&b, uint64(r.Actor))
	}
	if len(r.VV) > 0 { // repeated uint64 -> packed
		var packed bytes.Buffer
		for _, n := range r.VV {
			putVarint(&packed, n)
		}
		putLenField(&b, 2, packed.Bytes())
	}
	for _, k := range r.sortedValues() { // deterministic entry order
		putLenField(&b, 3, encodeEntry(k, r.Entries[k]))
	}
	return b.Bytes()
}

func encodeMergeRequest(dst, src *replica) []byte {
	var b bytes.Buffer
	putLenField(&b, 1, encodeReplica(dst))
	putLenField(&b, 2, encodeReplica(src))
	// delta=false, delta_semantics="", strict=false: proto3 zero values,
	// omitted — the full-state AWSet.Merge path (awset.go:103).
	return b.Bytes()
}

func encodeDeltaReplica(r *deltaReplica) []byte {
	var b bytes.Buffer
	b.Write(encodeReplica(&r.replica))
	keys := make([]string, 0, len(r.Deleted)) // deterministic log order
	for k := range r.Deleted {
		keys = append(keys, k)
	}
	sort.Strings(keys)
	for _, k := range keys {
		putLenField(&b, 4, encodeEntry(k, r.Deleted[k]))
	}
	return b.Bytes()
}

func encodeDeltaMergeRequest(dst, src *deltaReplica) []byte {
	var b bytes.Buffer
	putLenField(&b, 1, encodeDeltaReplica(dst))
	putLenField(&b, 2, encodeDeltaReplica(src))
	putTag(&b, 3, 0) // delta=true: AWSetDelta.Merge dispatch
	putVarint(&b, 1) // (awset-delta_test.go:51-65)
	putLenField(&b, 4, []byte("reference"))
	putTag(&b, 5, 0) // strict_reference_semantics: keep the empty-δ
	putVarint(&b, 1) // VV-skip quirk (awset-delta_test.go:60-64)
	return b.Bytes()
}

// ---------------------------------------------------------------------------
// Minimal proto3 wire decoding for MergeResponse.
// ---------------------------------------------------------------------------

type wireReader struct {
	buf []byte
	pos int
}

func (w *wireReader) done() bool { return w.pos >= len(w.buf) }

func (w *wireReader) varint() uint64 {
	v, n := binary.Uvarint(w.buf[w.pos:])
	if n <= 0 {
		fatalf("malformed varint at %d", w.pos)
	}
	w.pos += n
	return v
}

func (w *wireReader) lenField() []byte {
	n := int(w.varint())
	if w.pos+n > len(w.buf) {
		fatalf("truncated length-delimited field at %d", w.pos)
	}
	out := w.buf[w.pos : w.pos+n]
	w.pos += n
	return out
}

func (w *wireReader) skip(wire uint64) {
	switch wire {
	case 0:
		w.varint()
	case 1:
		w.pos += 8
	case 2:
		w.lenField()
	case 5:
		w.pos += 4
	default:
		fatalf("unsupported wire type %d", wire)
	}
}

func decodeDot(buf []byte) dot {
	w := wireReader{buf: buf}
	var d dot
	for !w.done() {
		tag := w.varint()
		switch tag >> 3 {
		case 1:
			d.Actor = uint32(w.varint())
		case 2:
			d.Counter = w.varint()
		default:
			w.skip(tag & 7)
		}
	}
	return d
}

func decodeEntryField(buf []byte) (string, dot) {
	e := wireReader{buf: buf}
	var key string
	var d dot
	for !e.done() {
		etag := e.varint()
		switch etag >> 3 {
		case 1:
			key = string(e.lenField())
		case 2:
			d = decodeDot(e.lenField())
		default:
			e.skip(etag & 7)
		}
	}
	return key, d
}

// decodeReplica parses a ReplicaState; the second return is the δ Deleted
// log (field 4), nil for plain-AWSet responses.
func decodeReplica(buf []byte) (*replica, map[string]dot) {
	w := wireReader{buf: buf}
	r := &replica{Entries: map[string]dot{}}
	var deleted map[string]dot
	for !w.done() {
		tag := w.varint()
		switch tag >> 3 {
		case 1:
			r.Actor = uint32(w.varint())
		case 2:
			if tag&7 == 2 { // packed
				p := wireReader{buf: w.lenField()}
				for !p.done() {
					r.VV = append(r.VV, p.varint())
				}
			} else { // unpacked writer
				r.VV = append(r.VV, w.varint())
			}
		case 3:
			key, d := decodeEntryField(w.lenField())
			r.Entries[key] = d
		case 4:
			key, d := decodeEntryField(w.lenField())
			if deleted == nil {
				deleted = map[string]dot{}
			}
			deleted[key] = d
		default:
			w.skip(tag & 7)
		}
	}
	return r, deleted
}

type mergeResponse struct {
	Merged        *replica
	MergedDeleted map[string]dot
	SortedValues  []string
	Canonical     string
	Err           string
}

func decodeMergeResponse(buf []byte) mergeResponse {
	w := wireReader{buf: buf}
	var resp mergeResponse
	for !w.done() {
		tag := w.varint()
		switch tag >> 3 {
		case 1:
			resp.Merged, resp.MergedDeleted = decodeReplica(w.lenField())
		case 2:
			resp.SortedValues = append(resp.SortedValues,
				string(w.lenField()))
		case 3:
			resp.Canonical = string(w.lenField())
		case 4:
			resp.Err = string(w.lenField())
		default:
			w.skip(tag & 7)
		}
	}
	return resp
}

// ---------------------------------------------------------------------------
// Framing + the remote Merge call.
// ---------------------------------------------------------------------------

func sendFrame(conn net.Conn, method byte, body []byte) {
	hdr := make([]byte, 5)
	hdr[0] = method
	binary.BigEndian.PutUint32(hdr[1:], uint32(len(body)))
	if _, err := conn.Write(append(hdr, body...)); err != nil {
		fatalf("send: %v", err)
	}
}

func recvFrame(conn net.Conn) (byte, []byte) {
	hdr := make([]byte, 5)
	if _, err := readFull(conn, hdr); err != nil {
		fatalf("recv header: %v", err)
	}
	body := make([]byte, binary.BigEndian.Uint32(hdr[1:]))
	if _, err := readFull(conn, body); err != nil {
		fatalf("recv body: %v", err)
	}
	return hdr[0], body
}

func readFull(conn net.Conn, buf []byte) (int, error) {
	total := 0
	for total < len(buf) {
		n, err := conn.Read(buf[total:])
		if err != nil {
			return total, err
		}
		total += n
	}
	return total, nil
}

// exchange ships one Merge request body and returns the decoded
// response, with the framing/method/error checks every merge shares.
// path labels failures so interleaved merge/deltaMerge scenarios stay
// localizable.
func exchange(conn net.Conn, path string, body []byte) mergeResponse {
	sendFrame(conn, methodMerge, body)
	method, reply := recvFrame(conn)
	if method != methodMerge {
		fatalf("unexpected reply method %#x (%s)", method, path)
	}
	resp := decodeMergeResponse(reply)
	if resp.Err != "" {
		fatalf("server %s error: %s", path, resp.Err)
	}
	return resp
}

// install replaces dst's state with the server's merged result and checks
// cross-language rendering parity: the server's canonical String
// (utils/codec.render_packed) must equal this client's Go rendering.
func install(dst *replica, path string, resp mergeResponse) {
	dst.VV = resp.Merged.VV
	dst.Entries = resp.Merged.Entries
	if got := dst.String(); got != resp.Canonical {
		fatalf("canonical mismatch (%s):\nserver: %q\nclient: %q",
			path, resp.Canonical, got)
	}
}

// merge performs dst.Merge(src) on the server: the framework's packed
// kernel computes the result, which replaces dst's state client-side.
func merge(conn net.Conn, dst, src *replica) {
	install(dst, "merge", exchange(conn, "merge",
		encodeMergeRequest(dst, src)))
}

// deltaMerge performs dst.Merge(src) with the δ dispatch
// (awset-delta_test.go:51-65) on the server: first contact takes the
// full-merge branch, later exchanges δ-extract + δ-apply — all computed by
// the framework's packed kernels, never by this client.
func deltaMerge(conn net.Conn, dst, src *deltaReplica) {
	resp := exchange(conn, "deltaMerge", encodeDeltaMergeRequest(dst, src))
	install(&dst.replica, "deltaMerge", resp)
	dst.Deleted = resp.MergedDeleted
}

// ---------------------------------------------------------------------------
// Scenario replay (awset_test.go:10-122).
// ---------------------------------------------------------------------------

var failures int

func fatalf(format string, args ...interface{}) {
	fmt.Fprintf(os.Stderr, "FATAL: "+format+"\n", args...)
	os.Exit(1)
}

func assertEntries(name string, r *replica, expected ...string) {
	sort.Strings(expected)
	got := r.sortedValues()
	ok := len(got) == len(expected)
	if ok {
		for i := range got {
			if got[i] != expected[i] {
				ok = false
				break
			}
		}
	}
	if !ok {
		failures++
		fmt.Fprintf(os.Stderr, "FAIL %s: expected %v, got %v\n",
			name, expected, got)
	}
}

// testAWSetXXX replays awset_test.go:10-29.
func testAWSetXXX(conn net.Conn) {
	A, B := newReplica(0, 2), newReplica(1, 2)
	A.add("A", "B", "C")
	B.add("A", "B", "C")
	merge(conn, A, B)
	merge(conn, B, A)
	assertEntries("XXX/A", A, "A", "B", "C")
	assertEntries("XXX/B", B, "A", "B", "C")

	A.del("B")
	B.add("B")
	merge(conn, B, A)
	merge(conn, A, B)
	assertEntries("XXX/A2", A, "A", "B", "C")
	assertEntries("XXX/B2", B, "A", "B", "C") // concurrent writer wins
}

// testAWSet replays awset_test.go:31-83.
func testAWSet(conn net.Conn) {
	A, B := newReplica(0, 2), newReplica(1, 2)
	assertEntries("AWSet/A-empty", A)
	assertEntries("AWSet/B-empty", B)

	A.add("Shelly")
	assertEntries("AWSet/A1", A, "Shelly")
	merge(conn, B, A)
	assertEntries("AWSet/B1", B, "Shelly")

	B.add("Bob", "Phil", "Pete")
	merge(conn, A, B)
	assertEntries("AWSet/A2", A, "Shelly", "Bob", "Phil", "Pete")

	A.del("Phil")
	A.add("Bob") // update
	A.add("Anna")
	merge(conn, B, A)
	assertEntries("AWSet/A3", A, "Shelly", "Bob", "Pete", "Anna")
	assertEntries("AWSet/B3", B, "Shelly", "Bob", "Pete", "Anna")

	A.del("Bob", "Pete")
	B.del("Bob", "Shelly")
	merge(conn, A, B)
	merge(conn, B, A)
	assertEntries("AWSet/A4", A, "Anna")
	assertEntries("AWSet/B4", B, "Anna")

	A.add("A", "B", "C")
	A.del("A")
	A.add("A")
	merge(conn, B, A)
	assertEntries("AWSet/A5", A, "Anna", "A", "B", "C")
	assertEntries("AWSet/B5", B, "Anna", "A", "B", "C")
}

// testConcurrentAddWins replays awset_test.go:85-122.
func testConcurrentAddWins(conn net.Conn) {
	A, B := newReplica(0, 2), newReplica(1, 2)
	A.add("Anne", "Bob")
	B.add("Anne")
	// fork state: concurrent add vs delete -> writer wins
	A2, B2 := A.clone(), B.clone()
	B2.add("Bob")
	A2.del("Bob")
	merge(conn, B2, A2)
	merge(conn, A2, B2)
	assertEntries("Conc/B-fork", B2, "Anne", "Bob")
	assertEntries("Conc/A-fork", A2, "Anne", "Bob")

	// merge before delete: non-concurrent delete sticks
	B.add("Bob")
	merge(conn, B, A)
	A.del("Bob")
	merge(conn, B, A)
	merge(conn, A, B)
	assertEntries("Conc/B-seq", B, "Anne")
	assertEntries("Conc/A-seq", A, "Anne")
}

// testAWSetDelta replays awset-delta_test.go:168-189 (T6): the first two
// merges take the full-merge branch (first contact), the last two take the
// δ extract/apply branch, with the empty-δ VV-skip quirk live server-side.
func testAWSetDelta(conn net.Conn) {
	A, B := newDeltaReplica(0, 2), newDeltaReplica(1, 2)
	A.add("A", "B")
	B.add("A", "C")
	deltaMerge(conn, A, B)
	deltaMerge(conn, B, A)
	assertEntries("Delta/A1", &A.replica, "A", "B", "C")
	assertEntries("Delta/B1", &B.replica, "A", "B", "C")

	A.del("B")
	A.add("D", "E")
	B.add("E")
	deltaMerge(conn, B, A)
	assertEntries("Delta/B2", &B.replica, "A", "C", "D", "E")

	deltaMerge(conn, A, B)
	assertEntries("Delta/A2", &A.replica, "A", "C", "D", "E")
}

func main() {
	addr := flag.String("addr", "127.0.0.1:7777",
		"MergerServer host:port (python -m go_crdt_playground_tpu serve)")
	flag.Parse()

	conn, err := net.Dial("tcp", *addr)
	if err != nil {
		fatalf("dial %s: %v", *addr, err)
	}
	defer conn.Close()

	sendFrame(conn, methodPing, nil)
	if m, _ := recvFrame(conn); m != methodPing {
		fatalf("ping not echoed (method %#x)", m)
	}

	testAWSetXXX(conn)
	testAWSet(conn)
	testConcurrentAddWins(conn)
	testAWSetDelta(conn)

	if failures > 0 {
		fmt.Fprintf(os.Stderr, "%d assertion(s) failed\n", failures)
		os.Exit(1)
	}
	fmt.Println("ok: T1-T3 + T6 replayed through the framework merge kernels")
}
