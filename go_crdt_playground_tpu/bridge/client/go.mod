module crdtbridge-client

go 1.21
