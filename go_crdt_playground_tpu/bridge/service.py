"""Merger bridge service: drive the packed merge kernels from outside.

SURVEY §7.3 step 1 keeps a Merger service so an external harness — the
natural endpoint is a Go port of the reference's own tests, which in the
reference call ``dst.Merge(src)`` directly (awset_test.go:16-17) — can
submit two replica states and get back this framework's merged result
plus the conformance oracles (SortedValues, canonical String).

Execution path is the REAL product path, not the spec model: proto ->
spec -> pack (utils/codec) -> packed kernel (ops/merge or ops/delta) ->
unpack -> proto.  The spec model is only used as the host-side
(de)serialization vehicle.

Transport (Go-friendly, zero dependencies beyond the stdlib):

    frame   = method(1 byte) | length(uint32 big-endian) | body
    request  body = crdtbridge.MergeRequest   (method 0x01)
    response body = crdtbridge.MergeResponse  (same method byte echoed)
    ping          = method 0x02, empty body, echoed empty

One TCP connection carries any number of frames.  When grpcio is
installed the same messages are served as proper gRPC instead
(``serve_grpc``); the proto file carries the service definition either
way.

A complete Go client lives at ``bridge/client/main.go``: it replays the
reference's T1-T3 test scenarios with every merge computed by this
server.  CI (which has no Go toolchain) exercises its exact byte stream
via tests/test_bridge_client.py.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional, Tuple

from go_crdt_playground_tpu.bridge import convert
from go_crdt_playground_tpu.bridge import merger_pb2 as pb

METHOD_MERGE = 0x01
METHOD_PING = 0x02

_MAX_BODY = 64 << 20


# ---------------------------------------------------------------------------
# Framing
# ---------------------------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, method: int, body: bytes) -> None:
    if len(body) > _MAX_BODY:
        raise ValueError(f"frame body {len(body)} exceeds {_MAX_BODY}")
    sock.sendall(struct.pack(">BI", method, len(body)) + body)


def recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    method, length = struct.unpack(">BI", _recv_exact(sock, 5))
    if length > _MAX_BODY:
        raise ValueError(f"frame body {length} exceeds {_MAX_BODY}")
    return method, _recv_exact(sock, length)


# ---------------------------------------------------------------------------
# Merge execution on the packed kernels
# ---------------------------------------------------------------------------


def _dimensions(*replicas) -> Tuple[int, int]:
    """(E, A) for packing a request's replica pair: E covers every key,
    A covers every VV slot and dot actor (zero-padding beyond is exact,
    crdt-misc.go:29-41)."""
    keys = set()
    num_actors = 1
    for rep in replicas:
        keys.update(rep.entries)
        num_actors = max(num_actors, len(rep.version_vector), rep.actor + 1)
        for d in rep.entries.values():
            num_actors = max(num_actors, d.actor + 1)
        deleted = getattr(rep, "deleted", None)
        if deleted:
            keys.update(deleted)
            for d in deleted.values():
                num_actors = max(num_actors, d.actor + 1)
        processed = getattr(rep, "processed", None)
        if processed:
            num_actors = max(num_actors, max(processed) + 1)
    return max(1, len(keys)), num_actors


def execute_merge(req: pb.MergeRequest) -> pb.MergeResponse:
    """Run one MergeRequest through the packed kernels."""
    from go_crdt_playground_tpu.models import awset as awset_mod
    from go_crdt_playground_tpu.models import awset_delta as delta_mod
    from go_crdt_playground_tpu.ops import delta as delta_ops
    from go_crdt_playground_tpu.ops import merge as merge_ops
    from go_crdt_playground_tpu.utils import codec

    try:
        convert.check_uint32(req.dst, "dst")
        convert.check_uint32(req.src, "src")
        semantics = req.delta_semantics or "reference"
        dst = convert.replica_from_proto(
            req.dst, req.delta, semantics, req.strict_reference_semantics)
        src = convert.replica_from_proto(
            req.src, req.delta, semantics, req.strict_reference_semantics)
        E, A = _dimensions(dst, src)
        dictionary = codec.ElementDict(capacity=E)
        if req.delta:
            arrays = codec.pack_awset_deltas([dst, src], dictionary, A)
            state = delta_mod.from_arrays(arrays)
            merged_state = delta_ops.delta_merge_one_into(
                state, 0, state, 1, semantics,
                req.strict_reference_semantics)
            merged = codec.unpack_awset_deltas(
                delta_mod.to_arrays(merged_state), dictionary, semantics)[0]
        else:
            arrays = codec.pack_awsets([dst, src], dictionary, A)
            state = awset_mod.from_arrays(arrays)
            merged_state, _ = merge_ops.merge_one_into(state, 0, state, 1)
            merged = codec.unpack_awsets(
                awset_mod.to_arrays(merged_state), dictionary)[0]
    except (OverflowError, ValueError) as exc:
        return pb.MergeResponse(error=str(exc))
    return pb.MergeResponse(
        merged=convert.replica_to_proto(merged),
        sorted_values=merged.sorted_values(),
        canonical=str(merged),
    )


# ---------------------------------------------------------------------------
# Plain-TCP server / client
# ---------------------------------------------------------------------------


class MergerServer:
    """Serve the Merger service over the Go-friendly TCP framing."""

    # Half-open clients must not pin threads forever (a partial frame
    # used to park recv_frame indefinitely), and connection threads are
    # capped so a misbehaving client can't grow one thread per dial.
    # Long-lived deployments whose clients legitimately idle past the
    # default should raise conn_timeout_s (or send periodic PING frames).
    CONN_TIMEOUT_S = 120.0
    MAX_CONNS = 64

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 conn_timeout_s: Optional[float] = None,
                 max_conns: Optional[int] = None):
        self.host = host
        self.port = port
        self.conn_timeout_s = (self.CONN_TIMEOUT_S if conn_timeout_s is None
                               else conn_timeout_s)
        self._sock: Optional[socket.socket] = None
        self._closing = threading.Event()
        self._conn_slots = threading.BoundedSemaphore(
            self.MAX_CONNS if max_conns is None else max_conns)

    def serve(self) -> Tuple[str, int]:
        """Bind + start accepting on a daemon thread; returns (host, port)."""
        self._sock = socket.create_server((self.host, self.port))
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self.host, self.port

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._closing.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            if not self._conn_slots.acquire(blocking=False):
                conn.close()  # at capacity: shed load instead of queueing
                continue
            # daemonic and unretained: connection threads die with their
            # socket, so a long-lived server doesn't accumulate objects
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            self._serve_conn(conn)
        finally:
            self._conn_slots.release()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(self.conn_timeout_s)
        with conn:
            while True:
                try:
                    method, body = recv_frame(conn)
                except (ConnectionError, OSError, ValueError):
                    # OSError includes socket.timeout (idle/half-open
                    # client); ValueError is an oversized frame length —
                    # all are shed quietly instead of killing the thread
                    return
                if method == METHOD_PING:
                    reply = (METHOD_PING, b"")
                elif method == METHOD_MERGE:
                    req = pb.MergeRequest()
                    try:
                        req.ParseFromString(body)
                        resp = execute_merge(req)
                    except Exception as exc:  # malformed proto, kernel error
                        resp = pb.MergeResponse(error=repr(exc))
                    reply = (METHOD_MERGE, resp.SerializeToString())
                else:
                    resp = pb.MergeResponse(error=f"unknown method {method}")
                    reply = (method, resp.SerializeToString())
                try:
                    send_frame(conn, *reply)
                except (ConnectionError, OSError):
                    # a client that stops reading (full TCP window) times
                    # out here too — drop it, don't kill the thread noisily
                    return

    def close(self) -> None:
        self._closing.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def __enter__(self) -> "MergerServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class MergerClient:
    """Python-side client for the TCP transport (tests and tooling; a Go
    harness implements the same five-byte header + proto body).

    ``backoff``: optional ``utils.backoff.BackoffPolicy`` — when given,
    the dial retries transient ``OSError`` failures on the shared
    jittered-exponential schedule (the same policy object the
    anti-entropy supervisor uses, so bridge tooling and the sync runtime
    degrade under one tunable law).  The default stays one-shot: an
    interactive client should fail fast unless its caller opted in."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 backoff=None, backoff_seed: int = 0):
        if backoff is None:
            self._sock = socket.create_connection((host, port),
                                                  timeout=timeout)
        else:
            from go_crdt_playground_tpu.utils.backoff import retry_call

            self._sock = retry_call(
                lambda: socket.create_connection((host, port),
                                                 timeout=timeout),
                backoff, retry_on=(OSError,), seed=backoff_seed)

    def ping(self) -> bool:
        send_frame(self._sock, METHOD_PING, b"")
        method, body = recv_frame(self._sock)
        return method == METHOD_PING and body == b""

    def merge_raw(self, req: pb.MergeRequest) -> pb.MergeResponse:
        send_frame(self._sock, METHOD_MERGE, req.SerializeToString())
        method, body = recv_frame(self._sock)
        resp = pb.MergeResponse()
        resp.ParseFromString(body)
        return resp

    def merge(self, dst, src, delta: bool = False,
              delta_semantics: str = "reference",
              strict_reference_semantics: bool = True):
        """Spec-model convenience: ship two spec replicas, return the
        merged spec replica (raises on service-reported errors)."""
        req = pb.MergeRequest(
            dst=convert.replica_to_proto(dst),
            src=convert.replica_to_proto(src),
            delta=delta,
            delta_semantics=delta_semantics,
            strict_reference_semantics=strict_reference_semantics,
        )
        resp = self.merge_raw(req)
        if resp.error:
            raise RuntimeError(f"merge service error: {resp.error}")
        return convert.replica_from_proto(
            resp.merged, delta, delta_semantics, strict_reference_semantics)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "MergerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# gRPC adapter (optional — grpcio is not in the base image)
# ---------------------------------------------------------------------------


def serve_grpc(host: str = "127.0.0.1", port: int = 0):
    """Serve the same Merger service as real gRPC when grpcio exists.

    Returns (server, port).  Raises ImportError with guidance otherwise —
    the TCP transport above is the always-available path.
    """
    try:
        import grpc  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "grpcio is not installed in this environment; use MergerServer "
            "(plain-TCP transport, same proto messages) or install grpcio "
            "to serve bridge/merger.proto as gRPC"
        ) from exc
    from concurrent import futures

    class _Servicer:
        def Merge(self, request, context):  # noqa: N802 (gRPC naming)
            return execute_merge(request)

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    # Generic handler keeps us independent of grpc codegen (only protoc's
    # message codegen is vendored).
    rpc = grpc.unary_unary_rpc_method_handler(
        lambda req, ctx: _Servicer().Merge(req, ctx),
        request_deserializer=pb.MergeRequest.FromString,
        response_serializer=pb.MergeResponse.SerializeToString,
    )
    service = grpc.method_handlers_generic_handler(
        "crdtbridge.Merger", {"Merge": rpc})
    server.add_generic_rpc_handlers((service,))
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound
