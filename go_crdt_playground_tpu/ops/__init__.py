"""Compute path: vmapped lattice-join kernels (JAX/XLA) and Pallas kernels."""
