"""Fused Pallas TPU kernels for the δ-AWSet gossip round (v2 semantics).

One δ exchange is extract → dispatch → apply (ops/delta.py): the sender
compresses against the receiver's VV (awset-delta_test.go:79-105), the
receiver takes the full-merge branch on first contact
(awset-delta_test.go:53-56) or the δ branch otherwise, absorbs deletion
records and joins the causal-stability vectors.  On the XLA path each of
those steps re-gathers HasDot with [R, E] indices, which lowers
pathologically inside compiled loops (see ops/pallas_merge.py regime
notes) — at R=100K a round costs over a second.  Fusing the whole
exchange into one kernel with the native lane-gather HasDot
(pallas_merge.gather_rows) brings it to HBM-bandwidth order.

Fusion also simplifies the algebra: extraction and application see the
SAME receiver VV, so phase-1's "take" mask collapses to the changed mask
(a changed lane is by construction not covered by the receiver's clock,
awset-delta_test.go:84-92 vs 126-147).

Two variants share one algebra body:

  * ``pallas_delta_gossip_round(state, perm)`` — arbitrary pairing;
    partner rows pre-gathered by XLA (one extra state copy in HBM).
  * ``pallas_delta_ring_round(state, offset)`` — ring pairing
    (r absorbs (r+offset) mod R, every production schedule here);
    partner rows are read IN PLACE via prefetch-driven block index maps
    (pallas_merge.ring_block_specs), so peak HBM is state + outputs.
    This is what lets the 1M-replica north star fit on one chip: with
    the gather path it needs state + gathered copy + outputs ~ 3 x
    6.5GB and OOMs a 16GB v5e.

Both δ semantics fuse: v2 (record-absorbing) and strict-reference.  The
strict empty-δ VV-skip quirk (awset-delta_test.go:60-64) needs one
cross-E reduction per pair; the kernels compute it as a per-element-
block emptiness bit accumulated in VMEM scratch across the grid's inner
(element) steps, finishing the per-row VV select at the last block
(_strict_vv_epilogue) — so reference-mode fleets no longer pay the ~40x
XLA HasDot path.  The XLA path (ops/delta.py) remains the conformance
reference these kernels are pinned against bitwise
(tests/test_pallas_delta.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState
from go_crdt_playground_tpu.ops.pallas_merge import (
    _BLOCK_R, _DOT_CMASK, _DOT_SHIFT, _RING_VMEM_LIMIT,
    _ring_round_dispatch, _ring_window, gather_rows, ring_block_specs,
    ring_meta, ring_supported, row_block_layout)

_A_NAMED = ("vv", "processed")
_E_NAMED = ("present", "dot_actor", "dot_counter", "deleted",
            "del_dot_actor", "del_dot_counter")
# dot-word layout (pallas_merge._DOT_SHIFT): each dot pair rides as one
# uint32 word, so the δ ring's six E-shaped operands become four — two
# bitpacked membership word arrays + two dot-word arrays
_E_NAMED_DOTS = ("present", "dots", "deleted", "del_dots")


def _delta_algebra(dst, src, s_actor, mode: str = "v2"):
    """The fused δ exchange on value tuples.

    dst/src: dicts of [blk_r, A]- and [blk_r, blk_e]-shaped values
    (present/deleted as uint8); s_actor: uint32[blk_r, 1] — the sender's
    actor id per row.

    mode selects the δ semantics (static):
      * "v2"              — record-absorbing semantics (ops/delta.py v2);
      * "reference"       — strict reference semantics incl. the empty-δ
                            VV-skip quirk (awset-delta_test.go:60-64):
                            the vv output is a PLACEHOLDER (dst's vv) and
                            extras carry what the kernel epilogue needs
                            to finish the per-row select after the
                            cross-E emptiness reduction accumulates over
                            every element block;
      * "reference_loose" — reference arbitration with an unconditional
                            VV join (strict_reference_semantics=False).

    Returns (outs, extras): outs = the 8 output arrays in state order;
    extras = (first_contact, joined_vv, nonempty_i32[blk_r, 1]) for
    "reference", None otherwise.
    """
    dvv, svv = dst["vv"], src["vv"]
    dproc, sproc = dst["processed"], src["processed"]
    dp, sp = dst["present"] != 0, src["present"] != 0
    dda, sda = dst["dot_actor"], src["dot_actor"]
    ddc, sdc = dst["dot_counter"], src["dot_counter"]
    dd, sd = dst["deleted"] != 0, src["deleted"] != 0
    ddda, sdda = dst["del_dot_actor"], src["del_dot_actor"]
    dddc, sddc = dst["del_dot_counter"], src["del_dot_counter"]

    as_i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)  # noqa: E731
    aonehot = (jax.lax.broadcasted_iota(jnp.uint32, dvv.shape, 1)
               == jnp.broadcast_to(s_actor, dvv.shape))

    # first contact: receiver's counter for the sender's actor is zero
    # (awset-delta_test.go:53).  Single-term masked sum, bit-exact via
    # the int32 view (Mosaic has no unsigned reductions).
    sender_cnt = jnp.sum(
        jnp.where(aonehot, as_i32(dvv), jnp.zeros_like(as_i32(dvv))),
        axis=1, keepdims=True)
    fc = sender_cnt == 0                             # bool[blk_r, 1]

    # shared HasDot gathers
    seen_s_by_d = sdc <= gather_rows(dvv, sda)       # receiver covers src dot
    seen_d_by_s = ddc <= gather_rows(svv, dda)       # sender covers dst dot

    # ---- FULL branch (first contact; ops/delta.full_merge_delta) ----
    take_f = sp & (dp | ~seen_s_by_d)
    present_f = take_f | (dp & ~sp & ~seen_d_by_s)
    da_f = jnp.where(present_f, jnp.where(take_f, sda, dda), 0)
    dc_f = jnp.where(present_f, jnp.where(take_f, sdc, ddc), 0)

    # ---- δ branch phase 1 (ops/delta.delta_extract, fused) ----
    changed = sp & ~seen_s_by_d                      # :84-92
    resurrected = sp & ((sda != sdda) | (sdc > sddc))  # :94-97
    deleted_p = sd & ~resurrected
    present1 = dp | changed                          # p1_take == changed
    da1 = jnp.where(changed, sda, dda)
    dc1 = jnp.where(changed, sdc, ddc)
    joined_vv = jnp.where(dvv < svv, svv, dvv)

    if mode == "v2":
        # deletion-record absorb is a (counter, actor) lexicographic
        # JOIN (ops/delta._delta_apply_impl) — the actor tie-break
        # keeps equal-counter records from different actors order-free,
        # which the digest regime needs for bitwise lane convergence
        rec_newer = (sddc > dddc) | ((sddc == dddc) & (sdda > ddda))
        rec_f = sd & (~dd | rec_newer)
        deleted_f = dd | sd
        del_da_f = jnp.where(rec_f, sdda, ddda)
        del_dc_f = jnp.where(rec_f, sddc, dddc)
        # v2 arbitration: remove iff the SENDER's clock covers our live
        # dot.  The gather runs on the post-phase-1 dots — do NOT
        # shortcut changed lanes as "trivially covered by the sender's
        # clock": the compact-overflow path ships partial data with no
        # clock advance (ops/compact.py), breaking the VV-covers-own-
        # dots invariant that shortcut needs, and there it would remove
        # entries the spec keeps (r4 review repro).
        remove = deleted_p & present1 & (dc1 <= gather_rows(svv, da1))
        present_d = present1 & ~remove
        da_d = jnp.where(present_d, da1, 0)
        dc_d = jnp.where(present_d, dc1, 0)
        rec_d = deleted_p & (~dd | rec_newer)
        deleted_d = dd | deleted_p
        del_da_d = jnp.where(rec_d, sdda, ddda)
        del_dc_d = jnp.where(rec_d, sddc, dddc)

        # ---- select per row; A-shaped outputs are branch-independent ----
        # (select between i1 vectors doesn't lower on Mosaic —
        # "Unsupported target bitwidth for truncation" — so widen the
        # operands first)
        out_p = jnp.where(fc, present_f.astype(jnp.uint8),
                          present_d.astype(jnp.uint8))
        out_da = jnp.where(fc, da_f, da_d)
        out_dc = jnp.where(fc, dc_f, dc_d)
        out_d = jnp.where(fc, deleted_f.astype(jnp.uint8),
                          deleted_d.astype(jnp.uint8))
        out_dda = jnp.where(fc, del_da_f, del_da_d)
        out_ddc = jnp.where(fc, del_dc_f, del_dc_d)
        proc = jnp.where(dproc < sproc, sproc, dproc)
        # the sender's own slot advances to its clock (spec _join_processed)
        out_proc = jnp.where(aonehot & (proc < svv), svv, proc)
        return (joined_vv, out_proc, out_p, out_da, out_dc, out_d,
                out_dda, out_ddc), None

    # ---- reference arbitration (awset-delta_test.go:153-158): keep iff
    # OUR clock covers the DELETION dot; deletion log / del dots /
    # processed are never touched by a reference-mode receive
    # (deltaMerge writes only Entries + VV, :126-165) ----
    remove = deleted_p & present1 & ~(sddc <= gather_rows(dvv, sdda))
    present_d = present1 & ~remove
    da_d = jnp.where(present_d, da1, 0)
    dc_d = jnp.where(present_d, dc1, 0)
    out_p = jnp.where(fc, present_f.astype(jnp.uint8),
                      present_d.astype(jnp.uint8))
    out_da = jnp.where(fc, da_f, da_d)
    out_dc = jnp.where(fc, dc_f, dc_d)
    out_d = dst["deleted"]
    if mode == "reference_loose":
        return (joined_vv, dproc, out_p, out_da, out_dc, out_d, ddda,
                dddc), None
    # strict: the empty-δ quirk needs ALL element blocks' payload masks;
    # emit this block's per-row emptiness bit and dst's vv as a
    # placeholder — the kernel epilogue accumulates the bits across the
    # grid's j steps and finishes the select (fc rows take the full-merge
    # branch, whose VV join is unconditional, awset-delta_test.go:55)
    nonempty = jnp.max((changed | deleted_p).astype(jnp.int32), axis=1,
                       keepdims=True)
    return (dvv, dproc, out_p, out_da, out_dc, out_d, ddda, dddc), (
        fc, joined_vv, nonempty)


def _strict_vv_epilogue(ovv_ref, dvv, extras, scratch_ref):
    """Finish the strict-reference VV select: accumulate this block's
    per-row payload-emptiness bit across the grid's (inner) element
    steps in VMEM scratch, and at the LAST element block write the
    final per-row choice — joined VV for first-contact or nonempty-δ
    rows, dst's VV otherwise (the empty-δ quirk,
    awset-delta_test.go:60-64).  The A-shaped vv output block's index
    map ignores j, so the block stays resident across the row's element
    steps and the last write is the one flushed to HBM."""
    fc, joined_vv, nonempty = extras
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _reset():
        scratch_ref[...] = jnp.zeros_like(scratch_ref)

    scratch_ref[...] = jnp.maximum(
        scratch_ref[...], jnp.broadcast_to(nonempty, scratch_ref.shape))

    @pl.when(j == n_j - 1)
    def _finish():
        seen_any = jnp.max(scratch_ref[...], axis=1, keepdims=True) != 0
        ovv_ref[...] = jnp.where(fc | seen_any, joined_vv, dvv)


def _make_delta_kernel(mode: str):
    """General-perm kernel: partner rows pre-gathered, dst-aligned.
    Strict-reference mode threads a [_BLOCK_R, _LANE] i32 VMEM scratch
    (last positional ref) for the cross-E emptiness accumulation."""
    def kernel(sact_ref, *refs):
        if mode == "reference":
            *refs, scratch_ref = refs
        in_refs, out_refs = refs[:16], refs[16:]
        names = [n for name in _A_NAMED + _E_NAMED for n in (name, name)]
        dst = {n: r[...] for n, r in zip(names[0::2], in_refs[0::2])}
        src = {n: r[...] for n, r in zip(names[1::2], in_refs[1::2])}
        outs, extras = _delta_algebra(dst, src, sact_ref[...], mode)
        for ref, val in zip(out_refs, outs):
            ref[...] = val
        if mode == "reference":
            _strict_vv_epilogue(out_refs[0], dst["vv"], extras,
                                scratch_ref)

    return kernel


def _out_shapes(num_r, a_pad, e_pad):
    u32, u8 = jnp.uint32, jnp.uint8
    dts = [u32, u32, u8, u32, u32, u8, u32, u32]
    widths = [a_pad, a_pad] + [e_pad] * 6
    return [jax.ShapeDtypeStruct((num_r, w), d)
            for w, d in zip(widths, dts)]


@functools.partial(jax.jit,
                   static_argnames=("block_e", "interpret", "mode"))
def _fused_delta_round(arrays, perm, block_e: int, interpret: bool,
                       mode: str = "v2"):
    """arrays: the 9 AWSetDeltaState fields as a dict of 2D device
    arrays (present/deleted as uint8)."""
    num_r, num_e = arrays["present"].shape
    num_a = arrays["vv"].shape[1]
    r_pad, e_pad, a_pad, blk = row_block_layout(num_r, num_e, num_a,
                                                block_e)

    def pad(x, last):
        return jnp.pad(x, ((0, r_pad - num_r), (0, last - x.shape[1])))

    perm = perm.astype(jnp.int32)
    s_actor = pad(arrays["actor"][perm].astype(jnp.uint32)[:, None], 1)

    dst, src = {}, {}
    for name in _A_NAMED + _E_NAMED:
        x = arrays[name]
        last = a_pad if name in _A_NAMED else e_pad
        dst[name] = pad(x, last)
        src[name] = pad(x[perm], last)

    grid = (r_pad // _BLOCK_R, e_pad // blk)
    a_blk = pl.BlockSpec((_BLOCK_R, a_pad), lambda i, j: (i, 0))
    e_blk = pl.BlockSpec((_BLOCK_R, blk), lambda i, j: (i, j))
    s_blk = pl.BlockSpec((_BLOCK_R, 1), lambda i, j: (i, 0))

    ins, in_specs = [s_actor], [s_blk]
    for name in _A_NAMED + _E_NAMED:
        ins += [dst[name], src[name]]
        in_specs += [a_blk, a_blk] if name in _A_NAMED else [e_blk, e_blk]

    scratch_shapes = ([pltpu.VMEM((_BLOCK_R, 128), jnp.int32)]
                      if mode == "reference" else [])
    outs = pl.pallas_call(
        _make_delta_kernel(mode),
        grid=grid,
        in_specs=in_specs,
        out_specs=[a_blk, a_blk, e_blk, e_blk, e_blk, e_blk, e_blk, e_blk],
        out_shape=_out_shapes(r_pad, a_pad, e_pad),
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(*ins)
    vv, proc, p, da, dc, d, dda, ddc = outs
    return (vv[:num_r, :num_a], proc[:num_r, :num_a], p[:num_r, :num_e],
            da[:num_r, :num_e], dc[:num_r, :num_e], d[:num_r, :num_e],
            dda[:num_r, :num_e], ddc[:num_r, :num_e])


_PACKED_NAMES = ("present", "deleted")


def _make_delta_ring_kernel(interpret: bool, packed_w: int = 0,
                            mode: str = "v2", aligned: bool = False,
                            dot_packed: bool = False):
    """packed_w > 0: ``present``/``deleted`` operands/outputs are
    bitpacked uint32[blk_r, packed_w]; unpack after windowing, repack
    before writing (pallas_merge bit helpers).  aligned: single-src-
    block form — one partner block per array instead of the lo/hi
    window pair, halving partner-read HBM traffic; valid only when
    offset % _BLOCK_R == 0 (callers dispatch via _ring_round_dispatch).
    mode="reference" threads the strict-quirk scratch (last ref).
    dot_packed: the two dot pairs ride as single uint32 words
    (pallas_merge dot-word layout), unpacked with shift/mask in VMEM;
    requires packed_w (the layout always bitpacks membership)."""
    from go_crdt_playground_tpu.ops.pallas_merge import (
        _kernel_pack_bits, _kernel_unpack_bits)

    assert packed_w or not dot_packed
    group = 2 if aligned else 3
    names = _A_NAMED + (_E_NAMED_DOTS if dot_packed else _E_NAMED)

    def kernel(meta_ref, sact_ref, *refs):
        scratch_ref = None
        if mode == "reference":
            *refs, scratch_ref = refs
        win = functools.partial(_ring_window, o_mod=meta_ref[1],
                                interpret=interpret)
        blk_e = refs[group * 3].shape[-1]   # the dot(s) dst block
        dst, src = {}, {}
        for k, name in enumerate(names):
            g = refs[group * k: group * k + group]
            d = g[0][...]
            s = g[1][...] if aligned else win(g[1][...], g[2][...])
            if packed_w and name in _PACKED_NAMES:
                d = _kernel_unpack_bits(d, blk_e).astype(jnp.uint8)
                s = _kernel_unpack_bits(s, blk_e).astype(jnp.uint8)
            dst[name] = d
            src[name] = s
        if dot_packed:
            cmask = jnp.uint32(_DOT_CMASK)
            for side in (dst, src):
                for pre, wname in (("", "dots"), ("del_", "del_dots")):
                    w = side.pop(wname)
                    side[pre + "dot_actor"] = w >> _DOT_SHIFT
                    side[pre + "dot_counter"] = w & cmask
        out_refs = refs[group * len(names):]
        outs, extras = _delta_algebra(dst, src, sact_ref[...], mode)
        if dot_packed:
            vvo, proco, p, da, dc, d, dda, ddc = outs
            outs = (vvo, proco, _kernel_pack_bits(p, packed_w),
                    (da << _DOT_SHIFT) | dc,
                    _kernel_pack_bits(d, packed_w),
                    (dda << _DOT_SHIFT) | ddc)
            for ref, val in zip(out_refs, outs):
                ref[...] = val
        else:
            for ref, name, val in zip(out_refs, names, outs):
                if packed_w and name in _PACKED_NAMES:
                    val = _kernel_pack_bits(val, packed_w)
                ref[...] = val
        if mode == "reference":
            _strict_vv_epilogue(out_refs[0], dst["vv"], extras,
                                scratch_ref)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("block_e", "interpret", "packed_w",
                                    "mode", "aligned", "dot_packed"))
def _fused_delta_ring(arrays, offset, block_e: int, interpret: bool,
                      packed_w: int = 0, mode: str = "v2",
                      aligned: bool = False, dot_packed: bool = False):
    """packed_w > 0: arrays["present"]/["deleted"] are bitpacked
    uint32[R, packed_w] (models.packed layout); the element grid tiles
    in 4096-element chunks (= one lane group of words each,
    pallas_merge._packed_tiling), so each j step unpacks/repacks one
    word group — E is bounded by HBM, not by the gather lane width.
    aligned=True is the single-src-block form, correct ONLY when
    offset % _BLOCK_R == 0 (callers dispatch via _ring_round_dispatch)."""
    from go_crdt_playground_tpu.ops.pallas_merge import _packed_tiling

    names = _A_NAMED + (_E_NAMED_DOTS if dot_packed else _E_NAMED)
    num_r, num_e = arrays[names[3]].shape
    num_a = arrays["vv"].shape[1]
    r_pad, e_pad, a_pad, blk = row_block_layout(num_r, num_e, num_a,
                                                block_e)
    assert r_pad == num_r, "callers must check ring_supported()"
    w_blk = total_w = packed_w
    if packed_w:
        blk, e_pad, w_blk, total_w = _packed_tiling(e_pad, packed_w)
    nb = num_r // _BLOCK_R
    group = 2 if aligned else 3

    offset = offset % num_r
    # the sender-actor column is dst-aligned and tiny ([R, 1]): compute
    # it with a plain XLA roll instead of threading it through the
    # window machinery
    s_actor = jnp.roll(arrays["actor"].astype(jnp.uint32),
                       -offset)[:, None]
    meta = ring_meta(offset, num_r)

    def pad(x, last):
        return jnp.pad(x, ((0, 0), (0, last - x.shape[1])))

    in_specs, out_specs = ring_block_specs(
        nb, blk, a_pad, a_named=len(_A_NAMED),
        e_named=len(names) - len(_A_NAMED), aligned=aligned)
    b_blk = lambda m: pl.BlockSpec((_BLOCK_R, w_blk), m)  # noqa: E731
    # bits blocks advance with the element grid step: word block j of a
    # row serves element block j, so the index maps must be the E-style
    # (i, j) ones, NOT the A-style (i, 0) ones (word tiling made the
    # packed grid multi-j)
    e0 = group * len(_A_NAMED)
    src_maps = [in_specs[e0 + g].index_map for g in range(group)]
    ins = [s_actor]
    for k, name in enumerate(names):
        if packed_w and name in _PACKED_NAMES:
            x = pad(arrays[name], total_w)
            in_specs[group * k: group * k + group] = [
                b_blk(m) for m in src_maps]
            out_specs[k] = b_blk(src_maps[0])
        else:
            x = pad(arrays[name], a_pad if name in _A_NAMED else e_pad)
        ins += [x] * group

    if dot_packed:
        u32 = jnp.uint32
        out_shape = [
            jax.ShapeDtypeStruct((num_r, a_pad), u32),
            jax.ShapeDtypeStruct((num_r, a_pad), u32),
            jax.ShapeDtypeStruct((num_r, total_w), u32),
            jax.ShapeDtypeStruct((num_r, e_pad), u32),
            jax.ShapeDtypeStruct((num_r, total_w), u32),
            jax.ShapeDtypeStruct((num_r, e_pad), u32),
        ]
    else:
        out_shape = _out_shapes(num_r, a_pad, e_pad)
        if packed_w:
            for k, name in enumerate(names):
                if name in _PACKED_NAMES:
                    out_shape[k] = jax.ShapeDtypeStruct((num_r, total_w),
                                                        jnp.uint32)
    s_blk = pl.BlockSpec((_BLOCK_R, 1), lambda i, j, meta: (i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, e_pad // blk),
        in_specs=[s_blk] + in_specs,
        out_specs=out_specs,
        scratch_shapes=([pltpu.VMEM((_BLOCK_R, 128), jnp.int32)]
                        if mode == "reference" else []),
    )
    outs = pl.pallas_call(
        _make_delta_ring_kernel(interpret, w_blk, mode, aligned,
                                dot_packed),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=_RING_VMEM_LIMIT,
    )(meta, *ins)
    if dot_packed:
        vv, proc, pb, dots, db, del_dots = outs
        return (vv[:, :num_a], proc[:, :num_a], pb[:, :packed_w],
                dots[:, :num_e], db[:, :packed_w], del_dots[:, :num_e])
    vv, proc, p, da, dc, d, dda, ddc = outs
    trim_p = ((lambda x: x[:, :packed_w]) if packed_w
              else (lambda x: x[:, :num_e]))
    return (vv[:, :num_a], proc[:, :num_a], trim_p(p), da[:, :num_e],
            dc[:, :num_e], trim_p(d), dda[:, :num_e], ddc[:, :num_e])


def _state_as_arrays(state: AWSetDeltaState):
    return {
        name: (getattr(state, name).astype(jnp.uint8)
               if getattr(state, name).dtype == jnp.bool_
               else getattr(state, name))
        for name in state._fields
    }


def _rebuild(state, vv, proc, p, da, dc, d, dda, ddc):
    return AWSetDeltaState(
        vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
        actor=state.actor, deleted=d != 0, del_dot_actor=dda,
        del_dot_counter=ddc, processed=proc,
    )


def _kernel_mode(delta_semantics: str,
                 strict_reference_semantics: bool) -> str:
    if delta_semantics == "v2":
        return "v2"
    if delta_semantics == "reference":
        return ("reference" if strict_reference_semantics
                else "reference_loose")
    raise ValueError(f"unknown delta_semantics {delta_semantics!r}")


def pallas_delta_gossip_round(state: AWSetDeltaState, perm, *,
                              delta_semantics: str = "v2",
                              strict_reference_semantics: bool = True,
                              block_e: int = 512,
                              interpret: bool | None = None
                              ) -> AWSetDeltaState:
    """One fused δ anti-entropy round: drop-in bitwise equivalent of
    ``parallel.gossip.delta_gossip_round(state, perm, ...)`` (the
    production TPU path — that function dispatches here on TPU
    backends).  Reference semantics fuse the empty-δ VV-skip quirk as a
    cross-E reduction accumulated across element blocks (see
    _strict_vv_epilogue) — reference-mode fleets no longer pay the ~40x
    XLA HasDot path."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    outs = _fused_delta_round(
        _state_as_arrays(state), jnp.asarray(perm), block_e, interpret,
        _kernel_mode(delta_semantics, strict_reference_semantics))
    return _rebuild(state, *outs)


def pallas_delta_ring_round(state: AWSetDeltaState, offset, *,
                            delta_semantics: str = "v2",
                            strict_reference_semantics: bool = True,
                            block_e: int = 512,
                            interpret: bool | None = None
                            ) -> AWSetDeltaState:
    """One fused δ ring round against partner (r + offset) mod R with
    partner rows read in place — no materialized ``state[perm]`` copy
    (peak HBM = state + outputs; the 1M-replica north-star enabler).
    Block-aligned offsets take the single-src-block form (half the
    partner-read traffic); ``offset`` may be traced: one compiled
    program serves a whole dissemination schedule, both variants inside
    it via lax.cond.  Bitwise-equal to
    ``pallas_delta_gossip_round(state, ring_perm(R, offset), ...)``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    mode = _kernel_mode(delta_semantics, strict_reference_semantics)
    if not ring_supported(state.present.shape[0]):
        from go_crdt_playground_tpu.parallel.gossip import ring_perm

        return pallas_delta_gossip_round(
            state, ring_perm(state.present.shape[0], offset),
            delta_semantics=delta_semantics,
            strict_reference_semantics=strict_reference_semantics,
            block_e=block_e, interpret=interpret)
    outs = _ring_round_dispatch(
        _state_as_arrays(state), offset,
        lambda a, o, al: _fused_delta_ring(a, o, block_e, interpret,
                                           mode=mode, aligned=al))
    return _rebuild(state, *outs)


def pallas_delta_ring_round_packed(state, offset, *,
                                   delta_semantics: str = "v2",
                                   strict_reference_semantics:
                                   bool = True,
                                   interpret: bool | None = None):
    """One fused δ ring round on the BITPACKED layout
    (models.packed.PackedAWSetDeltaState): ``present``/``deleted``
    cross HBM as uint32[R, E/32] — 8x less traffic and footprint for
    the two membership arrays (at the north-star fleet that is ~0.5GB
    of state and ~1GB of peak HBM).  All three δ semantics modes, like
    the bool and dot-word wrappers.  Bitwise-equal through pack/unpack
    to pallas_delta_ring_round; pinned by tests/test_packed.py."""
    from go_crdt_playground_tpu.models.packed import PackedAWSetDeltaState

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    mode = _kernel_mode(delta_semantics, strict_reference_semantics)
    if not ring_supported(state.present_bits.shape[0]):
        raise ValueError("packed ring kernel needs ring_supported(R); "
                         "unpack and use the bool-layout paths instead")
    arrays = {
        "vv": state.vv, "processed": state.processed,
        "present": state.present_bits, "dot_actor": state.dot_actor,
        "dot_counter": state.dot_counter, "deleted": state.deleted_bits,
        "del_dot_actor": state.del_dot_actor,
        "del_dot_counter": state.del_dot_counter, "actor": state.actor,
    }
    w = state.present_bits.shape[1]
    vv, proc, pb, da, dc, db, dda, ddc = _ring_round_dispatch(
        arrays, offset,
        lambda a, o, al: _fused_delta_ring(a, o, 512, interpret,
                                           packed_w=w, mode=mode,
                                           aligned=al))
    return PackedAWSetDeltaState(
        vv=vv, present_bits=pb, dot_actor=da, dot_counter=dc,
        actor=state.actor, deleted_bits=db, del_dot_actor=dda,
        del_dot_counter=ddc, processed=proc)


def pallas_delta_ring_round_dotpacked(state, offset, *,
                                      delta_semantics: str = "v2",
                                      strict_reference_semantics:
                                      bool = True,
                                      interpret: bool | None = None):
    """One fused δ ring round on the DOT-WORD layout
    (models.packed.DotPackedAWSetDeltaState): membership bitpacked AND
    both dot pairs fused to one uint32 word each, so the round streams
    two E-shaped arrays where the bool layout streams four uint32
    arrays plus two byte masks (~4.2KB vs ~6.7KB per row at A=E=256 —
    the north-star schedule's dominant traffic).  All three δ
    semantics modes (the strict empty-δ quirk's scratch epilogue is
    layout-independent); bitwise-equal through pack/unpack to
    pallas_delta_ring_round, pinned by tests/test_packed.py."""
    from go_crdt_playground_tpu.models.packed import (
        DotPackedAWSetDeltaState)

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    mode = _kernel_mode(delta_semantics, strict_reference_semantics)
    if not ring_supported(state.present_bits.shape[0]):
        raise ValueError("dot-packed ring kernel needs "
                         "ring_supported(R); unpack and use the "
                         "bool-layout paths instead")
    arrays = {
        "vv": state.vv, "processed": state.processed,
        "present": state.present_bits, "dots": state.dots,
        "deleted": state.deleted_bits, "del_dots": state.del_dots,
        "actor": state.actor,
    }
    w = state.present_bits.shape[1]
    vv, proc, pb, dots, db, del_dots = _ring_round_dispatch(
        arrays, offset,
        lambda a, o, al: _fused_delta_ring(a, o, 512, interpret,
                                           packed_w=w, mode=mode,
                                           aligned=al, dot_packed=True))
    return DotPackedAWSetDeltaState(
        vv=vv, present_bits=pb, dots=dots, actor=state.actor,
        deleted_bits=db, del_dots=del_dots, processed=proc)
