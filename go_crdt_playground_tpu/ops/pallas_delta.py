"""Fused Pallas TPU kernel for the δ-AWSet gossip round (v2 semantics).

One δ exchange is extract → dispatch → apply (ops/delta.py): the sender
compresses against the receiver's VV (awset-delta_test.go:79-105), the
receiver takes the full-merge branch on first contact
(awset-delta_test.go:53-56) or the δ branch otherwise, absorbs deletion
records and joins the causal-stability vectors.  On the XLA path each of
those steps re-gathers HasDot with [R, E] indices, which lowers
pathologically inside compiled loops (see ops/pallas_merge.py regime
notes) — at R=100K a round costs over a second.  Fusing the whole
exchange into one kernel with the block-diagonal MXU gather
(pallas_merge.gather_rows) brings it to HBM-bandwidth order.

Fusion also simplifies the algebra: extraction and application see the
SAME receiver VV, so phase-1's "take" mask collapses to the changed mask
(a changed lane is by construction not covered by the receiver's clock,
awset-delta_test.go:84-92 vs 126-147).

v2 δ semantics only — the strict-reference quirk path (empty-δ VV skip,
awset-delta_test.go:60-64) needs a cross-E reduction per pair and stays
on the XLA path, which is also the conformance reference this kernel is
pinned against bitwise (tests/test_pallas_delta.py).

Layout contract mirrors pallas_merge._fused_rows: 8 replica rows per
grid step, partner rows pre-gathered by XLA at HBM bandwidth, E in
lane-multiple tiles, A padded to a lane multiple (zero slots are "never
seen", crdt-misc.go:29-41).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState
from go_crdt_playground_tpu.ops.pallas_merge import (_BLOCK_R, gather_rows,
                                                     row_block_layout)


def _delta_kernel(dvv_ref, svv_ref, dpr_ref, spr_ref, ah_ref,
                  dp_ref, sp_ref, dda_ref, sda_ref, ddc_ref, sdc_ref,
                  dd_ref, sd_ref, ddda_ref, sdda_ref, dddc_ref, sddc_ref,
                  ovv_ref, opr_ref, op_ref, oda_ref, odc_ref,
                  od_ref, odda_ref, oddc_ref):
    dvv, svv = dvv_ref[...], svv_ref[...]            # uint32[8, A]
    dproc, sproc = dpr_ref[...], spr_ref[...]        # uint32[8, A]
    aonehot = ah_ref[...] != 0                       # bool[8, A]: sender slot
    dp, sp = dp_ref[...] != 0, sp_ref[...] != 0      # bool[8, blk]
    dda, sda = dda_ref[...], sda_ref[...]
    ddc, sdc = ddc_ref[...], sdc_ref[...]
    dd, sd = dd_ref[...] != 0, sd_ref[...] != 0      # deletion logs
    ddda, sdda = ddda_ref[...], sdda_ref[...]        # deletion dots
    dddc, sddc = dddc_ref[...], sddc_ref[...]

    as_i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)  # noqa: E731

    # first contact: receiver's counter for the sender's actor is zero
    # (awset-delta_test.go:53).  Single-term masked sum, bit-exact via
    # the int32 view (Mosaic has no unsigned reductions).
    sender_cnt = jnp.sum(
        jnp.where(aonehot, as_i32(dvv), jnp.zeros_like(as_i32(dvv))),
        axis=1, keepdims=True)
    fc = sender_cnt == 0                             # bool[8, 1]

    # shared HasDot gathers
    seen_s_by_d = sdc <= gather_rows(dvv, sda)       # receiver covers src dot
    seen_d_by_s = ddc <= gather_rows(svv, dda)       # sender covers dst dot

    # ---- FULL branch (first contact; ops/delta.full_merge_delta v2) ----
    take_f = sp & (dp | ~seen_s_by_d)
    present_f = take_f | (dp & ~sp & ~seen_d_by_s)
    da_f = jnp.where(present_f, jnp.where(take_f, sda, dda), 0)
    dc_f = jnp.where(present_f, jnp.where(take_f, sdc, ddc), 0)
    rec_f = sd & (~dd | (sddc > dddc))
    deleted_f = dd | sd
    del_da_f = jnp.where(rec_f, sdda, ddda)
    del_dc_f = jnp.where(rec_f, sddc, dddc)

    # ---- δ branch (ops/delta.delta_extract + delta_apply, fused) ----
    changed = sp & ~seen_s_by_d                      # :84-92
    resurrected = sp & ((sda != sdda) | (sdc > sddc))  # :94-97
    deleted_p = sd & ~resurrected
    present1 = dp | changed                          # p1_take == changed
    da1 = jnp.where(changed, sda, dda)
    dc1 = jnp.where(changed, sdc, ddc)
    # v2 arbitration: remove iff the SENDER's clock covers our live dot
    remove = deleted_p & present1 & (dc1 <= gather_rows(svv, da1))
    present_d = present1 & ~remove
    da_d = jnp.where(present_d, da1, 0)
    dc_d = jnp.where(present_d, dc1, 0)
    rec_d = deleted_p & (~dd | (sddc > dddc))
    deleted_d = dd | deleted_p
    del_da_d = jnp.where(rec_d, sdda, ddda)
    del_dc_d = jnp.where(rec_d, sddc, dddc)

    # ---- select per row; A-shaped outputs are branch-independent ----
    # (select between i1 vectors doesn't lower on Mosaic — "Unsupported
    # target bitwidth for truncation" — so widen the operands first)
    op_ref[...] = jnp.where(fc, present_f.astype(jnp.uint8),
                            present_d.astype(jnp.uint8))
    oda_ref[...] = jnp.where(fc, da_f, da_d)
    odc_ref[...] = jnp.where(fc, dc_f, dc_d)
    od_ref[...] = jnp.where(fc, deleted_f.astype(jnp.uint8),
                            deleted_d.astype(jnp.uint8))
    odda_ref[...] = jnp.where(fc, del_da_f, del_da_d)
    oddc_ref[...] = jnp.where(fc, del_dc_f, del_dc_d)
    ovv_ref[...] = jnp.where(dvv < svv, svv, dvv)
    proc = jnp.where(dproc < sproc, sproc, dproc)
    # the sender's own slot advances to its clock (spec _join_processed)
    opr_ref[...] = jnp.where(aonehot & (proc < svv), svv, proc)


@functools.partial(jax.jit, static_argnames=("block_e", "interpret"))
def _fused_delta_round(arrays, perm, block_e: int, interpret: bool):
    """arrays: the 9 AWSetDeltaState fields as a dict of padded 2D
    device arrays (present/deleted as uint8)."""
    num_r, num_e = arrays["present"].shape
    num_a = arrays["vv"].shape[1]
    r_pad, e_pad, a_pad, blk = row_block_layout(num_r, num_e, num_a,
                                                block_e)

    def pad(x, last):
        return jnp.pad(x, ((0, r_pad - num_r), (0, last - x.shape[1])))

    perm = perm.astype(jnp.int32)
    aonehot = (jnp.arange(a_pad, dtype=jnp.uint32)[None, :]
               == arrays["actor"][perm].astype(jnp.uint32)[:, None]
               ).astype(jnp.uint8)
    aonehot = jnp.pad(aonehot, ((0, r_pad - num_r), (0, 0)))

    a_named = ("vv", "processed")
    e_named = ("present", "dot_actor", "dot_counter", "deleted",
               "del_dot_actor", "del_dot_counter")
    dst, src = {}, {}
    for name in a_named + e_named:
        x = arrays[name]
        last = a_pad if name in a_named else e_pad
        dst[name] = pad(x, last)
        src[name] = pad(x[perm], last)

    grid = (r_pad // _BLOCK_R, e_pad // blk)
    a_blk = pl.BlockSpec((_BLOCK_R, a_pad), lambda i, j: (i, 0))
    e_blk = pl.BlockSpec((_BLOCK_R, blk), lambda i, j: (i, j))

    ins = [dst["vv"], src["vv"], dst["processed"], src["processed"],
           aonehot]
    in_specs = [a_blk] * 5
    for name in e_named:
        ins += [dst[name], src[name]]
        in_specs += [e_blk, e_blk]

    u32 = jnp.uint32
    outs = pl.pallas_call(
        _delta_kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[a_blk, a_blk, e_blk, e_blk, e_blk, e_blk, e_blk, e_blk],
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, a_pad), u32),   # vv
            jax.ShapeDtypeStruct((r_pad, a_pad), u32),   # processed
            jax.ShapeDtypeStruct((r_pad, e_pad), jnp.uint8),  # present
            jax.ShapeDtypeStruct((r_pad, e_pad), u32),   # dot_actor
            jax.ShapeDtypeStruct((r_pad, e_pad), u32),   # dot_counter
            jax.ShapeDtypeStruct((r_pad, e_pad), jnp.uint8),  # deleted
            jax.ShapeDtypeStruct((r_pad, e_pad), u32),   # del_dot_actor
            jax.ShapeDtypeStruct((r_pad, e_pad), u32),   # del_dot_counter
        ],
        interpret=interpret,
    )(*ins)
    vv, proc, p, da, dc, d, dda, ddc = outs
    return (vv[:num_r, :num_a], proc[:num_r, :num_a], p[:num_r, :num_e],
            da[:num_r, :num_e], dc[:num_r, :num_e], d[:num_r, :num_e],
            dda[:num_r, :num_e], ddc[:num_r, :num_e])


def pallas_delta_gossip_round(state: AWSetDeltaState, perm, *,
                              block_e: int = 512,
                              interpret: bool | None = None
                              ) -> AWSetDeltaState:
    """One fused δ anti-entropy round, v2 semantics: drop-in bitwise
    equivalent of ``parallel.gossip.delta_gossip_round(state, perm,
    delta_semantics="v2")`` (the production TPU path — that function
    dispatches here on TPU backends)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    arrays = {
        name: (getattr(state, name).astype(jnp.uint8)
               if getattr(state, name).dtype == jnp.bool_
               else getattr(state, name))
        for name in state._fields
    }
    vv, proc, p, da, dc, d, dda, ddc = _fused_delta_round(
        arrays, jnp.asarray(perm), block_e, interpret)
    return AWSetDeltaState(
        vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
        actor=state.actor, deleted=d != 0, del_dot_actor=dda,
        del_dot_counter=ddc, processed=proc,
    )
