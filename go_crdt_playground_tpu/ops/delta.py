"""δ-state merge kernels.

Tensorization of the reference δ prototype (awset-delta_test.go) plus this
framework's v2 semantics (see models/spec.py AWSetDelta docstring for the
full semantics discussion; every rule here mirrors a spec rule).

Wire model: the reference's ``MakeDeltaMergeData`` is sender-side payload
compression against the receiver's advertised VV (awset-delta_test.go:79-105).
Here a payload is a pair of masked dense tensors — ``changed`` lanes carry
live dots, ``deleted`` lanes carry deletion dots.  The empty-δ early return
(awset-delta_test.go:60-64) becomes a masked no-op lane, not control flow
(SURVEY §5.8).  Bandwidth-compacted payloads (fixed-K index form) live in
ops/compact.py; the dense form here is what the on-chip gossip rounds use.

GC is the one place the TPU design intentionally diverges from per-peer
bookkeeping: the spec tracks each peer's advertised ``processed`` vector,
while the batched SPMD system computes the exact causal-stability frontier
with one collective — ``min`` of ``processed`` over the replica axis
(gc_frontier).  Safety is identical (a record is dropped only when every
participating replica's state reflects it); the collective just learns the
frontier without per-peer gossip.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from typing import Optional

from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState
from go_crdt_playground_tpu.ops.merge import (MergeTrace, OUTCOME_ADD,
                                              OUTCOME_KEEP, OUTCOME_NONE,
                                              OUTCOME_REMOVE, OUTCOME_SKIP,
                                              OUTCOME_UPDATE)
from go_crdt_playground_tpu.ops.vv import has_dot, vv_join


class DeltaPayload(NamedTuple):
    """Sender-compressed δ payload (one replica pair; batched via vmap).

    changed lanes: entries the receiver's clock hasn't covered
    (awset-delta_test.go:84-92).  deleted lanes: deletion records not
    obsoleted by a local re-add (awset-delta_test.go:93-102).
    """

    src_vv: jnp.ndarray        # uint32[A]
    changed: jnp.ndarray       # bool[E]
    ch_da: jnp.ndarray         # uint32[E]  live dots on changed lanes
    ch_dc: jnp.ndarray         # uint32[E]
    deleted: jnp.ndarray       # bool[E]
    del_da: jnp.ndarray        # uint32[E]  deletion dots on deleted lanes
    del_dc: jnp.ndarray        # uint32[E]
    src_actor: jnp.ndarray     # uint32[]
    src_processed: jnp.ndarray # uint32[A]  (v2 bookkeeping; zeros otherwise)

    def nbytes_dense(self) -> int:
        return sum(x.size * x.dtype.itemsize for x in self)


def delta_extract(src: AWSetDeltaState, dst_vv: jnp.ndarray) -> DeltaPayload:
    """Sender-side ``MakeDeltaMergeData`` (awset-delta_test.go:79-105) for
    one src replica against one receiver VV.  Shapes: src fields [A]/[E]
    (single replica slice), dst_vv uint32[A]."""
    changed = src.present & ~has_dot(dst_vv, src.dot_actor, src.dot_counter)
    # re-add filter: skip records whose key is live locally under a
    # different actor or a higher counter (awset-delta_test.go:94-97)
    resurrected = src.present & (
        (src.dot_actor != src.del_dot_actor)
        | (src.dot_counter > src.del_dot_counter)
    )
    deleted = src.deleted & ~resurrected
    return DeltaPayload(
        src_vv=src.vv,
        changed=changed,
        ch_da=jnp.where(changed, src.dot_actor, 0),
        ch_dc=jnp.where(changed, src.dot_counter, 0),
        deleted=deleted,
        del_da=jnp.where(deleted, src.del_dot_actor, 0),
        del_dc=jnp.where(deleted, src.del_dot_counter, 0),
        src_actor=src.actor,
        src_processed=src.processed,
    )


def delta_apply(
    dst: AWSetDeltaState,
    p: DeltaPayload,
    delta_semantics: str = "reference",
    strict_reference_semantics: bool = True,
) -> AWSetDeltaState:
    """Receiver-side ``deltaMerge`` (awset-delta_test.go:107-166) for one
    dst replica slice.  Branch-free; the mode strings are static."""
    state, _ = _delta_apply_impl(dst, p, delta_semantics,
                                 strict_reference_semantics, False)
    return state


def delta_apply_traced(
    dst: AWSetDeltaState,
    p: DeltaPayload,
    delta_semantics: str = "reference",
    strict_reference_semantics: bool = True,
) -> Tuple[AWSetDeltaState, MergeTrace]:
    """delta_apply plus per-lane decision tensors — the δ counterpart of
    ops.merge's trace, covering the reference's deltaMerge logOutcome
    calls (awset-delta_test.go:113-123, logged at 126-163)."""
    state, trace = _delta_apply_impl(dst, p, delta_semantics,
                                     strict_reference_semantics, True)
    assert trace is not None
    return state, trace


def _delta_apply_impl(
    dst: AWSetDeltaState,
    p: DeltaPayload,
    delta_semantics: str,
    strict_reference_semantics: bool,
    with_trace: bool,
) -> Tuple[AWSetDeltaState, Optional[MergeTrace]]:
    # PHASE 1 over changed lanes — identical decision table to full-merge
    # phase 1 (awset-delta_test.go:126-147 vs awset.go:122-143).
    seen_by_dst = has_dot(dst.vv, p.ch_da, p.ch_dc)
    p1_take = p.changed & (dst.present | ~seen_by_dst)
    present1 = dst.present | p1_take
    da1 = jnp.where(p1_take, p.ch_da, dst.dot_actor)
    dc1 = jnp.where(p1_take, p.ch_dc, dst.dot_counter)

    # PHASE 2 over deletion lanes.
    if delta_semantics == "v2":
        # v2 arbitration == full-merge phase 2 (awset.go:152) restricted to
        # the payload keys: remove iff the SENDER's clock covers our LIVE
        # dot.  (Key absent at sender is guaranteed by payload
        # construction.)  Preserves add-wins in any topology.
        # NOTE: the gather must run on the POST-phase-1 dots (da1/dc1),
        # not be shortcut via "p1_take lanes are trivially covered by the
        # sender's clock": that identity leans on the every-VV-covers-its-
        # own-live-dots invariant, which the compact-overflow path
        # deliberately breaks (ops/compact.py ships partial data with NO
        # clock advance), and there the shortcut removes entries the spec
        # (models/spec.py v2 arbitration) keeps.
        remove = p.deleted & present1 & has_dot(p.src_vv, da1, dc1)
    else:
        # Reference arbitration (awset-delta_test.go:153-158): keep iff OUR
        # clock covers the DELETION dot.
        remove = p.deleted & present1 & ~has_dot(dst.vv, p.del_da, p.del_dc)

    present = present1 & ~remove
    da = jnp.where(present, da1, 0)
    dc = jnp.where(present, dc1, 0)

    # VV join — skipped on an all-empty payload under the strict reference
    # quirk (awset-delta_test.go:60-64), as a masked select rather than
    # control flow.
    joined = vv_join(dst.vv, p.src_vv)
    if delta_semantics == "reference" and strict_reference_semantics:
        empty = ~(jnp.any(p.changed) | jnp.any(p.deleted))
        vv = jnp.where(empty, dst.vv, joined)
        # the early return also skips the entry/dot updates; on an empty
        # payload the masks are all-false so present/da/dc already equal
        # dst's — nothing further to select.
    else:
        vv = joined

    if delta_semantics == "v2":
        # absorb received records for transitive re-gossip (spec
        # _absorb_records: overwrite if absent or (counter, actor)
        # lexicographically newer — the actor tie-break is what makes
        # the absorb a JOIN: without it, equal-counter records from
        # different actors are retained by arrival order and two
        # replicas never converge bitwise on the lane, which digest
        # sync (DESIGN.md §19) would re-ship forever)
        take_rec = p.deleted & (~dst.deleted
                                | (p.del_dc > dst.del_dot_counter)
                                | ((p.del_dc == dst.del_dot_counter)
                                   & (p.del_da > dst.del_dot_actor)))
        deleted_log = dst.deleted | p.deleted
        del_da = jnp.where(take_rec, p.del_da, dst.del_dot_actor)
        del_dc = jnp.where(take_rec, p.del_dc, dst.del_dot_counter)
        # join processed (spec _join_processed): elementwise max plus the
        # sender's own slot advancing to its clock
        processed = jnp.maximum(dst.processed, p.src_processed)
        idx = p.src_actor.astype(jnp.int32)
        processed = processed.at[idx].max(p.src_vv[idx])
    else:
        deleted_log = dst.deleted
        del_da = dst.del_dot_actor
        del_dc = dst.del_dot_counter
        processed = dst.processed

    trace = None
    if with_trace:
        # phase-1 table mirrors ops.merge's (same outcome labels,
        # awset-delta_test.go:126-147); lanes outside the payload are NONE
        both = p.changed & dst.present
        upd = both & ((dst.dot_actor != p.ch_da)
                      | (dst.dot_counter != p.ch_dc))
        t1 = jnp.where(
            upd, OUTCOME_UPDATE,
            jnp.where(
                both, OUTCOME_KEEP,
                jnp.where(
                    p.changed & seen_by_dst, OUTCOME_SKIP,
                    jnp.where(p.changed, OUTCOME_ADD, OUTCOME_NONE)))
        ).astype(jnp.uint8)
        # phase 2 over deletion lanes (awset-delta_test.go:149-163): the
        # no-op delete on an absent key also logs "remove" (:160-162)
        t2 = jnp.where(
            remove, OUTCOME_REMOVE,
            jnp.where(
                p.deleted & present1, OUTCOME_KEEP,
                jnp.where(p.deleted, OUTCOME_REMOVE, OUTCOME_NONE))
        ).astype(jnp.uint8)
        trace = MergeTrace(phase1=t1, phase2=t2)

    return AWSetDeltaState(
        vv=vv, present=present, dot_actor=da, dot_counter=dc,
        actor=dst.actor, deleted=deleted_log, del_dot_actor=del_da,
        del_dot_counter=del_dc, processed=processed,
    ), trace


def slice_apply(dst: AWSetDeltaState, p: DeltaPayload) -> AWSetDeltaState:
    """Keyspace-handoff apply (DESIGN.md §18): the payload is the
    donor's complete FENCED state for the lanes it names
    (``changed | deleted``), so those lanes are OVERWRITTEN — present
    bit, live dot, deletion record — never vv-arbitrated.

    Why not ``delta_apply``: slice payloads join donor vvs into the
    recipient, so after one handoff the recipient's vv covers donor
    dots it never received (a vv is per-LANE, a slice is per-ELEMENT —
    no single vv can scope the claim).  A later slice moving one of
    those dots here would then read as already-seen and be dropped by
    phase 1's arbitration: a silently lost acked op.  Overwrite is
    sound because the router fences the slice for the whole transfer —
    the donor state is the unique authority for those elements, and
    re-applying the same payload (the retry path) is idempotent.
    Lanes outside the payload are untouched; the vv/processed joins
    keep the recipient's clocks monotone for its own extraction
    paths."""
    in_slice = p.changed | p.deleted
    present = jnp.where(in_slice, p.changed, dst.present)
    da = jnp.where(in_slice, p.ch_da, dst.dot_actor)
    dc = jnp.where(in_slice, p.ch_dc, dst.dot_counter)
    deleted = jnp.where(in_slice, p.deleted, dst.deleted)
    del_da = jnp.where(in_slice, p.del_da, dst.del_dot_actor)
    del_dc = jnp.where(in_slice, p.del_dc, dst.del_dot_counter)
    vv = vv_join(dst.vv, p.src_vv)
    processed = jnp.maximum(dst.processed, p.src_processed)
    idx = p.src_actor.astype(jnp.int32)
    processed = processed.at[idx].max(p.src_vv[idx])
    return AWSetDeltaState(
        vv=vv, present=present, dot_actor=da, dot_counter=dc,
        actor=dst.actor, deleted=deleted, del_dot_actor=del_da,
        del_dot_counter=del_dc, processed=processed,
    )


def full_merge_delta(dst: AWSetDeltaState, src: AWSetDeltaState,
                     delta_semantics: str) -> AWSetDeltaState:
    """First-contact branch (awset-delta_test.go:53-56): plain full-state
    merge.  Reference mode leaves the receiver's log untouched; v2 absorbs
    src's log and processed vector (the merged state reflects every
    deletion src's state reflected — spec merge())."""
    from go_crdt_playground_tpu.ops.merge import merge_kernel

    vv, present, da, dc, _ = merge_kernel(
        dst.vv, dst.present, dst.dot_actor, dst.dot_counter,
        src.vv, src.present, src.dot_actor, src.dot_counter,
    )
    if delta_semantics == "v2":
        # (counter, actor) lexicographic max — the same join-not-
        # arrival-order absorb as _delta_apply_impl's
        take_rec = src.deleted & (
            ~dst.deleted
            | (src.del_dot_counter > dst.del_dot_counter)
            | ((src.del_dot_counter == dst.del_dot_counter)
               & (src.del_dot_actor > dst.del_dot_actor)))
        deleted_log = dst.deleted | src.deleted
        del_da = jnp.where(take_rec, src.del_dot_actor, dst.del_dot_actor)
        del_dc = jnp.where(take_rec, src.del_dot_counter, dst.del_dot_counter)
        processed = jnp.maximum(dst.processed, src.processed)
        idx = src.actor.astype(jnp.int32)
        processed = processed.at[idx].max(src.vv[idx])
    else:
        deleted_log = dst.deleted
        del_da = dst.del_dot_actor
        del_dc = dst.del_dot_counter
        processed = dst.processed
    return AWSetDeltaState(
        vv=vv, present=present, dot_actor=da, dot_counter=dc,
        actor=dst.actor, deleted=deleted_log, del_dot_actor=del_da,
        del_dot_counter=del_dc, processed=processed,
    )


def delta_merge_pair(
    dst: AWSetDeltaState,
    src: AWSetDeltaState,
    delta_semantics: str = "reference",
    strict_reference_semantics: bool = True,
) -> AWSetDeltaState:
    """One replica-pair δ-dispatch merge (awset-delta_test.go:51-65):
    full merge on first contact (our counter for src's actor is 0), δ
    extract+apply otherwise.  Both branches are computed densely and
    selected per field — the TPU way to express the reference's
    ``if Counter(src.Actor) <= 0`` control flow."""
    first_contact = dst.vv[src.actor.astype(jnp.int32)] == 0
    full = full_merge_delta(dst, src, delta_semantics)
    payload = delta_extract(src, dst.vv)
    delt = delta_apply(dst, payload, delta_semantics,
                       strict_reference_semantics)
    return jax.tree.map(
        lambda f, d: jnp.where(
            jnp.reshape(first_contact, (1,) * f.ndim), f, d),
        full, delt,
    )


def delta_merge_pairwise(
    dst: AWSetDeltaState,
    src: AWSetDeltaState,
    delta_semantics: str = "reference",
    strict_reference_semantics: bool = True,
) -> AWSetDeltaState:
    """Batched ``dst[r] <- src[r]`` δ merge (vmapped delta_merge_pair)."""
    return jax.vmap(
        lambda d, s: delta_merge_pair(
            d, s, delta_semantics, strict_reference_semantics)
    )(dst, src)


delta_merge_pairwise_jit = jax.jit(
    delta_merge_pairwise,
    static_argnames=("delta_semantics", "strict_reference_semantics"),
)


def delta_merge_one_into(
    dst: AWSetDeltaState, r_dst: int,
    src: AWSetDeltaState, r_src: int,
    delta_semantics: str = "reference",
    strict_reference_semantics: bool = True,
) -> AWSetDeltaState:
    """Scenario-style single δ merge (the reference harness's direct method
    call, awset-delta_test.go:173)."""
    d = jax.tree.map(lambda x: x[r_dst], dst)
    s = jax.tree.map(lambda x: x[r_src], src)
    merged = delta_merge_pair(d, s, delta_semantics,
                              strict_reference_semantics)
    return jax.tree.map(lambda full, row: full.at[r_dst].set(row), dst,
                        merged)


# ---------------------------------------------------------------------------
# δ-log GC — causal stability via a collective frontier (TPU-native design;
# the reference's gcDeleted is an empty stub, awset-delta_test.go:67-77)
# ---------------------------------------------------------------------------


def gc_frontier(processed: jnp.ndarray,
                participating: jnp.ndarray | None = None) -> jnp.ndarray:
    """Exact causal-stability frontier: frontier[a] = min over participating
    replicas of processed[r, a].  A deletion record (k, (a, c)) is stable
    iff c <= frontier[a] — every participating replica's state reflects it.

    processed: uint32[R, A]; participating: bool[R] (None = all).  Under a
    sharded replica axis this min is ``jax.lax.pmin`` over the mesh
    (parallel/collectives.py wraps it)."""
    if participating is not None:
        big = jnp.asarray(jnp.iinfo(processed.dtype).max, processed.dtype)
        processed = jnp.where(participating[:, None], processed, big)
    return jnp.min(processed, axis=0)


@jax.jit
def gc_apply(state: AWSetDeltaState,
             frontier: jnp.ndarray) -> AWSetDeltaState:
    """Drop stable deletion records: deleted lanes whose dot counter is
    covered by the frontier for the dot's origin actor."""
    covered = jnp.take(frontier, state.del_dot_actor.astype(jnp.int32),
                       mode="clip")
    stable = state.deleted & (state.del_dot_counter <= covered)
    keep = state.deleted & ~stable
    return state._replace(
        deleted=keep,
        del_dot_actor=jnp.where(keep, state.del_dot_actor, 0),
        del_dot_counter=jnp.where(keep, state.del_dot_counter, 0),
    )
