"""Packed micro-batch op-apply: B client op-rows in one compiled dispatch.

The serve frontend (serve/batcher.py) coalesces pending client requests
into one packed ``(batch, elems)`` tensor pair — ``add_rows[b]`` is the
key set of request b's ``Add(k...)`` call, ``del_rows[b]`` of its
``Del(k...)`` call — and applies the whole micro-batch to a single
replica slice with ONE dispatch of ``ingest_rows``.  Per row the algebra
is exactly the fused branch-free lane algebra of the host-driven ops
(models/awset_delta.add_elements / del_elements); ``lax.scan`` threads
the rows because ops against one replica serialize on its clock — the
batch saves dispatches and (through ``Node.ingest_batch``) WAL fsyncs,
never reorders semantics.

Semantics pinned to the reference (awset.go:89-101, awset-delta_test.go:
14-33), with the batching-specific deltas called out:

* an Add row ticks the clock once per touched key; dots are assigned in
  ASCENDING ELEMENT ORDER (the selector form has no call-site argument
  order — callers that care about intra-request dot order must sort,
  which the wire protocol's set-of-keys framing already implies);
* a Del row ticks the clock ONCE iff the row selects at least one key
  (reference δ-Del ticks even when nothing selected is present; an
  all-empty row here is a padding lane and must not tick) and stamps
  every actually-present selected key with that one shared deletion dot;
* ``live[b] = False`` masks row b entirely (bucketing padding), so one
  compiled program serves every batch occupancy.

The resulting state is bitwise-identical to applying the same requests
through ``add_elements``/``del_elements`` one dispatch each (pinned by
tests/test_serve.py); dissemination of the batch's δ rides the existing
kernel path (``ops/delta.delta_extract`` via ``Node._log_local_delta``
and the anti-entropy exchange).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState


def _apply_add_row(st: AWSetDeltaState, row: jnp.ndarray) -> AWSetDeltaState:
    """One Add(k...) op-row on a single-replica slice.  row: bool[E]."""
    a = st.actor.astype(jnp.int32)
    base = st.vv[a]
    # 1-based dot position per touched lane, ascending element order
    pos1 = jnp.cumsum(row.astype(jnp.uint32)) * row
    k = jnp.max(pos1)
    new_vv = base + k
    return st._replace(
        vv=st.vv.at[a].set(new_vv),
        present=st.present | row,
        dot_actor=jnp.where(row, st.actor, st.dot_actor),
        dot_counter=jnp.where(row, base + pos1, st.dot_counter),
        processed=st.processed.at[a].set(new_vv),
    )


def _apply_del_row(st: AWSetDeltaState, row: jnp.ndarray) -> AWSetDeltaState:
    """One Del(k...) op-row on a single-replica slice.  row: bool[E]."""
    a = st.actor.astype(jnp.int32)
    tick = jnp.any(row).astype(jnp.uint32)
    new_counter = st.vv[a] + tick
    hit = row & st.present
    return st._replace(
        vv=st.vv.at[a].set(new_counter),
        present=st.present & ~hit,
        dot_actor=jnp.where(hit, 0, st.dot_actor),
        dot_counter=jnp.where(hit, 0, st.dot_counter),
        deleted=st.deleted | hit,
        del_dot_actor=jnp.where(hit, st.actor, st.del_dot_actor),
        del_dot_counter=jnp.where(hit, new_counter, st.del_dot_counter),
        processed=st.processed.at[a].set(new_counter),
    )


@jax.jit
def ingest_rows(state: AWSetDeltaState, add_rows: jnp.ndarray,
                del_rows: jnp.ndarray,
                live: jnp.ndarray) -> AWSetDeltaState:
    """Apply B op-rows to ONE replica slice in a single compiled program.

    state: single-replica AWSetDeltaState slice (vv[A], present[E], ...).
    add_rows / del_rows: bool[B, E]; live: bool[B] (padding mask).  Rows
    apply in order b=0..B-1 (adds before dels within a row); the batcher
    keeps B static so every occupancy reuses one compiled program.
    """

    def step(st, x):
        add_row, del_row, is_live = x
        st = _apply_add_row(st, add_row & is_live)
        st = _apply_del_row(st, del_row & is_live)
        return st, None

    out, _ = jax.lax.scan(step, state, (add_rows, del_rows, live))
    return out
