"""Packed micro-batch op-apply: B client op-rows in one compiled dispatch.

The serve frontend (serve/batcher.py) coalesces pending client requests
into one packed ``(batch, elems)`` tensor pair — ``add_rows[b]`` is the
key set of request b's ``Add(k...)`` call, ``del_rows[b]`` of its
``Del(k...)`` call — and applies the whole micro-batch to a single
replica slice with ONE dispatch of ``ingest_rows``.  Per row the algebra
is exactly the fused branch-free lane algebra of the host-driven ops
(models/awset_delta.add_elements / del_elements); ``lax.scan`` threads
the rows because ops against one replica serialize on its clock — the
batch saves dispatches and (through ``Node.ingest_batch``) WAL fsyncs,
never reorders semantics.

Semantics pinned to the reference (awset.go:89-101, awset-delta_test.go:
14-33), with the batching-specific deltas called out:

* an Add row ticks the clock once per touched key; dots are assigned in
  ASCENDING ELEMENT ORDER (the selector form has no call-site argument
  order — callers that care about intra-request dot order must sort,
  which the wire protocol's set-of-keys framing already implies);
* a Del row ticks the clock ONCE iff the row selects at least one key
  (reference δ-Del ticks even when nothing selected is present; an
  all-empty row here is a padding lane and must not tick) and stamps
  every actually-present selected key with that one shared deletion dot;
* ``live[b] = False`` masks row b entirely (bucketing padding), so one
  compiled program serves every batch occupancy.

The resulting state is bitwise-identical to applying the same requests
through ``add_elements``/``del_elements`` one dispatch each (pinned by
tests/test_serve.py); dissemination of the batch's δ rides the existing
kernel path (``ops/delta.delta_extract`` via ``Node._log_local_delta``
and the anti-entropy exchange).

Fused ingest+δ (the serve-path throughput ladder, DESIGN.md §16):
``ingest_rows_delta`` returns the merged state AND the batch's δ vs the
PRE-batch vv — the exact payload ``Node.ingest_batch`` used to compute
with a second ``delta_extract`` dispatch for its WAL record — in ONE
compiled program, plus the δ's fixed-K compact form (ops/compact.py) so
the host pulls O(changed) lanes for the WAL record instead of the dense
O(E) masks.  ``ops/pallas_ingest.py`` is the Pallas twin of the same
contract (bitwise-pinned by tests/test_ingest_fused.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState


# fixed-K capacity of the fused path's on-device δ compaction: batches
# whose δ claims more lanes fall back to the dense WAL record — never
# dropped (net/peer.Node and bench.py both select through
# ingest_delta_regime, so there is exactly one policy)
WAL_COMPACT_K = 128


def ingest_delta_regime(num_elements: int):
    """THE backend regime for the fused ingest+δ path: returns
    ``(fused_fn, k)`` — the Pallas twin with the fixed-K on-device
    compaction on TPU backends (the compaction shrinks the
    device→host pull), the XLA fused path with ``k=0`` (host-side
    compaction from the dense payload) everywhere else.  One selection
    serves ``Node.ingest_batch`` and ``bench.py --ingest``: the bench
    cannot drift into measuring a path the server no longer runs."""
    import jax

    if jax.default_backend() == "tpu":
        from go_crdt_playground_tpu.ops.pallas_ingest import \
            pallas_ingest_rows_delta

        return pallas_ingest_rows_delta, min(WAL_COMPACT_K, num_elements)
    return ingest_rows_delta, 0


def _apply_add_row(st: AWSetDeltaState, row: jnp.ndarray) -> AWSetDeltaState:
    """One Add(k...) op-row on a single-replica slice.  row: bool[E]."""
    a = st.actor.astype(jnp.int32)
    base = st.vv[a]
    # 1-based dot position per touched lane, ascending element order
    pos1 = jnp.cumsum(row.astype(jnp.uint32)) * row
    k = jnp.max(pos1)
    new_vv = base + k
    return st._replace(
        vv=st.vv.at[a].set(new_vv),
        present=st.present | row,
        dot_actor=jnp.where(row, st.actor, st.dot_actor),
        dot_counter=jnp.where(row, base + pos1, st.dot_counter),
        processed=st.processed.at[a].set(new_vv),
    )


def _apply_del_row(st: AWSetDeltaState, row: jnp.ndarray) -> AWSetDeltaState:
    """One Del(k...) op-row on a single-replica slice.  row: bool[E]."""
    a = st.actor.astype(jnp.int32)
    tick = jnp.any(row).astype(jnp.uint32)
    new_counter = st.vv[a] + tick
    hit = row & st.present
    return st._replace(
        vv=st.vv.at[a].set(new_counter),
        present=st.present & ~hit,
        dot_actor=jnp.where(hit, 0, st.dot_actor),
        dot_counter=jnp.where(hit, 0, st.dot_counter),
        deleted=st.deleted | hit,
        del_dot_actor=jnp.where(hit, st.actor, st.del_dot_actor),
        del_dot_counter=jnp.where(hit, new_counter, st.del_dot_counter),
        processed=st.processed.at[a].set(new_counter),
    )


@jax.jit
def ingest_rows(state: AWSetDeltaState, add_rows: jnp.ndarray,
                del_rows: jnp.ndarray,
                live: jnp.ndarray) -> AWSetDeltaState:
    """Apply B op-rows to ONE replica slice in a single compiled program.

    state: single-replica AWSetDeltaState slice (vv[A], present[E], ...).
    add_rows / del_rows: bool[B, E]; live: bool[B] (padding mask).  Rows
    apply in order b=0..B-1 (adds before dels within a row); the batcher
    keeps B static so every occupancy reuses one compiled program.
    """

    def step(st, x):
        add_row, del_row, is_live = x
        st = _apply_add_row(st, add_row & is_live)
        st = _apply_del_row(st, del_row & is_live)
        return st, None

    out, _ = jax.lax.scan(step, state, (add_rows, del_rows, live))
    return out


@functools.partial(jax.jit, static_argnames=("k_changed", "k_deleted"))
def ingest_rows_delta(state: AWSetDeltaState, add_rows: jnp.ndarray,
                      del_rows: jnp.ndarray, live: jnp.ndarray,
                      k_changed: int, k_deleted: int) -> Tuple:
    """Fused ingest+δ: one dispatch returning ``(merged, payload,
    compact)`` — the merged single-replica slice, the batch δ vs the
    PRE-batch vv (``delta_extract(merged, pre_vv)``, bitwise what the
    two-pass path computed in its second dispatch), and the δ routed
    through ``ops/compact.py``'s fixed-K lanes (``compact.overflow``
    set when the δ doesn't fit — callers fall back to the dense
    payload, never drop).

    The δ is extracted against the pre-batch vv, so it contains the
    batch's own effects PLUS any pre-existing lanes whose dots the
    pre-batch vv did not cover (the compact-overflow gossip path can
    leave those behind); that is exactly what
    ``Node._log_local_delta`` always logged, preserved here bitwise.

    ``k_changed == 0`` (or ``k_deleted == 0``) skips the on-device
    compaction and returns ``compact=None``: the fixed-K form exists to
    shrink the device→host pull, which costs nothing on a CPU backend
    — there the caller compacts host-side from the dense payload
    (``Node._append_delta_record``), and the scatter-heavy compaction
    kernel would only slow the batch down.
    """
    from go_crdt_playground_tpu.ops import compact as compact_ops
    from go_crdt_playground_tpu.ops import delta as delta_ops

    pre_vv = state.vv
    merged = ingest_rows(state, add_rows, del_rows, live)
    payload = delta_ops.delta_extract(merged, pre_vv)
    if k_changed == 0 or k_deleted == 0:
        return merged, payload, None
    compact = compact_ops.compact_payload(payload, k_changed, k_deleted)
    return merged, payload, compact
