"""Fused Pallas TPU kernel for the serve ingest path: batch apply + δ.

``ops/ingest.ingest_rows`` applies one packed ``(B, E)`` micro-batch
with a ``lax.scan`` over rows; ``Node.ingest_batch`` then used to pay a
SECOND dispatch (``ops/delta.delta_extract``) to build the WAL record's
δ.  On the XLA path the scan materializes the full E-lane state B times
per batch; here the whole batch folds over each element block IN VMEM —
state streams HBM→VMEM once, all B rows apply to the resident block,
and the δ-vs-pre-batch-vv extraction reads the final lanes while they
are still on chip (the ``ops/pallas_delta.py`` treatment applied to the
ingest hot path).

The row algebra is sequential by semantics (ops/ingest.py docstring:
rows serialize on the replica clock), but its cross-row data
dependencies are only SCALAR: each row's dot counters depend on the
popcounts/ticks of earlier rows, never on their lane effects, except
through the present bit itself.  So the kernel receives the per-row
counter bases precomputed by cheap XLA prefix sums ([B]-shaped) plus
the per-lane add-dot counters ([B, E], ``add_base[b] + row prefix``),
and the in-kernel fold is a pure per-lane state machine:

    for b in 0..B:  present |= add_row; dots := add dots
                    hit = del_row & present; clear hits; log deletion

The A-shaped outputs (vv, processed) are closed-form (the batch ticks
one actor's counter) and computed in XLA around the kernel — the whole
thing is ONE jitted dispatch, like the fused XLA path.

``pallas_ingest_rows_delta`` is bitwise-pinned to
``ops/ingest.ingest_rows_delta`` (tests/test_ingest_fused.py) across
occupancies, padding rows, and the empty batch; off-TPU it runs in
interpret mode, and shapes the kernel cannot take (an empty batch
axis) fall back to the XLA fused path — the same
interpret-mode/XLA-fallback ladder as the merge and δ kernels.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState
from go_crdt_playground_tpu.ops.pallas_merge import (_LANE, _round_up,
                                                     gather_rows)


def _ingest_kernel(actor_ref, vv_ref, p_ref, da_ref, dc_ref, d_ref,
                   dda_ref, ddc_ref, arow_ref, drow_ref, adddc_ref,
                   delctr_ref, po_ref, dao_ref, dco_ref, do_ref,
                   ddao_ref, ddco_ref, cho_ref, chdao_ref, chdco_ref,
                   dmo_ref, dlda_ref, dldc_ref):
    """One element block: fold all B rows over the resident lanes, then
    extract the block's δ sections vs the PRE-batch vv.  Masks ride as
    uint8 (select between i1 vectors doesn't lower on Mosaic)."""
    actor = actor_ref[...]            # uint32[1, 1]
    num_rows = arow_ref.shape[0]

    def body(b, carry):
        p, da, dc, d, dda, ddc = carry
        on = arow_ref[pl.ds(b, 1), :] != 0           # uint32 row -> mask
        adc = adddc_ref[pl.ds(b, 1), :]
        p = jnp.where(on, jnp.uint8(1), p)
        da = jnp.where(on, actor, da)
        dc = jnp.where(on, adc, dc)
        hit = (drow_ref[pl.ds(b, 1), :] != 0) & (p != 0)
        p = jnp.where(hit, jnp.uint8(0), p)
        da = jnp.where(hit, jnp.uint32(0), da)
        dc = jnp.where(hit, jnp.uint32(0), dc)
        d = jnp.where(hit, jnp.uint8(1), d)
        dda = jnp.where(hit, actor, dda)
        ddc = jnp.where(hit, delctr_ref[pl.ds(b, 1), :], ddc)
        return p, da, dc, d, dda, ddc

    p, da, dc, d, dda, ddc = jax.lax.fori_loop(
        0, num_rows, body,
        (p_ref[...], da_ref[...], dc_ref[...], d_ref[...], dda_ref[...],
         ddc_ref[...]))
    po_ref[...] = p
    dao_ref[...] = da
    dco_ref[...] = dc
    do_ref[...] = d
    ddao_ref[...] = dda
    ddco_ref[...] = ddc

    # fused δ extraction vs the PRE-batch vv (ops/delta.delta_extract
    # on the merged lanes, while they are still in VMEM)
    covered = dc <= gather_rows(vv_ref[...], da)
    changed = (p != 0) & ~covered
    cho_ref[...] = changed.astype(jnp.uint8)
    chdao_ref[...] = jnp.where(changed, da, 0)
    chdco_ref[...] = jnp.where(changed, dc, 0)
    resurrected = (p != 0) & ((da != dda) | (dc > ddc))
    deleted_p = (d != 0) & ~resurrected
    dmo_ref[...] = deleted_p.astype(jnp.uint8)
    dlda_ref[...] = jnp.where(deleted_p, dda, 0)
    dldc_ref[...] = jnp.where(deleted_p, ddc, 0)


@functools.partial(jax.jit, static_argnames=("k_changed", "k_deleted",
                                             "block_e", "interpret"))
def _fused_ingest(state: AWSetDeltaState, add_rows, del_rows, live,
                  k_changed: int, k_deleted: int, block_e: int,
                  interpret: bool):
    from go_crdt_playground_tpu.ops import compact as compact_ops
    from go_crdt_playground_tpu.ops.delta import DeltaPayload

    num_b, num_e = add_rows.shape
    num_a = state.vv.shape[0]
    e_pad = _round_up(num_e, _LANE)
    a_pad = _round_up(num_a, _LANE)
    blk = min(_round_up(block_e, _LANE), e_pad)
    while e_pad % blk:
        blk -= _LANE
    b_pad = _round_up(max(num_b, 8), 8)

    a = state.actor.astype(jnp.int32)
    pre_vv = state.vv
    arow = (add_rows & live[:, None]).astype(jnp.uint32)
    drow = (del_rows & live[:, None]).astype(jnp.uint32)
    k = jnp.sum(arow, axis=1, dtype=jnp.uint32)        # adds per row
    t = jnp.max(drow, axis=1).astype(jnp.uint32)       # del tick per row
    steps = k + t
    c0 = pre_vv[a]
    add_base = c0 + jnp.cumsum(steps) - steps          # exclusive prefix
    del_ctr = add_base + steps                         # post-row counter
    add_dc = add_base[:, None] + jnp.cumsum(arow, axis=1, dtype=jnp.uint32)
    final = c0 + jnp.sum(steps, dtype=jnp.uint32)
    new_vv = pre_vv.at[a].set(final)
    new_processed = state.processed.at[a].set(final)

    def pad_rows(x):
        return jnp.pad(x, ((0, b_pad - num_b), (0, e_pad - num_e)))

    def pad_lane(x, width):
        x = x.astype(jnp.uint8) if x.dtype == jnp.bool_ else x
        return jnp.pad(x[None, :], ((0, 0), (0, width - x.shape[0])))

    ins = [
        state.actor.astype(jnp.uint32).reshape(1, 1),
        pad_lane(pre_vv, a_pad),
        pad_lane(state.present, e_pad),
        pad_lane(state.dot_actor, e_pad),
        pad_lane(state.dot_counter, e_pad),
        pad_lane(state.deleted, e_pad),
        pad_lane(state.del_dot_actor, e_pad),
        pad_lane(state.del_dot_counter, e_pad),
        pad_rows(arow),
        pad_rows(drow),
        pad_rows(add_dc),
        jnp.pad(del_ctr[:, None], ((0, b_pad - num_b), (0, 0))),
    ]
    one = pl.BlockSpec((1, 1), lambda j: (0, 0))
    a_blk = pl.BlockSpec((1, a_pad), lambda j: (0, 0))
    e_blk = pl.BlockSpec((1, blk), lambda j: (0, j))
    r_blk = pl.BlockSpec((b_pad, blk), lambda j: (0, j))
    c_blk = pl.BlockSpec((b_pad, 1), lambda j: (0, 0))
    in_specs = [one, a_blk, e_blk, e_blk, e_blk, e_blk, e_blk, e_blk,
                r_blk, r_blk, r_blk, c_blk]
    u8, u32 = jnp.uint8, jnp.uint32
    out_dts = [u8, u32, u32, u8, u32, u32, u8, u32, u32, u8, u32, u32]
    outs = pl.pallas_call(
        _ingest_kernel,
        grid=(e_pad // blk,),
        in_specs=in_specs,
        out_specs=[e_blk] * 12,
        out_shape=[jax.ShapeDtypeStruct((1, e_pad), d) for d in out_dts],
        interpret=interpret,
    )(*ins)
    (p, da, dc, d, dda, ddc,
     ch, chda, chdc, dm, dlda, dldc) = (o[0, :num_e] for o in outs)

    merged = AWSetDeltaState(
        vv=new_vv, present=p != 0, dot_actor=da, dot_counter=dc,
        actor=state.actor, deleted=d != 0, del_dot_actor=dda,
        del_dot_counter=ddc, processed=new_processed)
    payload = DeltaPayload(
        src_vv=new_vv, changed=ch != 0, ch_da=chda, ch_dc=chdc,
        deleted=dm != 0, del_da=dlda, del_dc=dldc,
        src_actor=state.actor, src_processed=new_processed)
    if k_changed == 0 or k_deleted == 0:
        return merged, payload, None
    compact = compact_ops.compact_payload(payload, k_changed, k_deleted)
    return merged, payload, compact


def pallas_ingest_rows_delta(state: AWSetDeltaState, add_rows, del_rows,
                             live, *, k_changed: int, k_deleted: int,
                             block_e: int = 512,
                             interpret: bool | None = None) -> Tuple:
    """Drop-in bitwise twin of ``ops/ingest.ingest_rows_delta`` (the
    fused batch apply + δ + fixed-K compaction) with the batch fold and
    the δ extraction in one Pallas kernel.  Off-TPU it runs in
    interpret mode; an empty batch axis falls back to the XLA fused
    path (the scan handles length 0, the kernel block shapes cannot)."""
    from go_crdt_playground_tpu.ops import ingest as ingest_ops

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    add_rows = jnp.asarray(add_rows, bool)
    del_rows = jnp.asarray(del_rows, bool)
    live = jnp.asarray(live, bool)
    if add_rows.shape[0] == 0:
        return ingest_ops.ingest_rows_delta(
            state, add_rows, del_rows, live,
            k_changed=k_changed, k_deleted=k_deleted)
    return _fused_ingest(state, add_rows, del_rows, live,
                         k_changed=k_changed, k_deleted=k_deleted,
                         block_e=block_e, interpret=interpret)
