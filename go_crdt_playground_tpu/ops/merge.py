"""The AWSet merge kernel — tensorization of the reference's hot loop.

``AWSet.merge`` (awset.go:107-161) is two sequential map loops plus a VV
join.  On TPU it becomes branch-free boolean algebra over the element axis
``E`` (SURVEY §7.2): every per-key decision in the Go code is a mask, the
two phases compose into closed-form expressions, and ``HasDot`` is a
gather + compare.  ``vmap`` batches replica pairs along ``R``; parallel/
shards ``R``/``E`` over the device mesh.

Phase-order note [verified in SURVEY §3.2]: tensor-form phase composition
is exact because Go's phase 2 reads only (a) src-absence, (b) the entry's
current dot — which for dst-only keys is untouched by phase 1 — and
phase 1 never creates dst-only keys.

Semantics preserved exactly, including the quirks:
  * unconditional dot overwrite when present on both sides (awset.go:142),
    even when the src dot is OLDER — see the stale-dot-overwrite pin in
    tests/test_spec_conformance.py;
  * ``skip`` when dst's clock covers an absent entry's dot (awset.go:133);
  * removal only when the SRC clock covers dst's live dot (awset.go:152).

Canonical form: dot lanes are zeroed where absent so merged states are
bitwise-comparable with packed spec states.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.models.awset import AWSetState
from go_crdt_playground_tpu.ops.vv import has_dot, vv_join

# Merge-decision outcome labels — the five labels of the reference's
# logOutcome tracing (awset.go:126-156), as tensor codes (SURVEY §5.1).
OUTCOME_NONE = 0
OUTCOME_UPDATE = 1   # present both sides, dots differ (awset.go:126)
OUTCOME_KEEP = 2     # awset.go:128, 148, 156
OUTCOME_SKIP = 3     # dst clock covers unseen entry (awset.go:134)
OUTCOME_ADD = 4      # genuinely new to dst (awset.go:139)
OUTCOME_REMOVE = 5   # src witnessed and dropped (awset.go:153)


class MergeTrace(NamedTuple):
    """Per-element decision tensors (uint8[..., E]) for the two phases.
    Array-comparable replacement for the reference's stdout tracing, whose
    line order is nondeterministic Go map iteration (SURVEY §5.1)."""

    phase1: jnp.ndarray
    phase2: jnp.ndarray


def merge_kernel(
    dst_vv: jnp.ndarray,       # uint32[A]
    dst_present: jnp.ndarray,  # bool[E]
    dst_da: jnp.ndarray,       # uint32[E]
    dst_dc: jnp.ndarray,       # uint32[E]
    src_vv: jnp.ndarray,
    src_present: jnp.ndarray,
    src_da: jnp.ndarray,
    src_dc: jnp.ndarray,
    with_trace: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
           Optional[MergeTrace]]:
    """One replica-pair merge ``dst <- src`` as closed-form masks."""
    # HasDot gathers (awset.go:133 / :152 via crdt-misc.go:28-34)
    seen_by_dst = has_dot(dst_vv, src_da, src_dc)   # dst clock covers src dot
    seen_by_src = has_dot(src_vv, dst_da, dst_dc)   # src clock covers dst dot

    # phase 1: lanes that end up carrying the src dot — present on both
    # (unconditional overwrite, awset.go:142) or src-only-and-unseen (add).
    take_src = src_present & (dst_present | ~seen_by_dst)
    # phase 2: dst-only lanes removed iff src witnessed them (awset.go:152-154)
    remove = dst_present & ~src_present & seen_by_src

    present = take_src | (dst_present & ~src_present & ~seen_by_src)
    da = jnp.where(take_src, src_da, dst_da)
    dc = jnp.where(take_src, src_dc, dst_dc)
    # canonical form: zero dots on absent lanes
    da = jnp.where(present, da, 0)
    dc = jnp.where(present, dc, 0)
    vv = vv_join(dst_vv, src_vv)  # awset.go:160

    trace = None
    if with_trace:
        both = dst_present & src_present
        p1 = jnp.where(
            both & (dst_da != src_da) | both & (dst_dc != src_dc),
            OUTCOME_UPDATE,
            jnp.where(
                both,
                OUTCOME_KEEP,
                jnp.where(
                    src_present & seen_by_dst,
                    OUTCOME_SKIP,
                    jnp.where(src_present, OUTCOME_ADD, OUTCOME_NONE),
                ),
            ),
        ).astype(jnp.uint8)
        present1 = dst_present | (src_present & ~seen_by_dst)
        p2 = jnp.where(
            present1 & remove,
            OUTCOME_REMOVE,
            jnp.where(present1, OUTCOME_KEEP, OUTCOME_NONE),
        ).astype(jnp.uint8)
        trace = MergeTrace(phase1=p1, phase2=p2)
    return vv, present, da, dc, trace


def _merge_state_arrays(dst: AWSetState, src: AWSetState, with_trace: bool):
    vv, present, da, dc, trace = merge_kernel(
        dst.vv, dst.present, dst.dot_actor, dst.dot_counter,
        src.vv, src.present, src.dot_actor, src.dot_counter,
        with_trace=with_trace,
    )
    return AWSetState(vv=vv, present=present, dot_actor=da, dot_counter=dc,
                      actor=dst.actor), trace


def merge_pairwise(dst: AWSetState, src: AWSetState,
                   with_trace: bool = False):
    """Batched ``dst[r] <- src[r]`` for every replica r (vmapped pair
    merge).  ``src`` is typically a permuted view of the same batch — the
    gossip pattern of parallel/gossip.py — or an independent batch.

    Returns (merged AWSetState, Optional[MergeTrace])."""
    merged, trace = jax.vmap(
        lambda d, s: _merge_state_arrays(d, s, with_trace),
        in_axes=(0, 0),
    )(dst, src)
    return merged, trace


merge_pairwise_jit = jax.jit(merge_pairwise, static_argnames=("with_trace",))


def _sample_awset(rng, n: int, n_ops: int) -> AWSetState:
    """Reachable AWSet rows for the lattice-law gate: seeded random
    adds/deletes plus gossip mixing through the merge itself.

    Single-add-per-element ownership: a RE-add while a stale copy of the
    element's earlier dot is still circulating exercises the reference's
    unconditional stale-dot overwrite (awset.go:142, pinned in
    tests/test_spec_conformance.py), which is order-sensitive by
    documented design — the laws are promised over the single-dot
    regime, the same one every soak workload (disjoint per-node element
    ranges) runs in."""
    from go_crdt_playground_tpu.models import awset
    from go_crdt_playground_tpu.ops import lattices

    n_elems = 8
    state = awset.init(n, n_elems, n)
    join = lambda d, s: merge_pairwise(d, s)[0]  # noqa: E731
    unadded = list(range(n_elems))
    for _ in range(n_ops):
        roll = rng.random()
        if roll < 0.35 and unadded:
            e = unadded.pop(int(rng.integers(len(unadded))))
            state = awset.add_element(state, jnp.uint32(e % n),
                                     jnp.uint32(e))
        elif roll < 0.55:
            state = awset.del_element(state, jnp.uint32(rng.integers(n)),
                                      jnp.uint32(rng.integers(n_elems)))
        else:
            state = lattices.mix_rows(join, state, rng)
    return state


def _register_awset_join() -> None:
    import numpy as np

    from go_crdt_playground_tpu.ops import lattices

    lattices.register_join(lattices.JoinSpec(
        "awset_merge", _sample_awset,
        lambda d, s: merge_pairwise(d, s)[0],
        # observable projection only: dot metadata is order-sensitive by
        # documented design (stale-dot overwrite) — the same exclusion
        # the crash soak's convergence digest makes
        lambda s: {"vv": np.asarray(s.vv),
                   "present": np.asarray(s.present)}))


_register_awset_join()


def merge_one_into(dst: AWSetState, r_dst, src: AWSetState, r_src,
                   with_trace: bool = False):
    """Scenario-style single merge: replica ``r_dst`` of ``dst`` absorbs
    replica ``r_src`` of ``src`` (the direct method call of the reference's
    simulation harness, awset_test.go:16-17)."""
    d = jax.tree.map(lambda x: x[r_dst], dst)
    s = jax.tree.map(lambda x: x[r_src], src)
    merged, trace = _merge_state_arrays(d, s, with_trace)
    out = AWSetState(
        vv=dst.vv.at[r_dst].set(merged.vv),
        present=dst.present.at[r_dst].set(merged.present),
        dot_actor=dst.dot_actor.at[r_dst].set(merged.dot_actor),
        dot_counter=dst.dot_counter.at[r_dst].set(merged.dot_counter),
        actor=dst.actor,
    )
    return out, trace
