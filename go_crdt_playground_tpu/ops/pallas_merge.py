"""Fused Pallas TPU kernel for the AWSet gossip round.

The XLA path (ops/merge.py + parallel/gossip.py) lowers the round as a
row gather (``state[perm]``) feeding a handful of elementwise fusions,
with ``HasDot`` via TPU's native gather engine.  This kernel fuses the
whole round — partner-row gather, both ``HasDot`` lookups, the two-phase
merge select, and the VV join — into ONE pass over HBM:

  * the gossip permutation rides in as a **scalar-prefetch** operand, so
    each grid step DMAs its partner row ``perm[r]`` straight out of the
    source arrays — the permuted copy of the state is never materialized;
  * ``HasDot`` (crdt-misc.go:28-34) is computed on the **MXU** as an
    exact one-hot matvec: ``cnt = vv @ onehot(dot_actor)`` with the
    uint32 counters split into hi/lo 16-bit halves so every f32 product
    is exact (one-hot rows sum a single term < 2^16);
  * the merge itself is the same closed-form mask algebra as
    ops/merge.py (awset.go:107-161, SURVEY §7.2), on the VPU;
  * the element axis is processed in VMEM-sized tiles (blockwise over
    ``E``), so element universes far beyond VMEM stream through.

Semantics are bit-identical to ``ops.merge.merge_kernel`` — the
conformance gate in tests/test_pallas_merge.py checks bitwise equality
against the XLA kernel (and transitively against the executable spec).

Layout contract: grid is ``(R, E_pad // block_e)`` with one replica row
per step; row blocks are ``(1, block_e)``.  ``E`` and ``A`` are padded
to lane multiples with absent/zero lanes, which is semantically inert:
a zero dot on an absent lane is "covered by every clock" and the lane's
``present`` bits are False on both sides, so every padded lane resolves
to absent (same canonical zeroing as ops/merge.py).

Measured regime guidance (v5e 1x1, R=10K, E=A=256, honest scan-timed
rounds — warm BOTH fit counts before timing, and the sync scalar must
consume every output or XLA dead-codes the dot/membership computation
and the number measures only the VV join):
  * XLA path: ~56ms/round — the elementwise HasDot gather
    (take_along_axis with [R, E] indices) hits a pathological lowering
    inside compiled loops; the VV-join chain alone runs at roofline
    (~45us/round), so the gather is ~99% of the cost.
  * this one-row kernel: ~2.4ms/round (grid overhead, ~240ns x R steps).
  * 8-row blocks + one-hot MXU HasDot (the round-2 production path):
    ~1.37ms/round (7.3M merges/s) — ~9x off the streaming bound; the
    O(A x E) one-hot selector materialization dominated.
  * 64-row blocks + native lane-gather HasDot, XLA partner gather
    (pallas_gossip_round_rows): ~0.37ms/round (26.7M merges/s).
  * ring-fused, windowed partner reads (lo+hi block pair):
    ~0.22ms/round (45.4M merges/s).
  * ring-fused + aligned single-src-block dispatch (offset % 64 == 0
    rounds read ONE partner block; most of a dissemination schedule):
    0.123ms/round (82.0M merges/s, BENCH_LADDER r4) — the production
    path.
HBM roofline at this config (state = R x 3.3KB = 33.4MB/array-set):
an aligned round moves read dst 33.4 + read partner 33.4 + write 33.4
= 100.3MB, which at the v5e spec bandwidth (819GB/s) is 0.1225ms —
the measured 0.1226ms/round (dissemination-mix average, 8/14 rounds
aligned) sits AT that bound; windowed rounds move 133.8MB so the true
mixed bound is ~0.137ms, i.e. the measurement is ~0.9x of the traffic
model.  Residual uncertainty is now in the model (achieved-vs-spec
bandwidth, possible cross-step block reuse), not in kernel overhead:
the round-3 1.33x residue is closed.
The one-row variant remains for huge-E/modest-R streaming (row state
>> VMEM) and as the scalar-prefetch reference; tests pin bitwise
equality across all paths, so schedulers may pick per shape freely.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from go_crdt_playground_tpu.models.awset import AWSetState

_LANE = 128


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _exact_u32_onehot_dot(values: jnp.ndarray,
                          onehot_f32: jnp.ndarray) -> jnp.ndarray:
    """uint32[M, K] x one-hot f32[K, N] -> uint32[M, N] on the MXU,
    exact over the full uint32 range: each output sums exactly one
    surviving term and both 16-bit halves are < 2^16 <= 2^24, so the
    f32 accumulation is exact.  (Mosaic has no u32<->f32 casts; both
    halves round-trip value-preservingly through an i32 bitcast.)"""
    as_i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)  # noqa: E731
    hi = as_i32(values >> 16).astype(jnp.float32)
    lo = as_i32(values & 0xFFFF).astype(jnp.float32)
    cnt_hi = jnp.dot(hi, onehot_f32, preferred_element_type=jnp.float32)
    cnt_lo = jnp.dot(lo, onehot_f32, preferred_element_type=jnp.float32)
    cnt = (cnt_hi.astype(jnp.int32) << 16) | cnt_lo.astype(jnp.int32)
    return jax.lax.bitcast_convert_type(cnt, jnp.uint32)


def _gather_counter(vv: jnp.ndarray, da: jnp.ndarray) -> jnp.ndarray:
    """``vv[0, da[0, e]]`` for every lane e — HasDot's clock lookup
    (crdt-misc.go:33) as an exact one-hot matvec on the MXU.

    vv: uint32[1, A]; da: uint32[1, E] with values < A.  Returns
    uint32[1, E].
    """
    a_pad, e_blk = vv.shape[1], da.shape[1]
    a_ids = jax.lax.broadcasted_iota(jnp.uint32, (a_pad, e_blk), 0)
    onehot = (a_ids == jnp.broadcast_to(da, (a_pad, e_blk))).astype(
        jnp.float32)
    return _exact_u32_onehot_dot(vv, onehot)


def _round_kernel(perm_ref, dvv_ref, svv_ref, dp_ref, sp_ref,
                  dda_ref, sda_ref, ddc_ref, sdc_ref,
                  ovv_ref, op_ref, oda_ref, odc_ref):
    del perm_ref  # consumed by the index maps
    # row blocks are (1, 1, X) — Mosaic requires the sublane dim of a
    # block to be 8-divisible or the full array dim, so the replica axis
    # is lifted to a leading grid-only dim and blocks drop to [1, X] here
    dvv, svv = dvv_ref[0], svv_ref[0]
    dp = dp_ref[0] != 0
    sp = sp_ref[0] != 0
    dda, sda = dda_ref[0], sda_ref[0]
    ddc, sdc = ddc_ref[0], sdc_ref[0]

    # HasDot gathers (awset.go:133 / :152)
    seen_by_dst = sdc <= _gather_counter(dvv, sda)
    seen_by_src = ddc <= _gather_counter(svv, dda)

    # two-phase merge as closed-form masks (awset.go:122-159, SURVEY §7.2)
    take_src = sp & (dp | ~seen_by_dst)
    present = take_src | (dp & ~sp & ~seen_by_src)
    da = jnp.where(take_src, sda, dda)
    dc = jnp.where(take_src, sdc, ddc)
    zero = jnp.zeros_like(da)
    oda_ref[0] = jnp.where(present, da, zero)
    odc_ref[0] = jnp.where(present, dc, zero)
    op_ref[0] = present.astype(jnp.uint8)
    # VV join (crdt-misc.go:43-55); Mosaic can't legalize unsigned max,
    # so spell it as compare+select
    ovv_ref[0] = jnp.where(dvv < svv, svv, dvv)


def _pad_arrays(vv, present_u8, da, dc, e_pad, a_pad):
    num_r, num_e = da.shape
    num_a = vv.shape[1]
    if e_pad != num_e:
        pad = ((0, 0), (0, e_pad - num_e))
        present_u8 = jnp.pad(present_u8, pad)
        da = jnp.pad(da, pad)
        dc = jnp.pad(dc, pad)
    if a_pad != num_a:
        vv = jnp.pad(vv, ((0, 0), (0, a_pad - num_a)))
    # lift the replica axis out of the tile: arrays become [R, 1, X] so
    # row blocks are (1, 1, X) and the tiled dims are (1, X)
    return (vv.reshape(num_r, 1, a_pad),
            present_u8.reshape(num_r, 1, e_pad),
            da.reshape(num_r, 1, e_pad),
            dc.reshape(num_r, 1, e_pad))


@functools.partial(
    jax.jit, static_argnames=("block_e", "interpret"))
def _fused_round(dst_arrays, src_arrays, perm, block_e: int,
                 interpret: bool):
    """dst/src are (vv, present_u8, da, dc) tuples; src may be the same
    arrays as dst (gossip: perm indexes the batch itself) or an
    independent batch of the same shape (pairwise merge)."""
    num_r, num_e = dst_arrays[2].shape
    num_a = dst_arrays[0].shape[1]
    e_pad = _round_up(num_e, _LANE)
    a_pad = _round_up(num_a, _LANE)
    blk = min(_round_up(block_e, _LANE), e_pad)
    while e_pad % blk:  # keep the grid exact; blk stays a lane multiple
        blk -= _LANE
    grid = (num_r, e_pad // blk)

    vv, present_u8, da, dc = _pad_arrays(*dst_arrays, e_pad, a_pad)
    svv, spresent_u8, sda, sdc = _pad_arrays(*src_arrays, e_pad, a_pad)

    def dst_el(i, j, perm_ref):
        del perm_ref
        return (i, 0, j)

    def src_el(i, j, perm_ref):
        return (perm_ref[i], 0, j)

    def dst_vv(i, j, perm_ref):
        del j, perm_ref
        return (i, 0, 0)

    def src_vv(i, j, perm_ref):
        del j
        return (perm_ref[i], 0, 0)

    vv_blk = pl.BlockSpec((1, 1, a_pad), dst_vv)
    vv_src_blk = pl.BlockSpec((1, 1, a_pad), src_vv)
    el = lambda: pl.BlockSpec((1, 1, blk), dst_el)       # noqa: E731
    el_src = lambda: pl.BlockSpec((1, 1, blk), src_el)   # noqa: E731

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[vv_blk, vv_src_blk, el(), el_src(), el(), el_src(),
                  el(), el_src()],
        out_specs=[vv_blk, el(), el(), el()],
    )
    out_vv, out_p, out_da, out_dc = pl.pallas_call(
        _round_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((num_r, 1, a_pad), jnp.uint32),
            jax.ShapeDtypeStruct((num_r, 1, e_pad), jnp.uint8),
            jax.ShapeDtypeStruct((num_r, 1, e_pad), jnp.uint32),
            jax.ShapeDtypeStruct((num_r, 1, e_pad), jnp.uint32),
        ],
        interpret=interpret,
    )(perm.astype(jnp.int32), vv, svv, present_u8, spresent_u8,
      da, sda, dc, sdc)
    return (out_vv[:, 0, :num_a], out_p[:, 0, :num_e],
            out_da[:, 0, :num_e], out_dc[:, 0, :num_e])


def _as_arrays(state: AWSetState):
    return (state.vv, state.present.astype(jnp.uint8), state.dot_actor,
            state.dot_counter)


def pallas_gossip_round(state: AWSetState, perm, *, block_e: int = 512,
                        interpret: bool | None = None) -> AWSetState:
    """One fused anti-entropy round: replica r absorbs replica perm[r].

    Drop-in equivalent of ``parallel.gossip.gossip_round`` (bitwise-equal
    output), with the partner-row gather fused into the kernel's DMA
    schedule instead of materialized.  ``interpret=None`` auto-selects
    interpreter mode off-TPU so the CPU test mesh can run it.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    arrays = _as_arrays(state)
    vv, p, da, dc = _fused_round(arrays, arrays, perm, block_e, interpret)
    return AWSetState(vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
                      actor=state.actor)


def pallas_merge_pairwise(dst: AWSetState, src: AWSetState, *,
                          block_e: int = 512,
                          interpret: bool | None = None) -> AWSetState:
    """Batched dst[r] <- src[r] between two independent batches (the
    fused analogue of ops.merge.merge_pairwise): the src batch rides in
    as the kernel's source operands with an identity permutation."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    num_r = dst.present.shape[0]
    perm = jnp.arange(num_r, dtype=jnp.int32)
    vv, p, da, dc = _fused_round(
        _as_arrays(dst), _as_arrays(src), perm, block_e, interpret)
    return AWSetState(vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
                      actor=dst.actor)


# ---------------------------------------------------------------------------
# Multi-row variant: the production gossip path
# ---------------------------------------------------------------------------
#
# The one-row-per-grid-step layout above pays ~240ns of grid overhead per
# replica — 2.4ms/round at R=10K, dwarfing the ~0.15ms of HBM traffic.
# This variant amortizes it 8 rows at a time (Mosaic's sublane rule: the
# block's second-minor dim must be 8-divisible), which demotes the
# arbitrary-permutation row gather from the kernel's scalar-prefetch DMA
# to a plain XLA gather BEFORE the kernel: partner rows of one 8-row
# block aren't contiguous under a general perm, but the XLA row gather
# runs at HBM bandwidth (it is the vv-join chain's own layout), so the
# split costs one extra state read and removes ~85% of the grid steps.


def gather_rows(vv: jnp.ndarray, da: jnp.ndarray) -> jnp.ndarray:
    """In-kernel HasDot gather, multi-row: cnt[r, e] = vv[r, da[r, e]].

    Mosaic lowers ``jnp.take_along_axis`` to the VPU's native lane
    gather, but ONLY for operands exactly one lane group (128) wide —
    wider shapes crash the compiler (probed empirically on v5e).  So the
    gather runs per (128-lane A-chunk x 128-lane E-slice): chunk c
    serves the lanes whose actor id lives in [128c, 128(c+1)), selected
    by mask.  O(A/128 x E) VPU work per row block — ~A/128 elementwise
    passes — which replaces the previous one-hot MXU formulation's
    O(A x E) selector materialization (the 9x-off-roofline culprit at
    A=256, see the regime notes below).

    vv: uint32[blk_r, A (128-multiple)]; da: uint32[blk_r, blk_e]
    -> uint32[blk_r, blk_e]
    """
    blk_r, a_pad = vv.shape
    blk_e = da.shape[1]
    chunk_shift = _LANE.bit_length() - 1   # log2(_LANE): da // _LANE
    out_slices = []
    for e0 in range(0, blk_e, _LANE):
        da_s = jax.lax.slice(da, (0, e0), (blk_r, e0 + _LANE))
        idx = da_s & jnp.uint32(_LANE - 1)     # in-chunk lane, all chunks
        chunk = da_s >> chunk_shift
        cnt = jnp.zeros((blk_r, _LANE), jnp.uint32)
        for c in range(a_pad // _LANE):
            vv_c = jax.lax.slice(vv, (0, c * _LANE),
                                 (blk_r, (c + 1) * _LANE))
            g = jnp.take_along_axis(vv_c, idx, axis=1)
            cnt = jnp.where(chunk == c, g, cnt)
        out_slices.append(cnt)
    if len(out_slices) == 1:
        return out_slices[0]
    return jnp.concatenate(out_slices, axis=1)


def _merge_algebra(dvv, svv, dp_u8, sp_u8, dda, sda, ddc, sdc):
    """The two-phase merge as closed-form masks on value blocks
    (awset.go:122-159, SURVEY §7.2) — shared by the gather-path and
    ring-path multi-row kernels so the bitwise-pinned semantics live in
    exactly one place.  Returns (vv, present_u8, dot_actor,
    dot_counter)."""
    dp, sp = dp_u8 != 0, sp_u8 != 0
    seen_by_dst = sdc <= gather_rows(dvv, sda)
    seen_by_src = ddc <= gather_rows(svv, dda)
    take_src = sp & (dp | ~seen_by_dst)
    present = take_src | (dp & ~sp & ~seen_by_src)
    da = jnp.where(take_src, sda, dda)
    dc = jnp.where(take_src, sdc, ddc)
    zero = jnp.zeros_like(da)
    # VV join (crdt-misc.go:43-55); Mosaic can't legalize unsigned max,
    # so spell it as compare+select
    return (jnp.where(dvv < svv, svv, dvv),
            present.astype(jnp.uint8),
            jnp.where(present, da, zero),
            jnp.where(present, dc, zero))


def _rows_kernel(dvv_ref, svv_ref, dp_ref, sp_ref, dda_ref, sda_ref,
                 ddc_ref, sdc_ref, ovv_ref, op_ref, oda_ref, odc_ref):
    outs = _merge_algebra(dvv_ref[...], svv_ref[...], dp_ref[...],
                          sp_ref[...], dda_ref[...], sda_ref[...],
                          ddc_ref[...], sdc_ref[...])
    for ref, val in zip((ovv_ref, op_ref, oda_ref, odc_ref), outs):
        ref[...] = val


# 64 rows per grid step: large enough that the ~µs-order per-step grid
# overhead amortizes to noise (the previous 8-row blocks left the kernel
# ~9x off its own HBM streaming bound at R=10K — grid steps, not bytes,
# dominated), small enough that a full operand set stays ~2MB of VMEM.
# Mosaic's sublane rule (second-minor block dim 8-divisible) holds.
_BLOCK_R = 64

# VMEM budget for one grid step's operand blocks (in + out).  The
# gather-based HasDot materializes nothing beyond the operands, so this
# is the only sizing constraint left.
_VMEM_BUDGET_BYTES = 8 << 20

# Worst-case block counts across every kernel this layout sizes: the
# ring δ kernel holds 8 A-shaped blocks (vv/processed x dst+lo+hi+out)
# and 24 E-shaped blocks (6 arrays x dst+lo+hi+out).
_A_BLOCKS_WORST = 8
_E_BLOCKS_WORST = 24

# Actor-axis cap for the fused row kernels: the A-shaped blocks alone
# must leave room for at least one lane group of E-blocks within the
# budget (2048 -> 4MB of A-blocks at _BLOCK_R=64); beyond it, use the
# XLA path.
MAX_FUSED_ACTORS = 2048


def row_block_layout(num_r: int, num_e: int, num_a: int, block_e: int):
    """Padded dims + element block size for the multi-row kernels:
    (r_pad, e_pad, a_pad, blk).  blk is a lane multiple that divides
    e_pad and keeps one grid step's operand blocks within the VMEM
    budget."""
    e_pad = _round_up(num_e, _LANE)
    a_pad = _round_up(num_a, _LANE)
    r_pad = _round_up(num_r, _BLOCK_R)
    if num_a > MAX_FUSED_ACTORS:
        raise ValueError(
            f"actor axis A={num_a} too large for the fused row kernels "
            f"(cap {MAX_FUSED_ACTORS}); use the XLA path")
    budget_blk = (
        _VMEM_BUDGET_BYTES - _A_BLOCKS_WORST * _BLOCK_R * a_pad * 4
    ) // (_E_BLOCKS_WORST * _BLOCK_R * 4)
    blk = max(_LANE, min(_round_up(block_e, _LANE), e_pad,
                         budget_blk // _LANE * _LANE))
    while e_pad % blk:
        blk -= _LANE
    return r_pad, e_pad, a_pad, blk


@functools.partial(jax.jit, static_argnames=("block_e", "interpret"))
def _fused_rows(dst_arrays, src_arrays, block_e: int, interpret: bool):
    num_r, num_e = dst_arrays[2].shape
    num_a = dst_arrays[0].shape[1]
    r_pad, e_pad, a_pad, blk = row_block_layout(num_r, num_e, num_a,
                                                block_e)

    def pad(arrays):
        vv, p_u8, da, dc = arrays
        pe = ((0, r_pad - num_r), (0, e_pad - num_e))
        pa = ((0, r_pad - num_r), (0, a_pad - num_a))
        return (jnp.pad(vv, pa), jnp.pad(p_u8, pe), jnp.pad(da, pe),
                jnp.pad(dc, pe))

    vv, p_u8, da, dc = pad(dst_arrays)
    svv, sp_u8, sda, sdc = pad(src_arrays)
    grid = (r_pad // _BLOCK_R, e_pad // blk)

    vv_blk = pl.BlockSpec((_BLOCK_R, a_pad), lambda i, j: (i, 0))
    el_blk = pl.BlockSpec((_BLOCK_R, blk), lambda i, j: (i, j))
    out_vv, out_p, out_da, out_dc = pl.pallas_call(
        _rows_kernel,
        grid=grid,
        in_specs=[vv_blk, vv_blk, el_blk, el_blk, el_blk, el_blk,
                  el_blk, el_blk],
        out_specs=[vv_blk, el_blk, el_blk, el_blk],
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, a_pad), jnp.uint32),
            jax.ShapeDtypeStruct((r_pad, e_pad), jnp.uint8),
            jax.ShapeDtypeStruct((r_pad, e_pad), jnp.uint32),
            jax.ShapeDtypeStruct((r_pad, e_pad), jnp.uint32),
        ],
        interpret=interpret,
    )(vv, svv, p_u8, sp_u8, da, sda, dc, sdc)
    return (out_vv[:num_r, :num_a], out_p[:num_r, :num_e],
            out_da[:num_r, :num_e], out_dc[:num_r, :num_e])


def pallas_merge_pairwise_rows(dst: AWSetState, src: AWSetState, *,
                               block_e: int = 512,
                               interpret: bool | None = None) -> AWSetState:
    """Batched dst[r] <- src[r] on the multi-row kernel — the pairwise
    (no-gather) form of pallas_gossip_round_rows, bitwise-equal to
    ops.merge.merge_pairwise.  This is the per-shard merge primitive for
    shard_map rings: the partner block arrives by ppermute, so the kernel
    needs no permutation at all and every grid step reads contiguous
    rows."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    vv, p, da, dc = _fused_rows(_as_arrays(dst), _as_arrays(src),
                                block_e, interpret)
    return AWSetState(vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
                      actor=dst.actor)


# ---------------------------------------------------------------------------
# Bitpacked membership (SURVEY §7.1/§7.3 step 5)
# ---------------------------------------------------------------------------
#
# ``present``/``deleted`` as uint32[R, E/32] — 8x less HBM and wire
# traffic than the u8 layout for two of the per-element arrays.  The
# packed form is the STORAGE layout; kernels unpack to bool lanes in
# VMEM (one lane gather + per-lane shift), run the identical merge
# algebra, and repack on the way out (an exact one-hot-weighted matmul:
# each 16-bit half sums < 2^24 so f32 accumulation is exact).

_WORD = 32


def packed_width(num_e: int) -> int:
    """Packed lane count for an element axis: ceil(E/32)."""
    return (num_e + _WORD - 1) // _WORD


def pack_bits(mask) -> jnp.ndarray:
    """bool[R, E] -> uint32[R, ceil(E/32)] (bit e%32 of word e//32).
    XLA-side helper for building/converting packed states."""
    num_r, num_e = mask.shape
    w = packed_width(num_e)
    pad = w * _WORD - num_e
    m = jnp.pad(mask.astype(jnp.uint32), ((0, 0), (0, pad)))
    m = m.reshape(num_r, w, _WORD)
    weights = (jnp.uint32(1) << jnp.arange(_WORD, dtype=jnp.uint32))
    return (m * weights).sum(axis=2, dtype=jnp.uint32)


def unpack_bits(bits, num_e: int) -> jnp.ndarray:
    """uint32[R, ceil(E/32)] -> bool[R, E] (inverse of pack_bits)."""
    num_r, w = bits.shape
    shifts = jnp.arange(_WORD, dtype=jnp.uint32)
    out = (bits[:, :, None] >> shifts[None, None, :]) & 1
    return out.reshape(num_r, w * _WORD)[:, :num_e] != 0


def _kernel_unpack_bits(bits, blk_e: int):
    """In-kernel unpack: uint32[blk_r, W<=128] -> bool[blk_r, blk_e].
    Word lookup is the same native lane gather HasDot uses; the bit
    extract is a per-lane variable shift.

    One lane group of words (W <= 128, i.e. <= 4096 elements) per call
    is an INVARIANT, not a feature cap: beyond one chunk the packed
    kernels tile the element axis into 4096-element j blocks
    (_packed_tiling), so each grid step hands this helper exactly one
    word group."""
    blk_r, w = bits.shape
    if w > _LANE:  # the word gather is one lane group wide
        raise ValueError(
            f"_kernel_unpack_bits is per-chunk (<= {_LANE} words); the "
            f"dispatchers tile larger E via _packed_tiling — got width "
            f"{w}")
    if w < _LANE:  # gather operands must be exactly one lane group wide
        bits = jnp.concatenate(
            [bits, jnp.zeros((blk_r, _LANE - w), jnp.uint32)], axis=1)
    out = []
    for e0 in range(0, blk_e, _LANE):
        lane = jax.lax.broadcasted_iota(jnp.uint32, (blk_r, _LANE), 1)
        eids = lane + jnp.uint32(e0)
        word = jnp.take_along_axis(bits, eids >> 5, axis=1)
        out.append((word >> (eids & 31)) & 1)
    bit = out[0] if len(out) == 1 else jnp.concatenate(out, axis=1)
    return bit != 0


_PACK_SUB = 2048   # elements per pack sub-step (64 words' worth)


def _kernel_pack_bits(mask_u8, w: int) -> jnp.ndarray:
    """In-kernel repack: uint8/bool[blk_r, blk_e] -> uint32[blk_r, W]
    via exact f32 matmuls (low/high 16 bits of each word; each product
    sums <= 16 terms < 2^16, exact in f32).  Elements are processed in
    _PACK_SUB-wide sub-steps that all share ONE [_PACK_SUB, lane-pad]
    weight pair, keeping the constant-mask VMEM footprint flat however
    wide the block grows — a single 4096-wide weight pair is 4MB of
    scoped VMEM, which pushed the windowed ring form 384KB past the
    v5e 16MB stack limit at blk_e=4096 (real-chip compile OOM)."""
    blk_r, blk_e = mask_u8.shape
    as_i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)  # noqa: E731
    sub_e = min(_round_up(blk_e, 32), _PACK_SUB)
    sub_w = sub_e // 32
    w_pad = _round_up(sub_w, _LANE)
    e_ids = jax.lax.broadcasted_iota(jnp.uint32, (sub_e, w_pad), 0)
    word = jax.lax.broadcasted_iota(jnp.uint32, (sub_e, w_pad), 1)
    in_word = (e_ids >> 5) == word
    bit = e_ids & 31
    w_lo = as_i32(jnp.where(in_word & (bit < 16),
                            jnp.uint32(1) << (bit & 15), 0)
                  ).astype(jnp.float32)
    w_hi = as_i32(jnp.where(in_word & (bit >= 16),
                            jnp.uint32(1) << (bit & 15), 0)
                  ).astype(jnp.float32)
    e_total = _round_up(blk_e, sub_e)
    if e_total != blk_e:   # zero bits pad the ragged tail harmlessly
        mask_u8 = jnp.concatenate(
            [mask_u8,
             jnp.zeros((blk_r, e_total - blk_e), mask_u8.dtype)], axis=1)
    words = []
    for e0 in range(0, e_total, sub_e):
        # Mosaic has no direct uint8->f32 cast; hop through int32 (free
        # on the VPU).
        m = jax.lax.slice(mask_u8, (0, e0), (blk_r, e0 + sub_e)
                          ).astype(jnp.int32).astype(jnp.float32)
        lo = jnp.dot(m, w_lo,
                     preferred_element_type=jnp.float32).astype(jnp.int32)
        hi = jnp.dot(m, w_hi,
                     preferred_element_type=jnp.float32).astype(jnp.int32)
        words.append(jax.lax.slice(
            jax.lax.bitcast_convert_type(lo | (hi << 16), jnp.uint32),
            (0, 0), (blk_r, sub_w)))
    packed = words[0] if len(words) == 1 else jnp.concatenate(words,
                                                              axis=1)
    return jax.lax.slice(packed, (0, 0), (blk_r, w))


# ---------------------------------------------------------------------------
# Ring-fused variant: partner rows via prefetch-driven block index maps
# ---------------------------------------------------------------------------
#
# Every production schedule here is a ring: gossip_round's dissemination
# offsets, the shard_map ICI ring, the north-star convergence loop — all
# pair replica r with (r + offset) mod R.  For a ring the partner rows of
# one 64-row block are CONTIGUOUS (rows [i*64+o, i*64+o+64) mod R), so
# instead of materializing state[perm] with an XLA gather (a full extra
# state copy in HBM — the allocation that OOMed the 1M-replica north
# star: state + gathered src + outputs ~ 3x 6.5GB), the kernel fetches
# the two aligned blocks the window spans via scalar-prefetch block
# index maps and shifts them into place with one dynamic sublane roll.
# The offset rides in as data (an int32[2] = [offset//64, offset%64]
# prefetch operand), so ONE compiled kernel serves every round of a
# dissemination schedule.


_PACK_CHUNK = _LANE * _WORD   # 4096 elements = one 128-lane group of words

# The WINDOWED (3-operand-group) ring form at the tiled blk_e=4096
# double-buffers ~16.8MB of operand/output blocks — 384KB past Mosaic's
# 16MB default scoped-VMEM budget (a compiler flag default, not the
# hardware: physical VMEM is far larger), and the δ twin carries FOUR
# unpacked uint32 E-arrays (~35MB double-buffered).  Raise the
# per-kernel cap for the ring kernels; the aligned (2-group) and
# small-E whole-axis forms never near it.
# jax renamed pltpu.TPUCompilerParams -> CompilerParams; accept either
# so one source serves both API generations (vmem_limit_bytes is spelled
# the same in both).
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams
_RING_VMEM_LIMIT = _COMPILER_PARAMS_CLS(
    vmem_limit_bytes=64 * 1024 * 1024)


def _packed_tiling(e_pad: int, packed_w: int):
    """Element/word tiling for the bitpacked ring kernels: one j step
    per 4096-element chunk (exactly one lane group of words — Pallas
    requires word-axis blocks divisible by the 128-lane width, so this
    is also the smallest legal tiled word block), so the in-kernel
    unpack's native lane gather never spans more than one group — this
    is what lifts the old E <= 4096 packed cap — and VMEM per grid step
    stays bounded however large E grows.  At or below one chunk the
    word axis rides whole (sub-lane word blocks are fine).

    Returns (blk_elements, e_pad, words_per_block, total_words)."""
    if e_pad <= _PACK_CHUNK:
        return e_pad, e_pad, packed_w, packed_w
    e_pad = _round_up(e_pad, _PACK_CHUNK)
    return _PACK_CHUNK, e_pad, _LANE, e_pad // _WORD


def _ring_window(lo, hi, o_mod, interpret: bool):
    """Rows [o_mod, o_mod + _BLOCK_R) of the stacked [2*_BLOCK_R, X]
    pair of adjacent blocks.  pltpu.roll lowers to the native dynamic
    sublane rotate; the interpreter has no rule for it, so interpret
    mode uses the jnp equivalent (identical semantics)."""
    stacked = jnp.concatenate([lo, hi], axis=0)
    roll = jnp.roll if interpret else pltpu.roll
    if stacked.dtype.itemsize != 4:  # Mosaic rotates 32-bit data only
        wide = roll(stacked.astype(jnp.uint32), -o_mod, 0)[:_BLOCK_R]
        return wide.astype(stacked.dtype)
    return roll(stacked, -o_mod, 0)[:_BLOCK_R]


def _ring_src_reader(meta_ref, refs, n_named: int, interpret: bool,
                     aligned: bool):
    """Split a ring kernel's flat ref list into per-name (dst, src)
    value pairs plus the output refs.  Windowed form: groups of
    (dst, lo, hi) with the dynamic roll; aligned form: groups of
    (dst, src) read directly (offset % _BLOCK_R == 0 — the window IS a
    block)."""
    group = 2 if aligned else 3
    ins, outs = refs[:n_named * group], refs[n_named * group:]
    pairs = []
    for k in range(n_named):
        g = ins[group * k: group * k + group]
        d = g[0][...]
        if aligned:
            s = g[1][...]
        else:
            s = _ring_window(g[1][...], g[2][...], o_mod=meta_ref[1],
                             interpret=interpret)
        pairs.append((d, s))
    return pairs, outs


# Dot-word layout: one uint32 per element lane, (actor << _DOT_SHIFT) |
# counter.  12 actor bits cover MAX_FUSED_ACTORS with headroom; 20
# counter bits cap per-actor adds at ~1M (pack_awset_dots guards).  The
# merge algebra only ever compares counters and gathers by actor, so
# shift+mask in VMEM recovers both for free relative to the HBM read of
# a second E-shaped array — the dot arrays are the dominant ring-round
# traffic (2KB of the bool layout's ~3.3KB row).
_DOT_SHIFT = 20
_DOT_CMASK = (1 << _DOT_SHIFT) - 1
DOT_MAX_ACTORS = 1 << (32 - _DOT_SHIFT)
DOT_MAX_COUNTER = _DOT_CMASK


def _make_ring_kernel_dotpacked(interpret: bool, packed_w: int,
                                aligned: bool):
    """Ring kernel on the dot-word layout: operands are vv (A-shaped),
    bitpacked membership (word-shaped), and the packed dot word
    (E-shaped).  Unpacks both in VMEM, runs the bitwise-pinned
    _merge_algebra, repacks on the way out."""
    def kernel(meta_ref, *refs):
        pairs, out_refs = _ring_src_reader(meta_ref, refs, 3, interpret,
                                           aligned)
        (dvv, svv), (dp, sp), (ddot, sdot) = pairs
        blk_e = ddot.shape[-1]
        dp = _kernel_unpack_bits(dp, blk_e).astype(jnp.uint8)
        sp = _kernel_unpack_bits(sp, blk_e).astype(jnp.uint8)
        cmask = jnp.uint32(_DOT_CMASK)
        vv, p_u8, da, dc = _merge_algebra(
            dvv, svv, dp, sp, ddot >> _DOT_SHIFT, sdot >> _DOT_SHIFT,
            ddot & cmask, sdot & cmask)
        ovv_ref, op_ref, odot_ref = out_refs
        ovv_ref[...] = vv
        op_ref[...] = _kernel_pack_bits(p_u8, packed_w)
        odot_ref[...] = (da << _DOT_SHIFT) | dc

    return kernel


def _make_ring_kernel(interpret: bool, packed_w: int = 0,
                      aligned: bool = False):
    """packed_w > 0: the membership operand/output is bitpacked
    uint32[blk_r, packed_w]; unpack after windowing, repack before
    writing.  aligned: single-src-block form (see ring_block_specs)."""
    def kernel(meta_ref, *refs):
        pairs, out_refs = _ring_src_reader(meta_ref, refs, 4, interpret,
                                           aligned)
        (dvv, svv), (dp, sp), (dda, sda), (ddc, sdc) = pairs
        if packed_w:
            blk_e = dda.shape[-1]
            dp = _kernel_unpack_bits(dp, blk_e).astype(jnp.uint8)
            sp = _kernel_unpack_bits(sp, blk_e).astype(jnp.uint8)
        vv, p_u8, da, dc = _merge_algebra(dvv, svv, dp, sp, dda, sda,
                                          ddc, sdc)
        ovv_ref, op_ref, oda_ref, odc_ref = out_refs
        ovv_ref[...] = vv
        op_ref[...] = _kernel_pack_bits(p_u8, packed_w) if packed_w else p_u8
        oda_ref[...] = da
        odc_ref[...] = dc

    return kernel


def ring_block_specs(nb: int, blk: int, a_pad: int, a_named: int,
                     e_named: int, aligned: bool = False):
    """(in_specs, out_specs) for a ring-fused kernel: per A-shaped array
    one dst block + the partner block(s), likewise per E-shaped array;
    outputs are dst-aligned.  Block index maps read the prefetched
    [offset//_BLOCK_R, offset%_BLOCK_R] meta operand.

    aligned=True emits the block-aligned-offset form: ONE partner block
    per array (the window is exactly a block when offset % _BLOCK_R
    == 0), cutting the round's src traffic in half — from 2x state to
    1x — on the aligned rounds, which at fleet scale is most of a
    dissemination schedule (every offset >= _BLOCK_R is a multiple)."""
    def dst_a(i, j, meta):
        del j, meta
        return (i, 0)

    def src_a_lo(i, j, meta):
        del j
        return ((i + meta[0]) % nb, 0)

    def src_a_hi(i, j, meta):
        del j
        return ((i + meta[0] + 1) % nb, 0)

    def dst_e(i, j, meta):
        del meta
        return (i, j)

    def src_e_lo(i, j, meta):
        return ((i + meta[0]) % nb, j)

    def src_e_hi(i, j, meta):
        return ((i + meta[0] + 1) % nb, j)

    a_blk = lambda m: pl.BlockSpec((_BLOCK_R, a_pad), m)   # noqa: E731
    e_blk = lambda m: pl.BlockSpec((_BLOCK_R, blk), m)     # noqa: E731
    a_group = ([a_blk(dst_a), a_blk(src_a_lo)] if aligned
               else [a_blk(dst_a), a_blk(src_a_lo), a_blk(src_a_hi)])
    e_group = ([e_blk(dst_e), e_blk(src_e_lo)] if aligned
               else [e_blk(dst_e), e_blk(src_e_lo), e_blk(src_e_hi)])
    in_specs = a_group * a_named + e_group * e_named
    out_specs = [a_blk(dst_a)] * a_named + [e_blk(dst_e)] * e_named
    return in_specs, out_specs


def ring_supported(num_r: int) -> bool:
    """The ring-fused kernels need whole aligned blocks on both sides of
    the window: an exact multiple of _BLOCK_R rows and at least two
    blocks."""
    return num_r % _BLOCK_R == 0 and num_r >= 2 * _BLOCK_R


def ring_meta(offset, num_r: int) -> jnp.ndarray:
    """The scalar-prefetch operand the ring kernels' index maps and
    window roll consume: int32[2] = [offset // _BLOCK_R (whole blocks),
    offset % _BLOCK_R (intra-window roll)].  Load-bearing for
    ring_block_specs — every ring kernel must build it here."""
    offset = offset % num_r
    return jnp.stack([offset // _BLOCK_R, offset % _BLOCK_R]).astype(
        jnp.int32)


@functools.partial(jax.jit, static_argnames=("block_e", "interpret",
                                             "packed_w", "aligned"))
def _fused_rows_ring(dst_arrays, offset, block_e: int, interpret: bool,
                     packed_w: int = 0, aligned: bool = False):
    """dst_arrays: (vv, present, da, dc) — present as uint8[R, E], or
    bitpacked uint32[R, packed_w] when packed_w > 0 (the element grid
    then tiles in 4096-element chunks, one lane group of words each —
    _packed_tiling — so each j step unpacks/repacks one word group and
    E is bounded by HBM, not the gather lane width).  aligned=True is
    the single-src-block form, correct ONLY when offset % _BLOCK_R == 0
    (callers dispatch via _ring_round_dispatch)."""
    num_r, num_e = dst_arrays[2].shape
    num_a = dst_arrays[0].shape[1]
    r_pad, e_pad, a_pad, blk = row_block_layout(num_r, num_e, num_a,
                                                block_e)
    assert r_pad == num_r, "callers must check ring_supported()"
    w_blk = total_w = packed_w
    if packed_w:
        blk, e_pad, w_blk, total_w = _packed_tiling(e_pad, packed_w)
    nb = num_r // _BLOCK_R
    group = 2 if aligned else 3

    def pad_e(x):
        return jnp.pad(x, ((0, 0), (0, e_pad - num_e)))

    vv, pres, da, dc = dst_arrays
    if a_pad != num_a:
        vv = jnp.pad(vv, ((0, 0), (0, a_pad - num_a)))
    if packed_w:
        if total_w != packed_w:   # word axis padded to whole chunks
            pres = jnp.pad(pres, ((0, 0), (0, total_w - packed_w)))
    else:
        pres = pad_e(pres)
    da, dc = pad_e(da), pad_e(dc)

    meta = ring_meta(offset, num_r)
    in_specs, out_specs = ring_block_specs(nb, blk, a_pad, a_named=1,
                                           e_named=3, aligned=aligned)
    p_shape = jax.ShapeDtypeStruct((num_r, e_pad), jnp.uint8)
    if packed_w:
        b_blk = lambda m: pl.BlockSpec((_BLOCK_R, w_blk), m)  # noqa: E731
        # E-style (i, j) maps for both ins and outs: word block j serves
        # element block j (the grid is multi-j once the word axis tiles)
        maps = [s.index_map for s in in_specs[group:2 * group]]
        in_specs[group:2 * group] = [b_blk(m) for m in maps]
        out_specs[1] = b_blk(maps[0])
        p_shape = jax.ShapeDtypeStruct((num_r, total_w), jnp.uint32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, e_pad // blk),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    ins = [x for arr in (vv, pres, da, dc) for x in (arr,) * group]
    out_vv, out_p, out_da, out_dc = pl.pallas_call(
        _make_ring_kernel(interpret, w_blk, aligned),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((num_r, a_pad), jnp.uint32),
            p_shape,
            jax.ShapeDtypeStruct((num_r, e_pad), jnp.uint32),
            jax.ShapeDtypeStruct((num_r, e_pad), jnp.uint32),
        ],
        interpret=interpret,
        compiler_params=_RING_VMEM_LIMIT,
    )(meta, *ins)
    out_p = out_p[:, :packed_w] if packed_w else out_p[:, :num_e]
    return (out_vv[:, :num_a], out_p,
            out_da[:, :num_e], out_dc[:, :num_e])


def _ring_round_dispatch(arrays, offset, run):
    """Route a ring round to the aligned (single-src-block, half the
    src traffic) or windowed kernel.  Static offsets pick at trace
    time; traced offsets go through lax.cond so one compiled program
    still serves a whole dissemination schedule — both kernel variants
    live in it and the untaken branch costs nothing at run time.  At
    fleet scale most dissemination rounds are aligned (every offset
    >= _BLOCK_R in a doubling schedule is a multiple of it)."""
    if isinstance(offset, (int, np.integer)):
        return run(arrays, offset, offset % _BLOCK_R == 0)
    return jax.lax.cond(
        (offset % _BLOCK_R) == 0,
        lambda a, o: run(a, o, True),
        lambda a, o: run(a, o, False),
        arrays, offset)


def pallas_ring_round_rows(state: AWSetState, offset, *,
                           block_e: int = 512,
                           interpret: bool | None = None) -> AWSetState:
    """One anti-entropy round against partner (r + offset) mod R, fully
    fused: partner rows are read in place via block index maps — no
    materialized ``state[perm]`` copy, so peak HBM is state + outputs
    (vs 3x state for the gather path; what lets the 1M-replica north
    star fit on one chip) and HBM traffic drops by a full state read.
    ``offset`` may be a traced scalar: one compiled program serves every
    offset of a dissemination schedule.  Bitwise-equal to
    ``gossip_round(state, ring_perm(R, offset))``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not ring_supported(state.present.shape[0]):
        from go_crdt_playground_tpu.parallel.gossip import ring_perm

        return pallas_gossip_round_rows(
            state, ring_perm(state.present.shape[0], offset),
            block_e=block_e, interpret=interpret)
    vv, p, da, dc = _ring_round_dispatch(
        _as_arrays(state), offset,
        lambda a, o, al: _fused_rows_ring(a, o, block_e, interpret,
                                          aligned=al))
    return AWSetState(vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
                      actor=state.actor)


def pallas_ring_round_rows_packed(state, offset, *,
                                  interpret: bool | None = None):
    """One fused ring round on the BITPACKED layout
    (models.packed.PackedAWSetState): membership crosses HBM as
    uint32[R, E/32] — 8x less traffic for that array — and is unpacked/
    repacked inside the kernel.  Bitwise-equal (through pack/unpack) to
    pallas_ring_round_rows on the bool layout; pinned by
    tests/test_packed.py."""
    from go_crdt_playground_tpu.models.packed import PackedAWSetState

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not ring_supported(state.present_bits.shape[0]):
        raise ValueError("packed ring kernel needs ring_supported(R); "
                         "unpack and use the bool-layout paths instead")
    w = state.present_bits.shape[1]
    vv, pb, da, dc = _ring_round_dispatch(
        (state.vv, state.present_bits, state.dot_actor,
         state.dot_counter), offset,
        lambda a, o, al: _fused_rows_ring(a, o, 512, interpret,
                                          packed_w=w, aligned=al))
    return PackedAWSetState(vv=vv, present_bits=pb, dot_actor=da,
                            dot_counter=dc, actor=state.actor)


@functools.partial(jax.jit, static_argnames=("interpret", "aligned"))
def _fused_rows_ring_dotpacked(arrays, offset, interpret: bool,
                               aligned: bool = False):
    """Ring round on (vv, present_bits, dots): the dot-word layout's
    E-shaped traffic is ONE uint32 array instead of two, on top of the
    bitpacked membership — ~1.6x less HBM per round than the bool
    layout at A=E=256.  Same block/window machinery as
    _fused_rows_ring."""
    vv, pres_bits, dots = arrays
    num_r, num_e = dots.shape
    num_a = vv.shape[1]
    packed_w = pres_bits.shape[1]
    r_pad, e_pad, a_pad, blk = row_block_layout(num_r, num_e, num_a, 512)
    assert r_pad == num_r, "callers must check ring_supported()"
    blk, e_pad, w_blk, total_w = _packed_tiling(e_pad, packed_w)
    nb = num_r // _BLOCK_R
    group = 2 if aligned else 3
    if a_pad != num_a:
        vv = jnp.pad(vv, ((0, 0), (0, a_pad - num_a)))
    if total_w != packed_w:
        pres_bits = jnp.pad(pres_bits, ((0, 0), (0, total_w - packed_w)))
    dots = jnp.pad(dots, ((0, 0), (0, e_pad - num_e)))

    meta = ring_meta(offset, num_r)
    in_specs, out_specs = ring_block_specs(nb, blk, a_pad, a_named=1,
                                           e_named=2, aligned=aligned)
    # the membership group (e-arrays slot 0) carries word blocks
    b_blk = lambda m: pl.BlockSpec((_BLOCK_R, w_blk), m)  # noqa: E731
    maps = [s.index_map for s in in_specs[group:2 * group]]
    in_specs[group:2 * group] = [b_blk(m) for m in maps]
    out_specs[1] = b_blk(maps[0])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, e_pad // blk),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    ins = [x for arr in (vv, pres_bits, dots) for x in (arr,) * group]
    out_vv, out_p, out_dot = pl.pallas_call(
        _make_ring_kernel_dotpacked(interpret, w_blk, aligned),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((num_r, a_pad), jnp.uint32),
            jax.ShapeDtypeStruct((num_r, total_w), jnp.uint32),
            jax.ShapeDtypeStruct((num_r, e_pad), jnp.uint32),
        ],
        interpret=interpret,
        compiler_params=_RING_VMEM_LIMIT,
    )(meta, *ins)
    return (out_vv[:, :num_a], out_p[:, :packed_w], out_dot[:, :num_e])


def pallas_ring_round_rows_dotpacked(state, offset, *,
                                     interpret: bool | None = None):
    """One fused ring round on the DOT-WORD layout
    (models.packed.DotPackedAWSetState): membership bitpacked AND the
    (actor, counter) dot fused into one uint32 word per element, so a
    round streams one E-shaped array where the bool layout streams two
    E-shaped uint32 arrays plus a byte mask.  Bitwise-equal (through
    pack/unpack) to pallas_ring_round_rows; pinned by
    tests/test_packed.py."""
    from go_crdt_playground_tpu.models.packed import DotPackedAWSetState

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not ring_supported(state.present_bits.shape[0]):
        raise ValueError("dot-packed ring kernel needs "
                         "ring_supported(R); unpack and use the "
                         "bool-layout paths instead")
    vv, pb, dots = _ring_round_dispatch(
        (state.vv, state.present_bits, state.dots), offset,
        lambda a, o, al: _fused_rows_ring_dotpacked(a, o, interpret,
                                                    aligned=al))
    return DotPackedAWSetState(vv=vv, present_bits=pb, dots=dots,
                               actor=state.actor)


def pallas_gossip_round_rows(state: AWSetState, perm, *,
                             block_e: int = 512,
                             interpret: bool | None = None) -> AWSetState:
    """One anti-entropy round on the multi-row kernel: partner rows are
    gathered by XLA at HBM bandwidth, then 8 replica rows merge per grid
    step.  Bitwise-equal to gossip_round / pallas_gossip_round; ~5x
    faster than the one-row kernel at large R (the production TPU path —
    parallel.gossip.gossip_round dispatches here on TPU backends).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    src = jax.tree.map(lambda x: x[perm], state)
    vv, p, da, dc = _fused_rows(_as_arrays(state), _as_arrays(src),
                                block_e, interpret)
    return AWSetState(vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
                      actor=state.actor)
