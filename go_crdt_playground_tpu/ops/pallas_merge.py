"""Fused Pallas TPU kernel for the AWSet gossip round.

The XLA path (ops/merge.py + parallel/gossip.py) lowers the round as a
row gather (``state[perm]``) feeding a handful of elementwise fusions,
with ``HasDot`` via TPU's native gather engine.  This kernel fuses the
whole round — partner-row gather, both ``HasDot`` lookups, the two-phase
merge select, and the VV join — into ONE pass over HBM:

  * the gossip permutation rides in as a **scalar-prefetch** operand, so
    each grid step DMAs its partner row ``perm[r]`` straight out of the
    source arrays — the permuted copy of the state is never materialized;
  * ``HasDot`` (crdt-misc.go:28-34) is computed on the **MXU** as an
    exact one-hot matvec: ``cnt = vv @ onehot(dot_actor)`` with the
    uint32 counters split into hi/lo 16-bit halves so every f32 product
    is exact (one-hot rows sum a single term < 2^16);
  * the merge itself is the same closed-form mask algebra as
    ops/merge.py (awset.go:107-161, SURVEY §7.2), on the VPU;
  * the element axis is processed in VMEM-sized tiles (blockwise over
    ``E``), so element universes far beyond VMEM stream through.

Semantics are bit-identical to ``ops.merge.merge_kernel`` — the
conformance gate in tests/test_pallas_merge.py checks bitwise equality
against the XLA kernel (and transitively against the executable spec).

Layout contract: grid is ``(R, E_pad // block_e)`` with one replica row
per step; row blocks are ``(1, block_e)``.  ``E`` and ``A`` are padded
to lane multiples with absent/zero lanes, which is semantically inert:
a zero dot on an absent lane is "covered by every clock" and the lane's
``present`` bits are False on both sides, so every padded lane resolves
to absent (same canonical zeroing as ops/merge.py).

Measured regime guidance (v5e 1x1, R=10K, E=A=256, honest scan-timed
rounds — the sync scalar must consume every output or XLA dead-codes
the dot/membership computation and the number measures only the VV
join):
  * XLA path: ~56ms/round — the elementwise HasDot gather
    (take_along_axis with [R, E] indices) hits a pathological lowering
    inside compiled loops; the VV-join chain alone runs at roofline
    (~45us/round), so the gather is ~99% of the cost.
  * this one-row kernel: ~2.4ms/round (grid overhead, ~240ns x R steps).
  * the multi-row variant below: ~1.4ms/round — the production path.
Prefer pallas_gossip_round_rows on TPU everywhere; this one-row variant
remains for huge-E/modest-R streaming (row state >> VMEM) and as the
scalar-prefetch reference.  tests/test_pallas_merge.py pins bitwise
equality across all paths, so schedulers may pick per shape freely.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from go_crdt_playground_tpu.models.awset import AWSetState

_LANE = 128


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _exact_u32_onehot_dot(values: jnp.ndarray,
                          onehot_f32: jnp.ndarray) -> jnp.ndarray:
    """uint32[M, K] x one-hot f32[K, N] -> uint32[M, N] on the MXU,
    exact over the full uint32 range: each output sums exactly one
    surviving term and both 16-bit halves are < 2^16 <= 2^24, so the
    f32 accumulation is exact.  (Mosaic has no u32<->f32 casts; both
    halves round-trip value-preservingly through an i32 bitcast.)"""
    as_i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)  # noqa: E731
    hi = as_i32(values >> 16).astype(jnp.float32)
    lo = as_i32(values & 0xFFFF).astype(jnp.float32)
    cnt_hi = jnp.dot(hi, onehot_f32, preferred_element_type=jnp.float32)
    cnt_lo = jnp.dot(lo, onehot_f32, preferred_element_type=jnp.float32)
    cnt = (cnt_hi.astype(jnp.int32) << 16) | cnt_lo.astype(jnp.int32)
    return jax.lax.bitcast_convert_type(cnt, jnp.uint32)


def _gather_counter(vv: jnp.ndarray, da: jnp.ndarray) -> jnp.ndarray:
    """``vv[0, da[0, e]]`` for every lane e — HasDot's clock lookup
    (crdt-misc.go:33) as an exact one-hot matvec on the MXU.

    vv: uint32[1, A]; da: uint32[1, E] with values < A.  Returns
    uint32[1, E].
    """
    a_pad, e_blk = vv.shape[1], da.shape[1]
    a_ids = jax.lax.broadcasted_iota(jnp.uint32, (a_pad, e_blk), 0)
    onehot = (a_ids == jnp.broadcast_to(da, (a_pad, e_blk))).astype(
        jnp.float32)
    return _exact_u32_onehot_dot(vv, onehot)


def _round_kernel(perm_ref, dvv_ref, svv_ref, dp_ref, sp_ref,
                  dda_ref, sda_ref, ddc_ref, sdc_ref,
                  ovv_ref, op_ref, oda_ref, odc_ref):
    del perm_ref  # consumed by the index maps
    # row blocks are (1, 1, X) — Mosaic requires the sublane dim of a
    # block to be 8-divisible or the full array dim, so the replica axis
    # is lifted to a leading grid-only dim and blocks drop to [1, X] here
    dvv, svv = dvv_ref[0], svv_ref[0]
    dp = dp_ref[0] != 0
    sp = sp_ref[0] != 0
    dda, sda = dda_ref[0], sda_ref[0]
    ddc, sdc = ddc_ref[0], sdc_ref[0]

    # HasDot gathers (awset.go:133 / :152)
    seen_by_dst = sdc <= _gather_counter(dvv, sda)
    seen_by_src = ddc <= _gather_counter(svv, dda)

    # two-phase merge as closed-form masks (awset.go:122-159, SURVEY §7.2)
    take_src = sp & (dp | ~seen_by_dst)
    present = take_src | (dp & ~sp & ~seen_by_src)
    da = jnp.where(take_src, sda, dda)
    dc = jnp.where(take_src, sdc, ddc)
    zero = jnp.zeros_like(da)
    oda_ref[0] = jnp.where(present, da, zero)
    odc_ref[0] = jnp.where(present, dc, zero)
    op_ref[0] = present.astype(jnp.uint8)
    # VV join (crdt-misc.go:43-55); Mosaic can't legalize unsigned max,
    # so spell it as compare+select
    ovv_ref[0] = jnp.where(dvv < svv, svv, dvv)


def _pad_arrays(vv, present_u8, da, dc, e_pad, a_pad):
    num_r, num_e = da.shape
    num_a = vv.shape[1]
    if e_pad != num_e:
        pad = ((0, 0), (0, e_pad - num_e))
        present_u8 = jnp.pad(present_u8, pad)
        da = jnp.pad(da, pad)
        dc = jnp.pad(dc, pad)
    if a_pad != num_a:
        vv = jnp.pad(vv, ((0, 0), (0, a_pad - num_a)))
    # lift the replica axis out of the tile: arrays become [R, 1, X] so
    # row blocks are (1, 1, X) and the tiled dims are (1, X)
    return (vv.reshape(num_r, 1, a_pad),
            present_u8.reshape(num_r, 1, e_pad),
            da.reshape(num_r, 1, e_pad),
            dc.reshape(num_r, 1, e_pad))


@functools.partial(
    jax.jit, static_argnames=("block_e", "interpret"))
def _fused_round(dst_arrays, src_arrays, perm, block_e: int,
                 interpret: bool):
    """dst/src are (vv, present_u8, da, dc) tuples; src may be the same
    arrays as dst (gossip: perm indexes the batch itself) or an
    independent batch of the same shape (pairwise merge)."""
    num_r, num_e = dst_arrays[2].shape
    num_a = dst_arrays[0].shape[1]
    e_pad = _round_up(num_e, _LANE)
    a_pad = _round_up(num_a, _LANE)
    blk = min(_round_up(block_e, _LANE), e_pad)
    while e_pad % blk:  # keep the grid exact; blk stays a lane multiple
        blk -= _LANE
    grid = (num_r, e_pad // blk)

    vv, present_u8, da, dc = _pad_arrays(*dst_arrays, e_pad, a_pad)
    svv, spresent_u8, sda, sdc = _pad_arrays(*src_arrays, e_pad, a_pad)

    def dst_el(i, j, perm_ref):
        del perm_ref
        return (i, 0, j)

    def src_el(i, j, perm_ref):
        return (perm_ref[i], 0, j)

    def dst_vv(i, j, perm_ref):
        del j, perm_ref
        return (i, 0, 0)

    def src_vv(i, j, perm_ref):
        del j
        return (perm_ref[i], 0, 0)

    vv_blk = pl.BlockSpec((1, 1, a_pad), dst_vv)
    vv_src_blk = pl.BlockSpec((1, 1, a_pad), src_vv)
    el = lambda: pl.BlockSpec((1, 1, blk), dst_el)       # noqa: E731
    el_src = lambda: pl.BlockSpec((1, 1, blk), src_el)   # noqa: E731

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[vv_blk, vv_src_blk, el(), el_src(), el(), el_src(),
                  el(), el_src()],
        out_specs=[vv_blk, el(), el(), el()],
    )
    out_vv, out_p, out_da, out_dc = pl.pallas_call(
        _round_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((num_r, 1, a_pad), jnp.uint32),
            jax.ShapeDtypeStruct((num_r, 1, e_pad), jnp.uint8),
            jax.ShapeDtypeStruct((num_r, 1, e_pad), jnp.uint32),
            jax.ShapeDtypeStruct((num_r, 1, e_pad), jnp.uint32),
        ],
        interpret=interpret,
    )(perm.astype(jnp.int32), vv, svv, present_u8, spresent_u8,
      da, sda, dc, sdc)
    return (out_vv[:, 0, :num_a], out_p[:, 0, :num_e],
            out_da[:, 0, :num_e], out_dc[:, 0, :num_e])


def _as_arrays(state: AWSetState):
    return (state.vv, state.present.astype(jnp.uint8), state.dot_actor,
            state.dot_counter)


def pallas_gossip_round(state: AWSetState, perm, *, block_e: int = 512,
                        interpret: bool | None = None) -> AWSetState:
    """One fused anti-entropy round: replica r absorbs replica perm[r].

    Drop-in equivalent of ``parallel.gossip.gossip_round`` (bitwise-equal
    output), with the partner-row gather fused into the kernel's DMA
    schedule instead of materialized.  ``interpret=None`` auto-selects
    interpreter mode off-TPU so the CPU test mesh can run it.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    arrays = _as_arrays(state)
    vv, p, da, dc = _fused_round(arrays, arrays, perm, block_e, interpret)
    return AWSetState(vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
                      actor=state.actor)


def pallas_merge_pairwise(dst: AWSetState, src: AWSetState, *,
                          block_e: int = 512,
                          interpret: bool | None = None) -> AWSetState:
    """Batched dst[r] <- src[r] between two independent batches (the
    fused analogue of ops.merge.merge_pairwise): the src batch rides in
    as the kernel's source operands with an identity permutation."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    num_r = dst.present.shape[0]
    perm = jnp.arange(num_r, dtype=jnp.int32)
    vv, p, da, dc = _fused_round(
        _as_arrays(dst), _as_arrays(src), perm, block_e, interpret)
    return AWSetState(vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
                      actor=dst.actor)


# ---------------------------------------------------------------------------
# Multi-row variant: the production gossip path
# ---------------------------------------------------------------------------
#
# The one-row-per-grid-step layout above pays ~240ns of grid overhead per
# replica — 2.4ms/round at R=10K, dwarfing the ~0.15ms of HBM traffic.
# This variant amortizes it 8 rows at a time (Mosaic's sublane rule: the
# block's second-minor dim must be 8-divisible), which demotes the
# arbitrary-permutation row gather from the kernel's scalar-prefetch DMA
# to a plain XLA gather BEFORE the kernel: partner rows of one 8-row
# block aren't contiguous under a general perm, but the XLA row gather
# runs at HBM bandwidth (it is the vv-join chain's own layout), so the
# split costs one extra state read and removes ~85% of the grid steps.


def gather_rows(vv: jnp.ndarray, da: jnp.ndarray) -> jnp.ndarray:
    """In-kernel HasDot gather, multi-row: cnt[r, e] = vv[r, da[r, e]]
    for a whole row block with ONE 2D MXU matmul.  The vv rows become a
    block-diagonal [blk_r, blk_r*A] operand and the one-hot selector
    [blk_r*A, blk_e] row q = r*A + a answers "does row r's lane e name
    actor a".  Mosaic can't lower batched dot_general and axis-1
    reductions of [blk_r, A, blk_e] are layout-hostile; both 2D shapes
    here keep lanes minor.  Exact over the full uint32 range via the
    16-bit halves (the one-hot contraction sums a single term < 2^16).

    vv: uint32[blk_r, A]; da: uint32[blk_r, blk_e] -> uint32[blk_r, blk_e]
    """
    blk_r, a_pad = vv.shape
    blk_e = da.shape[1]
    q = blk_r * a_pad
    q_a = jax.lax.broadcasted_iota(jnp.uint32, (q, blk_e), 0) % a_pad
    da_rep = jnp.broadcast_to(
        da[:, None, :], (blk_r, a_pad, blk_e)).reshape(q, blk_e)
    onehot = (q_a == da_rep).astype(jnp.float32)
    eye = (jax.lax.broadcasted_iota(jnp.uint32, (blk_r, blk_r, a_pad), 0)
           == jax.lax.broadcasted_iota(jnp.uint32,
                                       (blk_r, blk_r, a_pad), 1))
    tiled = jnp.broadcast_to(vv[None, :, :], (blk_r, blk_r, a_pad))
    vvd = jnp.where(eye, tiled, jnp.zeros_like(tiled)).reshape(blk_r, q)
    return _exact_u32_onehot_dot(vvd, onehot)


def _rows_kernel(dvv_ref, svv_ref, dp_ref, sp_ref, dda_ref, sda_ref,
                 ddc_ref, sdc_ref, ovv_ref, op_ref, oda_ref, odc_ref):
    dvv, svv = dvv_ref[...], svv_ref[...]          # [8, A]
    dp = dp_ref[...] != 0                           # [8, blk]
    sp = sp_ref[...] != 0
    dda, sda = dda_ref[...], sda_ref[...]
    ddc, sdc = ddc_ref[...], sdc_ref[...]

    seen_by_dst = sdc <= gather_rows(dvv, sda)
    seen_by_src = ddc <= gather_rows(svv, dda)
    take_src = sp & (dp | ~seen_by_dst)
    present = take_src | (dp & ~sp & ~seen_by_src)
    da = jnp.where(take_src, sda, dda)
    dc = jnp.where(take_src, sdc, ddc)
    zero = jnp.zeros_like(da)
    oda_ref[...] = jnp.where(present, da, zero)
    odc_ref[...] = jnp.where(present, dc, zero)
    op_ref[...] = present.astype(jnp.uint8)
    ovv_ref[...] = jnp.where(dvv < svv, svv, dvv)


_BLOCK_R = 8

# In-kernel one-hot budget: gather_rows materializes a
# [_BLOCK_R * a_pad, blk_e] f32 selector (plus the same-shaped da_rep),
# so blk_e must shrink as A grows to stay inside VMEM.
_ONEHOT_BUDGET_BYTES = 4 << 20

# Above this actor-axis size even blk_e = one lane group blows the
# budget — callers (gossip auto-dispatch) fall back to the XLA path.
MAX_FUSED_ACTORS = _ONEHOT_BUDGET_BYTES // (_BLOCK_R * 4 * _LANE)


def row_block_layout(num_r: int, num_e: int, num_a: int, block_e: int):
    """Padded dims + element block size for the multi-row kernels:
    (r_pad, e_pad, a_pad, blk).  blk is a lane multiple that divides
    e_pad and keeps the one-hot selector within the VMEM budget."""
    e_pad = _round_up(num_e, _LANE)
    a_pad = _round_up(num_a, _LANE)
    r_pad = _round_up(num_r, _BLOCK_R)
    budget_blk = _ONEHOT_BUDGET_BYTES // (_BLOCK_R * a_pad * 4)
    if budget_blk < _LANE:
        raise ValueError(
            f"actor axis A={num_a} too large for the fused row kernels "
            f"(one-hot selector would exceed the {_ONEHOT_BUDGET_BYTES >> 20}"
            "MB VMEM budget at the minimum block width); use the XLA path")
    blk = min(_round_up(block_e, _LANE), e_pad,
              budget_blk // _LANE * _LANE)
    while e_pad % blk:
        blk -= _LANE
    return r_pad, e_pad, a_pad, blk


@functools.partial(jax.jit, static_argnames=("block_e", "interpret"))
def _fused_rows(dst_arrays, src_arrays, block_e: int, interpret: bool):
    num_r, num_e = dst_arrays[2].shape
    num_a = dst_arrays[0].shape[1]
    r_pad, e_pad, a_pad, blk = row_block_layout(num_r, num_e, num_a,
                                                block_e)

    def pad(arrays):
        vv, p_u8, da, dc = arrays
        pe = ((0, r_pad - num_r), (0, e_pad - num_e))
        pa = ((0, r_pad - num_r), (0, a_pad - num_a))
        return (jnp.pad(vv, pa), jnp.pad(p_u8, pe), jnp.pad(da, pe),
                jnp.pad(dc, pe))

    vv, p_u8, da, dc = pad(dst_arrays)
    svv, sp_u8, sda, sdc = pad(src_arrays)
    grid = (r_pad // _BLOCK_R, e_pad // blk)

    vv_blk = pl.BlockSpec((_BLOCK_R, a_pad), lambda i, j: (i, 0))
    el_blk = pl.BlockSpec((_BLOCK_R, blk), lambda i, j: (i, j))
    out_vv, out_p, out_da, out_dc = pl.pallas_call(
        _rows_kernel,
        grid=grid,
        in_specs=[vv_blk, vv_blk, el_blk, el_blk, el_blk, el_blk,
                  el_blk, el_blk],
        out_specs=[vv_blk, el_blk, el_blk, el_blk],
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, a_pad), jnp.uint32),
            jax.ShapeDtypeStruct((r_pad, e_pad), jnp.uint8),
            jax.ShapeDtypeStruct((r_pad, e_pad), jnp.uint32),
            jax.ShapeDtypeStruct((r_pad, e_pad), jnp.uint32),
        ],
        interpret=interpret,
    )(vv, svv, p_u8, sp_u8, da, sda, dc, sdc)
    return (out_vv[:num_r, :num_a], out_p[:num_r, :num_e],
            out_da[:num_r, :num_e], out_dc[:num_r, :num_e])


def pallas_merge_pairwise_rows(dst: AWSetState, src: AWSetState, *,
                               block_e: int = 512,
                               interpret: bool | None = None) -> AWSetState:
    """Batched dst[r] <- src[r] on the multi-row kernel — the pairwise
    (no-gather) form of pallas_gossip_round_rows, bitwise-equal to
    ops.merge.merge_pairwise.  This is the per-shard merge primitive for
    shard_map rings: the partner block arrives by ppermute, so the kernel
    needs no permutation at all and every grid step reads contiguous
    rows."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    vv, p, da, dc = _fused_rows(_as_arrays(dst), _as_arrays(src),
                                block_e, interpret)
    return AWSetState(vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
                      actor=dst.actor)


def pallas_gossip_round_rows(state: AWSetState, perm, *,
                             block_e: int = 512,
                             interpret: bool | None = None) -> AWSetState:
    """One anti-entropy round on the multi-row kernel: partner rows are
    gathered by XLA at HBM bandwidth, then 8 replica rows merge per grid
    step.  Bitwise-equal to gossip_round / pallas_gossip_round; ~5x
    faster than the one-row kernel at large R (the production TPU path —
    parallel.gossip.gossip_round dispatches here on TPU backends).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    src = jax.tree.map(lambda x: x[perm], state)
    vv, p, da, dc = _fused_rows(_as_arrays(state), _as_arrays(src),
                                block_e, interpret)
    return AWSetState(vv=vv, present=p != 0, dot_actor=da, dot_counter=dc,
                      actor=state.actor)
