"""Packed per-lane digest kernel: O(diff) anti-entropy fingerprints.

"Efficient Synchronization of State-based CRDTs" (PAPERS.md, arxiv
1803.02750) cuts a sync round's cost to O(diff) by exchanging join-
decomposition DIGESTS before any state.  This module is the tensorized
half of that design (net/digestsync.py is the wire half): one jitted
pass fingerprints every element lane of a packed ``AWSetDeltaState``
slice — present bit, live dot, deletion record, with the lane id folded
in — and XOR-folds the fingerprints into fixed-size GROUP digests.  Two
replicas exchange ``ceil(E / group) * 4`` bytes of digests plus their
vvs; equal digests mean (to a 2^-32-per-group collision bound, below)
the groups' lanes are identical and nothing ships; a mismatched group
names exactly which lanes to exchange.

This extends the host-only ``models/digest.py`` CRC approach (whole-
array integrity digests for checkpoints) into a VECTORIZED per-element
fingerprint the sync path can compute on-device every round: the CRC
digest answers "is this stored state intact?", the lane digest answers
"WHICH lanes differ between two live replicas?".

Fingerprint function: a murmur3-finalizer-style avalanche mix
(``_mix32``) folded over the lane's CONVERGENT projection — the
present bit, deletion-log membership, and the deletion record's dot —
seeded with the lane id so identical content on different lanes
digests differently (and so the group XOR fold cannot cancel two
equal-content lanes).  Every operation is uint32 add/xor/shift/
multiply — elementwise over [E], no gathers — so the XLA form is one
fused pass and the Pallas twin (``ops/pallas_digest.py``) computes it
block-resident in VMEM.

WHY LIVE DOTS ARE EXCLUDED: the reference merge's both-present rule
(awset.go:122-147, ``take_src = sp & (dp | ~seen)``) OVERWRITES the
receiver's live dot with the sender's whenever both hold the element,
so after concurrent adds of one key a push-pull pair permanently holds
DIFFERENT (and on every full exchange, swapping) dots for the same
present lane — divergent by design, converged in every observable.
Folding live dots in would make such lanes mismatch forever and the
digest regime would re-ship them every round without ever reaching
quiescence (measured: a 4-node soak fleet never went lane-silent).
Excluding them is sound: a lane pair differing ONLY in live dots has
equal membership on both sides, so withholding it ships nothing the
receiver observably lacks, and the dot divergence heals through
ordinary δ arbitration the moment it matters (any delete/re-add moves
the projection, which IS fingerprinted).  Deletion records, by
contrast, stay folded in — their absorb rule is a true join
((counter, actor) lexicographic max, ops/delta.py), so converged
replicas agree on them bitwise.

SOUNDNESS (the direction the protocol's correctness leans on): the
fingerprint is a deterministic pure function of (lane id, lane state),
so equal lanes ALWAYS produce equal fingerprints, and a group-digest
mismatch PROVES some lane in the group differs (pinned by
tests/test_digest_kernel.py).  The converse is probabilistic:

COLLISION BOUND (documented contract): two DIFFERING groups collide —
digest-equal while a lane differs — with probability ~2^-32 per group
pair per comparison (the XOR of >= 1 differing well-mixed 32-bit lane
fingerprints is ~uniform).  A collision makes one digest round ship
nothing for a group that differs; the protocol layer additionally
falls back to a δ exchange whenever the digests claim equality while
the vvs differ (net/digestsync.py), so a collided round degrades to
the always-sound δ ladder instead of silently diverging.  At 2^-32
per group per round, a 6-node fleet syncing 1024 lanes (16 groups)
every 100ms expects one collision per ~4.5 years; each is healed by
the very next round's δ fallback (vv inequality persists until joined).

Group size: ``DIGEST_GROUP_LANES`` = 64 lanes per uint32 digest — the
summary costs E/16 bytes against the dense δ payload's two E/8-byte
section bitmasks, while a single divergent lane ships at most its
64-lane group.  The value is a protocol parameter (carried in the
digest summary frame and checked for equality — peers must agree), and
must divide the Pallas lane width (128) so both kernel forms pad to
identical group boundaries.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState
from go_crdt_playground_tpu.ops.delta import DeltaPayload, delta_extract

# protocol parameter (net/digestsync.py carries + checks it on the
# wire): lanes per uint32 group digest.  Must divide the Pallas lane
# width (ops/pallas_merge._LANE = 128) — see module docstring.
DIGEST_GROUP_LANES = 64

# fingerprint seed: folded into every lane's hash so a digest is
# versioned implicitly — changing the mix (or this constant) makes
# every group mismatch, which degrades to a δ exchange, never to a
# false "equal".  numpy scalars, not jnp: they must stay concrete
# literals inside the Pallas kernel body (traced module constants get
# rejected as captured consts).
_SEED = np.uint32(0x9E3779B9)
_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)


def _mix32(h: jnp.ndarray) -> jnp.ndarray:
    """murmur3 fmix32: full avalanche over uint32 lanes (every input
    bit flips each output bit with ~1/2 probability — what the 2^-32
    collision bound in the module docstring leans on)."""
    h = h ^ (h >> 16)
    h = h * _M1
    h = h ^ (h >> 13)
    h = h * _M2
    return h ^ (h >> 16)


def _fold(h: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """Fold one uint32 state component into the running lane hash."""
    return _mix32(h ^ v.astype(jnp.uint32))


def lane_fingerprint_arrays(lane_ids, present, deleted, del_dot_actor,
                            del_dot_counter) -> jnp.ndarray:
    """The fingerprint algebra on raw component arrays — shared
    verbatim by the XLA pass below and the Pallas twin's in-kernel
    body (ops/pallas_digest.py), so the bitwise-pinned definition
    lives in exactly one place.  Covers the lane's CONVERGENT
    projection only (module docstring: live dots are divergent by
    design and deliberately excluded).  All inputs broadcast over the
    lane axis; masks may be bool or uint8."""
    h = _mix32(lane_ids.astype(jnp.uint32) ^ _SEED)
    h = _fold(h, present != 0)
    h = _fold(h, deleted != 0)
    h = _fold(h, del_dot_actor)
    h = _fold(h, del_dot_counter)
    return h


@jax.jit
def lane_fingerprints(state: AWSetDeltaState) -> jnp.ndarray:
    """uint32[E] per-lane fingerprints of one single-replica slice
    (fields shaped [E]/[A]).  vv/processed are deliberately NOT folded
    in: they are A-shaped replica clocks, exchanged explicitly in the
    digest summary — the lane digest answers only "do these LANES
    match" (in their convergent projection)."""
    e = state.present.shape[-1]
    return lane_fingerprint_arrays(
        jnp.arange(e, dtype=jnp.uint32), state.present, state.deleted,
        state.del_dot_actor, state.del_dot_counter)


def group_fold(fp: jnp.ndarray, group_size: int) -> jnp.ndarray:
    """XOR-fold uint32[E] lane fingerprints into uint32[ceil(E/gs)]
    group digests.  Lanes past E pad with the fingerprint OF A ZERO
    LANE AT THAT LANE ID — the same value every replica of the same
    universe computes, so the ragged last group is comparison-stable
    (pinned by tests/test_digest_kernel.py)."""
    e = fp.shape[-1]
    pad = (-e) % group_size
    if pad:
        pad_ids = jnp.arange(e, e + pad, dtype=jnp.uint32)
        z = jnp.zeros(pad, jnp.uint32)
        fp = jnp.concatenate(
            [fp, lane_fingerprint_arrays(pad_ids, z, z, z, z)])
    grouped = fp.reshape(-1, group_size)
    return jax.lax.reduce(grouped, jnp.uint32(0), jax.lax.bitwise_xor,
                          (1,))


@functools.partial(jax.jit, static_argnames=("group_size",))
def state_group_digests(state: AWSetDeltaState,
                        group_size: int = DIGEST_GROUP_LANES
                        ) -> jnp.ndarray:
    """One dispatch: per-lane fingerprints + group XOR fold (XLA
    form).  ``digest_regime`` is the backend dispatch callers should
    use."""
    return group_fold(lane_fingerprints(state), group_size)


def digest_regime(num_elements: int):
    """THE backend dispatch for the digest kernel (the
    ``ops/ingest.ingest_delta_regime`` pattern): returns a
    ``digests_fn(state_slice, group_size) -> uint32[G]`` — the Pallas
    twin on TPU backends (fingerprints computed block-resident in
    VMEM), the fused XLA pass everywhere else.  Both are bitwise-
    pinned (tests/test_digest_kernel.py), so the protocol tier may
    call either side of an exchange on either backend."""
    del num_elements  # shape-independent today; keeps the seam stable
    if jax.default_backend() == "tpu":
        from go_crdt_playground_tpu.ops.pallas_digest import \
            pallas_state_group_digests

        return pallas_state_group_digests
    return state_group_digests


def num_groups(num_elements: int,
               group_size: int = DIGEST_GROUP_LANES) -> int:
    return -(-num_elements // group_size)


@functools.partial(jax.jit, static_argnames=("group_size",))
def digest_diff_payload(state: AWSetDeltaState, own_digests,
                        peer_digests,
                        group_size: int = DIGEST_GROUP_LANES
                        ) -> DeltaPayload:
    """The mismatching-lane set, computed ON-DEVICE in one dispatch:
    compare our group digests (``own_digests`` — the caller computed
    them once via the backend regime; recomputing here would double
    the fingerprint pass and pin the XLA form even on TPU) against the
    peer's, expand the mismatched groups to a lane mask, and extract
    our COMPLETE state for exactly those lanes (the
    ``Node.extract_slice`` shape: ``delta_extract`` vs a zero vv,
    masked) — every present lane with its dot and every un-resurrected
    deletion record in a mismatched group, nothing from matched
    groups.

    The payload's ``src_vv`` is our FULL vv (unlike the compact-
    overflow path, which must neutralize it): lanes withheld here are
    in digest-MATCHED groups, i.e. OBSERVABLY identical on the
    receiver (equal convergent projection — a withheld lane may differ
    in its live dot, but then the receiver already holds the element
    present under its own dot) to the collision bound, so joining the
    full clock cannot cover an add the receiver lacks — the module-
    docstring collision bound is exactly the probability of that
    invariant failing, and the protocol's δ fallback on vv-divergence-
    without-digest-mismatch is the healing path (net/digestsync.py)."""
    e = state.present.shape[-1]
    mism = jnp.asarray(own_digests, jnp.uint32) != \
        jnp.asarray(peer_digests, jnp.uint32)
    lane_mask = jnp.repeat(mism, group_size, total_repeat_length=
                           mism.shape[0] * group_size)[:e]
    p = delta_extract(state, jnp.zeros_like(state.vv))
    return p._replace(
        changed=p.changed & lane_mask,
        ch_da=jnp.where(lane_mask, p.ch_da, 0),
        ch_dc=jnp.where(lane_mask, p.ch_dc, 0),
        deleted=p.deleted & lane_mask,
        del_da=jnp.where(lane_mask, p.del_da, 0),
        del_dc=jnp.where(lane_mask, p.del_dc, 0))


def mismatched_group_count(own_digests, peer_digests) -> int:
    """Host-side census for the Recorder (the wire decision itself
    stays on-device in digest_diff_payload)."""
    return int(np.sum(np.asarray(own_digests, np.uint32)
                      != np.asarray(peer_digests, np.uint32)))
