"""Fixed-K compact δ payloads: bandwidth-shaped gossip for sparse deltas.

The reference's ``MakeDeltaMergeData`` ships two *maps* whose size is the
number of changed/deleted keys, not the universe (awset-delta_test.go:
79-105).  The dense tensor payload (ops/delta.DeltaPayload) loses that:
its wire cost is O(E) regardless of sparsity.  This module restores the
reference's bandwidth shape under XLA's static-shape rules: a payload is
compacted to fixed-capacity index/value lanes (``K`` slots), which is
what actually crosses ICI in the compact ring round
(parallel/gossip.compact_ring_round_shardmap) — O(K) bytes instead of
O(E).

Overflow policy: when more than K lanes changed, the surplus lanes are
left out of this round's payload and ``overflow`` is set.  Dropping
lanes is SAFE — an anti-entropy exchange is idempotent and monotone, so
a truncated payload is just a smaller exchange; the missing lanes ship
on a later round once the receiver's VV (which did NOT advance past
them — truncation also drops their dots from nothing, and VV join uses
the sender's full VV...) — see the correctness note below.

CORRECTNESS NOTE (why truncation must also mask the VV join): applying
the sender's full VV while withholding changed lanes would let the
receiver's clock cover adds it never saw — phase-1 ``HasDot`` would then
treat the missing adds as already-deleted on a later exchange
(awset.go:133-135), dropping them permanently.  So on overflow the
compact payload carries the sender VV only for CLAIMED lanes to stay
below: ``src_vv`` is replaced by the receiver-safe join input
``where(overflow, receiver_vv_advancing_nothing, src_vv)`` — i.e. the
whole exchange degrades to "partial data, no clock advance", which is
exactly a lossy network round (SURVEY §5.3) and converges by retry.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from go_crdt_playground_tpu.ops.delta import DeltaPayload


class CompactDeltaPayload(NamedTuple):
    """One replica pair's δ payload in fixed-K index form (vmap-batched).

    ``*_idx`` are element ids for the claimed lanes, valid where
    ``*_valid``; capacity (K_changed, K_deleted) is static.  ``src_vv``
    here is already the RECEIVER-SAFE join input (see module docstring):
    equal to the sender's VV on complete payloads, and neutralized to
    zeros on overflow so a truncated exchange cannot advance the
    receiver's clock past unshipped adds.
    """

    src_vv: jnp.ndarray         # uint32[A]
    ch_idx: jnp.ndarray         # uint32[Kc]
    ch_valid: jnp.ndarray       # bool[Kc]
    ch_da: jnp.ndarray          # uint32[Kc]
    ch_dc: jnp.ndarray          # uint32[Kc]
    del_idx: jnp.ndarray        # uint32[Kd]
    del_valid: jnp.ndarray      # bool[Kd]
    del_da: jnp.ndarray         # uint32[Kd]
    del_dc: jnp.ndarray         # uint32[Kd]
    overflow: jnp.ndarray       # bool[]  (either section truncated)
    src_actor: jnp.ndarray      # uint32[]
    src_processed: jnp.ndarray  # uint32[A]

    def nbytes_wire(self) -> int:
        """Dense device bytes of the compact form — the ICI payload cost
        of one exchange (compare DeltaPayload.nbytes_dense: O(E))."""
        return sum(x.size * x.dtype.itemsize for x in self)


def _compact_section(mask: jnp.ndarray, idx_dtype, k: int, *values):
    """Pack the lanes where ``mask`` into the first ``count`` of k slots
    (stable, ascending element id).  Returns (idx, valid, packed_values,
    overflowed)."""
    E = mask.shape[-1]
    pos = jnp.cumsum(mask) - 1                      # destination slot
    claim = mask & (pos < k)
    dest = jnp.where(claim, pos, k).astype(jnp.int32)  # k = dropped
    eids = jnp.arange(E, dtype=idx_dtype)
    idx = jnp.zeros((k,), idx_dtype).at[dest].set(eids, mode="drop")
    valid = jnp.zeros((k,), bool).at[dest].set(claim, mode="drop")
    packed = tuple(
        jnp.zeros((k,), v.dtype).at[dest].set(
            jnp.where(claim, v, 0), mode="drop")
        for v in values
    )
    overflowed = jnp.sum(mask) > k
    return idx, valid, packed, overflowed


def compact_payload(p: DeltaPayload, k_changed: int,
                    k_deleted: int) -> CompactDeltaPayload:
    """Dense payload (single replica slice, [E] fields) -> fixed-K form."""
    ch_idx, ch_valid, (ch_da, ch_dc), ch_over = _compact_section(
        p.changed, jnp.uint32, k_changed, p.ch_da, p.ch_dc)
    del_idx, del_valid, (del_da, del_dc), del_over = _compact_section(
        p.deleted, jnp.uint32, k_deleted, p.del_da, p.del_dc)
    overflow = ch_over | del_over
    # Receiver-safe VV (module docstring): neutralize the clock advance
    # whenever any lane was truncated.
    safe_vv = jnp.where(overflow, jnp.zeros_like(p.src_vv), p.src_vv)
    return CompactDeltaPayload(
        src_vv=safe_vv,
        ch_idx=ch_idx, ch_valid=ch_valid, ch_da=ch_da, ch_dc=ch_dc,
        del_idx=del_idx, del_valid=del_valid, del_da=del_da,
        del_dc=del_dc, overflow=overflow,
        src_actor=p.src_actor,
        src_processed=jnp.where(overflow,
                                jnp.zeros_like(p.src_processed),
                                p.src_processed),
    )


def expand_payload(c: CompactDeltaPayload,
                   num_elements: int) -> DeltaPayload:
    """Fixed-K form -> dense payload (inverse of compact_payload on
    payloads that fit; the truncated-lane subset otherwise)."""
    E = num_elements

    def scatter(idx, valid, vals, dtype):
        dest = jnp.where(valid, idx, E).astype(jnp.int32)
        return jnp.zeros((E,), dtype).at[dest].set(vals, mode="drop")

    changed = scatter(c.ch_idx, c.ch_valid, c.ch_valid, bool)
    deleted = scatter(c.del_idx, c.del_valid, c.del_valid, bool)
    return DeltaPayload(
        src_vv=c.src_vv,
        changed=changed,
        ch_da=scatter(c.ch_idx, c.ch_valid, c.ch_da, jnp.uint32),
        ch_dc=scatter(c.ch_idx, c.ch_valid, c.ch_dc, jnp.uint32),
        deleted=deleted,
        del_da=scatter(c.del_idx, c.del_valid, c.del_da, jnp.uint32),
        del_dc=scatter(c.del_idx, c.del_valid, c.del_dc, jnp.uint32),
        src_actor=c.src_actor,
        src_processed=c.src_processed,
    )


compact_payload_batch = jax.vmap(compact_payload,
                                 in_axes=(0, None, None))
expand_payload_batch = jax.vmap(expand_payload, in_axes=(0, None))
