"""Batched lattice-join kernels for the non-AWSet CRDT families.

Every family is a NamedTuple of arrays batched over the replica axis ``R``
with an elementwise monotone join — the same shape as the AWSet kernel but
simpler, so they all ride the existing gossip machinery: any ``join(dst,
src) -> merged`` pytree function plugs into a permutation round exactly
like ops/merge.merge_pairwise (parallel/gossip.py's pattern of
``src = state[perm]``).

Conformance oracles: models/spec_extra.py.  The G-Counter join IS the
reference's VersionVector.Merge (crdt-misc.go:43-55) batched; BASELINE
config 2 measures it at 1K replicas.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# G-Counter / PN-Counter
# ---------------------------------------------------------------------------


class GCounterState(NamedTuple):
    counts: jnp.ndarray   # uint32[R, A]
    actor: jnp.ndarray    # uint32[R]


def gcounter_init(num_replicas: int, num_actors: int,
                  actors=None) -> GCounterState:
    if actors is None:
        if num_actors < num_replicas:
            raise ValueError("need num_actors >= num_replicas by default")
        actors = jnp.arange(num_replicas, dtype=jnp.uint32)
    return GCounterState(
        counts=jnp.zeros((num_replicas, num_actors), jnp.uint32),
        actor=jnp.asarray(actors, jnp.uint32),
    )


@jax.jit
def gcounter_inc(state: GCounterState, replica: jnp.ndarray,
                 amount: jnp.ndarray) -> GCounterState:
    r = replica.astype(jnp.int32)
    a = state.actor[r].astype(jnp.int32)
    return state._replace(counts=state.counts.at[r, a].add(amount))


def gcounter_value(state: GCounterState) -> "np.ndarray":
    """uint64[R] host array — sums can exceed uint32, and JAX truncates
    64-bit math without the global x64 flag, so the observer runs on host
    (it is not a merge-path op)."""
    import numpy as np

    return np.asarray(state.counts).astype(np.uint64).sum(axis=-1)


def gcounter_join(dst: GCounterState, src: GCounterState) -> GCounterState:
    """Elementwise max (VersionVector.Merge batched, crdt-misc.go:43-55)."""
    return dst._replace(counts=jnp.maximum(dst.counts, src.counts))


class PNCounterState(NamedTuple):
    p: jnp.ndarray        # uint32[R, A]
    n: jnp.ndarray        # uint32[R, A]
    actor: jnp.ndarray    # uint32[R]


def pncounter_init(num_replicas: int, num_actors: int,
                   actors=None) -> PNCounterState:
    g = gcounter_init(num_replicas, num_actors, actors)
    return PNCounterState(p=g.counts, n=g.counts, actor=g.actor)


@jax.jit
def pncounter_add(state: PNCounterState, replica: jnp.ndarray,
                  amount: jnp.ndarray) -> PNCounterState:
    """amount: int32 scalar; positive increments P, negative increments N."""
    r = replica.astype(jnp.int32)
    a = state.actor[r].astype(jnp.int32)
    pos = jnp.maximum(amount, 0).astype(jnp.uint32)
    neg = jnp.maximum(-amount, 0).astype(jnp.uint32)
    return state._replace(
        p=state.p.at[r, a].add(pos),
        n=state.n.at[r, a].add(neg),
    )


def pncounter_value(state: PNCounterState) -> "np.ndarray":
    """int64[R] host array (see gcounter_value for why host-side)."""
    import numpy as np

    return (np.asarray(state.p).astype(np.int64).sum(axis=-1)
            - np.asarray(state.n).astype(np.int64).sum(axis=-1))


def pncounter_join(dst: PNCounterState, src: PNCounterState) -> PNCounterState:
    return dst._replace(p=jnp.maximum(dst.p, src.p),
                        n=jnp.maximum(dst.n, src.n))


# ---------------------------------------------------------------------------
# 2P-Set
# ---------------------------------------------------------------------------


class TwoPSetState(NamedTuple):
    added: jnp.ndarray     # bool[R, E]
    removed: jnp.ndarray   # bool[R, E]


def twopset_init(num_replicas: int, num_elements: int) -> TwoPSetState:
    z = jnp.zeros((num_replicas, num_elements), bool)
    return TwoPSetState(added=z, removed=z)


@jax.jit
def twopset_add(state: TwoPSetState, replica: jnp.ndarray,
                element: jnp.ndarray) -> TwoPSetState:
    r, e = replica.astype(jnp.int32), element.astype(jnp.int32)
    return state._replace(added=state.added.at[r, e].set(True))


@jax.jit
def twopset_del(state: TwoPSetState, replica: jnp.ndarray,
                element: jnp.ndarray) -> TwoPSetState:
    """Remove-wins tombstone; only observed elements can be removed."""
    r, e = replica.astype(jnp.int32), element.astype(jnp.int32)
    observed = state.added[r, e]
    return state._replace(
        removed=state.removed.at[r, e].set(state.removed[r, e] | observed))


def twopset_member(state: TwoPSetState) -> jnp.ndarray:
    return state.added & ~state.removed


def twopset_join(dst: TwoPSetState, src: TwoPSetState) -> TwoPSetState:
    """Pairwise OR joins — remove wins forever."""
    return TwoPSetState(added=dst.added | src.added,
                        removed=dst.removed | src.removed)


# ---------------------------------------------------------------------------
# LWW-Map (last-writer-wins cells; LWW-Register is the E == 1 case)
# ---------------------------------------------------------------------------


class LWWMapState(NamedTuple):
    ts: jnp.ndarray        # uint32[R, E]  caller-supplied logical stamps,
                           #               >= 1 (0 means "never written")
    wr_actor: jnp.ndarray  # uint32[R, E]  tie-break (higher actor wins)
    val: jnp.ndarray       # uint32[R, E]
    live: jnp.ndarray      # bool[R, E]    False = tombstone / never written
    actor: jnp.ndarray     # uint32[R]


def lwwmap_init(num_replicas: int, num_elements: int,
                actors=None) -> LWWMapState:
    if actors is None:
        actors = jnp.arange(num_replicas, dtype=jnp.uint32)
    zE = jnp.zeros((num_replicas, num_elements), jnp.uint32)
    return LWWMapState(ts=zE, wr_actor=zE, val=zE,
                       live=jnp.zeros((num_replicas, num_elements), bool),
                       actor=jnp.asarray(actors, jnp.uint32))


def _lww_newer(ts_a, actor_a, ts_b, actor_b):
    """Lexicographic (ts, actor) comparison: a > b."""
    return (ts_a > ts_b) | ((ts_a == ts_b) & (actor_a > actor_b))


@jax.jit
def lwwmap_put(state: LWWMapState, replica: jnp.ndarray,
               element: jnp.ndarray, value: jnp.ndarray,
               ts: jnp.ndarray, live: jnp.ndarray) -> LWWMapState:
    """Write (or tombstone with live=False) if (ts, actor) beats the cell.
    ts must be >= 1 — unwritten cells are (0, 0), so any valid stamp beats
    them (callers own the logical clock; the spec model validates)."""
    r, e = replica.astype(jnp.int32), element.astype(jnp.int32)
    a = state.actor[r]
    take = _lww_newer(ts, a, state.ts[r, e], state.wr_actor[r, e])
    return LWWMapState(
        ts=state.ts.at[r, e].set(jnp.where(take, ts, state.ts[r, e])),
        wr_actor=state.wr_actor.at[r, e].set(
            jnp.where(take, a, state.wr_actor[r, e])),
        val=state.val.at[r, e].set(jnp.where(take, value, state.val[r, e])),
        live=state.live.at[r, e].set(
            jnp.where(take, live, state.live[r, e])),
        actor=state.actor,
    )


def lwwmap_join(dst: LWWMapState, src: LWWMapState) -> LWWMapState:
    """Per-cell lexicographic (ts, actor) max; deterministic in any merge
    order."""
    take = _lww_newer(src.ts, src.wr_actor, dst.ts, dst.wr_actor)
    return LWWMapState(
        ts=jnp.where(take, src.ts, dst.ts),
        wr_actor=jnp.where(take, src.wr_actor, dst.wr_actor),
        val=jnp.where(take, src.val, dst.val),
        live=jnp.where(take, src.live, dst.live),
        actor=dst.actor,
    )


# ---------------------------------------------------------------------------
# MV-Register (multi-value; optimized per-actor slots)
# ---------------------------------------------------------------------------


class MVRegisterState(NamedTuple):
    ctx: jnp.ndarray    # uint32[R, A] causal context
    live: jnp.ndarray   # bool[R, A]   slot holds a visible value
    cnt: jnp.ndarray    # uint32[R, A] write counter per slot
    val: jnp.ndarray    # uint32[R, A]
    actor: jnp.ndarray  # uint32[R]


def mvregister_init(num_replicas: int, num_actors: int,
                    actors=None) -> MVRegisterState:
    if actors is None:
        if num_actors < num_replicas:
            raise ValueError("need num_actors >= num_replicas by default")
        actors = jnp.arange(num_replicas, dtype=jnp.uint32)
    z = jnp.zeros((num_replicas, num_actors), jnp.uint32)
    return MVRegisterState(ctx=z, live=z.astype(bool), cnt=z, val=z,
                           actor=jnp.asarray(actors, jnp.uint32))


@jax.jit
def mvregister_write(state: MVRegisterState, replica: jnp.ndarray,
                     value: jnp.ndarray) -> MVRegisterState:
    """A write observes (and so replaces) every currently-visible value."""
    r = replica.astype(jnp.int32)
    a = state.actor[r].astype(jnp.int32)
    new_c = state.ctx[r, a] + 1
    A = state.ctx.shape[-1]
    onehot = jnp.arange(A, dtype=jnp.uint32) == state.actor[r]
    return MVRegisterState(
        ctx=state.ctx.at[r, a].set(new_c),
        live=state.live.at[r].set(onehot),
        cnt=state.cnt.at[r].set(jnp.where(onehot, new_c, 0)),
        val=state.val.at[r].set(jnp.where(onehot, value, 0)),
        actor=state.actor,
    )


def mvregister_join(dst: MVRegisterState,
                    src: MVRegisterState) -> MVRegisterState:
    """Per-actor-slot arbitration mirroring spec_extra.MVRegister.merge:
    both live -> newer counter; src-only live -> adopt iff beyond our
    context; dst-only live -> drop iff src's context covers it."""
    both = dst.live & src.live
    take_src = (both & (src.cnt > dst.cnt)) | (
        src.live & ~dst.live & (src.cnt > dst.ctx))
    drop_dst = dst.live & ~src.live & (dst.cnt <= src.ctx)
    live = (dst.live & ~drop_dst) | take_src
    cnt = jnp.where(take_src, src.cnt, dst.cnt)
    val = jnp.where(take_src, src.val, dst.val)
    cnt = jnp.where(live, cnt, 0)
    val = jnp.where(live, val, 0)
    return MVRegisterState(
        ctx=jnp.maximum(dst.ctx, src.ctx),
        live=live, cnt=cnt, val=val, actor=dst.actor,
    )


# ---------------------------------------------------------------------------
# OR-Map (AWSet key membership + LWW value cells)
# ---------------------------------------------------------------------------


class ORMapState(NamedTuple):
    """Keys follow the AWSet arrays exactly (models/awset.py layout);
    cells are an LWWMapState sans its own actor row.  See
    spec_extra.ORMap for the value-lifetime semantics."""

    vv: jnp.ndarray           # uint32[R, A]
    present: jnp.ndarray      # bool[R, E]
    dot_actor: jnp.ndarray    # uint32[R, E]
    dot_counter: jnp.ndarray  # uint32[R, E]
    actor: jnp.ndarray        # uint32[R]
    ts: jnp.ndarray           # uint32[R, E]
    wr_actor: jnp.ndarray     # uint32[R, E]
    val: jnp.ndarray          # uint32[R, E]


def ormap_init(num_replicas: int, num_elements: int, num_actors: int,
               actors=None) -> ORMapState:
    from go_crdt_playground_tpu.models import awset

    base = awset.init(num_replicas, num_elements, num_actors, actors)
    zE = jnp.zeros((num_replicas, num_elements), jnp.uint32)
    return ORMapState(vv=base.vv, present=base.present,
                      dot_actor=base.dot_actor,
                      dot_counter=base.dot_counter, actor=base.actor,
                      ts=zE, wr_actor=zE, val=zE)


@jax.jit
def ormap_put(state: ORMapState, replica: jnp.ndarray,
              element: jnp.ndarray, value: jnp.ndarray,
              ts: jnp.ndarray) -> ORMapState:
    from go_crdt_playground_tpu.models import awset

    base = awset.add_element(
        awset.AWSetState(vv=state.vv, present=state.present,
                         dot_actor=state.dot_actor,
                         dot_counter=state.dot_counter, actor=state.actor),
        replica, element)
    r, e = replica.astype(jnp.int32), element.astype(jnp.int32)
    a = state.actor[r]
    take = _lww_newer(ts, a, state.ts[r, e], state.wr_actor[r, e])
    return ORMapState(
        vv=base.vv, present=base.present, dot_actor=base.dot_actor,
        dot_counter=base.dot_counter, actor=state.actor,
        ts=state.ts.at[r, e].set(jnp.where(take, ts, state.ts[r, e])),
        wr_actor=state.wr_actor.at[r, e].set(
            jnp.where(take, a, state.wr_actor[r, e])),
        val=state.val.at[r, e].set(jnp.where(take, value, state.val[r, e])),
    )


@jax.jit
def ormap_delete(state: ORMapState, replica: jnp.ndarray,
                 element: jnp.ndarray) -> ORMapState:
    from go_crdt_playground_tpu.models import awset

    base = awset.del_element(
        awset.AWSetState(vv=state.vv, present=state.present,
                         dot_actor=state.dot_actor,
                         dot_counter=state.dot_counter, actor=state.actor),
        replica, element)
    return state._replace(vv=base.vv, present=base.present,
                          dot_actor=base.dot_actor,
                          dot_counter=base.dot_counter)


def ormap_join(dst: ORMapState, src: ORMapState) -> ORMapState:
    """AWSet merge kernel for membership + LWW join for cells."""
    from go_crdt_playground_tpu.ops.merge import merge_kernel

    vv, present, da, dc, _ = merge_kernel(
        dst.vv, dst.present, dst.dot_actor, dst.dot_counter,
        src.vv, src.present, src.dot_actor, src.dot_counter)
    take = _lww_newer(src.ts, src.wr_actor, dst.ts, dst.wr_actor)
    return ORMapState(
        vv=vv, present=present, dot_actor=da, dot_counter=dc,
        actor=dst.actor,
        ts=jnp.where(take, src.ts, dst.ts),
        wr_actor=jnp.where(take, src.wr_actor, dst.wr_actor),
        val=jnp.where(take, src.val, dst.val),
    )


# ---------------------------------------------------------------------------
# Model-merging joins over float weight lanes (ROADMAP: "CRDTs for
# Neural Network Model Merging", arxiv 2605.19373)
# ---------------------------------------------------------------------------
#
# Weight merging treats a model's parameter tensor as CRDT state and a
# merge strategy as the join — the first genuinely TPU-shaped workload
# on this substrate (float lanes sharded like the AWSet element axis,
# PR 10's mesh target).  Three strategies register here, each with its
# HONEST law subset (JoinSpec.laws):
#
# * elementwise max  — a true lattice join (all three laws, exact):
#   convergent under any gossip schedule, the analyzer's full J001-J003
#   treatment for free.
# * elementwise mean — commutative ONLY: mean(mean(a,b),c) weights a
#   and b at 1/4 against c's 1/2, and mean(a,a) == a holds but
#   re-merging a stale copy mid-stream re-weights history.  Usable as a
#   pairwise merge STEP (the paper's iterative schedules), not as
#   anti-entropy: delivery order and multiplicity are semantics.
# * weighted average — the running-sum form (Σwᵢxᵢ, Σwᵢ): commutative
#   and associative (up to IEEE rounding — checked at atol), NOT
#   idempotent: joining a state with itself double-counts every
#   contribution.  Convergent under EXACTLY-ONCE op delivery (each
#   contribution applied once per replica — the op-based regime of the
#   semidirect-product composition line, arxiv 2004.04303), which is
#   what the serve frontend's idempotence story must NOT be assumed to
#   cover; the declared law subset records exactly that.


class TensorMergeState(NamedTuple):
    w: jnp.ndarray  # float32[R, D] weight lanes


def tensormerge_init(num_replicas: int, dim: int) -> TensorMergeState:
    return TensorMergeState(
        w=jnp.zeros((num_replicas, dim), jnp.float32))


def tensor_max_join(dst: TensorMergeState,
                    src: TensorMergeState) -> TensorMergeState:
    """Elementwise max over weight lanes — a real lattice join."""
    return dst._replace(w=jnp.maximum(dst.w, src.w))


def tensor_mean_join(dst: TensorMergeState,
                     src: TensorMergeState) -> TensorMergeState:
    """Pairwise elementwise mean — a merge STEP, not a lattice join
    (commutative only; see the section comment)."""
    return dst._replace(w=(dst.w + src.w) * jnp.float32(0.5))


class WeightedMergeState(NamedTuple):
    """Weighted-average merging in running-sum form: ``acc`` carries
    Σ weightᵢ·xᵢ per lane, ``weight`` Σ weightᵢ per replica — the
    grow-only-pair shape that makes the average order-free."""

    acc: jnp.ndarray     # float32[R, D]
    weight: jnp.ndarray  # float32[R, 1]


def weightedmerge_init(num_replicas: int, dim: int) -> WeightedMergeState:
    return WeightedMergeState(
        acc=jnp.zeros((num_replicas, dim), jnp.float32),
        weight=jnp.zeros((num_replicas, 1), jnp.float32))


def weighted_mean_join(dst: WeightedMergeState,
                       src: WeightedMergeState) -> WeightedMergeState:
    return WeightedMergeState(acc=dst.acc + src.acc,
                              weight=dst.weight + src.weight)


def weighted_mean_value(state: WeightedMergeState) -> np.ndarray:
    """The merged model: acc/weight per lane (host-side observer;
    zero-weight replicas read as zero, not NaN)."""
    acc = np.asarray(state.acc, np.float64)
    w = np.asarray(state.weight, np.float64)
    return np.where(w > 0, acc / np.maximum(w, 1e-30), 0.0)


# ---------------------------------------------------------------------------
# Generic batched rounds (any of the joins above)
# ---------------------------------------------------------------------------


def join_pairwise(join_fn, dst, src):
    """Batched dst[r] <- join(dst[r], src[r]) — lattice analogue of
    ops/merge.merge_pairwise; plugs into parallel/gossip permutation
    rounds."""
    return jax.vmap(join_fn)(dst, src)


def gossip_round(join_fn, state, perm):
    src = jax.tree.map(lambda x: x[perm], state)
    return join_pairwise(join_fn, state, src)


# ---------------------------------------------------------------------------
# Join registry (consumed by analysis/lattice_laws.py)
# ---------------------------------------------------------------------------


ALL_LAWS = ("commutativity", "associativity", "idempotence")


class JoinSpec(NamedTuple):
    """One registered join, packaged for property checking.

    ``sample(rng, n_rows, n_ops)`` returns a batched state of reachable
    rows — built by replaying seeded random ops of the family plus
    gossip mixing through the join itself, because the lattice laws are
    only promised over REACHABLE states (an arbitrary bit pattern can
    encode causal nonsense no replica could ever hold).  ``project``
    maps a state to the dict of observable arrays the laws are checked
    on; families whose non-observable metadata is order-sensitive by
    documented design (the AWSet stale-dot-overwrite quirk, merge.py)
    exclude it here, exactly as the crash soak's convergence digest
    does.

    ``laws`` is the family's DECLARED law subset — the model-merging
    strategies (arxiv 2605.19373) register joins that are deliberately
    not lattice joins (mean is not associative or idempotent; weighted
    accumulation is not idempotent), and recording the subset keeps
    them inside the J001-J003 pass instead of skipping it: the laws a
    family claims are still property-checked, and the report shows
    which were claimed.  ``atol`` switches the comparison to a
    float tolerance (0 = exact) for joins whose claimed laws hold only
    up to IEEE rounding (float addition is bitwise commutative but not
    bitwise associative)."""

    name: str
    sample: Callable[[np.random.Generator, int, int], Any]
    join: Callable[[Any, Any], Any]
    project: Callable[[Any], Dict[str, np.ndarray]]
    laws: Tuple[str, ...] = ALL_LAWS
    atol: float = 0.0


JOIN_REGISTRY: Dict[str, JoinSpec] = {}


def register_join(spec: JoinSpec) -> JoinSpec:
    """Idempotent by name (re-import safe); the analysis gate enumerates
    this registry, so a new family is law-checked the moment it
    registers."""
    JOIN_REGISTRY[spec.name] = spec
    return spec


def mix_rows(join_fn, state, rng: np.random.Generator, p: float = 0.5):
    """One gossip-style mixing step of the reachable-state samplers:
    each row joins a permuted partner row with probability ``p``."""
    n = int(state[0].shape[0])
    perm = jnp.asarray(rng.permutation(n))
    src = jax.tree.map(lambda x: x[perm], state)
    merged = join_fn(state, src)
    mask = rng.random(n) < p

    def sel(m, o):
        mm = jnp.asarray(mask.reshape((n,) + (1,) * (m.ndim - 1)))
        return jnp.where(mm, m, o)

    return jax.tree.map(sel, merged, state)


_SAMPLE_ELEMS = 8  # element universe of the set/map family samplers


def _sample_gcounter(rng: np.random.Generator, n: int, n_ops: int):
    state = gcounter_init(n, n)
    for _ in range(n_ops):
        if rng.random() < 0.6:
            state = gcounter_inc(state, jnp.uint32(rng.integers(n)),
                                 jnp.uint32(rng.integers(1, 5)))
        else:
            state = mix_rows(gcounter_join, state, rng)
    return state


def _sample_pncounter(rng: np.random.Generator, n: int, n_ops: int):
    state = pncounter_init(n, n)
    for _ in range(n_ops):
        if rng.random() < 0.6:
            state = pncounter_add(state, jnp.uint32(rng.integers(n)),
                                  jnp.int32(rng.integers(-4, 5)))
        else:
            state = mix_rows(pncounter_join, state, rng)
    return state


def _sample_twopset(rng: np.random.Generator, n: int, n_ops: int):
    state = twopset_init(n, _SAMPLE_ELEMS)
    for _ in range(n_ops):
        roll = rng.random()
        r = jnp.uint32(rng.integers(n))
        e = jnp.uint32(rng.integers(_SAMPLE_ELEMS))
        if roll < 0.4:
            state = twopset_add(state, r, e)
        elif roll < 0.6:
            state = twopset_del(state, r, e)
        else:
            state = mix_rows(twopset_join, state, rng)
    return state


def _sample_lwwmap(rng: np.random.Generator, n: int, n_ops: int):
    state = lwwmap_init(n, _SAMPLE_ELEMS)
    ts = 0
    for _ in range(n_ops):
        if rng.random() < 0.6:
            ts += 1  # globally unique stamps: the documented caller
            #          contract (ties on (ts, actor) are out of model)
            state = lwwmap_put(
                state, jnp.uint32(rng.integers(n)),
                jnp.uint32(rng.integers(_SAMPLE_ELEMS)),
                jnp.uint32(rng.integers(1000)), jnp.uint32(ts),
                jnp.bool_(bool(rng.random() < 0.8)))
        else:
            state = mix_rows(lwwmap_join, state, rng)
    return state


def _sample_mvregister(rng: np.random.Generator, n: int, n_ops: int):
    state = mvregister_init(n, n)
    val = 0
    for _ in range(n_ops):
        if rng.random() < 0.6:
            val += 1
            state = mvregister_write(state, jnp.uint32(rng.integers(n)),
                                     jnp.uint32(val))
        else:
            state = mix_rows(mvregister_join, state, rng)
    return state


def _sample_ormap(rng: np.random.Generator, n: int, n_ops: int):
    state = ormap_init(n, _SAMPLE_ELEMS, n)
    # single-put-per-element ownership: re-adding a live element
    # exercises the documented stale-dot-overwrite order sensitivity of
    # the underlying AWSet merge (merge.py docstring) — in scope for the
    # soaks' convergence story, out of model for the lattice laws
    unput = list(range(_SAMPLE_ELEMS))
    ts = 0
    for _ in range(n_ops):
        roll = rng.random()
        if roll < 0.35 and unput:
            e = unput.pop(int(rng.integers(len(unput))))
            ts += 1
            state = ormap_put(state, jnp.uint32(e % n), jnp.uint32(e),
                              jnp.uint32(rng.integers(1000)),
                              jnp.uint32(ts))
        elif roll < 0.55:
            state = ormap_delete(state, jnp.uint32(rng.integers(n)),
                                 jnp.uint32(rng.integers(_SAMPLE_ELEMS)))
        else:
            state = mix_rows(ormap_join, state, rng)
    return state


_SAMPLE_DIM = 16  # weight-lane universe of the model-merging samplers


def _sample_tensor_merge(join_fn):
    """Reachable-state sampler for the float-lane families: seeded
    local 'train steps' (row perturbations) interleaved with gossip
    mixing through the join itself."""

    def sample(rng: np.random.Generator, n: int, n_ops: int):
        state = TensorMergeState(w=jnp.asarray(
            rng.normal(0.0, 1.0, (n, _SAMPLE_DIM)).astype(np.float32)))
        for _ in range(n_ops):
            if rng.random() < 0.6:
                r = int(rng.integers(n))
                step = jnp.asarray(
                    rng.normal(0.0, 0.5, _SAMPLE_DIM)
                    .astype(np.float32))
                state = state._replace(w=state.w.at[r].add(step))
            else:
                state = mix_rows(join_fn, state, rng)
        return state

    return sample


def _sample_weighted_merge(rng: np.random.Generator, n: int,
                           n_ops: int):
    # start from one weighted contribution per replica, then keep
    # contributing (acc += w·x, weight += w — the op) and mixing
    w0 = rng.uniform(0.1, 2.0, (n, 1)).astype(np.float32)
    x0 = rng.normal(0.0, 1.0, (n, _SAMPLE_DIM)).astype(np.float32)
    state = WeightedMergeState(acc=jnp.asarray(w0 * x0),
                               weight=jnp.asarray(w0))
    for _ in range(n_ops):
        if rng.random() < 0.6:
            r = int(rng.integers(n))
            w = float(rng.uniform(0.1, 2.0))
            x = rng.normal(0.0, 1.0, _SAMPLE_DIM).astype(np.float32)
            state = WeightedMergeState(
                acc=state.acc.at[r].add(jnp.asarray(
                    (w * x).astype(np.float32))),
                weight=state.weight.at[r, 0].add(jnp.float32(w)))
        else:
            state = mix_rows(weighted_mean_join, state, rng)
    return state


def _np_fields(state, names) -> Dict[str, np.ndarray]:
    return {f: np.asarray(getattr(state, f)) for f in names}


register_join(JoinSpec(
    "gcounter", _sample_gcounter, gcounter_join,
    lambda s: _np_fields(s, ("counts",))))
register_join(JoinSpec(
    "pncounter", _sample_pncounter, pncounter_join,
    lambda s: _np_fields(s, ("p", "n"))))
register_join(JoinSpec(
    "twopset", _sample_twopset, twopset_join,
    lambda s: _np_fields(s, ("added", "removed"))))
register_join(JoinSpec(
    "lwwmap", _sample_lwwmap, lwwmap_join,
    lambda s: _np_fields(s, ("ts", "wr_actor", "val", "live"))))
register_join(JoinSpec(
    "mvregister", _sample_mvregister, mvregister_join,
    lambda s: _np_fields(s, ("ctx", "live", "cnt", "val"))))
register_join(JoinSpec(
    "ormap", _sample_ormap, ormap_join,
    # membership + cells; dot metadata excluded (AWSet overwrite quirk)
    lambda s: _np_fields(s, ("vv", "present", "ts", "wr_actor", "val"))))
# model-merging strategies, each with its HONEST law subset (the
# section comment above documents why mean/weighted claim fewer laws —
# recorded via JoinSpec.laws, never by skipping the pass)
register_join(JoinSpec(
    "tensor_max", _sample_tensor_merge(tensor_max_join),
    tensor_max_join, lambda s: _np_fields(s, ("w",))))
register_join(JoinSpec(
    "tensor_mean", _sample_tensor_merge(tensor_mean_join),
    tensor_mean_join, lambda s: _np_fields(s, ("w",)),
    laws=("commutativity",)))
register_join(JoinSpec(
    "weighted_mean", _sample_weighted_merge, weighted_mean_join,
    lambda s: _np_fields(s, ("acc", "weight")),
    laws=("commutativity", "associativity"), atol=1e-3))
