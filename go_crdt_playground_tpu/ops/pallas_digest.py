"""Pallas TPU twin of the packed per-lane digest kernel.

``ops/digest.state_group_digests`` is one fused elementwise XLA pass;
this kernel computes the identical fingerprints with each element block
resident in VMEM — the convergent-projection arrays (present, deletion
log, deletion dots) stream HBM→VMEM once and the whole mix runs on the
VPU, the ``ops/pallas_ingest.py`` treatment applied to the digest path.  The group XOR fold runs in XLA around the
kernel (a [E]→[G] reduction is bandwidth-trivial next to the state
read), so the bitwise-pinned fingerprint algebra
(``ops/digest.lane_fingerprint_arrays``) is shared verbatim.

Ladder (the merge/δ/ingest kernels' contract): off-TPU the kernel runs
in interpret mode; block shapes the kernel cannot take fall back to
the XLA pass.  ``tests/test_digest_kernel.py`` pins bitwise equality
across occupancies, paddings, and the fallback boundary.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState
from go_crdt_playground_tpu.ops.digest import (DIGEST_GROUP_LANES,
                                               group_fold,
                                               lane_fingerprint_arrays)
from go_crdt_playground_tpu.ops.pallas_merge import _LANE, _round_up


def _digest_kernel(blk: int, p_ref, d_ref, dda_ref, ddc_ref, out_ref):
    """One element block: fingerprint the resident lanes (the
    convergent projection: present, deletion log, deletion dots —
    ops/digest.py).  Lane ids are reconstructed from the grid position
    (block j covers lanes [j*blk, (j+1)*blk)), so padded lanes hash as
    zero-state lanes at their true ids — exactly the XLA pass's
    padding semantics."""
    j = pl.program_id(0)
    base = (j * blk).astype(jnp.uint32)
    lane_ids = base + jax.lax.broadcasted_iota(jnp.uint32, (1, blk), 1)
    out_ref[...] = lane_fingerprint_arrays(
        lane_ids, p_ref[...], d_ref[...], dda_ref[...], ddc_ref[...])


@functools.partial(jax.jit, static_argnames=("block_e", "interpret"))
def _fused_fingerprints(state: AWSetDeltaState, block_e: int,
                        interpret: bool) -> jnp.ndarray:
    num_e = state.present.shape[-1]
    e_pad = _round_up(num_e, _LANE)
    blk = min(_round_up(block_e, _LANE), e_pad)
    while e_pad % blk:
        blk -= _LANE

    def pad_lane(x):
        x = x.astype(jnp.uint8) if x.dtype == jnp.bool_ else x
        return jnp.pad(x[None, :], ((0, 0), (0, e_pad - num_e)))

    ins = [pad_lane(state.present), pad_lane(state.deleted),
           pad_lane(state.del_dot_actor),
           pad_lane(state.del_dot_counter)]
    e_blk = pl.BlockSpec((1, blk), lambda j: (0, j))
    out = pl.pallas_call(
        functools.partial(_digest_kernel, blk),
        grid=(e_pad // blk,),
        in_specs=[e_blk] * 4,
        out_specs=e_blk,
        out_shape=jax.ShapeDtypeStruct((1, e_pad), jnp.uint32),
        interpret=interpret,
    )(*ins)
    return out[0, :num_e]


def pallas_lane_fingerprints(state: AWSetDeltaState, *,
                             block_e: int = 512,
                             interpret: bool | None = None) -> jnp.ndarray:
    """Drop-in bitwise twin of ``ops/digest.lane_fingerprints``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _fused_fingerprints(state, block_e, interpret)


def pallas_state_group_digests(state: AWSetDeltaState,
                               group_size: int = DIGEST_GROUP_LANES, *,
                               block_e: int = 512,
                               interpret: bool | None = None
                               ) -> jnp.ndarray:
    """Drop-in bitwise twin of ``ops/digest.state_group_digests`` (the
    ``digest_regime`` TPU arm): Pallas fingerprints + the shared XLA
    group fold."""
    return group_fold(
        pallas_lane_fingerprints(state, block_e=block_e,
                                 interpret=interpret), group_size)
