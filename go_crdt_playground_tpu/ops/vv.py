"""Version-vector primitives, tensorized.

Reference semantics: crdt-misc.go:23-74.  The packed representation is
``vv: uint32[..., A]`` with a fixed actor axis; zero-padding is exact
because counter 0 means "never seen" (crdt-misc.go:29-41 — and fixes the
reference's latent OOB panic for ``d.Actor == len(vv)``).
"""

from __future__ import annotations

import jax.numpy as jnp


def has_dot(vv: jnp.ndarray, dot_actor: jnp.ndarray,
            dot_counter: jnp.ndarray) -> jnp.ndarray:
    """Vectorized ``VersionVector.HasDot`` (crdt-misc.go:28-34).

    vv: uint32[A]; dot_actor/dot_counter: uint32[...] element-shaped.
    Returns bool[...]: vv[dot_actor] >= dot_counter.

    A gather + compare (SURVEY §7.1).  Callers guarantee dot_actor < A by
    construction (packed dots are produced from in-range actors; absent
    lanes are zeroed and masked out by the caller's boolean algebra).
    ``mode="clip"`` semantics of jnp.take keep even garbage indices safe.
    """
    counters = jnp.take(vv, dot_actor.astype(jnp.int32), mode="clip")
    return counters >= dot_counter


def vv_join(vv_dst: jnp.ndarray, vv_src: jnp.ndarray) -> jnp.ndarray:
    """Elementwise-max lattice join (``VersionVector.Merge``,
    crdt-misc.go:43-55).  With a fixed actor axis the append-extension
    branch (crdt-misc.go:50-52) is subsumed by zero padding."""
    return jnp.maximum(vv_dst, vv_src)
