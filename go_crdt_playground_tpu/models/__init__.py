"""CRDT model families.

``spec`` is the executable specification (pure Python, conformance oracle).
The sibling modules define packed-tensor replica states and host-level APIs
for each CRDT family.
"""

from go_crdt_playground_tpu.models import spec
from go_crdt_playground_tpu.models.digest import (array_digest,  # noqa: F401
                                                  state_digest)

__all__ = ["spec", "array_digest", "state_digest"]
