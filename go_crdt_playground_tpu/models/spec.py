"""Executable specification of the reference CRDT semantics.

This module is the *conformance oracle* for the TPU framework: a small,
dependency-free, pure-Python model of the reference's OR-SWOT (add-wins,
tombstone-free observed-remove set) with dotted version vectors, plus the
δ-state prototype.  Every behavioral subtlety of the reference is preserved
here bit-for-bit so the packed-tensor kernels in
:mod:`go_crdt_playground_tpu.ops` can be checked against it on arbitrary
operation sequences (see ``tests/test_spec_conformance.py`` and
``tests/test_merge_kernel.py``).

Reference anchors (cited as file:line into /root/reference):

* ``Actor``       — crdt-misc.go:9      (0-based replica/client id)
* ``Dot``         — crdt-misc.go:12-19  ((actor, counter) event stamp)
* ``VersionVector`` — crdt-misc.go:23-74
* ``AWSet``       — awset.go:55-171
* ``AWSetDelta``  — awset-delta_test.go:9-105
* ``AWSet.deltaMerge`` — awset-delta_test.go:107-166

Deliberate deviations from the reference (documented quirk fixes; each is
exercised by a dedicated test):

1. ``VersionVector.has_dot`` / ``counter`` use a ``>=`` bounds guard.  The
   reference's guard is ``Actor(len(vv)) < d.Actor`` (crdt-misc.go:29, :37),
   which panics (index out of range) when ``d.Actor == len(vv)``.  We return
   False / 0 for *any* out-of-range actor, which is the semantically intended
   behavior ("never seen this actor").
2. ``AWSet.reset`` restores a version vector of the original length rather
   than hard-coding length 1 (awset.go:73 shrinks the VV to ``{0}``
   regardless of actor count — latent bug, method is never called by the
   reference's tests).
3. No ``os.Exit(0)`` mid-suite (awset_test.go:153 kills the Go test binary
   before TestVersionVector can run; our port runs everything).

Reference quirks that ARE preserved (they are semantics, not bugs):

* ``AWSet.del_`` does NOT tick the actor's clock (awset.go:97 — the
  increment is commented out in the reference).
* ``AWSetDelta.del_`` DOES tick the clock, exactly once per call (not per
  key), and stamps every key deleted in that call with the same dot
  (awset-delta_test.go:15-16, 26).
* Merge phase 1 *unconditionally overwrites* the destination dot when the
  element is present on both sides (awset.go:142), so per-entry dots can
  diverge across replicas after a simultaneous snapshot exchange even though
  membership and VVs converge.  Convergence is therefore defined on
  (membership, VV) — see ``AWSet.converged_with``.
* ``AWSetDelta.merge`` with an empty δ payload returns early WITHOUT joining
  version vectors (awset-delta_test.go:60-64): entries converge before
  clocks do.  Controlled by ``strict_reference_semantics``.
* δ-merge phase 2 logs a no-op "remove" for keys absent on the receiver
  (awset-delta_test.go:160-162).
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

__all__ = [
    "Actor",
    "Dot",
    "VersionVector",
    "AWSet",
    "AWSetDelta",
    "TraceEvent",
    "TraceFn",
]

# Actor is a 0-based identifier for a specific actor (crdt-misc.go:9).
Actor = int


class Dot(NamedTuple):
    """One event on one actor's clock (crdt-misc.go:12-15)."""

    actor: Actor
    counter: int

    def __str__(self) -> str:
        # "(A 1)" — crdt-misc.go:17-19
        return f"({chr(ord('A') + self.actor)} {self.counter})"


class TraceEvent(NamedTuple):
    """One merge decision, mirroring the reference's ``logOutcome`` printf
    tracing (awset.go:109-119, awset-delta_test.go:113-123).

    ``outcome`` is one of the reference's five labels:
    ``update | keep | skip | add | remove``.
    """

    phase: int
    key: str
    dst_dot: Optional[Dot]
    src_dot: Optional[Dot]
    outcome: str


# Optional trace sink; replaces the reference's unconditional fmt.Printf.
TraceFn = Callable[[TraceEvent], None]


class VersionVector:
    """Per-actor max counter — the causal-context lattice (crdt-misc.go:23).

    Backed by a plain list indexed by actor.  Unlike the packed-tensor
    representation (fixed actor axis ``A``), the spec keeps the reference's
    variable-length growth semantics (crdt-misc.go:50-52: merge appends
    unseen actor slots).
    """

    __slots__ = ("v",)

    def __init__(self, counters: Optional[List[int]] = None):
        self.v: List[int] = list(counters) if counters else []

    def has_dot(self, d: Dot) -> bool:
        """True iff ``d`` is within this causal context (crdt-misc.go:28-34).

        Out-of-range actors were never seen → False.  (Bounds guard fixed
        relative to the reference; see module docstring, deviation 1.)
        """
        if d.actor >= len(self.v) or d.actor < 0:
            return False
        return self.v[d.actor] >= d.counter

    def counter(self, a: Actor) -> int:
        """Max counter seen for actor ``a`` (crdt-misc.go:36-41)."""
        if a >= len(self.v) or a < 0:
            return 0
        return self.v[a]

    def merge(self, src: "VersionVector") -> None:
        """Elementwise max join, extending with src's extra slots
        (crdt-misc.go:43-55)."""
        for i, n in enumerate(src.v):
            if i < len(self.v):
                if self.v[i] < n:
                    self.v[i] = n
            else:
                self.v.append(n)

    def clone(self) -> "VersionVector":
        return VersionVector(self.v)  # crdt-misc.go:70-74

    def __eq__(self, other: object) -> bool:
        return isinstance(other, VersionVector) and self.v == other.v

    def __len__(self) -> int:
        return len(self.v)

    def __getitem__(self, a: Actor) -> int:
        return self.v[a]

    def __setitem__(self, a: Actor, n: int) -> None:
        self.v[a] = n

    def __str__(self) -> str:
        # "[(A 1), (B 2)]" — crdt-misc.go:57-68
        inner = ", ".join(
            f"({chr(ord('A') + i)} {n})" for i, n in enumerate(self.v)
        )
        return f"[{inner}]"

    def __repr__(self) -> str:
        return f"VersionVector({self.v!r})"


def _go_quote(s: str) -> str:
    """Go's ``%q`` for the subset of strings the tests use (printable ASCII).

    Canonical rendering is the de-facto state-equality format of the
    reference (awset.go:163-171); keeping it byte-compatible lets conformance
    tests compare serialized states across spec and tensor paths.
    """
    out = ['"']
    for ch in s:
        if ch in ('"', "\\"):
            out.append("\\" + ch)
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\t":
            out.append("\\t")
        elif 0x20 <= ord(ch) < 0x7F or (ord(ch) > 0x7F and ch.isprintable()):
            # Go's strconv.Quote keeps printable runes literal.
            out.append(ch)
        elif ord(ch) > 0xFFFF:
            out.append(f"\\U{ord(ch):08x}")
        else:
            out.append(f"\\u{ord(ch):04x}")
    out.append('"')
    return "".join(out)


class AWSet:
    """OR-SWOT: tombstone-free observed-remove set, concurrent add wins
    (awset.go:55-59 and the algorithm doc at awset.go:9-53).

    One instance = one replica.  "Network exchange" is ``dst.merge(src)``
    with direct access to src's state, exactly as in the reference's
    simulation harness (awset_test.go:16-17).
    """

    def __init__(
        self,
        actor: Actor = 0,
        version_vector: Optional[VersionVector] = None,
        entries: Optional[Dict[str, Dot]] = None,
        trace: Optional[TraceFn] = None,
    ):
        self.actor: Actor = actor
        self.version_vector: VersionVector = (
            version_vector if version_vector is not None else VersionVector()
        )
        self.entries: Dict[str, Dot] = entries if entries is not None else {}
        self.trace: Optional[TraceFn] = trace

    # -- observers ---------------------------------------------------------

    def sorted_values(self) -> List[str]:
        """Sorted live membership (awset.go:61-70)."""
        return sorted(self.entries)

    def has(self, k: str) -> bool:
        """Membership test (awset.go:87)."""
        return k in self.entries

    # -- lifecycle ---------------------------------------------------------

    def reset(self) -> None:
        """Reinitialize (awset.go:72-75; VV length preserved — deviation 2)."""
        self.version_vector = VersionVector([0] * max(1, len(self.version_vector)))
        self.entries = {}

    def clone(self) -> "AWSet":
        """Deep copy; used by tests to fork timelines (awset.go:77-85)."""
        return AWSet(
            actor=self.actor,
            version_vector=self.version_vector.clone(),
            entries=dict(self.entries),
            trace=self.trace,
        )

    # -- mutators ----------------------------------------------------------

    def add(self, *keys: str) -> None:
        """Add/update: tick own clock per key, stamp birth dot (awset.go:89-94).

        Invariant established: every entry's dot is covered by its own
        replica's VV (merge phase 2 relies on this).
        """
        for k in keys:
            self.version_vector[self.actor] += 1
            self.entries[k] = Dot(self.actor, self.version_vector[self.actor])

    def del_(self, *keys: str) -> None:
        """Remove without tombstone and WITHOUT ticking the clock
        (awset.go:96-101; the increment is commented out at awset.go:97)."""
        for k in keys:
            self.entries.pop(k, None)

    # -- sync --------------------------------------------------------------

    def merge(self, src: "AWSet") -> None:
        """Full-state anti-entropy: ``dst <- src`` (awset.go:103-105)."""
        self._merge(src.version_vector, src.entries)

    def _log(self, phase: int, k: str, dst_dot, src_dot, outcome: str) -> None:
        if self.trace is not None:
            self.trace(TraceEvent(phase, k, dst_dot, src_dot, outcome))

    def _merge(self, src_vv: VersionVector, src_entries: Dict[str, Dot]) -> None:
        """The two-phase merge (awset.go:107-161).  THE hot loop that the
        tensor kernel in ops/merge.py vectorizes."""
        dst = self
        # PHASE 1: walk src entries (awset.go:122-143).
        for k, src_dot in src_entries.items():
            dst_dot = dst.entries.get(k)
            if dst_dot is not None:
                # Present on both sides: unconditional dot overwrite
                # (awset.go:123-129, 142).  This is why per-entry dots may
                # diverge across replicas; see module docstring.
                self._log(1, k, dst_dot, src_dot,
                          "update" if dst_dot != src_dot else "keep")
            else:
                # Absent locally: if our clock already covers the dot we saw
                # this add and deleted it — skip; else it's a new add
                # (awset.go:131-141).
                if dst.version_vector.has_dot(src_dot):
                    self._log(1, k, None, src_dot, "skip")
                    continue
                self._log(1, k, None, src_dot, "add")
            dst.entries[k] = src_dot
        # PHASE 2: walk dst entries; remove what src has witnessed-and-dropped
        # (awset.go:145-159).
        for k in list(dst.entries):
            dst_dot = dst.entries[k]
            src_dot = src_entries.get(k)
            if src_dot is not None:
                self._log(2, k, dst_dot, src_dot, "keep")
            elif src_vv.has_dot(dst_dot):
                self._log(2, k, dst_dot, None, "remove")
                del dst.entries[k]
            else:
                self._log(2, k, dst_dot, None, "keep")
        # VV join (awset.go:160).
        dst.version_vector.merge(src_vv)

    # -- equality / rendering ---------------------------------------------

    def converged_with(self, other: "AWSet") -> bool:
        """Convergence is (membership, VV) equality — per-entry dots may
        legitimately diverge (SURVEY §3.2 [verified] semantics)."""
        return (
            self.sorted_values() == other.sorted_values()
            and self.version_vector == other.version_vector
        )

    def __str__(self) -> str:
        # Canonical sorted rendering (awset.go:163-171):
        #   [(A 1), (B 2)]\n  (A 1)  "Alice"\n  ...
        parts = [str(self.version_vector)]
        for value in self.sorted_values():
            parts.append(f"\n  {self.entries[value]}  {_go_quote(value)}")
        return "".join(parts)


class AWSetDelta(AWSet):
    """δ-state AWSet: tracks a deletion log so only changed/deleted entries
    ship on subsequent merges (awset-delta_test.go:9-12).

    Two δ semantics are offered via ``delta_semantics``:

    ``"reference"`` (default) — byte-faithful to the reference prototype:

      * The payload ships only the sender's OWN-origin deletion records
        (``Deleted`` is written only by local ``Del``,
        awset-delta_test.go:14-33; ``deltaMerge`` never writes the
        receiver's log).  Deletions therefore propagate on the δ path only
        pairwise-directly from their originator; a third replica that never
        syncs with the originator keeps the entry forever.
      * Deletion arbitration at the receiver checks the receiver's VV
        against the DELETION dot (awset-delta_test.go:153): remove iff
        ``not dst.vv.has_dot(deletion_dot)``.  In 3+ actor topologies this
        can delete an entry whose live dot came from a concurrent add the
        deleter never observed — i.e. it can violate add-wins, unlike the
        full-state merge whose phase 2 checks the sender's VV against the
        LIVE dot (awset.go:152).  Both behaviors are pinned by tests.
      * An all-empty payload returns early WITHOUT joining VVs
        (awset-delta_test.go:60-64) when ``strict_reference_semantics``.
      * No GC (the reference's gcDeleted is an empty stub,
        awset-delta_test.go:67-77); ``gc_enabled=True`` adds a pairwise ack
        frontier that is sound for the 2-replica topology the reference
        exercises (and only there — see gc_deleted).

    ``"v2"`` — the principled δ-ORSWOT this framework actually ships for
    scale (cf. Almeida/Shoker/Baquero delta-state CRDTs, PAPERS.md):

      * Deletion arbitration is EXACTLY full-merge phase 2 restricted to
        the payload's key set: remove a live entry iff the sender's VV
        covers its LIVE dot (and it is absent at the sender).  δ-merge and
        full merge therefore agree in every topology; add-wins holds.
      * Received deletion records are absorbed into the receiver's own log
        and re-gossip transitively, so deletions reach replicas that never
        talk to the originator.
      * Each replica maintains a ``processed`` vector — for each origin
        actor, the highest deletion counter whose effects its state
        reflects — advertised with the VV.  It is joined only on exchanges
        that actually transfer those effects (never inferred from VV joins,
        which propagate counters without deletion records).
      * GC by causal stability: a record (k, (a, c)) is dropped once every
        known peer's advertised ``processed[a] >= c``.
      * Clocks always join (no empty-δ quirk) and GC runs on every
        exchange.

    The v2 receiver rule being "full merge masked to a key set" is also
    what makes it the TPU-friendly variant: the dense kernel is the same
    boolean algebra as the full merge with a payload mask (ops/delta.py).
    """

    def __init__(self, *args, gc_enabled: bool = False,
                 strict_reference_semantics: bool = True,
                 delta_semantics: str = "reference", **kwargs):
        super().__init__(*args, **kwargs)
        if delta_semantics not in ("reference", "v2"):
            raise ValueError(f"unknown delta_semantics {delta_semantics!r}")
        self.delta_semantics = delta_semantics
        self.deleted: Dict[str, Dot] = {}
        # reference-mode GC: peer actor -> highest counter for OUR actor's
        # clock that the peer has directly advertised.
        self.peer_acked: Dict[Actor, int] = {}
        # v2: origin actor -> highest deletion counter whose effects this
        # replica's state reflects.  Invariant: processed[self] == vv[self].
        self.processed: Dict[Actor, int] = {}
        # v2: peer actor -> that peer's last advertised processed vector.
        self.peer_processed: Dict[Actor, Dict[Actor, int]] = {}
        self.gc_enabled = gc_enabled
        # When True, an all-empty δ payload skips the VV join exactly like
        # awset-delta_test.go:60-64.  When False, VVs are always joined
        # (clocks converge with entries).  Reference mode only.
        self.strict_reference_semantics = strict_reference_semantics

    def clone(self) -> "AWSetDelta":
        c = AWSetDelta(
            actor=self.actor,
            version_vector=self.version_vector.clone(),
            entries=dict(self.entries),
            trace=self.trace,
            gc_enabled=self.gc_enabled,
            strict_reference_semantics=self.strict_reference_semantics,
            delta_semantics=self.delta_semantics,
        )
        c.deleted = dict(self.deleted)  # awset-delta_test.go:35-49
        c.peer_acked = dict(self.peer_acked)
        c.processed = dict(self.processed)
        c.peer_processed = {a: dict(p) for a, p in self.peer_processed.items()}
        return c

    def add(self, *keys: str) -> None:
        super().add(*keys)
        # Invariant: a replica has trivially processed its own events.
        self.processed[self.actor] = self.version_vector[self.actor]

    def del_(self, *keys: str) -> None:
        """δ-Del ticks the clock ONCE PER CALL and stamps all keys deleted in
        this call with that one shared dot (awset-delta_test.go:14-33).
        Note the clock ticks even if no key is present."""
        self.version_vector[self.actor] += 1
        dot2 = Dot(self.actor, self.version_vector[self.actor])
        for k in keys:
            if k in self.entries:
                self.deleted[k] = dot2
                del self.entries[k]
        self.processed[self.actor] = self.version_vector[self.actor]

    def merge(self, src: "AWSetDelta") -> None:  # type: ignore[override]
        """δ-dispatch (awset-delta_test.go:51-65): first contact → full
        merge; otherwise sender compresses a δ payload against our VV."""
        if self.version_vector.counter(src.actor) <= 0:
            # Never seen src's actor: full merge.  Reference mode does NOT
            # transfer src.deleted (deletions propagate via the VV in
            # phase 2); v2 additionally absorbs the log and processed
            # vector, since the merged state reflects every deletion src's
            # state reflected.
            self._merge(src.version_vector, src.entries)
            if self.delta_semantics == "v2":
                self._absorb_records(src.deleted)
                self._join_processed(src)
                self._note_peer_processed(src)
                self.gc_deleted(src.actor, src.version_vector)
            return
        changed, deleted = src.make_delta_merge_data(self.version_vector)
        if changed is None and deleted is None:
            # Empty δ: reference mode EARLY-RETURNS — VV not merged and no
            # GC pass (the reference's gcDeleted call sits inside the
            # non-empty branch, awset-delta_test.go:60-64).  Entries
            # converge before clocks.  Non-strict/v2 join clocks and still
            # count the ack.
            if self.delta_semantics == "v2":
                self.version_vector.merge(src.version_vector)
                self._join_processed(src)
                self._note_peer_processed(src)
                self.gc_deleted(src.actor, src.version_vector)
            elif not self.strict_reference_semantics:
                self.version_vector.merge(src.version_vector)
                self.gc_deleted(src.actor, src.version_vector)
            return
        self.delta_merge(src.version_vector, changed or {}, deleted or {})
        if self.delta_semantics == "v2":
            self._absorb_records(deleted or {})
            self._join_processed(src)
            self._note_peer_processed(src)
        self.gc_deleted(src.actor, src.version_vector)

    # -- v2 bookkeeping ----------------------------------------------------

    def _absorb_records(self, records: Dict[str, Dot]) -> None:
        """v2: received deletion records enter our own log so they re-gossip
        transitively (reference mode never does this — that is why its
        deletions only travel originator→peer).

        The retained record is the lexicographic MAX on (counter,
        actor): counter ties between records from DIFFERENT actors are
        broken by actor id, never by arrival order — without the
        tie-break the absorb is not a join (two replicas receiving the
        same tied records in opposite orders keep different ones
        forever), which the digest-sync regime (DESIGN.md §19) would
        read as permanent lane divergence."""
        for k, d in records.items():
            cur = self.deleted.get(k)
            if cur is None or (d.counter, d.actor) > (cur.counter,
                                                     cur.actor):
                self.deleted[k] = d

    def _join_processed(self, src: "AWSetDelta") -> None:
        """v2: join src's processed vector.  Sound because the exchange that
        carries it also carries (changed, deleted-records) — after applying
        them our state reflects every deletion src's state reflected.  The
        sender's own-origin log is always complete in the payload, so its
        own slot advances to its clock."""
        for a, c in src.processed.items():
            if self.processed.get(a, 0) < c:
                self.processed[a] = c
        own = src.version_vector.counter(src.actor)
        if self.processed.get(src.actor, 0) < own:
            self.processed[src.actor] = own

    def _note_peer_processed(self, src: "AWSetDelta") -> None:
        adv = dict(src.processed)
        adv[src.actor] = src.version_vector.counter(src.actor)
        cur = self.peer_processed.setdefault(src.actor, {})
        for a, c in adv.items():
            if cur.get(a, 0) < c:
                cur[a] = c

    def make_delta_merge_data(
        self, dst_vv: VersionVector
    ) -> Tuple[Optional[Dict[str, Dot]], Optional[Dict[str, Dot]]]:
        """SENDER-side δ-computation (awset-delta_test.go:79-105): the
        receiver advertises its VV; we ship only entries it can't have seen
        plus deletions not masked by a later re-add.

        Returns (changed, deleted); each is None when empty — the None-ness
        (not just emptiness) drives the early-return quirk upstream."""
        changed: Optional[Dict[str, Dot]] = None
        deleted: Optional[Dict[str, Dot]] = None
        for k, dot in self.entries.items():
            if not dst_vv.has_dot(dot):
                if changed is None:
                    changed = {}
                changed[k] = dot
        for k, dot in self.deleted.items():
            mdot = self.entries.get(k)
            if mdot is not None and (mdot.actor != dot.actor or mdot.counter > dot.counter):
                # deleted then re-added; the deletion is obsolete — skip
                # (awset-delta_test.go:93-97).
                continue
            if deleted is None:
                deleted = {}
            deleted[k] = dot
        return changed, deleted

    def delta_merge(
        self,
        src_vv: VersionVector,
        src_changes: Dict[str, Dot],
        src_deleted: Dict[str, Dot],
    ) -> None:
        """Receiver-side δ-apply (awset-delta_test.go:107-166).

        In the reference this is a method on AWSet (not AWSetDelta) — it only
        touches (entries, VV), never the receiver's own deletion log."""
        dst = self
        # PHASE 1 over changes: identical decision table to full-merge
        # phase 1 (awset-delta_test.go:126-147).
        for k, src_dot in src_changes.items():
            dst_dot = dst.entries.get(k)
            if dst_dot is not None:
                self._log(1, k, dst_dot, src_dot,
                          "update" if dst_dot != src_dot else "keep")
            else:
                if dst.version_vector.has_dot(src_dot):
                    self._log(1, k, None, src_dot, "skip")
                    continue
                self._log(1, k, None, src_dot, "add")
            dst.entries[k] = src_dot
        # PHASE 2 over the deletion payload (awset-delta_test.go:149-164).
        # The HasDot checks use dst's PRE-JOIN VV (the join happens below).
        for k, src_dot in src_deleted.items():
            dst_dot = dst.entries.get(k)
            if dst_dot is not None:
                if getattr(self, "delta_semantics", "reference") == "v2":
                    # v2 arbitration == full-merge phase 2 (awset.go:152)
                    # restricted to this key: remove iff the SENDER's VV
                    # covers our LIVE dot (sender witnessed that very add
                    # and still says gone).  Keeps add-wins in any topology.
                    if src_vv.has_dot(dst_dot):
                        self._log(2, k, dst_dot, None, "remove")
                        del dst.entries[k]
                    else:
                        self._log(2, k, dst_dot, src_dot, "keep")
                elif dst.version_vector.has_dot(src_dot):
                    # Reference arbitration (awset-delta_test.go:153-155):
                    # our VV covers the DELETION dot — we already knew a
                    # state at/after it and the entry is (re-)present
                    # locally: keep.  (Can violate add-wins with 3+ actors;
                    # pinned by test_reference_delta_add_wins_violation.)
                    self._log(2, k, None, src_dot, "keep")
                else:
                    self._log(2, k, dst_dot, None, "remove")
                    del dst.entries[k]
            else:
                # No-op delete; the reference logs it with a zero-value Dot
                # (awset-delta_test.go:160-162) — cosmetic; we log None.
                self._log(2, k, None, None, "remove")
        dst.version_vector.merge(src_vv)

    def _known_peers(self) -> set:
        known = {
            a
            for a in range(len(self.version_vector))
            if a != self.actor and self.version_vector.counter(a) > 0
        }
        known |= set(self.peer_acked)
        known |= set(self.peer_processed)
        known.discard(self.actor)
        return known

    def gc_deleted(self, src_actor: Actor, src_vv: VersionVector) -> None:
        """δ-log GC.  Reference: EMPTY STUB (awset-delta_test.go:67-77) whose
        comments sketch two designs (per-actor refcounts, or one Deleted map
        per known actor).  Disabled by default for strict conformance with
        the stub (the reference's log grows forever).

        Reference mode (``gc_enabled=True``): an ack frontier over peers'
        advertised VV counters for our actor.  This is sound ONLY for the
        pairwise 2-replica topology the reference prototype exercises: with
        3+ replicas, VV counters propagate transitively through VV joins
        WITHOUT the deletion records (reference δ payloads carry only the
        sender's own-origin log), so a peer's vv[us] >= c does not imply it
        processed our deletion c.  Matching the prototype's scope, we keep
        it for 2-replica use; general topologies must use v2.

        v2 mode: causal stability over ``processed`` vectors.  ``processed``
        advances only on exchanges that actually transfer deletion effects
        (payload apply / full merge / transitive record absorption), never
        by bare VV joins, so a record (k, (a, c)) is dropped exactly when
        every known peer has advertised ``processed[a] >= c`` — i.e. every
        known peer's state reflects the deletion.  Peers that never sync
        block the frontier; that is inherent to causal stability and the
        price of a sound distributed GC."""
        if not self.gc_enabled:
            return
        if self.delta_semantics == "v2":
            known = self._known_peers()
            if not known:
                return

            def stable(d: Dot) -> bool:
                return all(
                    self.peer_processed.get(p, {}).get(d.actor, 0) >= d.counter
                    for p in known
                )

            self.deleted = {
                k: d for k, d in self.deleted.items() if not stable(d)
            }
            return
        # reference mode: pairwise VV ack frontier (2-replica sound only).
        prev = self.peer_acked.get(src_actor, 0)
        self.peer_acked[src_actor] = max(prev, src_vv.counter(self.actor))
        known = self._known_peers()
        if not known:
            return
        frontier = min(self.peer_acked.get(a, 0) for a in known)
        self.deleted = {
            k: d
            for k, d in self.deleted.items()
            if d.actor != self.actor or d.counter > frontier
        }
