"""Packed-tensor δ-AWSet replica state.

Extends the AWSet arrays (models/awset.py) with the δ-state machinery of
the reference prototype (awset-delta_test.go:9-12) and this framework's v2
extensions (models/spec.py AWSetDelta docstring):

  deleted:         bool[R, E]    deletion log membership (Deleted map keys)
  del_dot_actor:   uint32[R, E]  deletion dots (Deleted map values)
  del_dot_counter: uint32[R, E]
  processed:       uint32[R, A]  v2 causal-stability vector: per origin
                                 actor, the highest deletion counter whose
                                 effects this replica's state reflects

The reference's per-peer ack bookkeeping (spec ``peer_processed``) is NOT
materialized on device: in the batched SPMD world the GC frontier is an
exact global snapshot — ``min`` over the replica axis of ``processed`` —
computed with one collective (ops/delta.py:gc_frontier), which is the
TPU-native replacement for gossiping VV matrices (SURVEY §5.8).
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from go_crdt_playground_tpu.models import awset as awset_mod
from go_crdt_playground_tpu.models.awset import AWSetState


class AWSetDeltaState(NamedTuple):
    vv: jnp.ndarray              # uint32[R, A]
    present: jnp.ndarray         # bool[R, E]
    dot_actor: jnp.ndarray       # uint32[R, E]
    dot_counter: jnp.ndarray     # uint32[R, E]
    actor: jnp.ndarray           # uint32[R]
    deleted: jnp.ndarray         # bool[R, E]
    del_dot_actor: jnp.ndarray   # uint32[R, E]
    del_dot_counter: jnp.ndarray # uint32[R, E]
    processed: jnp.ndarray       # uint32[R, A]

    @property
    def num_replicas(self) -> int:
        return self.vv.shape[0]

    @property
    def num_actors(self) -> int:
        return self.vv.shape[-1]

    @property
    def num_elements(self) -> int:
        return self.present.shape[-1]

    def base(self) -> AWSetState:
        return AWSetState(vv=self.vv, present=self.present,
                          dot_actor=self.dot_actor,
                          dot_counter=self.dot_counter, actor=self.actor)


def _extend(base: awset_mod.AWSetState, deleted, del_da, del_dc,
            processed) -> AWSetDeltaState:
    return AWSetDeltaState(
        vv=base.vv, present=base.present, dot_actor=base.dot_actor,
        dot_counter=base.dot_counter, actor=base.actor,
        deleted=deleted, del_dot_actor=del_da, del_dot_counter=del_dc,
        processed=processed,
    )


def init(num_replicas: int, num_elements: int, num_actors: int,
         actors=None) -> AWSetDeltaState:
    base = awset_mod.init(num_replicas, num_elements, num_actors, actors)
    zE = jnp.zeros((num_replicas, num_elements), jnp.uint32)
    return _extend(
        base,
        deleted=jnp.zeros((num_replicas, num_elements), bool),
        del_da=zE, del_dc=zE,
        processed=jnp.zeros((num_replicas, num_actors), jnp.uint32),
    )


def from_arrays(arrays: Dict[str, np.ndarray]) -> AWSetDeltaState:
    """Lift a utils.codec.pack_awset_deltas result onto device."""
    base = awset_mod.from_arrays(arrays)
    return _extend(
        base,
        deleted=jnp.asarray(arrays["deleted"], bool),
        del_da=jnp.asarray(arrays["del_dot_actor"], jnp.uint32),
        del_dc=jnp.asarray(arrays["del_dot_counter"], jnp.uint32),
        processed=jnp.asarray(arrays["processed"], jnp.uint32),
    )


def to_arrays(state: AWSetDeltaState) -> Dict[str, np.ndarray]:
    return {name: np.asarray(getattr(state, name)) for name in state._fields}


# ---------------------------------------------------------------------------
# Local mutations (host-driven scenario ops; bulk path is ops/delta.py)
# ---------------------------------------------------------------------------


@jax.jit
def add_element(state: AWSetDeltaState, replica: jnp.ndarray,
                element: jnp.ndarray) -> AWSetDeltaState:
    """δ-state ``Add``: the plain AWSet add (awset.go:89-94) plus the v2
    invariant processed[self] == vv[self] (a replica has trivially
    processed its own events; spec AWSetDelta.add)."""
    r = replica.astype(jnp.int32)
    a = state.actor[r].astype(jnp.int32)
    base = awset_mod.add_element(state.base(), replica, element)
    return state._replace(
        vv=base.vv,
        present=base.present,
        dot_actor=base.dot_actor,
        dot_counter=base.dot_counter,
        processed=state.processed.at[r, a].set(base.vv[r, a]),
    )


@jax.jit
def add_elements(state: AWSetDeltaState, replica: jnp.ndarray,
                 elements: jnp.ndarray,
                 count: jnp.ndarray | None = None) -> AWSetDeltaState:
    """Batched ``Add(k...)``: ONE dispatch for the whole call, exactly
    the per-key loop semantics of awset.go:89-94 — the clock ticks once
    per key occurrence (position i gets counter vv[r,a]+1+i), and a key
    appearing twice keeps its LAST occurrence's dot (the loop overwrites).

    elements: uint32[K] element ids (K static per call shape).  count:
    optional traced scalar — only the first ``count`` positions are real,
    the rest padding; callers bucket K (e.g. to powers of two) so varying
    arities reuse one compiled program instead of one per K."""
    r = replica.astype(jnp.int32)
    a = state.actor[r].astype(jnp.int32)
    base = state.vv[r, a]
    k = elements.shape[0]
    pos = jnp.arange(1, k + 1, dtype=jnp.uint32)
    if count is None:
        count = jnp.uint32(k)
    else:
        count = count.astype(jnp.uint32)
        pos = jnp.where(pos <= count, pos, 0)  # padding: max-identity
    # last-occurrence position (1-based) per touched element lane
    pos1 = jnp.zeros(state.num_elements, jnp.uint32).at[elements].max(pos)
    touched = pos1 > 0
    new_vv = base + count
    return state._replace(
        vv=state.vv.at[r, a].set(new_vv),
        present=state.present.at[r].set(state.present[r] | touched),
        dot_actor=state.dot_actor.at[r].set(
            jnp.where(touched, state.actor[r], state.dot_actor[r])),
        dot_counter=state.dot_counter.at[r].set(
            jnp.where(touched, base + pos1, state.dot_counter[r])),
        processed=state.processed.at[r, a].set(new_vv),
    )


@jax.jit
def del_elements(state: AWSetDeltaState, replica: jnp.ndarray,
                 selector: jnp.ndarray) -> AWSetDeltaState:
    """δ-state ``Del`` (awset-delta_test.go:14-33): ticks the clock ONCE
    PER CALL — even when nothing selected is present — and stamps every
    actually-present selected key with that one shared deletion dot.

    selector: bool[E] — the key set of one Del(k...) call."""
    r = replica.astype(jnp.int32)
    a = state.actor[r].astype(jnp.int32)
    new_counter = state.vv[r, a] + 1
    hit = selector & state.present[r]
    return state._replace(
        vv=state.vv.at[r, a].set(new_counter),
        present=state.present.at[r].set(state.present[r] & ~hit),
        dot_actor=state.dot_actor.at[r].set(
            jnp.where(hit, 0, state.dot_actor[r])),
        dot_counter=state.dot_counter.at[r].set(
            jnp.where(hit, 0, state.dot_counter[r])),
        deleted=state.deleted.at[r].set(state.deleted[r] | hit),
        del_dot_actor=state.del_dot_actor.at[r].set(
            jnp.where(hit, state.actor[r], state.del_dot_actor[r])),
        del_dot_counter=state.del_dot_counter.at[r].set(
            jnp.where(hit, new_counter, state.del_dot_counter[r])),
        processed=state.processed.at[r, a].set(new_counter),
    )
