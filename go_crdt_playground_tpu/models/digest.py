"""Content digests for packed replica states.

One CRC32 per array (dtype and shape folded in, so a reinterpreted
buffer cannot pass as intact) and one order-stable digest per state
(field names folded in, so two states whose arrays happen to collide
field-for-field still differ).  Two consumers share these:

* the durability layer (utils/checkpoint.py) digests every array into
  the checkpoint manifest at save time and re-verifies on restore —
  bit rot is REFUSED, never silently loaded;
* the crash soak (tools/crash_soak.py) compares replica fixed points
  ACROSS PROCESSES by digest alone, without shipping state.

CRC32 is deliberate: this is an integrity check against torn writes and
media rot, not an authenticity check against an adversary, and it is
cheap enough to run on every checkpoint save/restore.

Jax-free on purpose (numpy only), like models/layout.py: importable
from host-only recovery paths before any device initialization.
"""

from __future__ import annotations

import zlib

import numpy as np


def array_digest(a) -> int:
    """CRC32 over dtype, shape, and bytes of one array."""
    a = np.asarray(a)
    h = zlib.crc32(f"{a.dtype.str}|{a.shape}|".encode("ascii"))
    return zlib.crc32(np.ascontiguousarray(a).tobytes(), h)


def state_digest(state) -> int:
    """Order-stable CRC32 of a whole packed state (any framework state
    NamedTuple): per-field digests chained in field order with the field
    names folded in."""
    fields = getattr(state, "_fields", None)
    if fields is None:
        raise TypeError(
            f"state_digest wants a state NamedTuple, got {type(state)!r}")
    h = 0
    for name in fields:
        h = zlib.crc32(f"{name}|".encode("ascii"), h)
        h = zlib.crc32(array_digest(getattr(state, name))
                       .to_bytes(4, "little"), h)
    return h
