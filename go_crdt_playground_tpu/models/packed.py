"""Bitpacked state layouts: membership as uint32[R, ceil(E/32)].

SURVEY §7.1/§7.3 step 5: the Pallas variant packs ``present`` (and the
δ state's ``deleted``) 32 lanes per word — the ``Entries`` map keys
(awset.go:58) as bits, not bytes.  8x less HBM traffic and checkpoint/
wire footprint for those arrays; kernels unpack to bool lanes in VMEM
(ops/pallas_merge._kernel_unpack_bits) and run the identical, bitwise-
pinned merge algebra.

The packed forms are pytrees of arrays only; the element count is not
recoverable from the packed width (ceil rounds), so ``unpack_*`` take
``num_elements`` explicitly.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from go_crdt_playground_tpu.models.awset import AWSetState
from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState
from go_crdt_playground_tpu.ops.pallas_merge import pack_bits, unpack_bits


class PackedAWSetState(NamedTuple):
    vv: jnp.ndarray            # uint32[R, A]
    present_bits: jnp.ndarray  # uint32[R, ceil(E/32)]
    dot_actor: jnp.ndarray     # uint32[R, E]
    dot_counter: jnp.ndarray   # uint32[R, E]
    actor: jnp.ndarray         # uint32[R]


class PackedAWSetDeltaState(NamedTuple):
    vv: jnp.ndarray
    present_bits: jnp.ndarray
    dot_actor: jnp.ndarray
    dot_counter: jnp.ndarray
    actor: jnp.ndarray
    deleted_bits: jnp.ndarray  # uint32[R, ceil(E/32)]
    del_dot_actor: jnp.ndarray
    del_dot_counter: jnp.ndarray
    processed: jnp.ndarray


def pack_awset(state: AWSetState) -> PackedAWSetState:
    return PackedAWSetState(
        vv=state.vv, present_bits=pack_bits(state.present),
        dot_actor=state.dot_actor, dot_counter=state.dot_counter,
        actor=state.actor)


def unpack_awset(packed: PackedAWSetState, num_elements: int) -> AWSetState:
    return AWSetState(
        vv=packed.vv,
        present=unpack_bits(packed.present_bits, num_elements),
        dot_actor=packed.dot_actor, dot_counter=packed.dot_counter,
        actor=packed.actor)


def pack_awset_delta(state: AWSetDeltaState) -> PackedAWSetDeltaState:
    return PackedAWSetDeltaState(
        vv=state.vv, present_bits=pack_bits(state.present),
        dot_actor=state.dot_actor, dot_counter=state.dot_counter,
        actor=state.actor, deleted_bits=pack_bits(state.deleted),
        del_dot_actor=state.del_dot_actor,
        del_dot_counter=state.del_dot_counter, processed=state.processed)


def unpack_awset_delta(packed: PackedAWSetDeltaState,
                       num_elements: int) -> AWSetDeltaState:
    return AWSetDeltaState(
        vv=packed.vv,
        present=unpack_bits(packed.present_bits, num_elements),
        dot_actor=packed.dot_actor, dot_counter=packed.dot_counter,
        actor=packed.actor,
        deleted=unpack_bits(packed.deleted_bits, num_elements),
        del_dot_actor=packed.del_dot_actor,
        del_dot_counter=packed.del_dot_counter,
        processed=packed.processed)
