"""Bitpacked state layouts: membership as uint32[R, ceil(E/32)].

SURVEY §7.1/§7.3 step 5: the Pallas variant packs ``present`` (and the
δ state's ``deleted``) 32 lanes per word — the ``Entries`` map keys
(awset.go:58) as bits, not bytes.  8x less HBM traffic and checkpoint/
wire footprint for those arrays; kernels unpack to bool lanes in VMEM
(ops/pallas_merge._kernel_unpack_bits) and run the identical, bitwise-
pinned merge algebra.

The packed forms are pytrees of arrays only; the element count is not
recoverable from the packed width (ceil rounds), so ``unpack_*`` take
``num_elements`` explicitly.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from go_crdt_playground_tpu.models.awset import AWSetState
from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState
from go_crdt_playground_tpu.ops.pallas_merge import (
    _DOT_CMASK, _DOT_SHIFT, DOT_MAX_ACTORS, DOT_MAX_COUNTER, pack_bits,
    unpack_bits)


class PackedAWSetState(NamedTuple):
    vv: jnp.ndarray            # uint32[R, A]
    present_bits: jnp.ndarray  # uint32[R, ceil(E/32)]
    dot_actor: jnp.ndarray     # uint32[R, E]
    dot_counter: jnp.ndarray   # uint32[R, E]
    actor: jnp.ndarray         # uint32[R]


class PackedAWSetDeltaState(NamedTuple):
    vv: jnp.ndarray
    present_bits: jnp.ndarray
    dot_actor: jnp.ndarray
    dot_counter: jnp.ndarray
    actor: jnp.ndarray
    deleted_bits: jnp.ndarray  # uint32[R, ceil(E/32)]
    del_dot_actor: jnp.ndarray
    del_dot_counter: jnp.ndarray
    processed: jnp.ndarray


def pack_awset(state: AWSetState) -> PackedAWSetState:
    return PackedAWSetState(
        vv=state.vv, present_bits=pack_bits(state.present),
        dot_actor=state.dot_actor, dot_counter=state.dot_counter,
        actor=state.actor)


def unpack_awset(packed: PackedAWSetState, num_elements: int) -> AWSetState:
    return AWSetState(
        vv=packed.vv,
        present=unpack_bits(packed.present_bits, num_elements),
        dot_actor=packed.dot_actor, dot_counter=packed.dot_counter,
        actor=packed.actor)


class DotPackedAWSetState(NamedTuple):
    """Bitpacked membership AND dot-word layout: each element's (actor,
    counter) dot lives in ONE uint32 ((actor << 20) | counter), so a
    ring round streams one E-shaped array where the bool layout streams
    two.  Opt-in: counters are capped at DOT_MAX_COUNTER (~1M adds per
    actor — pack_awset_dots guards), actors at DOT_MAX_ACTORS (4096,
    above MAX_FUSED_ACTORS)."""

    vv: jnp.ndarray            # uint32[R, A]
    present_bits: jnp.ndarray  # uint32[R, ceil(E/32)]
    dots: jnp.ndarray          # uint32[R, E]: (actor << 20) | counter
    actor: jnp.ndarray         # uint32[R]


def pack_awset_dots(state: AWSetState) -> DotPackedAWSetState:
    """Host-side pack with the layout's soundness guards: the word has
    12 actor bits and 20 counter bits, and a counter at the cap could
    alias a neighbouring actor's dot after overflowing — refuse loudly
    instead (the same posture as utils/guards' uint32 headroom)."""
    _check_dot_caps(state.vv.shape[1], state.dot_counter)
    return DotPackedAWSetState(
        vv=state.vv, present_bits=pack_bits(state.present),
        dots=(state.dot_actor << _DOT_SHIFT) | state.dot_counter,
        actor=state.actor)


def unpack_awset_dots(packed: DotPackedAWSetState,
                      num_elements: int) -> AWSetState:
    dots = packed.dots
    return AWSetState(
        vv=packed.vv,
        present=unpack_bits(packed.present_bits, num_elements),
        dot_actor=dots >> _DOT_SHIFT,
        dot_counter=dots & jnp.uint32(_DOT_CMASK),
        actor=packed.actor)


class DotPackedAWSetDeltaState(NamedTuple):
    """δ-state analogue of DotPackedAWSetState: membership bitpacked
    and BOTH dot pairs (add + deletion) fused to one uint32 word per
    element — the δ ring round's six E-shaped arrays become four, two
    of them 32x narrower."""

    vv: jnp.ndarray            # uint32[R, A]
    present_bits: jnp.ndarray  # uint32[R, ceil(E/32)]
    dots: jnp.ndarray          # uint32[R, E]: (actor << 20) | counter
    actor: jnp.ndarray         # uint32[R]
    deleted_bits: jnp.ndarray  # uint32[R, ceil(E/32)]
    del_dots: jnp.ndarray      # uint32[R, E]
    processed: jnp.ndarray     # uint32[R, A]


def _check_dot_caps(num_actors: int, *counters) -> None:
    if num_actors > DOT_MAX_ACTORS:
        raise ValueError(
            f"dot-word layout holds {32 - _DOT_SHIFT} actor bits "
            f"(A <= {DOT_MAX_ACTORS}); got A={num_actors}")
    for c in counters:
        max_c = int(jnp.max(c)) if c.size else 0
        if max_c > DOT_MAX_COUNTER:
            raise ValueError(
                f"dot counter {max_c} exceeds the dot-word layout's "
                f"{_DOT_SHIFT}-bit counter cap {DOT_MAX_COUNTER}; use "
                "the uint32 layouts for unbounded-counter fleets")


def pack_awset_delta_dots(state: AWSetDeltaState) -> DotPackedAWSetDeltaState:
    _check_dot_caps(state.vv.shape[1], state.dot_counter,
                    state.del_dot_counter)
    return DotPackedAWSetDeltaState(
        vv=state.vv, present_bits=pack_bits(state.present),
        dots=(state.dot_actor << _DOT_SHIFT) | state.dot_counter,
        actor=state.actor, deleted_bits=pack_bits(state.deleted),
        del_dots=((state.del_dot_actor << _DOT_SHIFT)
                  | state.del_dot_counter),
        processed=state.processed)


def unpack_awset_delta_dots(packed: DotPackedAWSetDeltaState,
                            num_elements: int) -> AWSetDeltaState:
    cmask = jnp.uint32(_DOT_CMASK)
    return AWSetDeltaState(
        vv=packed.vv,
        present=unpack_bits(packed.present_bits, num_elements),
        dot_actor=packed.dots >> _DOT_SHIFT,
        dot_counter=packed.dots & cmask,
        actor=packed.actor,
        deleted=unpack_bits(packed.deleted_bits, num_elements),
        del_dot_actor=packed.del_dots >> _DOT_SHIFT,
        del_dot_counter=packed.del_dots & cmask,
        processed=packed.processed)


def pack_awset_delta(state: AWSetDeltaState) -> PackedAWSetDeltaState:
    return PackedAWSetDeltaState(
        vv=state.vv, present_bits=pack_bits(state.present),
        dot_actor=state.dot_actor, dot_counter=state.dot_counter,
        actor=state.actor, deleted_bits=pack_bits(state.deleted),
        del_dot_actor=state.del_dot_actor,
        del_dot_counter=state.del_dot_counter, processed=state.processed)


def unpack_awset_delta(packed: PackedAWSetDeltaState,
                       num_elements: int) -> AWSetDeltaState:
    return AWSetDeltaState(
        vv=packed.vv,
        present=unpack_bits(packed.present_bits, num_elements),
        dot_actor=packed.dot_actor, dot_counter=packed.dot_counter,
        actor=packed.actor,
        deleted=unpack_bits(packed.deleted_bits, num_elements),
        del_dot_actor=packed.del_dot_actor,
        del_dot_counter=packed.del_dot_counter,
        processed=packed.processed)
