"""Axis roles of packed-state fields, by field name — the single source
of truth shared by the sharding layout (parallel/mesh.py) and the
host-side repack helpers (utils/codec.py).  Field names are used because
shapes alone are ambiguous when A == E.

Jax-free on purpose: importable from host-only code paths.
"""

# trailing axis is the actor axis A (vv[R, A]-shaped)
ACTOR_AXIS_FIELDS = frozenset({"vv", "processed"})

# replica axis only (no trailing data axis)
REPLICA_ONLY_FIELDS = frozenset({"actor"})
