"""Executable specs for the additional CRDT families.

The reference implements exactly one CRDT (the AWSet) plus its δ variant;
its version vector is itself a G-Counter-shaped lattice (crdt-misc.go:43-55
is an elementwise max join).  The BASELINE config ladder requires more
families (G-Counter at config 2, 2P-Set at config 5), and a framework
replacing the reference should cover the standard state-based menagerie.
These dict/list models are the conformance oracles for the tensor kernels
in ops/lattices.py — same role models/spec.py plays for the AWSet kernels.

All follow the reference's design language: actor-indexed arrays, join =
pairwise monotone merge, ops tick per-actor slots (cf. the Shapiro et al.
"comprehensive study" the reference cites at awset.go:43-44).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "GCounter",
    "PNCounter",
    "TwoPSet",
    "LWWMap",
    "MVRegister",
    "ORMap",
]


class GCounter:
    """Grow-only counter: per-actor monotone counts, value = sum, join =
    elementwise max — the lattice the reference's VersionVector.Merge
    already implements (crdt-misc.go:43-55)."""

    def __init__(self, actor: int, num_actors: int):
        self.actor = actor
        self.counts: List[int] = [0] * num_actors

    def inc(self, amount: int = 1) -> None:
        if amount < 0:
            raise ValueError("GCounter only grows")
        self.counts[self.actor] += amount

    def value(self) -> int:
        return sum(self.counts)

    def merge(self, src: "GCounter") -> None:
        self.counts = [max(a, b) for a, b in zip(self.counts, src.counts)]


class PNCounter:
    """Increment/decrement counter: two G-Counters (P - N)."""

    def __init__(self, actor: int, num_actors: int):
        self.p = GCounter(actor, num_actors)
        self.n = GCounter(actor, num_actors)

    def inc(self, amount: int = 1) -> None:
        self.p.inc(amount)

    def dec(self, amount: int = 1) -> None:
        self.n.inc(amount)

    def value(self) -> int:
        return self.p.value() - self.n.value()

    def merge(self, src: "PNCounter") -> None:
        self.p.merge(src.p)
        self.n.merge(src.n)


class TwoPSet:
    """Two-phase set: add-set + remove-set, remove wins forever (an element
    can never be re-added).  The tombstone-ful contrast to the reference's
    tombstone-free AWSet (awset.go:9-35 discusses exactly this trade)."""

    def __init__(self):
        self.added: Set[str] = set()
        self.removed: Set[str] = set()

    def add(self, *keys: str) -> None:
        self.added.update(keys)

    def del_(self, *keys: str) -> None:
        # only observed elements can be removed (classic 2P rule)
        for k in keys:
            if k in self.added:
                self.removed.add(k)

    def has(self, k: str) -> bool:
        return k in self.added and k not in self.removed

    def values(self) -> List[str]:
        return sorted(self.added - self.removed)

    def merge(self, src: "TwoPSet") -> None:
        self.added |= src.added
        self.removed |= src.removed


class LWWMap:
    """Last-writer-wins map: per key (timestamp, actor, value); join keeps
    the lexicographically larger (ts, actor) — actor id breaks timestamp
    ties deterministically.  Timestamps are caller-supplied logical clocks
    (the framework never reads wall clocks; determinism is a design rule).
    Deletes are LWW tombstones (value None)."""

    def __init__(self, actor: int):
        self.actor = actor
        # key -> (ts, actor, value | None)
        self.cells: Dict[str, Tuple[int, int, Optional[int]]] = {}

    def put(self, k: str, value: Optional[int], ts: int) -> None:
        if ts < 1:
            raise ValueError("logical timestamps start at 1 (0 = unwritten)")
        cur = self.cells.get(k)
        cand = (ts, self.actor, value)
        if cur is None or cand[:2] > cur[:2]:
            self.cells[k] = cand

    def delete(self, k: str, ts: int) -> None:
        self.put(k, None, ts)

    def get(self, k: str) -> Optional[int]:
        cur = self.cells.get(k)
        return cur[2] if cur is not None else None

    def items(self) -> Dict[str, int]:
        return {k: v for k, (ts, a, v) in sorted(self.cells.items())
                if v is not None}

    def merge(self, src: "LWWMap") -> None:
        for k, cand in src.cells.items():
            cur = self.cells.get(k)
            if cur is None or cand[:2] > cur[:2]:
                self.cells[k] = cand


class ORMap:
    """Observed-remove map: key membership follows the AWSet's add-wins
    semantics exactly (delegation to models/spec.AWSet — same dots, same
    two-phase merge), with one LWW cell per key for the value.

    Value lifetime is INDEPENDENT of key membership: deleting a key hides
    it, but a later re-add shows the latest value ever written (the cells
    lattice never forgets).  This is the pragmatic LWW-value OR-Map; a
    causally-reset value (Riak-map style) would need per-cell causal
    contexts and is future work — documented so users aren't surprised."""

    def __init__(self, actor: int, num_actors: int):
        from go_crdt_playground_tpu.models.spec import AWSet, VersionVector

        self.keys = AWSet(actor=actor,
                          version_vector=VersionVector([0] * num_actors))
        self.cells = LWWMap(actor=actor)

    def put(self, k: str, value: int, ts: int) -> None:
        self.keys.add(k)
        self.cells.put(k, value, ts)

    def delete(self, k: str) -> None:
        """Observed-remove of the key (awset.go:96-101 semantics: no clock
        tick, no tombstone); the value cell is untouched."""
        self.keys.del_(k)

    def get(self, k: str) -> Optional[int]:
        if not self.keys.has(k):
            return None
        return self.cells.get(k)

    def items(self) -> Dict[str, int]:
        out = {}
        for k in self.keys.sorted_values():
            v = self.cells.get(k)
            if v is not None:
                out[k] = v
        return out

    def merge(self, src: "ORMap") -> None:
        self.keys.merge(src.keys)
        self.cells.merge(src.cells)


class MVRegister:
    """Multi-value register (optimized, per-actor slots): a write replaces
    all currently-visible values; concurrent writes all survive until
    causally dominated.  State per actor: latest (counter, value) write
    plus a causal-context VV; an entry survives a join iff present on both
    sides or newer than the other side's context — the same
    presence/causality arbitration pattern as the AWSet (awset.go:28-35),
    specialized to one slot per actor."""

    def __init__(self, actor: int, num_actors: int):
        self.actor = actor
        self.ctx: List[int] = [0] * num_actors          # causal context
        self.live: List[bool] = [False] * num_actors
        self.cnt: List[int] = [0] * num_actors
        self.val: List[int] = [0] * num_actors

    def write(self, value: int) -> None:
        self.ctx[self.actor] += 1
        for a in range(len(self.live)):
            # dead slots are zeroed — canonical form shared with the packed
            # tensor state so bitwise conformance checks are meaningful
            self.live[a] = False
            self.cnt[a] = 0
            self.val[a] = 0
        self.live[self.actor] = True
        self.cnt[self.actor] = self.ctx[self.actor]
        self.val[self.actor] = value

    def read(self) -> List[int]:
        """All concurrent values, ordered by actor id."""
        return [self.val[a] for a in range(len(self.live)) if self.live[a]]

    def merge(self, src: "MVRegister") -> None:
        for a in range(len(self.live)):
            if self.live[a] and src.live[a]:
                # same actor's writes: the higher counter is newer
                if src.cnt[a] > self.cnt[a]:
                    self.cnt[a], self.val[a] = src.cnt[a], src.val[a]
            elif src.live[a] and src.cnt[a] > self.ctx[a]:
                # news we haven't seen: adopt
                self.live[a] = True
                self.cnt[a], self.val[a] = src.cnt[a], src.val[a]
            elif self.live[a] and not src.live[a] and self.cnt[a] <= src.ctx[a]:
                # src witnessed this write and no longer shows it: overwritten
                self.live[a] = False
                self.cnt[a] = 0
                self.val[a] = 0
        self.ctx = [max(a, b) for a, b in zip(self.ctx, src.ctx)]
