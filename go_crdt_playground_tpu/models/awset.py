"""Packed-tensor AWSet replica state + host-driven local ops.

The central design decision (SURVEY §7.1): one Go ``AWSet`` struct per
replica (awset.go:55-59) becomes a batch of replicas packed along axis
``R`` of four dense arrays.  The merge hot loop (awset.go:107-161) then
runs as elementwise boolean algebra over axis ``E`` (ops/merge.py), vmapped
over ``R`` and sharded over a device mesh (parallel/).

State arrays:
  vv:          uint32[R, A]  version vectors (crdt-misc.go:23)
  present:     bool[R, E]    set membership (keys of Entries, awset.go:58)
  dot_actor:   uint32[R, E]  birth-dot actor (awset.go:92)
  dot_counter: uint32[R, E]  birth-dot counter
  actor:       uint32[R]     each replica's own actor id (awset.go:56)

Canonical form: dot arrays are zero where ``present`` is false, so states
are bitwise-comparable (the dict model has no dot at all for absent keys).
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class AWSetState(NamedTuple):
    """A batch of R replica states (a pytree of arrays)."""

    vv: jnp.ndarray          # uint32[R, A]
    present: jnp.ndarray     # bool[R, E]
    dot_actor: jnp.ndarray   # uint32[R, E]
    dot_counter: jnp.ndarray # uint32[R, E]
    actor: jnp.ndarray       # uint32[R]

    @property
    def num_replicas(self) -> int:
        return self.vv.shape[0]

    @property
    def num_actors(self) -> int:
        return self.vv.shape[-1]

    @property
    def num_elements(self) -> int:
        return self.present.shape[-1]


def init(num_replicas: int, num_elements: int, num_actors: int,
         actors=None) -> AWSetState:
    """Fresh empty replicas (the testAWSetInit fixture shape,
    awset_test.go:159-168: replica r is actor r).

    INVARIANT — unique writers: an actor id must never be ticked by two
    replicas concurrently; dots are only causally meaningful if (actor,
    counter) names one event (the reference guarantees this structurally,
    one Actor per struct).  Two replicas sharing an actor id and both
    calling add() produce colliding dots, after which VV coverage triggers
    spurious phase-1 skips / phase-2 removals.  The default therefore
    requires A >= R with actor r for replica r.  Pass ``actors`` explicitly
    for observer topologies (A < R) where the extra replicas only merge,
    never add — e.g. read-replica fleets and the large-R benchmarks."""
    if actors is None:
        if num_actors < num_replicas:
            raise ValueError(
                f"default actor assignment needs num_actors ({num_actors}) "
                f">= num_replicas ({num_replicas}); pass explicit actors= "
                "for an observer topology (replicas that never add)"
            )
        actors = jnp.arange(num_replicas, dtype=jnp.uint32)
    else:
        actors = jnp.asarray(actors, jnp.uint32)
    return AWSetState(
        vv=jnp.zeros((num_replicas, num_actors), jnp.uint32),
        present=jnp.zeros((num_replicas, num_elements), bool),
        dot_actor=jnp.zeros((num_replicas, num_elements), jnp.uint32),
        dot_counter=jnp.zeros((num_replicas, num_elements), jnp.uint32),
        actor=actors,
    )


def from_arrays(arrays: Dict[str, np.ndarray]) -> AWSetState:
    """Lift a utils.codec.pack_awsets result onto device."""
    return AWSetState(
        vv=jnp.asarray(arrays["vv"], jnp.uint32),
        present=jnp.asarray(arrays["present"], bool),
        dot_actor=jnp.asarray(arrays["dot_actor"], jnp.uint32),
        dot_counter=jnp.asarray(arrays["dot_counter"], jnp.uint32),
        actor=jnp.asarray(arrays["actor"], jnp.uint32),
    )


def to_arrays(state: AWSetState) -> Dict[str, np.ndarray]:
    return {
        "vv": np.asarray(state.vv),
        "present": np.asarray(state.present),
        "dot_actor": np.asarray(state.dot_actor),
        "dot_counter": np.asarray(state.dot_counter),
        "actor": np.asarray(state.actor),
    }


# ---------------------------------------------------------------------------
# Local mutations (host-driven scenario ops; the bulk path is ops/merge.py)
# ---------------------------------------------------------------------------


@jax.jit
def add_element(state: AWSetState, replica: jnp.ndarray,
                element: jnp.ndarray) -> AWSetState:
    """``AWSet.Add`` for one key on one replica (awset.go:89-94): tick own
    clock, stamp the birth dot (re-add = dot update)."""
    r = replica.astype(jnp.int32)
    e = element.astype(jnp.int32)
    a = state.actor[r].astype(jnp.int32)
    new_counter = state.vv[r, a] + 1
    return AWSetState(
        vv=state.vv.at[r, a].set(new_counter),
        present=state.present.at[r, e].set(True),
        dot_actor=state.dot_actor.at[r, e].set(state.actor[r]),
        dot_counter=state.dot_counter.at[r, e].set(new_counter),
        actor=state.actor,
    )


@jax.jit
def del_element(state: AWSetState, replica: jnp.ndarray,
                element: jnp.ndarray) -> AWSetState:
    """``AWSet.Del`` (awset.go:96-101): pure removal, NO clock tick (the
    increment is commented out at awset.go:97).  Dots are zeroed to keep
    the canonical form."""
    r = replica.astype(jnp.int32)
    e = element.astype(jnp.int32)
    return AWSetState(
        vv=state.vv,
        present=state.present.at[r, e].set(False),
        dot_actor=state.dot_actor.at[r, e].set(0),
        dot_counter=state.dot_counter.at[r, e].set(0),
        actor=state.actor,
    )


def has_element(state: AWSetState, replica: int, element: int) -> bool:
    """``AWSet.Has`` (awset.go:87)."""
    return bool(state.present[replica, element])


@jax.jit
def reset(state: AWSetState) -> AWSetState:
    """``AWSet.Reset`` (awset.go:72-75) — with the VV keeping its actor
    axis rather than collapsing to length 1 (reference's latent bug)."""
    return AWSetState(
        vv=jnp.zeros_like(state.vv),
        present=jnp.zeros_like(state.present),
        dot_actor=jnp.zeros_like(state.dot_actor),
        dot_counter=jnp.zeros_like(state.dot_counter),
        actor=state.actor,
    )


def clone(state: AWSetState) -> AWSetState:
    """``AWSet.Clone`` (awset.go:77-85).  Arrays are immutable in JAX, so a
    clone is the state itself; provided for API parity."""
    return state
