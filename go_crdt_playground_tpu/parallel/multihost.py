"""Multi-host deployment: the same SPMD gossip program over a
DCN-spanning mesh (SURVEY §5.8).

The reference has no distribution at all — replicas are structs in one
process and "exchange" is a method call (awset_test.go:16-17).  The
TPU-native scaling story is one program, three regimes:

  1. single chip      — jit, no mesh (bench.py).
  2. single host pod  — ``mesh.make_mesh`` over the local devices;
                        gossip permutations lower to collective-permute
                        over ICI.
  3. multi-host       — initialize JAX's distributed runtime, then build
                        the SAME mesh over ``jax.devices()`` (now global):
                        XLA routes the replica-axis collectives over ICI
                        within a host/pod slice and DCN across slices.

The mesh axis ORDER is the placement policy: the replica axis is
outermost, so contiguous replica blocks live on one host and ring/
dissemination offsets smaller than a host's block stay entirely on ICI;
only the block-crossing residue rides DCN.  ``dissemination_offsets``
ordering therefore starts with the smallest offsets (ICI-local) and
touches DCN only in the last log2(hosts) rounds.

Nothing here can be exercised in a 1-process CI; the functions are thin,
deliberately side-effect-explicit wrappers kept separate from mesh.py so
the testable single-process surface stays import-clean.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax

from go_crdt_playground_tpu.parallel import mesh as mesh_mod


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up JAX's distributed runtime (one call per host process,
    before any other JAX API).  Arguments default to the standard
    environment autodetection (JAX_COORDINATOR_ADDRESS etc.).

    On the CPU backend, multiprocess collectives need the gloo
    transport, which some jax generations leave off by default
    ("Multiprocess computations aren't implemented on the CPU
    backend") — opt in when the knob exists so the 2-process CI run
    and any CPU rehearsal of a multi-host deployment work out of the
    box.  TPU backends ignore it."""
    try:
        current = jax.config._read("jax_cpu_collectives_implementation")
    except Exception:  # noqa: BLE001 — private reader; absent/renamed ok
        current = None
    if current in (None, "", "none"):  # don't clobber an explicit choice
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass  # knob absent (old jax) or gloo not built in
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(element_shards: int = 1):
    """A DCN-spanning (replica, element) mesh over every device of every
    participating process.  Call after ``initialize()``; identical
    programs (same jit-compiled gossip rounds) then run unchanged —
    sharding constraints place replica blocks host-contiguously so small
    gossip offsets ride ICI."""
    devices = jax.devices()
    if len(devices) % element_shards:
        raise ValueError(
            f"{len(devices)} devices not divisible by "
            f"element_shards={element_shards}")
    return mesh_mod.make_mesh(
        (len(devices) // element_shards, element_shards), devices=devices)


def process_replica_block(num_replicas: int) -> Tuple[int, int]:
    """[start, stop) of the replica rows whose shards live on THIS
    process under the canonical layout — the slice a host-local ingest
    pipeline (e.g. net.Node feeding adds into the fleet) should write.

    Requires even division (the mesh's replica axis does too); raises
    instead of reporting a placement the sharding cannot realize."""
    n = jax.process_count()
    if num_replicas % n:
        raise ValueError(
            f"num_replicas={num_replicas} not divisible by "
            f"process_count={n}; pad the replica axis (observer rows are "
            "free: they never tick a clock)")
    per = num_replicas // n
    start = jax.process_index() * per
    return start, start + per
