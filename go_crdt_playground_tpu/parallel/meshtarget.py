"""Device-mesh replica tier: one frontend's state sharded over devices.

PRs 6-7 scaled the serving fleet across PROCESSES (consistent-hash ring
+ router + live resharding); this module is the DEVICE half of the
ROADMAP's sharded-fleet item: ``MeshApplyTarget`` is a ``net/peer.Node``
whose single-replica ``AWSetDeltaState`` lives lane-partitioned across a
1-D ``"batch"`` device mesh under ``jax.sharding.NamedSharding`` (the
SNIPPETS.md mesh exemplar shape), so one frontend can hold a universe
larger than a single device's HBM and drive every device's VPU per
batch.  δ-state CRDTs join over disjoint state decompositions (arxiv
1410.2803), which is exactly what makes the lane partition clean: every
lane-shaped field shards over the mesh, while the A-shaped clocks
(``vv``/``processed``) stay replicated — they are read by every lane's
arbitration and are a few words per device.

Write path (``ingest_batch``): ONE ``shard_map`` dispatch per packed
micro-batch.  The only cross-lane couplings in the row algebra are the
per-row dot POSITIONS (a prefix count over touched lanes) and the
per-row clock tick totals — both are functions of the host-built
selector masks alone, so the host precomputes per-(row, shard) base
offsets and per-row totals while packing the batch, and each shard
applies its lanes with a purely LOCAL cumsum plus its replicated
offsets: no cross-device traffic on the write path, bitwise-identical
dots to the single-device kernel (pinned in tests/test_meshtarget.py).
The batch δ (vs the pre-batch vv) is extracted in the same dispatch —
the fused ingest+δ contract of ``ops/ingest.ingest_rows_delta`` — and
the WAL record pull stays ONE ``jax.device_get`` of the payload pytree.

Read path: summary-first (arxiv 1803.02750's motivation applied across
the mesh rather than the wire).  The digest/vv reads ride a collective
digest kernel — per-shard ``ops/digest`` lane fingerprints folded into
group digests shard-locally (global lane ids via ``axis_index``) and
concatenated, so QUERY freshness checks, digest sync, and the router's
member cache move E/16 bytes off-device, not the state.  Membership
reads pull only the ``present`` bitmask (``Node.members_vv``); slice
extraction for live resharding gathers ONLY the moving lanes by index
(one K-lane device_get, not a dense E sweep).

Everything else — WAL/durability ladder, checkpoints, anti-entropy
dissemination, compaction, the serve frontend — runs UNCHANGED against
this class: it is a ``Node``, and the batcher/handoff seams
(serve/apply.py ``ApplyTarget``/``HandoffTarget``) are satisfied by
inheritance.  Paths that mutate state outside the mesh dispatch
(payload applies, WAL replay, GC) run under GSPMD on the sharded
arrays and re-pin the result to the canonical layout afterwards
(``_repin_state``), so placement never decays across a restore or a
sync storm.

CPU testing: ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
(the root conftest.py forces it) gives the whole ladder real multi-device
coverage without a TPU; ``serve --mesh-devices N`` is the CLI wiring.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from go_crdt_playground_tpu.models.layout import (ACTOR_AXIS_FIELDS,
                                                  REPLICA_ONLY_FIELDS)
from go_crdt_playground_tpu.net import framing
from go_crdt_playground_tpu.net.framing import MODE_SLICE
from go_crdt_playground_tpu.net.peer import Node
from go_crdt_playground_tpu.ops.delta import DeltaPayload, delta_extract
from go_crdt_playground_tpu.parallel.gossip import _shard_map

# the serve tier's original mesh is 1-D: lane parallelism is the only
# axis a single replica needs.  The 2-D ("dp", "mp") composition —
# replicated ingest stripes over lane shards — lives in
# parallel/meshtarget2d.py and reuses this module's lane-axis layout
# with MP_AXIS as the lane axis.
BATCH_AXIS = "batch"


def make_batch_mesh(num_devices: Optional[int] = None) -> Mesh:
    """A 1-D ``"batch"`` mesh over the first ``num_devices`` devices
    (default: all).  Device order is jax's stable enumeration, so every
    restart of the same topology places shards identically."""
    from go_crdt_playground_tpu.parallel.mesh import take_devices

    return Mesh(np.asarray(take_devices(num_devices)), (BATCH_AXIS,))


def state_partition_specs(state_cls, lane_axis: str = BATCH_AXIS):
    """PartitionSpecs for a FULL ``(R=1, ...)``-shaped state pytree:
    lane fields shard their trailing E axis over ``lane_axis``; the
    actor-axis clocks and the actor id replicate (models/layout.py is
    the shared field-role table).  The 2-D tier passes its ``"mp"``
    axis — any mesh axis NOT named here replicates, which is exactly
    how the dp ingest replicas share one logical state."""
    return state_cls(**{
        name: (P(None) if name in REPLICA_ONLY_FIELDS
               else P(None, None) if name in ACTOR_AXIS_FIELDS
               else P(None, lane_axis))
        for name in state_cls._fields})


def payload_partition_specs(lane_axis: str = BATCH_AXIS) -> DeltaPayload:
    """PartitionSpecs for a single-replica ``DeltaPayload``: lane
    sections shard over ``lane_axis``, clocks replicate."""
    return DeltaPayload(
        src_vv=P(None), changed=P(lane_axis), ch_da=P(lane_axis),
        ch_dc=P(lane_axis), deleted=P(lane_axis), del_da=P(lane_axis),
        del_dc=P(lane_axis), src_actor=P(), src_processed=P(None))


_PAYLOAD_SPECS = payload_partition_specs(BATCH_AXIS)


# Compiled mesh programs, memoized at MODULE level by (device ids,
# program config): jax.jit caches executables per wrapper identity, so
# per-instance caches would make every MeshApplyTarget re-trace and
# re-compile — in particular the serve frontend's WARMUP node would
# warm a program the serving node never sees, landing the multi-second
# compile stall on the first live batch (the exact stall the warmup
# exists to prevent).  Two equal meshes over the same devices compile
# interchangeable programs, so device ids key the cache; growth is
# bounded by the handful of (mesh, config) shapes a process ever runs.
_PROGRAM_CACHE: dict = {}


# ---------------------------------------------------------------------------
# Shard-local row algebra (the ops/ingest kernels with the cross-lane
# reductions replaced by host-precomputed replicated scalars)
# ---------------------------------------------------------------------------


def _mesh_add_row(st, row, base_off, total, base=None):
    """One Add(k...) row on THIS SHARD's lanes.  ``base_off`` is the
    count of touched lanes in shards left of this one (host-built
    exclusive prefix), ``total`` the row's global touched count — with
    those replicated-in, the dot positions need only a LOCAL cumsum and
    come out bitwise equal to ``ops/ingest._apply_add_row``'s.

    ``base`` overrides the clock read: the 2-D tier's striped stripes
    pass the row's ABSOLUTE pre-row counter (host-precomputed global
    prefix over the whole super-batch) so rows interleaved across dp
    replicas land the exact counters the sequential kernel assigns;
    ``None`` (the 1-D path) reads the replica clock — within one
    sequential stripe the two are the same number."""
    a = st.actor.astype(jnp.int32)
    if base is None:
        base = st.vv[a]
    pos1 = (jnp.cumsum(row.astype(jnp.uint32)) + base_off) * row
    new_vv = base + total
    return st._replace(
        vv=st.vv.at[a].set(new_vv),
        present=st.present | row,
        dot_actor=jnp.where(row, st.actor, st.dot_actor),
        dot_counter=jnp.where(row, base + pos1, st.dot_counter),
        processed=st.processed.at[a].set(new_vv),
    )


def _mesh_del_row(st, row, tick, base=None):
    """One Del(k...) row on this shard's lanes; ``tick`` (0/1, host-
    computed ``any(row)`` over the GLOBAL row) replaces the kernel's
    cross-lane ``jnp.any`` — ``ops/ingest._apply_del_row`` otherwise.
    ``base`` as in ``_mesh_add_row``: the absolute post-add counter of
    this row when striped (None = read the clock)."""
    a = st.actor.astype(jnp.int32)
    if base is None:
        base = st.vv[a]
    new_counter = base + tick
    hit = row & st.present
    return st._replace(
        vv=st.vv.at[a].set(new_counter),
        present=st.present & ~hit,
        dot_actor=jnp.where(hit, 0, st.dot_actor),
        dot_counter=jnp.where(hit, 0, st.dot_counter),
        deleted=st.deleted | hit,
        del_dot_actor=jnp.where(hit, st.actor, st.del_dot_actor),
        del_dot_counter=jnp.where(hit, new_counter, st.del_dot_counter),
        processed=st.processed.at[a].set(new_counter),
    )


def build_mesh_ingest(mesh: Mesh, state_cls, with_delta: bool):
    """Compile the one-dispatch mesh batch apply: full ``(1, ...)``
    state in, merged state (+ batch δ vs the pre-batch vv when
    ``with_delta``) out, everything shard-local.  The δ mirrors
    ``ops/ingest.ingest_rows_delta``'s contract (``delta_extract`` is
    elementwise over lanes with a replicated vv, so it runs per shard
    unchanged); compaction stays host-side — the record encoder's
    break-even rule is the same one the single-device CPU regime
    (``k=0``) uses, and the payload leaves the device in one
    ``device_get`` either way.  Memoized in ``_PROGRAM_CACHE`` so
    every node on the same device set shares one compiled program."""
    key = ("ingest", tuple(d.id for d in mesh.devices.flat), state_cls,
           bool(with_delta))
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached
    st_specs = state_partition_specs(state_cls)

    def body(state, add_rows, del_rows, live, add_base, add_total,
             del_tick):
        st = jax.tree.map(lambda x: x[0], state)
        pre_vv = st.vv

        def step(s, x):
            add_row, del_row, is_live, base, a_tot, d_tick = x
            s = _mesh_add_row(s, add_row & is_live,
                              jnp.where(is_live, base, 0),
                              jnp.where(is_live, a_tot, 0))
            s = _mesh_del_row(s, del_row & is_live,
                              jnp.where(is_live, d_tick, 0))
            return s, None

        merged, _ = jax.lax.scan(
            step, st, (add_rows, del_rows, live, add_base[:, 0],
                       add_total, del_tick))
        full = jax.tree.map(lambda r: r[None], merged)
        if not with_delta:
            return full
        return full, delta_extract(merged, pre_vv)

    in_specs = (st_specs, P(None, BATCH_AXIS), P(None, BATCH_AXIS),
                P(None), P(None, BATCH_AXIS), P(None), P(None))
    out_specs = ((st_specs, _PAYLOAD_SPECS) if with_delta else st_specs)
    # check_vma=False: the clock updates are replicated by construction
    # (every operand is replicated), but the scan carry mixes sharded
    # lanes with replicated clocks and the static replication checker
    # refuses mixed carries on some jax generations — the bitwise pins
    # against the single-device kernel are the actual correctness gate
    fn = jax.jit(_shard_map(body, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=False))
    _PROGRAM_CACHE[key] = fn
    return fn


def build_mesh_digests(mesh: Mesh, num_elements: int, group_size: int,
                       lane_axis: str = BATCH_AXIS):
    """The collective summary read: per-shard ``ops/digest`` lane
    fingerprints (GLOBAL lane ids via ``axis_index`` so the fold is
    comparison-stable across placements) XOR-folded into group digests
    shard-locally and concatenated along the mesh — bitwise equal to
    ``ops/digest.state_group_digests`` whenever group boundaries align
    with shard boundaries (the caller checks divisibility and falls
    back to the GSPMD pass otherwise).  ``lane_axis`` names the mesh
    axis the lanes shard over (the 2-D tier's ``"mp"``); any other
    mesh axis replicates the read."""
    from go_crdt_playground_tpu.ops import digest as digest_ops

    n = mesh.shape[lane_axis]
    e_loc = num_elements // n
    if e_loc % group_size or num_elements % n:
        raise ValueError("shard/group boundary mismatch")
    key = ("digests", tuple(d.id for d in mesh.devices.flat),
           tuple(mesh.shape.items()), lane_axis, num_elements,
           group_size)
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached

    def body(present, deleted, del_da, del_dc):
        lane0 = jax.lax.axis_index(lane_axis).astype(jnp.uint32) \
            * jnp.uint32(e_loc)
        ids = lane0 + jnp.arange(e_loc, dtype=jnp.uint32)
        fp = digest_ops.lane_fingerprint_arrays(ids, present, deleted,
                                                del_da, del_dc)
        return digest_ops.group_fold(fp, group_size)

    fn = jax.jit(_shard_map(body, mesh=mesh,
                            in_specs=(P(lane_axis),) * 4,
                            out_specs=P(lane_axis), check_vma=False))
    _PROGRAM_CACHE[key] = fn
    return fn


def build_mesh_summary(mesh: Mesh, num_elements: int, group_size: int,
                       lane_axis: str = BATCH_AXIS):
    """The WHOLE digest-summary read as ONE compiled program over the
    node's resident ``(1, ...)``-shaped state arrays: leading-axis
    squeeze + per-shard fingerprints + group fold + the clock reads,
    returning ``(digests, vv, processed)``.  This is the re-gather fix
    for the MESH_CURVE digest fall-off (ISSUE 15): the summary path
    used to eagerly slice ``x[0]`` off all NINE state fields — nine
    per-device dispatch rounds whose cost grew monotonically with mesh
    width (0.63→7.0 ms across 1→8 forced host devices) before the
    digest program even ran.  One program, one digest device_get, two
    replicated A-word clock pulls."""
    digests_fn = build_mesh_digests(mesh, num_elements, group_size,
                                    lane_axis)
    key = ("summary", tuple(d.id for d in mesh.devices.flat),
           tuple(mesh.shape.items()), lane_axis, num_elements,
           group_size)
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached

    @jax.jit
    def fn(present, deleted, del_da, del_dc, vv, processed):
        return (digests_fn(present[0], deleted[0], del_da[0],
                           del_dc[0]),
                vv[0], processed[0])

    _PROGRAM_CACHE[key] = fn
    return fn


@jax.jit
def _gather_slice_lanes(state, idx):
    """The moving lanes of a keyspace-handoff slice, by index: exactly
    ``delta_extract(state, zero_vv)`` restricted to ``idx`` (present
    lanes always carry a nonzero dot counter, so the zero-vv ``changed``
    filter reduces to the present bit; the re-add filter is lanewise).
    Returns ``(K,)`` arrays — the host pulls K lanes, never E."""
    def take(x):
        return jnp.take(x, idx, axis=0)

    pres = take(state.present)
    da = take(state.dot_actor)
    dc = take(state.dot_counter)
    dl = take(state.deleted)
    dda = take(state.del_dot_actor)
    ddc = take(state.del_dot_counter)
    resurrected = pres & ((da != dda) | (dc > ddc))
    deleted = dl & ~resurrected
    return (pres, jnp.where(pres, da, 0), jnp.where(pres, dc, 0),
            deleted, jnp.where(deleted, dda, 0),
            jnp.where(deleted, ddc, 0))


class MeshApplyTarget(Node):
    """A ``Node`` whose replica state is lane-sharded across a device
    mesh.  Drop-in for every Node role (serve frontend replica, sync
    peer, handoff donor/recipient); ``mesh_devices=1`` degenerates to
    bitwise the plain node (pinned in tests/test_meshtarget.py).

    ``ingest_fused`` is ignored: the mesh write path is always the
    one-dispatch fused ingest+δ program (there is no two-dispatch mesh
    regime worth keeping for comparison — the single-device Node covers
    that axis)."""

    def __init__(self, actor: int, num_elements: int, num_actors: int,
                 mesh_devices: Optional[int] = None, **node_kwargs):
        super().__init__(actor, num_elements, num_actors, **node_kwargs)
        self._mesh = self._build_mesh(mesh_devices)
        # race-ok: read-only configuration after __init__
        self.mesh_devices = int(self._mesh.devices.size)
        # lane shards = the extent of the lane axis (for this 1-D tier
        # that IS the device count; the 2-D tier's mp extent)
        # race-ok: read-only configuration after __init__
        self.lane_shards = int(self._mesh.shape[self.LANE_AXIS])
        if num_elements % self.lane_shards:
            raise ValueError(
                f"element universe E={num_elements} must divide over "
                f"the {self.lane_shards} lane shards (shards are "
                "equal-sized)")
        # race-ok: read-only configuration after __init__
        self._shardings = jax.tree.map(
            lambda spec: NamedSharding(self._mesh, spec),
            state_partition_specs(type(self._state), self.LANE_AXIS),
            is_leaf=lambda x: isinstance(x, P))
        # (group_size -> fn) collective digest programs
        # race-ok: idempotent lazy init (same program either way)
        self._mesh_digests = {}
        # (group_size -> fn) one-dispatch summary programs
        # race-ok: idempotent lazy init (same program either way)
        self._mesh_summary = {}
        # ``_lock`` is inherited, so this __init__ gets no implicit
        # hold from the lint's pre-sharing rule — take it for real
        with self._lock:
            # compiled mesh programs, resolved lazily per variant (the
            # δ-less one only exists for WAL-less runs)
            self._mesh_ingest = {}  # guarded-by: _lock
            self._repin_state()

    def _build_mesh(self, mesh_devices):
        """The mesh-construction hook: this tier builds the 1-D
        ``"batch"`` lane mesh; ``Mesh2DApplyTarget`` overrides it with
        the ``("dp", "mp")`` serve mesh."""
        return make_batch_mesh(mesh_devices)

    # -- placement ----------------------------------------------------------

    # requires-lock: _lock
    def _repin_state(self) -> None:
        """Re-place the state on the canonical mesh layout.  A no-op
        (no copy) for leaves already placed; called after every
        mutation path that runs outside the mesh ingest program
        (payload applies, WAL replay, restores, GC), so GSPMD output
        placements never accumulate drift."""
        self._state = jax.tree.map(jax.device_put, self._state,
                                   self._shardings)

    # -- write path (the batcher's one dispatch) ----------------------------

    # requires-lock: _lock
    def _apply_batch_locked(self, add_rows: np.ndarray,
                            del_rows: np.ndarray, live: np.ndarray,
                            pre_vv: Optional[np.ndarray],
                            stripe_hint: Optional[np.ndarray] = None
                            ) -> None:
        # stripe_hint is the 2-D subclass's pre-striping seam; the 1-D
        # mesh applies the whole batch in one stripe and ignores it
        n = self.lane_shards
        B = add_rows.shape[0]
        # host-side prefix data: the ONLY cross-shard facts of the row
        # algebra, computed from the selector masks the batcher already
        # built host-side (O(B*E), the same order as packing them)
        counts = add_rows.reshape(B, n, -1).sum(axis=2, dtype=np.uint32)
        add_base = np.cumsum(counts, axis=1, dtype=np.uint32) - counts
        add_total = counts.sum(axis=1, dtype=np.uint32)
        del_tick = del_rows.any(axis=1).astype(np.uint32)
        with_delta = pre_vv is not None
        fn = self._mesh_ingest.get(with_delta)
        if fn is None:
            fn = build_mesh_ingest(self._mesh, type(self._state),
                                   with_delta)
            self._mesh_ingest[with_delta] = fn
        args = (self._state, jnp.asarray(add_rows),
                jnp.asarray(del_rows), jnp.asarray(live),
                jnp.asarray(add_base), jnp.asarray(add_total),
                jnp.asarray(del_tick))
        if with_delta:
            self._state, payload = fn(*args)
            self._count("ingest.dispatches")
            # ONE device→host pull for the whole δ pytree; the record
            # encoder's host-side break-even ladder (compact vs dense)
            # then runs on numpy
            # transfer-ok: one bounded fixed-K pull per ingest chunk —
            # replacing the per-field sweep is the PR-8 fix itself
            payload = jax.device_get(payload)
            self._append_delta_record(pre_vv, payload, None)
        else:
            self._state = fn(*args)
            self._count("ingest.dispatches")

    # -- read path (summary-first) ------------------------------------------

    # the mesh axis lane fields shard over — the 2-D subclass
    # (parallel/meshtarget2d.py) overrides it with its "mp" axis and
    # every collective read below follows
    LANE_AXIS = BATCH_AXIS

    def _digest_fn(self, state_slice, group_size):
        """Collective group digests: shard-local fingerprint+fold when
        shard and group boundaries align (the common case — group size
        64 divides every equal lane shard of a 2^k universe), the
        GSPMD-sharded base pass otherwise.  Either way only the G-word
        summary crosses to the host."""
        fn = self._mesh_digests.get(group_size)
        if fn is None:
            try:
                fn = build_mesh_digests(self._mesh, self.num_elements,
                                        group_size, self.LANE_AXIS)
            except ValueError:
                fn = False  # boundary mismatch: remember the fallback
            self._mesh_digests[group_size] = fn
        if fn is False:
            # misaligned boundaries: gather the slice onto one device
            # first — the base pass's XOR group reduce is not GSPMD-
            # partitionable over sharded lanes, and this config is the
            # rare one (group size 64 divides every equal lane shard
            # of a 2^k universe)
            device = self._mesh.devices.flat[0]
            state_slice = jax.tree.map(
                lambda x: jax.device_put(x, device), state_slice)
            return super()._digest_fn(state_slice, group_size)
        return fn(state_slice.present, state_slice.deleted,
                  state_slice.del_dot_actor, state_slice.del_dot_counter)

    def digest_summary(self, group_size: Optional[int] = None) -> bytes:
        """This replica's digest summary frame body (vv, processed,
        group digests) — the collective read the serve DSUM verb and
        the router's member cache consume.  Moves E/16 + O(A) bytes
        off-device regardless of mesh size."""
        from go_crdt_playground_tpu.net import digestsync
        from go_crdt_playground_tpu.ops.digest import DIGEST_GROUP_LANES

        if group_size is None:
            group_size = DIGEST_GROUP_LANES
        return digestsync.node_summary(self, group_size)

    def digest_summary_arrays(self, group_size: int):
        """The summary read's array triple ``(vv, processed, digests)``
        as ONE compiled dispatch over the resident sharded state (see
        ``build_mesh_summary``) — overriding ``Node``'s default, which
        eagerly slices ``x[0]`` off every state field (nine per-device
        dispatch rounds before the digest program runs; the measured
        MESH_CURVE digest fall-off).  The misaligned-boundary config
        keeps the base fallback."""
        fn = self._mesh_summary.get(group_size)
        if fn is None:
            try:
                fn = build_mesh_summary(self._mesh, self.num_elements,
                                        group_size, self.LANE_AXIS)
            except ValueError:
                fn = False  # boundary mismatch: remember the fallback
            self._mesh_summary[group_size] = fn
        if fn is False:
            return super().digest_summary_arrays(group_size)
        with self._lock:
            state = self._state
        digests, vv, processed = fn(state.present, state.deleted,
                                    state.del_dot_actor,
                                    state.del_dot_counter, state.vv,
                                    state.processed)
        # transfer-ok: deliberately OUTSIDE the lock block above (only
        # the state ref is read under it); one G-word summary pull —
        # callers in the digest-sync exchange may still hold theirs
        digests, vv, processed = jax.device_get(
            (digests, vv, processed))
        return (np.asarray(vv), np.asarray(processed),
                np.asarray(digests))

    # -- payload / recovery paths (GSPMD + re-pin) --------------------------

    # requires-lock: _lock
    def _apply_payload(self, mode: int, payload) -> None:
        super()._apply_payload(mode, payload)
        self._repin_state()

    def gc_deletions(self, frontier=None, participants=None) -> dict:
        out = super().gc_deletions(frontier, participants)
        with self._lock:
            self._repin_state()
        return out

    @classmethod
    def restore_durable(cls, dirpath: str, **kw) -> "MeshApplyTarget":
        node = super().restore_durable(dirpath, **kw)
        with node._lock:
            if isinstance(node, MeshApplyTarget):
                # (a fallback_init factory may construct a plain Node;
                # its placement is its own business)
                node._repin_state()
        return node

    # -- keyspace handoff (lane-index gathers) ------------------------------

    def extract_slice(self, element_mask: np.ndarray) -> bytes:
        """The donor half of a keyspace handoff, pulling ONLY the
        moving lanes: an on-device index gather of the masked lanes'
        fields (one K-lane device_get) scattered into the dense wire
        sections host-side — same bytes as ``Node.extract_slice``
        (pinned), without the dense E-lane device→host sweep."""
        mask = np.asarray(element_mask, bool)
        if mask.shape != (self.num_elements,):
            raise ValueError(f"slice mask shape {mask.shape} does not "
                             f"match universe ({self.num_elements},)")
        idx = np.nonzero(mask)[0]
        with self._lock:
            me = jax.tree.map(lambda x: x[0], self._state)
            if idx.size:
                # transfer-ok: one K-lane gather pull per handoff (a
                # rare admin op), vs the dense E-lane sweep it replaces
                lanes = jax.device_get(
                    _gather_slice_lanes(me, jnp.asarray(idx)))
            else:
                z = np.zeros(0, np.uint32)
                lanes = (z.astype(bool), z, z, z.astype(bool), z, z)
            vv = np.asarray(me.vv, np.uint32)
            processed = np.asarray(me.processed, np.uint32)
        pres, da, dc, dl, dda, ddc = (np.asarray(x) for x in lanes)
        E = self.num_elements
        changed = np.zeros(E, bool)
        ch_da = np.zeros(E, np.uint32)
        ch_dc = np.zeros(E, np.uint32)
        deleted = np.zeros(E, bool)
        del_da = np.zeros(E, np.uint32)
        del_dc = np.zeros(E, np.uint32)
        changed[idx] = pres
        ch_da[idx] = da
        ch_dc[idx] = dc
        deleted[idx] = dl
        del_da[idx] = dda
        del_dc[idx] = ddc
        payload = DeltaPayload(
            src_vv=vv, changed=changed, ch_da=ch_da, ch_dc=ch_dc,
            deleted=deleted, del_da=del_da, del_dc=del_dc,
            src_actor=np.uint32(self.actor), src_processed=processed)
        return framing.encode_payload_msg(MODE_SLICE, self.actor,
                                          processed, payload)
