"""2-D ``("dp", "mp")`` serve mesh: replicated ingest stripes over
lane-sharded state in one process (ISSUE 15, DESIGN.md §24).

PR 10's ``parallel/meshtarget.py`` shards one replica's lane axis over
a 1-D device mesh — state capacity scales with devices, but batch
throughput is pinned to ONE micro-batch per dispatch.  This module
composes the second axis (the SNIPPETS.md pjit dp×mp exemplar shape):
lane fields shard their trailing E over ``mp``; the ``dp`` axis holds
REPLICATED copies of that sharded state, and each dp replica applies
its own STRIPE of a super-batch concurrently, so one
``serve --mesh-devices DPxMP`` process applies up to dp micro-batches
per dispatch at mp× the per-device state capacity.

The parity contract (the hard part and the point) is BITWISE — state,
dots, WAL record bytes — against the 1-D worker fed the same op log.
Three mechanisms together make that exact rather than eventual:

1. **Key-disjoint striping** (``plan_stripes``).  The host packs ops
   into up to dp stripes such that no element key is touched by two
   stripes of one super-batch; an op whose keys span two stripes CUTS
   the super-batch (the remainder dispatches next, in order).  Each
   lane therefore has at most ONE writer per dispatch, which is what
   turns the dp join below into an exact select instead of a merge.
2. **Absolute counter bases.**  The row algebra's only cross-row
   couplings are clock prefix sums; the host precomputes every row's
   GLOBAL pre-row counter offset over the super-batch (replica-
   independent by construction — the ROADMAP seam), so rows
   interleaved across stripes assign the exact dot/deletion counters
   the sequential kernel assigns.  Striping changes WHERE a row runs,
   never WHAT it writes.
3. **Dissemination join over dp** (``gossip.disjoint_update_join``).
   After the stripes apply, ceil(log2 dp) ring rounds (the gossip
   dissemination-offset schedule, ``ppermute`` under shard_map) leave
   every dp replica holding the unique-writer select of all stripes —
   bitwise the sequential post-state, dots included (a general merge
   could not promise that: its both-present rule is order-sensitive).
   Replicas CONVERGE INSIDE every dispatch, so the replicated
   ``NamedSharding`` invariant holds at every read point and QUERY /
   DSUM / slice extraction see the joined replica by construction —
   no read-side reduce over dp is needed.

The batch δ for the WAL record is ``delta_extract`` of the joined
state against the pre-batch vv, in the same dispatch — identical
payload, identical record bytes (single-chunk batches) to the 1-D and
single-device paths.  A key-conflicted super-batch logs one record per
chunk; replay composes them in order, so durability semantics are
unchanged (the records ride the same causal guard).

Everything else — WAL/checkpoints, anti-entropy, digest summaries,
compaction, resharding slice transfer, the serve frontend — runs
UNCHANGED: this is a ``MeshApplyTarget`` whose lane axis is ``mp``,
and every collective read follows ``LANE_AXIS``.

CPU testing: ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
(the root conftest.py forces it) gives dp×mp ≤ 8 real coverage;
``serve --mesh-devices DPxMP`` is the CLI wiring.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from go_crdt_playground_tpu.parallel.gossip import (_shard_map,
                                                    disjoint_update_join)
from go_crdt_playground_tpu.parallel.meshtarget import (
    _PROGRAM_CACHE, MeshApplyTarget, _mesh_add_row, _mesh_del_row,
    payload_partition_specs, state_partition_specs)
from go_crdt_playground_tpu.ops.delta import delta_extract

DP_AXIS = "dp"
MP_AXIS = "mp"

MeshSpec = Union[int, Tuple[int, int], str]


def parse_mesh_spec(spec: MeshSpec):
    """Normalize a ``--mesh-devices`` value: ``"N"``/``N`` stays an int
    (the 1-D lane mesh), ``"DPxMP"``/``(dp, mp)`` becomes a 2-tuple
    (this module's mesh).  Raises ``ValueError`` with an operator-
    grade message on anything else — the serve CLI converts it to a
    typed argparse error (the ``--gc-participants`` precedent)."""
    one_d = False
    if isinstance(spec, (tuple, list)):
        if len(spec) != 2:
            raise ValueError(
                f"mesh spec {spec!r}: expected (dp, mp)")
        dp, mp = int(spec[0]), int(spec[1])
    elif isinstance(spec, int):
        one_d, dp, mp = True, 1, int(spec)
    else:
        text = str(spec).strip().lower()
        head, sep, tail = text.partition("x")
        if not head.isdigit() or (sep and not tail.isdigit()):
            raise ValueError(
                f"mesh spec {spec!r}: expected N (1-D lane mesh) or "
                "DPxMP (2-D replicated-ingest mesh), e.g. 8 or 2x4")
        if not sep:
            one_d, dp, mp = True, 1, int(head)
        else:
            dp, mp = int(head), int(tail)
    if dp < 1 or mp < 1:
        raise ValueError(
            f"mesh spec {spec!r}: every mesh extent must be >= 1")
    return int(mp) if one_d else (dp, mp)


def make_serve_mesh(dp: int, mp: int) -> Mesh:
    """The 2-D ``("dp", "mp")`` serve mesh over the first dp*mp devices
    in jax's stable enumeration — restarts of one topology place
    shards identically (the make_batch_mesh discipline)."""
    from go_crdt_playground_tpu.parallel.mesh import take_devices

    devices = take_devices(dp * mp)
    return Mesh(np.asarray(devices).reshape(dp, mp), (DP_AXIS, MP_AXIS))


# ---------------------------------------------------------------------------
# Host-side striping: key-disjoint stripes with global counter prefixes
# ---------------------------------------------------------------------------


class StripePlan:
    """One dispatch's packed stripes (all arrays ready for the mesh
    program; counter offsets are ABSOLUTE over the chunk's global row
    order — see the module docstring)."""

    __slots__ = ("add", "dl", "prefix", "add_total", "del_tick",
                 "rows", "stripes_used")

    def __init__(self, add, dl, prefix, add_total, del_tick, rows,
                 stripes_used):
        self.add = add                  # bool[dp, cap, E]
        self.dl = dl                    # bool[dp, cap, E]
        self.prefix = prefix            # uint32[dp, cap] pre-row ticks
        self.add_total = add_total      # uint32[dp, cap]
        self.del_tick = del_tick        # uint32[dp, cap]
        self.rows = rows                # keyed rows packed this chunk
        self.stripes_used = stripes_used


def plan_stripes(add_rows: np.ndarray, del_rows: np.ndarray,
                 live: np.ndarray, dp: int, cap: int,
                 assign: Optional[np.ndarray] = None
                 ) -> Tuple[List[StripePlan], int]:
    """Greedy order-preserving striping of one ``(B, E)`` op-batch
    into chunks of ≤ dp key-disjoint stripes of ≤ ``cap`` rows each.

    Rows are considered in batch order (the op-log order the sequential
    kernel applies).  A row lands in the stripe already owning one of
    its keys, or — when its keys are unowned — the stripe its
    ``assign`` hint names (the conflict-aware admission scheduler's
    pre-striping, serve/scheduler.py; entries outside ``[0, dp)`` mean
    unhinted), falling back to the least-loaded stripe.  A row whose
    keys span TWO stripes — or whose target stripe is full — cuts the
    chunk: everything before it dispatches now, it and every later row
    re-stripe fresh.  The hint steers PLACEMENT only; key-disjointness
    and capacity are enforced here regardless, so a bad hint costs
    cuts, never correctness.  Cutting (never reordering) is what
    keeps the global counter prefixes, and therefore the assigned
    dots, bitwise the sequential kernel's.  Dead/empty rows are
    dropped (they are padding: no tick, no lanes — the sequential
    kernel's masked no-op).

    Returns ``(plans, cuts)``.  An all-padding batch yields one empty
    plan, so the caller still runs one dispatch and logs one (empty)
    WAL record — byte-compatible with the single-device path.
    """
    B, E = add_rows.shape
    eff_add = add_rows & live[:, None]
    eff_del = del_rows & live[:, None]
    keyed = [r for r in range(B)
             if eff_add[r].any() or eff_del[r].any()]
    plans: List[StripePlan] = []
    cuts = 0
    i = 0
    while True:
        key_owner = np.full(E, -1, np.int32)
        loads = np.zeros(dp, np.int64)
        stripe_rows: List[List[int]] = [[] for _ in range(dp)]
        chunk: List[int] = []
        while i < len(keyed):
            r = keyed[i]
            keys = np.flatnonzero(eff_add[r] | eff_del[r])
            owners = np.unique(key_owner[keys])
            owners = owners[owners >= 0]
            if owners.size > 1:
                cuts += 1
                break  # cross-stripe keys: serialize at the cut
            if owners.size:
                s = int(owners[0])  # ownership beats any hint
            elif assign is not None and 0 <= assign[r] < dp:
                s = int(assign[r])
            else:
                s = int(np.argmin(loads))
            if loads[s] >= cap:
                cuts += 1
                break  # stripe full: the remainder dispatches next
            stripe_rows[s].append(r)
            chunk.append(r)
            loads[s] += 1
            key_owner[keys] = s
            i += 1
        # global counter prefixes over the chunk, in batch order
        add = np.zeros((dp, cap, E), bool)
        dl = np.zeros((dp, cap, E), bool)
        add_total = np.zeros((dp, cap), np.uint32)
        del_tick = np.zeros((dp, cap), np.uint32)
        row_prefix = {}
        run = 0
        for r in chunk:
            row_prefix[r] = run
            run += int(eff_add[r].sum()) + int(eff_del[r].any())
        # padding slots carry the end-of-chunk prefix: their (no-op)
        # clock writes land ≤ the chunk's final counter, and the vv
        # join's elementwise max recovers the exact final value
        prefix = np.full((dp, cap), run, np.uint32)
        for s, rlist in enumerate(stripe_rows):
            for j, r in enumerate(rlist):
                add[s, j] = eff_add[r]
                dl[s, j] = eff_del[r]
                prefix[s, j] = row_prefix[r]
                add_total[s, j] = eff_add[r].sum()
                del_tick[s, j] = bool(eff_del[r].any())
        plans.append(StripePlan(add, dl, prefix, add_total, del_tick,
                                len(chunk),
                                int(sum(1 for x in stripe_rows if x))))
        if i >= len(keyed):
            return plans, cuts


# ---------------------------------------------------------------------------
# The one-dispatch 2-D program
# ---------------------------------------------------------------------------


def build_mesh2d_ingest(mesh: Mesh, state_cls, with_delta: bool):
    """Compile the 2-D super-batch apply: full ``(1, ...)`` state in
    (lane fields mp-sharded, replicated over dp), merged state (+ the
    super-batch δ vs the pre-batch vv when ``with_delta``) out.  Per
    (dp, mp) device: scan THIS stripe's rows over THIS lane shard with
    the host's absolute counter bases, then the dp dissemination join
    (gossip.disjoint_update_join) converges the stripes in-dispatch —
    the output honestly satisfies its replicated-over-dp out_spec.
    Memoized in the shared ``_PROGRAM_CACHE``."""
    dp = mesh.shape[DP_AXIS]
    # the mesh SHAPE is part of the key: one device set factors as
    # (2, 2) or (1, 4) with identical flat ids but different programs
    key = ("ingest2d", tuple(d.id for d in mesh.devices.flat),
           (dp, mesh.shape[MP_AXIS]), state_cls, bool(with_delta))
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached
    st_specs = state_partition_specs(state_cls, MP_AXIS)

    def body(state, add, dl, prefix, add_base, add_total, del_tick):
        st = jax.tree.map(lambda x: x[0], state)
        pre_vv = st.vv
        a = st.actor.astype(jnp.int32)
        pre_ctr = pre_vv[a]

        def step(s, x):
            add_row, del_row, pre, base_off, a_tot, d_tick = x
            s = _mesh_add_row(s, add_row, base_off, a_tot,
                              base=pre_ctr + pre)
            s = _mesh_del_row(s, del_row, d_tick,
                              base=pre_ctr + pre + a_tot)
            return s, None

        stripe, _ = jax.lax.scan(
            step, st, (add[0], dl[0], prefix[0], add_base[0, :, 0],
                       add_total[0], del_tick[0]))
        joined = disjoint_update_join(stripe, st, DP_AXIS, dp)
        full = jax.tree.map(lambda r: r[None], joined)
        if not with_delta:
            return full
        return full, delta_extract(joined, pre_vv)

    in_specs = (st_specs,
                P(DP_AXIS, None, MP_AXIS),   # add stripes
                P(DP_AXIS, None, MP_AXIS),   # del stripes
                P(DP_AXIS, None),            # absolute row prefixes
                P(DP_AXIS, None, MP_AXIS),   # per-(row, mp) base offs
                P(DP_AXIS, None),            # per-row add totals
                P(DP_AXIS, None))            # per-row del ticks
    out_specs = ((st_specs, payload_partition_specs(MP_AXIS))
                 if with_delta else st_specs)
    # check_vma=False for the same reason as the 1-D program, plus the
    # join's replication-by-construction claim: after the dissemination
    # rounds every dp replica holds the identical joined state (the
    # unique-writer select), which the static checker cannot see
    # through ppermute — the bitwise pins vs the sequential kernel are
    # the actual correctness gate (tests/test_meshtarget.py)
    fn = jax.jit(_shard_map(body, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=False))
    _PROGRAM_CACHE[key] = fn
    return fn


class Mesh2DApplyTarget(MeshApplyTarget):
    """A ``Node`` serving dp replicated ingest stripes over mp lane
    shards (module docstring).  Drop-in for every Node role; the
    ``(1, N)`` and ``(N, 1)`` degenerate meshes are bitwise the 1-D
    mesh / single-device paths (pinned in tests/test_meshtarget.py).

    ``ingest_stripes`` is the serve batcher's width multiplier: the
    micro-batcher packs up to ``dp * max_batch`` admitted ops per
    super-batch (serve/batcher.py), which is where the dp× throughput
    comes from — more rows per dispatch, one WAL fsync per chunk.
    """

    LANE_AXIS = MP_AXIS

    def __init__(self, actor: int, num_elements: int, num_actors: int,
                 mesh_shape: MeshSpec = None, **node_kwargs):
        if node_kwargs.get("delta_semantics", "v2") != "v2":
            # the in-dispatch δ extraction + record composition lean on
            # v2's deletion-record join; the serve tier is v2-only
            # already (compaction, digest sync) — refuse loudly rather
            # than diverge quietly
            raise ValueError(
                "Mesh2DApplyTarget requires delta_semantics='v2'")
        super().__init__(actor, num_elements, num_actors,
                         mesh_devices=mesh_shape, **node_kwargs)
        # race-ok: read-only configuration after __init__
        self.dp = int(self._mesh.shape[DP_AXIS])
        self.mp = int(self._mesh.shape[MP_AXIS])
        # the batcher's width multiplier (serve/apply.py contract)
        # race-ok: read-only configuration after __init__
        self.ingest_stripes = self.dp

    def _build_mesh(self, mesh_devices):
        spec = parse_mesh_spec(mesh_devices if mesh_devices is not None
                               else (1, 1))
        if isinstance(spec, int):
            spec = (1, spec)
        return make_serve_mesh(*spec)

    # requires-lock: _lock
    def _apply_batch_locked(self, add_rows: np.ndarray,
                            del_rows: np.ndarray, live: np.ndarray,
                            pre_vv: Optional[np.ndarray],
                            stripe_hint: Optional[np.ndarray] = None
                            ) -> None:
        B = add_rows.shape[0]
        cap = max(1, -(-B // self.dp))
        plans, cuts = plan_stripes(add_rows, del_rows, live, self.dp,
                                   cap, assign=stripe_hint)
        if cuts:
            self._count("mesh.stripe.cuts", cuts)
        with_delta = pre_vv is not None
        fn = self._mesh_ingest.get(with_delta)
        if fn is None:
            fn = build_mesh2d_ingest(self._mesh, type(self._state),
                                     with_delta)
            self._mesh_ingest[with_delta] = fn
        for k, plan in enumerate(plans):
            if k > 0 and with_delta:
                # chunk k's record compresses against the post-chunk-
                # (k-1) clock — the same guard discipline as any two
                # successive batches
                pre_vv = np.asarray(self._state.vv[0]).copy()
            dp, cap_ = plan.add.shape[0], plan.add.shape[1]
            counts = plan.add.reshape(dp, cap_, self.mp, -1).sum(
                axis=3, dtype=np.uint32)
            add_base = np.cumsum(counts, axis=2, dtype=np.uint32) \
                - counts
            args = (self._state, jnp.asarray(plan.add),
                    jnp.asarray(plan.dl), jnp.asarray(plan.prefix),
                    jnp.asarray(add_base), jnp.asarray(plan.add_total),
                    jnp.asarray(plan.del_tick))
            self._count("ingest.dispatches")
            self._count("mesh.stripe.dispatches")
            if plan.rows:
                self._count("mesh.stripe.rows", plan.rows)
                self._count("mesh.stripe.width", plan.stripes_used)
            if with_delta:
                self._state, payload = fn(*args)
                # ONE device→host pull for the chunk's δ pytree; the
                # record encoder's host-side break-even ladder runs on
                # numpy, exactly the 1-D path
                # transfer-ok: one bounded fixed-K pull per chunk —
                # same sanction as the 1-D ingest path
                payload = jax.device_get(payload)
                self._append_delta_record(pre_vv, payload, None)
            else:
                self._state = fn(*args)
