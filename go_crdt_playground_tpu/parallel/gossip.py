"""Anti-entropy gossip: pairing schedules, merge rounds, fault injection,
convergence loops.

Reference analogue: a "message exchange" is ``dst.Merge(src)`` between two
in-process structs (awset_test.go:16-17).  Here one gossip round is a
single batched tensor op: every replica r absorbs replica ``perm[r]``
(``state[perm]`` is a gather that XLA lowers to collective-permute /
all-to-all over ICI when the replica axis is sharded), then the vmapped
merge kernel runs with zero cross-replica data dependence.

Schedules:
  * ring (offset 1)        — classic neighbor gossip; O(R) rounds.
  * dissemination (doubling offsets 1,2,4,...) — converges in ceil(log2 R)
    rounds; the butterfly realization of "all-pairs" (SURVEY §5.7c): valid
    because membership-convergence is associative across merge chains
    [verified, SURVEY §3.2].
  * butterfly (XOR pairs)  — symmetric exchanges, R power of two.
  * random pairing         — uniform gossip for fault-injection studies.

Fault injection (SURVEY §5.3): a dropped exchange is a masked no-op lane —
replica keeps its old state for the round.  State-based merge is idempotent
and commutative-on-membership, so drops only delay convergence; the
rounds-to-convergence-under-drop-rate curve is a north-star metric.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from go_crdt_playground_tpu.models.awset import AWSetState
from go_crdt_playground_tpu.models.awset_delta import AWSetDeltaState
from go_crdt_playground_tpu.ops.merge import merge_pairwise
from go_crdt_playground_tpu.ops.delta import (
    delta_apply, delta_extract, delta_merge_pairwise)
from go_crdt_playground_tpu.parallel import collectives
from go_crdt_playground_tpu.parallel import mesh as mesh_mod
from go_crdt_playground_tpu.parallel.mesh import (
    ELEMENT_AXIS, REPLICA_AXIS, partition_specs)

# One fused program for the per-round convergence predicate — the
# measurement loop calls it up to max_rounds times.
converged_jit = jax.jit(collectives.converged)


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """jax moved shard_map from jax.experimental to the top level and
    renamed check_rep -> check_vma along the way; accept every
    generation so one source serves them all (same dance as the
    pltpu.CompilerParams shim in ops/pallas_merge.py)."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    except TypeError:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)

# ---------------------------------------------------------------------------
# Pairing schedules (permutations of the replica axis)
# ---------------------------------------------------------------------------


def ring_perm(num_replicas: int, offset: int = 1) -> jnp.ndarray:
    """Partner of r is (r + offset) mod R."""
    return (jnp.arange(num_replicas, dtype=jnp.uint32) + offset) % num_replicas


def butterfly_perm(num_replicas: int, stage: int) -> jnp.ndarray:
    """Partner of r is r XOR 2^stage (symmetric pairs; R power of two)."""
    if num_replicas & (num_replicas - 1):
        raise ValueError("butterfly needs a power-of-two replica count")
    if not 0 <= stage or (1 << stage) >= num_replicas:
        raise ValueError(
            f"butterfly stage {stage} out of range for R={num_replicas} "
            f"(need 1 << stage < R; JAX would silently clamp the partners)")
    return jnp.arange(num_replicas, dtype=jnp.uint32) ^ jnp.uint32(1 << stage)


def random_perm(key: jax.Array, num_replicas: int) -> jnp.ndarray:
    return jax.random.permutation(key, num_replicas).astype(jnp.uint32)


# ---------------------------------------------------------------------------
# Gossip rounds
# ---------------------------------------------------------------------------


def _auto_kernel(state, delta_semantics: Optional[str] = None,
                 single_device: bool = True) -> str:
    """The fused-kernel auto-dispatch rule, in ONE place: Pallas on TPU
    backends (single-device processes unless the caller runs per shard
    inside shard_map) when the actor axis fits the fused row kernels.
    Both δ semantics fuse — the strict-reference empty-δ quirk is a
    scratch-accumulated cross-E reduction inside the kernel
    (ops/pallas_delta._strict_vv_epilogue).  All choices are
    bitwise-identical; on TPU the XLA HasDot gather lowers
    pathologically inside compiled loops (~40x slower, see
    ops/pallas_merge.py regime notes)."""
    from go_crdt_playground_tpu.ops.pallas_merge import MAX_FUSED_ACTORS

    fusible = (state.vv.shape[-1] <= MAX_FUSED_ACTORS
               and delta_semantics in (None, "v2", "reference"))
    ok = (jax.default_backend() == "tpu"
          and (not single_device or jax.device_count() == 1)
          and fusible)
    if (not ok and fusible and single_device
            and jax.default_backend() == "tpu"
            and jax.device_count() > 1):
        # the ONLY reason this fleet fell off the fused path is the
        # multi-device process: a bare pallas_call has no GSPMD
        # partitioning rule under an arbitrary perm, and the XLA HasDot
        # gather lowers pathologically on TPU (~40x, see
        # ops/pallas_merge.py regime notes).  Don't let users pay that
        # silently — the mesh-native rounds keep the fused kernel.
        import warnings

        warnings.warn(
            "multi-device TPU process: this gossip round is running the "
            "XLA gather path (~40x slower than the fused kernel on TPU). "
            "Use ring_round_shardmap / delta-ring or "
            "butterfly_round_shardmap for mesh schedules, or pass "
            "kernel='xla' to acknowledge the slow path.",
            stacklevel=3)
    return "pallas" if ok else "xla"


def _select_rows(mask_r: jnp.ndarray, new, old):
    """Per-replica select between two state pytrees (mask True -> new)."""
    return jax.tree.map(
        lambda n, o: jnp.where(mask_r.reshape((-1,) + (1,) * (n.ndim - 1)),
                               n, o),
        new, old,
    )


def gossip_round(
    state: AWSetState,
    perm: jnp.ndarray,
    drop_mask: Optional[jnp.ndarray] = None,
    kernel: str = "auto",
) -> AWSetState:
    """One full-state anti-entropy round: r <- perm[r] for all r.

    drop_mask: bool[R], True = this replica's exchange is lost this round
    (it keeps its old state) — fault injection as a masked lane.

    kernel: "auto" (fused Pallas kernel on single-device TPU processes,
    XLA elsewhere), "xla", or "pallas".  All choices are bitwise-
    identical; on TPU the XLA HasDot gather lowers pathologically
    inside compiled loops (~40x slower, see ops/pallas_merge.py regime
    notes), so auto picks the multi-row fused kernel there.  auto stays
    on XLA when more than one device is visible — a bare pallas_call
    has no GSPMD partitioning rule under an arbitrary perm; mesh
    programs get the fused path through ring_round_shardmap (its auto
    dispatch invokes the kernel per shard inside shard_map, so TPU
    meshes never pay the XLA HasDot penalty on the ring schedule).
    """
    if kernel == "auto":
        kernel = _auto_kernel(state)
    if kernel == "pallas":
        from go_crdt_playground_tpu.ops.pallas_merge import (
            pallas_gossip_round_rows)

        merged = pallas_gossip_round_rows(state, perm)
    else:
        src = jax.tree.map(lambda x: x[perm], state)
        merged, _ = merge_pairwise(state, src)
    if drop_mask is not None:
        merged = _select_rows(~drop_mask, merged, state)
    return merged


gossip_round_jit = jax.jit(gossip_round, static_argnames=("kernel",))


def ring_gossip_round(
    state: AWSetState,
    offset,
    drop_mask: Optional[jnp.ndarray] = None,
    kernel: str = "auto",
) -> AWSetState:
    """One full-state ring round: r <- (r + offset) mod R, the pairing
    every production schedule here uses (dissemination offsets, ICI
    rings).  Bitwise-equal to ``gossip_round(state, ring_perm(R,
    offset))`` but on TPU it dispatches the ring-FUSED kernel: partner
    rows are read in place via block index maps, so no ``state[perm]``
    copy is materialized — peak HBM drops from ~3x to ~2x state and a
    full state read of HBM traffic disappears (ops/pallas_merge.py).
    ``offset`` may be a traced scalar: one compiled program serves a
    whole dissemination schedule."""
    if kernel == "auto":
        kernel = _auto_kernel(state)
    if kernel == "pallas":
        from go_crdt_playground_tpu.ops.pallas_merge import (
            pallas_ring_round_rows)

        merged = pallas_ring_round_rows(state, offset)
    else:
        merged = gossip_round(state, ring_perm(state.vv.shape[0], offset),
                              kernel=kernel)
    if drop_mask is not None:
        merged = _select_rows(~drop_mask, merged, state)
    return merged


ring_gossip_round_jit = jax.jit(ring_gossip_round,
                                static_argnames=("kernel",))


def delta_gossip_round(
    state: AWSetDeltaState,
    perm: jnp.ndarray,
    drop_mask: Optional[jnp.ndarray] = None,
    delta_semantics: str = "v2",
    strict_reference_semantics: bool = True,
    kernel: str = "auto",
) -> AWSetDeltaState:
    """One δ anti-entropy round (payload-compressed exchanges).

    kernel: "auto" picks the fused Pallas δ kernel on single-device TPU
    processes (bitwise-identical, ~44x faster at fleet scale — the XLA
    HasDot gathers lower pathologically there, ops/pallas_merge.py
    regime notes); both δ semantics fuse, incl. the strict empty-δ
    quirk (scratch-accumulated cross-E reduction in the kernel).  Mesh
    programs keep XLA (same GSPMD caveat as gossip_round — use
    shard_map + kernel="pallas" per shard instead).
    """
    if kernel == "auto":
        kernel = _auto_kernel(state, delta_semantics)
    if kernel == "pallas":
        from go_crdt_playground_tpu.ops.pallas_delta import (
            pallas_delta_gossip_round)

        merged = pallas_delta_gossip_round(
            state, perm, delta_semantics=delta_semantics,
            strict_reference_semantics=strict_reference_semantics)
    else:
        src = jax.tree.map(lambda x: x[perm], state)
        merged = delta_merge_pairwise(state, src, delta_semantics,
                                      strict_reference_semantics)
    if drop_mask is not None:
        merged = _select_rows(~drop_mask, merged, state)
    return merged


delta_gossip_round_jit = jax.jit(
    delta_gossip_round,
    static_argnames=("delta_semantics", "strict_reference_semantics",
                     "kernel"),
)


def delta_ring_gossip_round(
    state: AWSetDeltaState,
    offset,
    drop_mask: Optional[jnp.ndarray] = None,
    delta_semantics: str = "v2",
    strict_reference_semantics: bool = True,
    kernel: str = "auto",
) -> AWSetDeltaState:
    """One δ ring round: r absorbs (r + offset) mod R.  On TPU this
    dispatches the ring-fused δ kernel (BOTH semantics — reference mode
    fuses the empty-δ VV-skip as an in-kernel emptiness reduction),
    which reads partner rows in place — no materialized ``state[perm]``
    copy.  That is what lets the 1M-replica north star fit on one 16GB
    chip: the gather path peaks at ~3x the 6.5GB state and OOMs.
    Bitwise-equal to ``delta_gossip_round(state, ring_perm(R, offset),
    ...)``."""
    if kernel == "auto":
        kernel = _auto_kernel(state, delta_semantics)
    if kernel == "pallas":
        from go_crdt_playground_tpu.ops.pallas_delta import (
            pallas_delta_ring_round)

        merged = pallas_delta_ring_round(
            state, offset, delta_semantics=delta_semantics,
            strict_reference_semantics=strict_reference_semantics)
    else:
        merged = delta_gossip_round(
            state, ring_perm(state.vv.shape[0], offset),
            delta_semantics=delta_semantics,
            strict_reference_semantics=strict_reference_semantics,
            kernel=kernel)
    if drop_mask is not None:
        merged = _select_rows(~drop_mask, merged, state)
    return merged


delta_ring_gossip_round_jit = jax.jit(
    delta_ring_gossip_round,
    static_argnames=("delta_semantics", "strict_reference_semantics",
                     "kernel"),
)


def ormap_gossip_round(state, perm: jnp.ndarray, kernel: str = "auto"):
    """One OR-Map anti-entropy round: the key membership is exactly the
    AWSet round (fused Pallas kernel on single-device TPU, same dispatch
    as gossip_round), the value cells join with the elementwise LWW rule.
    Bitwise-equivalent to ``lattices.gossip_round(lattices.ormap_join,
    state, perm)`` — that XLA path pays the pathological HasDot-gather
    lowering at fleet scale, this one doesn't."""
    from go_crdt_playground_tpu.ops.lattices import ORMapState, _lww_newer

    base = AWSetState(vv=state.vv, present=state.present,
                      dot_actor=state.dot_actor,
                      dot_counter=state.dot_counter, actor=state.actor)
    merged = gossip_round(base, perm, kernel=kernel)
    src_ts = state.ts[perm]
    src_wa = state.wr_actor[perm]
    take = _lww_newer(src_ts, src_wa, state.ts, state.wr_actor)
    return ORMapState(
        vv=merged.vv, present=merged.present, dot_actor=merged.dot_actor,
        dot_counter=merged.dot_counter, actor=state.actor,
        ts=jnp.where(take, src_ts, state.ts),
        wr_actor=jnp.where(take, src_wa, state.wr_actor),
        val=jnp.where(take, state.val[perm], state.val),
    )


def ormap_ring_gossip_round(state, offset, kernel: str = "auto"):
    """OR-Map ring round: the key membership runs the ring-FUSED AWSet
    kernel (in-place partner reads), the LWW value cells join against
    partner rows obtained by a row roll (a contiguous-slice shift, not
    the pathological elementwise gather).  Bitwise-equivalent to
    ``ormap_gossip_round(state, ring_perm(R, offset))``."""
    from go_crdt_playground_tpu.ops.lattices import ORMapState, _lww_newer

    base = AWSetState(vv=state.vv, present=state.present,
                      dot_actor=state.dot_actor,
                      dot_counter=state.dot_counter, actor=state.actor)
    merged = ring_gossip_round(base, offset, kernel=kernel)
    # row gather, not jnp.roll: with a traced offset roll lowers to
    # concatenate((x, x)) + dynamic_slice — a transient 2x copy per
    # value plane — while a [R]-index row gather materializes exactly
    # one partner copy at HBM bandwidth
    src_rows = ring_perm(state.ts.shape[0], offset)
    roll = lambda x: jnp.take(x, src_rows, axis=0)  # noqa: E731
    src_ts, src_wa = roll(state.ts), roll(state.wr_actor)
    take = _lww_newer(src_ts, src_wa, state.ts, state.wr_actor)
    return ORMapState(
        vv=merged.vv, present=merged.present, dot_actor=merged.dot_actor,
        dot_counter=merged.dot_counter, actor=state.actor,
        ts=jnp.where(take, src_ts, state.ts),
        wr_actor=jnp.where(take, src_wa, state.wr_actor),
        val=jnp.where(take, roll(state.val), state.val),
    )


def _extract_round(state: AWSetDeltaState, perm: jnp.ndarray):
    """Batched sender-side δ-extraction for one round's pairing: replica r
    will absorb perm[r], so extract perm[r]'s payload against r's VV."""
    src = jax.tree.map(lambda x: x[perm], state)
    return jax.vmap(delta_extract)(src, state.vv)


@jax.jit
def pipelined_delta_gossip(state: AWSetDeltaState,
                           perms: jnp.ndarray) -> AWSetDeltaState:
    """PP-analogue δ gossip (SURVEY §2.3 PP row): the δ-extract →
    δ-apply → VV-join pipeline is staged ACROSS rounds with a
    double-buffered payload.

    Round i's apply consumes the payload extracted during round i-1, and
    round i+1's payload is extracted from the PRE-apply state — so inside
    the compiled ``lax.scan`` body the extraction (and, on a sharded
    replica axis, its collective-permute traffic) has no data dependence
    on the in-flight apply and XLA overlaps the two stages.  The price is
    one round of staleness: payloads are compressed against a receiver VV
    that is one round old.  A stale receiver VV only ever ENLARGES the
    payload (the receiver's clock is monotone), and δ-apply is idempotent
    and mask-guarded, so the schedule stays convergent — it just ships
    data learned in round i starting at round i+2 instead of i+1
    (pipeline depth 2, exactly the double buffer).

    v2 δ semantics (payload-only exchanges subsume the first-contact full
    merge: extraction against a never-seen receiver VV ships every present
    lane and live deletion record).  perms: uint32[n_rounds, R].
    """
    apply_round = jax.vmap(
        lambda d, p: delta_apply(d, p, delta_semantics="v2"))
    payload = _extract_round(state, perms[0])
    n = perms.shape[0]

    def body(carry, i):
        s, p = carry
        return (apply_round(s, p), _extract_round(s, perms[i + 1])), None

    if n > 1:  # scan the first n-1 rounds; the last apply needs no staging
        (state, payload), _ = jax.lax.scan(
            body, (state, payload), jnp.arange(n - 1))
    return apply_round(state, payload)


@functools.partial(jax.jit, static_argnames=("k_changed", "k_deleted"))
def compact_delta_gossip_round(
    state: AWSetDeltaState,
    perm: jnp.ndarray,
    k_changed: int = 64,
    k_deleted: int = 64,
) -> AWSetDeltaState:
    """One δ round through the fixed-K compact payload form
    (ops/compact.py): extract -> compact to K index/value lanes ->
    expand -> apply (v2 semantics).

    This is the steady-state gossip path — the analogue of the
    reference's δ branch after first contact (awset-delta_test.go:57-62).
    When a pair's payload exceeds K, that exchange degrades to a safe
    partial one (entries up to capacity, NO clock advance — see
    ops/compact.py's correctness note), exactly like a lossy network
    round; schedules should bootstrap bulk divergence with dense rounds
    (delta_gossip_round / gossip_round, the full-merge analogue of
    awset-delta_test.go:53-56) and use compact rounds once payloads fit.
    """
    from go_crdt_playground_tpu.ops import compact as compact_ops

    E = state.present.shape[-1]
    src = jax.tree.map(lambda x: x[perm], state)
    payload = jax.vmap(delta_extract)(src, state.vv)
    comp = compact_ops.compact_payload_batch(payload, k_changed, k_deleted)
    dense = compact_ops.expand_payload_batch(comp, E)
    return jax.vmap(
        lambda d, p: delta_apply(d, p, delta_semantics="v2"))(state, dense)


@functools.lru_cache(maxsize=None)
def _compact_ring_step_compiled(mesh: Mesh, k_changed: int, k_deleted: int):
    """Cached jitted compact-payload ring: the only arrays that cross
    devices are the receiver VV advertisement (backward) and the fixed-K
    payload (forward) — O(K) ICI bytes per replica instead of O(E)."""
    from go_crdt_playground_tpu.ops import compact as compact_ops

    n = mesh.shape[REPLICA_AXIS]
    fwd = [(i, (i + 1) % n) for i in range(n)]       # sender -> receiver
    bwd = [(i, (i - 1) % n) for i in range(n)]       # receiver VV -> sender
    # The element mesh dim is pinned to 1 (caller-checked), so the EP
    # spec — actor axes formally sharded over it — is the same layout
    # while letting shard_map's replication inference accept vv/processed
    # outputs that mix element-tagged values (the payload path) in.
    specs = partition_specs(AWSetDeltaState, shard_actors=True)

    def step(local):
        E = local.present.shape[-1]
        # 1. receiver advertises its VV to its ring sender
        #    (the wire protocol of awset-delta_test.go:59: δ-extraction
        #    is compressed against the receiver's clock)
        recv_vv = jax.lax.ppermute(local.vv, REPLICA_AXIS, bwd)
        # 2. sender-side extract + compact against the advertised VV
        payload = jax.vmap(delta_extract)(local, recv_vv)
        comp = compact_ops.compact_payload_batch(
            payload, k_changed, k_deleted)
        # 3. only the compact payload crosses the ring
        shipped = jax.tree.map(
            lambda x: jax.lax.ppermute(x, REPLICA_AXIS, fwd), comp)
        # 4. receiver-side expand + apply
        dense = compact_ops.expand_payload_batch(shipped, E)
        return jax.vmap(
            lambda d, p: delta_apply(d, p, delta_semantics="v2"))(
                local, dense)

    return jax.jit(
        _shard_map(step, mesh=mesh, in_specs=(specs,), out_specs=specs)
    )


def compact_ring_round_shardmap(
    state: AWSetDeltaState,
    mesh: Mesh,
    k_changed: int = 64,
    k_deleted: int = 64,
) -> AWSetDeltaState:
    """One compact-payload ring round with the communication pinned to
    ICI neighbors: device i's replica block syncs into device i+1's,
    shipping only the fixed-K payload lanes (plus the receiver's VV
    advertisement going the other way).  Equivalent to
    ``compact_delta_gossip_round`` with the block-shift permutation;
    requires the element axis unsharded (compaction scans E locally).
    """
    if mesh.shape[ELEMENT_AXIS] != 1:
        raise ValueError(
            "compact ring needs the element axis unsharded "
            f"(mesh element dim {mesh.shape[ELEMENT_AXIS]}): lane "
            "compaction is a scan over the full element axis")
    return _compact_ring_step_compiled(mesh, k_changed, k_deleted)(state)


def dissemination_offsets(num_replicas: int):
    """Doubling offsets 1, 2, 4, ... — ceil(log2 R) rounds to full
    convergence on any replica count."""
    offs, o = [], 1
    while o < num_replicas:
        offs.append(o)
        o *= 2
    return offs


def disjoint_update_join(local, base, axis_name: str, num_shards: int):
    """Converge per-device copies of a REPLICATED state whose devices
    applied KEY-DISJOINT updates, via dissemination-doubling ring
    rounds over ``axis_name`` — the 2-D serve mesh's dp-axis
    convergence (parallel/meshtarget2d.py): each dp replica applies
    its own stripe of a super-batch, then ceil(log2 dp) ring rounds
    (offsets 1, 2, 4, ... — the ``dissemination_offsets`` schedule,
    realized as ``ppermute`` neighbor exchanges under shard_map) leave
    every replica holding the exact join.

    The join rule leans on the striping invariant instead of the
    general merge kernel: every lane was updated by AT MOST ONE
    replica (the batcher's key-disjoint stripes), so "partner's lane
    differs from the shared pre-update ``base``" identifies the unique
    writer and a plain select reconstructs the sequential result
    BITWISE — dots included, which the general full-merge rule cannot
    promise (its both-present overwrite is order-sensitive).  Clocks
    join elementwise (vv/processed are monotone counters, max IS their
    join).  Overlapping dissemination windows are safe: two rounds
    that both carry a lane carry the identical value (unique writer),
    so the select is idempotent.

    Must run inside ``shard_map`` with ``axis_name`` bound; ``local``
    and ``base`` are single-replica slices (fields [E_loc]/[A]).
    """
    from go_crdt_playground_tpu.models.layout import (ACTOR_AXIS_FIELDS,
                                                      REPLICA_ONLY_FIELDS)

    if num_shards == 1:
        return local
    clock_fields = set(ACTOR_AXIS_FIELDS) | set(REPLICA_ONLY_FIELDS)
    lane_fields = [f for f in type(local)._fields
                   if f not in clock_fields]

    def lane_diff(candidate):
        d = None
        for f in lane_fields:
            neq = getattr(candidate, f) != getattr(base, f)
            d = neq if d is None else (d | neq)
        return d

    for off in dissemination_offsets(num_shards):
        pairs = [((d + off) % num_shards, d) for d in range(num_shards)]
        partner = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, pairs), local)
        take = lane_diff(partner)
        updates = {f: jnp.where(take, getattr(partner, f),
                                getattr(local, f))
                   for f in lane_fields}
        for f in ACTOR_AXIS_FIELDS:
            if f in type(local)._fields:
                updates[f] = jnp.maximum(getattr(local, f),
                                         getattr(partner, f))
        local = local._replace(**updates)
    return local


@functools.partial(jax.jit, static_argnames=("delta", "delta_semantics"))
def all_pairs_converge(state, delta: bool = False,
                       delta_semantics: str = "v2"):
    """The all-pairs exchange realized as ceil(log2 R) doubling-offset
    rounds instead of O(R^2) work (SURVEY §5.7c)."""
    R = state.vv.shape[0]
    for off in dissemination_offsets(R):
        if delta:
            state = delta_ring_gossip_round(
                state, off, delta_semantics=delta_semantics)
        else:
            state = ring_gossip_round(state, off)
    return state


@functools.lru_cache(maxsize=None)
def _advance_program(delta: bool, schedule: str, delta_semantics: str,
                     has_drop: bool):
    """Cached jitted multi-round advance for rounds_to_convergence: a
    whole chunk of rounds is ONE dispatch — the round index drives
    offset selection and the drop/perm randomness INSIDE a lax.scan
    (fold_in on the traced index reproduces the exact stream the old
    eager loop drew), so a remote-tunnel measurement pays
    rounds/check_every round trips instead of 2-3 per round.  The
    eager form ground through ~1.8K tiny tunnel dispatches per droprate
    run and looked like a hang (round-4 postmortem).  key and
    drop_rate are traced operands, so the six-rate droprate sweep
    shares one compiled program per chunk width; distinct static n
    values are the chunk size plus O(log check_every) bisection
    widths.  has_drop is static so no-drop runs keep the drop=None fast
    path (no mask draw, no per-round full-state select)."""
    round_fn = delta_gossip_round if delta else gossip_round
    ring_fn = delta_ring_gossip_round if delta else ring_gossip_round
    kw = {"delta_semantics": delta_semantics} if delta else {}

    @functools.partial(jax.jit, static_argnames=("n",))
    def advance_jit(s, key, offsets_arr, drop_rate, start, n: int):
        R = s.vv.shape[0]

        def body(c, i):
            rnd = start + i
            drop = None
            if has_drop:
                drop = jax.random.bernoulli(
                    jax.random.fold_in(key, 2 * rnd + 1), drop_rate, (R,))
            if schedule == "random":
                perm = random_perm(jax.random.fold_in(key, 2 * rnd), R)
                return round_fn(c, perm, drop, **kw), None
            if schedule == "butterfly":
                # stages cycle 0..log2(R)-1; the m distinct XOR stages
                # are hypercube dissemination — all-pairs in exactly m
                # rounds (R power-of-two, validated by the caller)
                stage = rnd % jnp.uint32(R.bit_length() - 1)
                perm = (jnp.arange(R, dtype=jnp.uint32)
                        ^ (jnp.uint32(1) << stage))
                return round_fn(c, perm, drop, **kw), None
            off = (jnp.uint32(1) if schedule == "ring"
                   else offsets_arr[rnd % offsets_arr.shape[0]])
            return ring_fn(c, off, drop, **kw), None

        s, _ = jax.lax.scan(body, s, jnp.arange(n, dtype=jnp.uint32))
        # the convergence digest rides in the same program: a chunk costs
        # ONE device->host sync (the bool), not a second digest dispatch
        return s, collectives.converged(s.present, s.vv)

    return advance_jit


def rounds_to_convergence(
    state,
    key: Optional[jax.Array] = None,
    drop_rate: float = 0.0,
    max_rounds: int = 10_000,
    delta: bool = False,
    delta_semantics: str = "v2",
    schedule: str = "dissemination",
    check_every: int = 8,
) -> Tuple[int, object]:
    """Host-driven convergence loop: gossip until every replica agrees on
    (membership, VV); returns (rounds, final state).  The north-star
    metric's measurement harness (BASELINE.md).

    With drop_rate > 0 each replica's exchange is lost independently per
    round (requires ``key``).

    check_every: how many rounds run between host-synced convergence
    checks.  Every check is a device->host round trip (~60ms through a
    remote-TPU tunnel), so per-round checking dominates measurement at
    fleet scale; with a chunk size k the loop pays rounds/k + O(log k)
    syncs instead of rounds.  The returned round count is EXACT for any
    chunk size: when a chunk lands converged, the minimal prefix is
    found by bisection, replaying rounds from the chunk-start state —
    valid because round randomness derives from the round INDEX
    (fold_in), so replay reproduces the same drops/pairings, and a
    converged fleet stays converged under further gossip (merge is
    idempotent), making convergence monotone within the chunk.

    Memory note: chunking keeps the chunk-start state live for replay —
    ONE extra fleet copy on device.  When a fleet barely fits (e.g. the
    1M-replica δ north star at ~6.5GB state), pass check_every=1 to
    trade the sync savings back for the old single-copy footprint.
    """
    R = state.vv.shape[0]
    offsets = dissemination_offsets(R) or [1]
    if schedule not in ("dissemination", "ring", "random", "butterfly"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if schedule == "random" and key is None:
        raise ValueError("random schedule requires a key")
    if schedule == "butterfly" and R & (R - 1):
        raise ValueError(
            f"butterfly schedule needs a power-of-two replica count "
            f"(R={R})")
    if drop_rate > 0.0 and key is None:
        raise ValueError("drop_rate requires a key")
    offsets_arr = jnp.asarray(offsets, jnp.uint32)
    advance_prog = _advance_program(bool(delta), schedule, delta_semantics,
                                    drop_rate > 0.0)
    # key/drop_rate ride as DATA so one compiled program serves every
    # (positive rate, seed) run of a measurement sweep; no-drop runs
    # share a second, mask-free program (a dummy key placates the
    # signature — its stream is never drawn there)
    key_arr = key if key is not None else jax.random.key(0)
    rate_arr = jnp.float32(drop_rate)

    def advance(s, start: int, n: int):
        """n rounds + the fused digest: (state, converged) for ONE
        device->host sync (the bool fetch)."""
        s, c = advance_prog(s, key_arr, offsets_arr, rate_arr,
                            jnp.uint32(start), n)
        return s, bool(c)

    if bool(converged_jit(state.present, state.vv)):
        return 0, state
    rnd = 0
    while rnd < max_rounds:
        k = min(max(1, check_every), max_rounds - rnd)
        chunk_start = state
        state, chunk_conv = advance(state, rnd, k)
        if chunk_conv:
            # invariants: NOT converged after lo rounds, converged after
            # hi; each probe resumes from the last non-converged prefix
            # (lo_state), so the whole bisection replays O(k) rounds
            # total, not O(k log k)
            lo, hi = 0, k
            lo_state, hi_state = chunk_start, state
            while lo + 1 < hi:
                mid = (lo + hi) // 2
                s_mid, mid_conv = advance(lo_state, rnd + lo, mid - lo)
                if mid_conv:
                    hi, hi_state = mid, s_mid
                else:
                    lo, lo_state = mid, s_mid
            return rnd + hi, hi_state
        rnd += k
    raise RuntimeError(
        f"no convergence within {max_rounds} rounds "
        f"(schedule={schedule!r}, drop_rate={drop_rate}) — refusing to "
        "report an exhausted budget as a measured rounds-to-convergence")


# ---------------------------------------------------------------------------
# Explicit shard_map ring (collectives pinned to ICI neighbors)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _ring_step_compiled(mesh: Mesh, state_cls, kernel: str):
    """Cached jitted shard_map ring step per (mesh, state type, kernel) —
    a fresh jit per call would recompile the program every round.

    kernel="pallas" runs the fused multi-row merge kernel PER SHARD: the
    partner block arrives by ppermute, so each device invokes
    pallas_merge_pairwise_rows on its local rows — this is how mesh
    programs get the fused path (a bare pallas_call has no GSPMD
    partitioning rule, but inside shard_map the kernel only ever sees
    the local block)."""
    n = mesh.shape[REPLICA_AXIS]
    pairs = [(i, (i + 1) % n) for i in range(n)]
    specs = partition_specs(state_cls)

    def step(local):
        recv = jax.tree.map(
            lambda x: jax.lax.ppermute(x, REPLICA_AXIS, pairs), local)
        if kernel == "pallas":
            from go_crdt_playground_tpu.ops.pallas_merge import (
                pallas_merge_pairwise_rows)

            return pallas_merge_pairwise_rows(local, recv)
        merged, _ = merge_pairwise(local, recv)
        return merged

    # pallas_call's out_shape carries no varying-manual-axes annotation,
    # so the vma consistency check can't see through it — disable it for
    # the fused path (the bitwise-equality test vs the checked XLA path
    # is the stronger guarantee anyway).
    return jax.jit(
        _shard_map(step, mesh=mesh, in_specs=(specs,), out_specs=specs,
                      check_vma=(kernel != "pallas"))
    )


@functools.lru_cache(maxsize=None)
def _ep_ring_step_compiled(mesh: Mesh, state_cls):
    """Cached jitted EP ring step: vv's actor axis lives sharded over the
    mesh element dim (SURVEY §2.3 EP row — per-actor ownership of VV
    slots, awset.go:91)."""
    n_r = mesh.shape[REPLICA_AXIS]
    n_e = mesh.shape[ELEMENT_AXIS]
    pairs = [(i, (i + 1) % n_r) for i in range(n_r)]
    specs = partition_specs(state_cls, shard_actors=True)

    def step(local):
        # HasDot reads arbitrary actor slots, so the EP gather is one
        # all_gather of the vv shards per round (the expert-parallel
        # pattern: gather the sharded table, compute, re-slice).
        vv_full = jax.lax.all_gather(
            local.vv, ELEMENT_AXIS, axis=1, tiled=True)
        full = local._replace(vv=vv_full)
        recv = jax.tree.map(
            lambda x: jax.lax.ppermute(x, REPLICA_AXIS, pairs), full)
        merged, _ = merge_pairwise(full, recv)
        a_shard = merged.vv.shape[1] // n_e
        idx = jax.lax.axis_index(ELEMENT_AXIS)
        vv_local = jax.lax.dynamic_slice_in_dim(
            merged.vv, idx * a_shard, a_shard, axis=1)
        return merged._replace(vv=vv_local)

    return jax.jit(
        _shard_map(step, mesh=mesh, in_specs=(specs,), out_specs=specs)
    )


def ep_ring_round_shardmap(state: AWSetState, mesh: Mesh) -> AWSetState:
    """One ring round under the EP layout (mesh.partition_specs with
    shard_actors=True): version-vector slots are owned per actor shard,
    all-gathered for the round's HasDot gathers, and the joined vv is
    sliced back to this shard's slots.  Bitwise-identical results to
    ring_round_shardmap — EP is a layout choice, never a semantics choice.

    Wants A large relative to the element-dim shard count; the win is VV
    memory (A can be as big as R in an every-replica-writes world, making
    vv[R, A] the dominant array) spread over the mesh instead of
    replicated per element shard.
    """
    mesh_mod.validate_ep_layout(state, mesh)
    return _ep_ring_step_compiled(mesh, type(state))(state)


def ring_round_shardmap(state: AWSetState, mesh: Mesh,
                        kernel: str = "auto") -> AWSetState:
    """One ring round with the communication pinned explicitly: each
    replica-shard ppermutes its whole block to the next device over the
    ring (ICI neighbor), then every replica merges with the received
    peer — the ring-anti-entropy schedule of SURVEY §5.7b, the set-merge
    analogue of ring attention's neighbor exchange.

    kernel: "auto" runs the fused Pallas merge per shard on TPU meshes
    (the v5e-4 fast path — no 40x XLA HasDot penalty on mesh programs),
    XLA elsewhere; "pallas"/"xla" force a path.  All bitwise-identical
    (pinned by tests/test_gossip.py on the CPU mesh in interpret mode).

    Full-state AWSet only: the merge kernel has no cross-element
    reductions, so an element-sharded block is self-contained.  (The δ
    kernel's strict mode reduces over E — route δ gossip through
    delta_gossip_round under jit instead, where XLA inserts the psum.)
    """
    if kernel == "auto":
        kernel = _auto_kernel(state, single_device=False)
    return _ring_step_compiled(mesh, type(state), kernel)(state)


@functools.lru_cache(maxsize=None)
def _butterfly_step_compiled(mesh: Mesh, state_cls, stage: int,
                             kernel: str):
    """Cached jitted shard_map butterfly stage per (mesh, state type,
    stage, kernel).

    The XOR pairing decomposes cleanly over a power-of-two block layout
    (global row r = d*blk + i):

      * 2^stage <  blk — block-LOCAL: i ^ 2^stage stays inside the
        block, so the stage is a per-shard permuted merge with zero
        communication (the fused multi-row kernel per shard on TPU);
      * 2^stage >= blk — device-pair swap: partner row is the SAME
        intra index on device d ^ (2^stage/blk), so the stage is one
        symmetric ppermute of whole blocks + the pairwise-rows merge.
    """
    n = mesh.shape[REPLICA_AXIS]
    s = 1 << stage
    specs = partition_specs(state_cls)

    def step(local):
        blk = local.vv.shape[0]
        if s < blk:
            local_perm = (jnp.arange(blk, dtype=jnp.uint32)
                          ^ jnp.uint32(s))
            if kernel == "pallas":
                from go_crdt_playground_tpu.ops.pallas_merge import (
                    pallas_gossip_round_rows)

                return pallas_gossip_round_rows(local, local_perm)
            src = jax.tree.map(lambda x: x[local_perm], local)
            merged, _ = merge_pairwise(local, src)
            return merged
        pairs = [(d, d ^ (s // blk)) for d in range(n)]
        recv = jax.tree.map(
            lambda x: jax.lax.ppermute(x, REPLICA_AXIS, pairs), local)
        if kernel == "pallas":
            from go_crdt_playground_tpu.ops.pallas_merge import (
                pallas_merge_pairwise_rows)

            return pallas_merge_pairwise_rows(local, recv)
        merged, _ = merge_pairwise(local, recv)
        return merged

    return jax.jit(
        _shard_map(step, mesh=mesh, in_specs=(specs,), out_specs=specs,
                      check_vma=(kernel != "pallas"))
    )


def butterfly_round_shardmap(state: AWSetState, mesh: Mesh, stage: int,
                             kernel: str = "auto") -> AWSetState:
    """One butterfly stage (partner = r XOR 2^stage, SURVEY §5.7c) with
    the replica axis explicitly sharded — the mesh-native realization of
    butterfly_perm, bitwise-identical to ``gossip_round(state,
    butterfly_perm(R, stage))``.

    Stages below the per-device block size are block-local (zero ICI);
    stages at or above it are one whole-block ppermute between XOR
    device pairs.  Either way the merge runs the fused kernel per shard
    on TPU meshes, so butterfly schedules never pay the multi-device
    XLA HasDot penalty that _auto_kernel warns about.

    Full-state AWSet family only (same restriction as
    ring_round_shardmap: the merge kernel has no cross-element
    reductions, so element-sharded blocks are self-contained).
    """
    R = state.vv.shape[0]
    n = mesh.shape[REPLICA_AXIS]
    if R & (R - 1):
        raise ValueError(f"butterfly needs a power-of-two replica count "
                         f"(R={R})")
    if R % n:
        raise ValueError(f"R={R} not divisible by replica mesh dim {n}")
    blk = R // n
    if blk & (blk - 1):
        raise ValueError(
            f"per-device block {blk} must be a power of two for the XOR "
            "pairing to decompose into block-local and block-swap stages")
    if not 0 <= stage or (1 << stage) >= R:
        raise ValueError(
            f"butterfly stage {stage} out of range for R={R} "
            "(need 1 << stage < R)")
    if kernel == "auto":
        kernel = _auto_kernel(state, single_device=False)
    return _butterfly_step_compiled(mesh, type(state), stage, kernel)(state)


# ---------------------------------------------------------------------------
# Bitpacked δ gossip with an explicitly sharded replica axis
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _packed_block_ring_compiled(mesh: Mesh, shift: int, kernel_offset: int,
                                state_cls=None):
    from jax.sharding import PartitionSpec as P

    from go_crdt_playground_tpu.models.packed import (
        DotPackedAWSetDeltaState, DotPackedAWSetState,
        PackedAWSetDeltaState, PackedAWSetState)
    from go_crdt_playground_tpu.ops.pallas_delta import (
        pallas_delta_ring_round_dotpacked, pallas_delta_ring_round_packed)
    from go_crdt_playground_tpu.ops.pallas_merge import (
        pallas_ring_round_rows_dotpacked, pallas_ring_round_rows_packed)

    if state_cls is None:
        state_cls = PackedAWSetDeltaState
    round_fn = {
        PackedAWSetDeltaState: pallas_delta_ring_round_packed,
        DotPackedAWSetDeltaState: pallas_delta_ring_round_dotpacked,
        PackedAWSetState: pallas_ring_round_rows_packed,
        DotPackedAWSetState: pallas_ring_round_rows_dotpacked,
    }[state_cls]
    n = mesh.shape[REPLICA_AXIS]
    # device d receives the block of device (d + shift) mod n
    pairs = [((i + shift) % n, i) for i in range(n)]
    row = P(REPLICA_AXIS, None)
    # every array is row-sharded 2-D except the 1-D actor column
    specs = state_cls(**{f: (P(REPLICA_AXIS) if f == "actor" else row)
                         for f in state_cls._fields})

    def step(local):
        if shift:
            recv = jax.tree.map(
                lambda x: jax.lax.ppermute(x, REPLICA_AXIS, pairs), local)
        else:
            recv = local
        stacked = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], axis=0), local, recv)
        out = round_fn(stacked, kernel_offset)
        return jax.tree.map(lambda x: x[: x.shape[0] // 2], out)

    # check_vma off for the same reason as _ring_step_compiled's pallas
    # path: pallas_call's out_shape carries no varying-manual-axes
    # annotation (the bitwise pin vs the global-jit packed round in
    # tests/test_gossip.py is the stronger guarantee).
    return jax.jit(
        _shard_map(step, mesh=mesh, in_specs=(specs,), out_specs=specs,
                      check_vma=False)
    )


def packed_block_ring_round_shardmap(state, mesh: Mesh, offset):
    """One packed-layout gossip round with the replica axis explicitly
    sharded.  Accepts any of the four packed layouts (models/packed.py:
    bitpacked or dot-word, full-state or δ) and dispatches the matching
    single-device ring kernel per shard; membership crosses ICI as
    uint32[blk, E/32] words — 8x less wire traffic for the membership
    sections than the bool layouts — and the dot-word forms halve the
    dot-section traffic on top.

    Pairing, with ``blk = R / n_devices`` rows per device:

    * ``offset % blk == 0`` — block-aligned ring: row r absorbs
      r + offset globally, i.e. device d's rows absorb device
      (d + offset/blk)'s rows pairwise.  Bitwise-identical to
      ``pallas_delta_ring_round_packed(state, offset)`` on one device.
    * ``offset < blk`` — intra-device ring: row i absorbs row
      (i + offset) mod blk WITHIN its device block, no communication.
      This wraps per block rather than globally, so it is a different
      (equally convergent, v2-semantics) anti-entropy pairing than the
      global ring at that offset — dissemination schedules compose
      intra rounds (offsets < blk) with block-aligned rounds (offset
      multiples of blk) to reach all-pairs in ceil(log2 R) rounds.

    Both forms run the packed ring kernel on the stacked [local; recv]
    (or [local; local]) 2*blk block at an in-kernel offset that lands
    every kept row on its partner; rows >= blk are partner-absorbing
    scratch and are discarded (2x compute for zero gather/copy of the
    partner block — the shard-side analogue of the in-place ring reads).
    Requires the element mesh dim unsharded and blk a multiple of 64
    (ring_supported on the stacked block).
    """
    if mesh.shape[ELEMENT_AXIS] != 1:
        raise ValueError(
            "packed block ring needs the element axis unsharded (mesh "
            f"element dim {mesh.shape[ELEMENT_AXIS]}): packed words are "
            "not element-shardable")
    n = mesh.shape[REPLICA_AXIS]
    R = state.vv.shape[0]
    if R % n:
        raise ValueError(f"R={R} not divisible by replica mesh dim {n}")
    blk = R // n
    from go_crdt_playground_tpu.ops.pallas_merge import ring_supported
    if not ring_supported(2 * blk):
        # the kernel runs on the stacked [local; recv] 2*blk block, so
        # the per-device block itself must satisfy the ring kernel's
        # whole-aligned-blocks layout; failing here beats a
        # kernel-internal layout assert (or a silently odd tiling)
        raise ValueError(
            f"per-device block {blk} (R={R} / {n} devices) stacks to a "
            f"{2 * blk}-row kernel block, which the packed ring kernel "
            "cannot tile (needs a multiple of 64 rows, at least 128)")
    offset = int(offset) % R
    if offset == 0:
        raise ValueError("offset 0 is a no-op round")
    if offset % blk == 0:
        shift, kernel_offset = offset // blk, blk
    elif offset < blk:
        shift, kernel_offset = 0, blk + offset
    else:
        raise ValueError(
            f"offset {offset} is neither intra-block (< {blk}) nor "
            f"block-aligned (multiple of {blk})")
    return _packed_block_ring_compiled(mesh, shift, kernel_offset,
                                       type(state))(state)
