"""Global reductions over the replica axis: VV join, convergence detection,
GC frontier.

Reference analogue: none — the reference's "network" is a direct method
call (awset_test.go:16-17) and convergence is eyeballed via printstate.
Here convergence detection is a first-class collective: a commutative
membership hash per replica, reduced with min/max — two scalars per replica
round instead of shipping states around (SURVEY §5.5's
rounds-to-convergence metric needs this to be cheap).

All reductions are plain jnp ops over the (possibly sharded) replica axis;
under pjit XLA lowers them to psum/pmax-style collectives over ICI.
"""

from __future__ import annotations

import jax.numpy as jnp

# Fibonacci hashing multiplier (2^32 / golden ratio, odd) — good avalanche
# for sequential element ids.  Kept as a plain Python int: a module-scope
# jnp.uint32(...) would create a device array at import time and initialize
# whatever backend is the ambient default — which must never happen before
# the caller has picked a platform (the round-1 dryrun hang).
_MIX = 0x9E3779B1


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    """xorshift-multiply mix of uint32 lanes."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(_MIX)
    x = (x ^ (x >> 13)) * jnp.uint32(0x85EBCA77)
    return x ^ (x >> 16)


def membership_hash(present: jnp.ndarray) -> jnp.ndarray:
    """Commutative per-replica membership digest: sum of mixed element ids
    over present lanes.  present: bool[R, E] -> uint32[R].

    Sum (mod 2^32) keeps it order-independent and shard-composable: the
    hash of a row sharded over E is the psum of shard-local hashes."""
    E = present.shape[-1]
    lane = _mix32(jnp.arange(1, E + 1, dtype=jnp.uint32))
    return jnp.sum(jnp.where(present, lane, 0).astype(jnp.uint32), axis=-1,
                   dtype=jnp.uint32)


def _vv_hash(vv: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(_mix32(vv) * _mix32(jnp.arange(
        1, vv.shape[-1] + 1, dtype=jnp.uint32)), axis=-1, dtype=jnp.uint32)


def state_digest(present: jnp.ndarray, vv: jnp.ndarray) -> jnp.ndarray:
    """(membership, VV) digest per replica — the convergence criterion of
    the reference semantics (per-entry dots may legitimately diverge,
    SURVEY §3.2, so they are NOT part of the digest)."""
    return membership_hash(present) ^ _vv_hash(vv)


def all_equal(digest: jnp.ndarray) -> jnp.ndarray:
    """True iff every replica's digest agrees (min == max reduction)."""
    return jnp.min(digest) == jnp.max(digest)


def converged(present: jnp.ndarray, vv: jnp.ndarray) -> jnp.ndarray:
    """Scalar bool: has the whole batch converged on (membership, VV)?"""
    return all_equal(state_digest(present, vv))


def converged_packed(present_bits: jnp.ndarray,
                     vv: jnp.ndarray) -> jnp.ndarray:
    """``converged`` on the bitpacked membership layout
    (models/packed.py): equal uint32 words <=> equal membership (padding
    tail bits are zero by construction), so the digest hashes word lanes
    directly — no unpack.  present_bits: uint32[R, E/32]."""
    w = present_bits.shape[-1]
    lane = _mix32(jnp.arange(1, w + 1, dtype=jnp.uint32))
    mh = jnp.sum(_mix32(present_bits) * lane, axis=-1, dtype=jnp.uint32)
    return all_equal(mh ^ _vv_hash(vv))


def global_vv_join(vv: jnp.ndarray) -> jnp.ndarray:
    """The all-replica VV join: elementwise max over the replica axis
    (VersionVector.Merge lifted to the whole fleet, crdt-misc.go:43-55).
    vv: uint32[R, A] -> uint32[A]."""
    return jnp.max(vv, axis=0)
