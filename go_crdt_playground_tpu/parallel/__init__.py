"""SPMD layer: meshes, gossip schedules, collectives, convergence detection."""
