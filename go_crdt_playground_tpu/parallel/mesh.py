"""Device mesh + sharding layout for packed CRDT states.

The scaling axes (SURVEY §2.3) are replicas ``R`` (data-parallel: each Go
``AWSet`` struct was one replica) and the element universe ``E``
(tensor-parallel: the merge is elementwise, so sharding E is clean).  The
actor axis ``A`` is replicated by default — it is small and every HasDot
gather reads it — but can be sharded over the mesh element dim for the
EP analogue (SURVEY §2.3: per-actor ownership of VV slots, awset.go:91;
``shard_actors=True``), in which case HasDot becomes a gather across the
actor shard, realized as one ``all_gather`` per merge round
(gossip.ep_ring_round_shardmap).

Default layout:
  vv[R, A], processed[R, A]  -> P(REPLICA_AXIS, None)
  present/dots[R, E]         -> P(REPLICA_AXIS, ELEMENT_AXIS)
  actor[R]                   -> P(REPLICA_AXIS)
EP layout (shard_actors=True) differs only in
  vv[R, A], processed[R, A]  -> P(REPLICA_AXIS, ELEMENT_AXIS)

Gossip permutations move whole replica rows between replica shards
(XLA lowers them to collective-permute/all-to-all over ICI); element shards
never need to communicate during a merge — the kernel is elementwise over E
with only the (replicated) vv read across lanes.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

REPLICA_AXIS = "replica"
ELEMENT_AXIS = "element"


def take_devices(num_devices: Optional[int] = None) -> list:
    """The first ``num_devices`` devices in jax's stable enumeration
    (default: all), with the shared bounds check — every serve-tier
    mesh builder (the 1-D ``"batch"`` mesh and the 2-D ``("dp", "mp")``
    mesh) slices its device set through here so restarts of the same
    topology place shards identically and the CPU-testing hint lives
    in ONE error message."""
    devices = jax.devices()
    n = len(devices) if num_devices is None else int(num_devices)
    if not 1 <= n <= len(devices):
        raise ValueError(
            f"mesh wants {n} devices; {len(devices)} visible "
            f"(CPU runs force more via "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    return list(devices[:n])


def make_mesh(mesh_shape: Optional[Tuple[int, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (replica_shards, element_shards) mesh.  Default: all devices
    on the replica axis (gossip bandwidth rides ICI; the element axis only
    matters once E outgrows a single chip's HBM)."""
    if devices is None:
        devices = jax.devices()
    if mesh_shape is None:
        mesh_shape = (len(devices), 1)
    r, e = mesh_shape
    if r * e != len(devices):
        raise ValueError(f"mesh_shape {mesh_shape} != #devices {len(devices)}")
    arr = np.asarray(devices).reshape(r, e)
    return Mesh(arr, (REPLICA_AXIS, ELEMENT_AXIS))


# Actor-axis fields stay replicated across element shards (default
# layout); everything else element-shaped is sharded on both axes.  The
# field tables live in models/layout.py, shared with the host-side
# repack helpers.
from go_crdt_playground_tpu.models.layout import (  # noqa: E402
    ACTOR_AXIS_FIELDS as _ACTOR_AXIS_FIELDS,
    REPLICA_ONLY_FIELDS as _REPLICA_ONLY_FIELDS,
)


def partition_specs(state_cls, shard_actors: bool = False):
    """PartitionSpec pytree for an AWSetState / AWSetDeltaState class —
    the single source of truth for the layout (state_sharding and the
    shard_map rounds both build on it).  ``shard_actors`` switches the
    actor-axis fields to the EP layout (module docstring)."""
    actor_spec = (P(REPLICA_AXIS, ELEMENT_AXIS) if shard_actors
                  else P(REPLICA_AXIS, None))
    return state_cls(**{
        name: (
            P(REPLICA_AXIS) if name in _REPLICA_ONLY_FIELDS
            else actor_spec if name in _ACTOR_AXIS_FIELDS
            else P(REPLICA_AXIS, ELEMENT_AXIS)
        )
        for name in state_cls._fields
    })


def validate_ep_layout(state, mesh: Mesh) -> None:
    """EP layout precondition: the actor axis must divide evenly over the
    mesh element dim (shard_map and NamedSharding both require it)."""
    if state.vv.shape[-1] % mesh.shape[ELEMENT_AXIS]:
        raise ValueError(
            f"EP layout needs A={state.vv.shape[-1]} divisible by the mesh "
            f"element dim {mesh.shape[ELEMENT_AXIS]}")


def state_sharding(state, mesh: Mesh, shard_actors: bool = False):
    """NamedShardings for an AWSetState / AWSetDeltaState pytree."""
    if shard_actors:
        validate_ep_layout(state, mesh)
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        partition_specs(type(state), shard_actors),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_state(state, mesh: Mesh, shard_actors: bool = False):
    """Place a packed state onto the mesh with the canonical layout."""
    return jax.tree.map(jax.device_put, state,
                        state_sharding(state, mesh, shard_actors))
