"""Native (C++) host-runtime bindings.

``codec.cpp`` implements the element-dictionary interning and the delta
wire codec behind a plain C ABI; this module builds it with g++ on
first use (cached next to the source, keyed by a source hash) and binds
it via ctypes.  Everything degrades gracefully: if no toolchain is
available, ``available()`` is False and callers use the pure-Python
paths (utils/codec.py, utils/wire.py) — same observable behavior,
tested for parity in tests/test_native_codec.py.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Iterable, List, Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "codec.cpp")
_LOCK = threading.Lock()
_LIB = None
_LIB_ERR: Optional[str] = None


def _lib_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_HERE, f"_codec-{digest}.so")


def _build(path: str) -> None:
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", path, _SRC]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, u8p, u32p, i64p = (ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
                            ctypes.POINTER(ctypes.c_uint32),
                            ctypes.POINTER(ctypes.c_int64))
    void_p, char_p = ctypes.c_void_p, ctypes.c_char_p
    sigs = {
        "ed_new": ([i64], void_p),
        "ed_free": ([void_p], None),
        "ed_len": ([void_p], i64),
        "ed_capacity": ([void_p], i64),
        "ed_set_capacity": ([void_p, i64], None),
        "ed_lookup": ([void_p, char_p, i64], i64),
        "ed_encode_batch": ([void_p, char_p, i64p, i64, i64p], i64),
        "ed_decode_size": ([void_p, i64p, i64], i64),
        "ed_decode_batch": ([void_p, i64p, i64, char_p, i64, i64p], i64),
        "delta_encode_bound": ([i64], i64),
        "delta_encode": ([u8p, u32p, u32p, i64, u8p, i64], i64),
        "delta_decode": ([u8p, i64, i64, u8p, u32p, u32p], i64),
        "vv_encode_bound": ([i64], i64),
        "vv_encode": ([u32p, i64, u8p, i64], i64),
        "vv_decode": ([u8p, i64, i64, u32p], i64),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native library; None on failure."""
    global _LIB, _LIB_ERR
    with _LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        try:
            path = _lib_path()  # reads codec.cpp (may be absent/stripped)
            if not os.path.exists(path):
                _build(path)
            _LIB = _bind(ctypes.CDLL(path))
        except (OSError, subprocess.CalledProcessError,
                AttributeError) as e:
            _LIB_ERR = str(e)
        return _LIB


def available() -> bool:
    return load() is not None


def build_error() -> Optional[str]:
    load()
    return _LIB_ERR


def _flat_utf8(values: Sequence[str]):
    """Concatenated utf-8 buffer + int64 offsets[n+1] for a string batch."""
    encoded = [v.encode("utf-8") for v in values]
    offsets = np.zeros(len(encoded) + 1, np.int64)
    np.cumsum([len(b) for b in encoded], out=offsets[1:])
    return b"".join(encoded), offsets


class NativeElementDict:
    """Drop-in for utils.codec.ElementDict backed by the C++ interner.

    Same API and the same observable behavior (first-sight id
    assignment, OverflowError at capacity, state_dict roundtrip); the
    batch paths accept flat utf-8 buffers, which is where the native
    implementation earns its keep (wire/disk ingestion).
    """

    def __init__(self, capacity: int = 16,
                 values: Optional[Iterable[str]] = None):
        lib = load()
        if lib is None:
            raise RuntimeError(
                f"native codec unavailable: {build_error()}")
        self._lib = lib
        self._h = lib.ed_new(capacity)
        if values:
            self.encode_many(list(values))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ed_free(h)
            self._h = None

    def __len__(self) -> int:
        return int(self._lib.ed_len(self._h))

    @property
    def capacity(self) -> int:
        return int(self._lib.ed_capacity(self._h))

    def __contains__(self, value: str) -> bool:
        raw = value.encode("utf-8")
        return int(self._lib.ed_lookup(self._h, raw, len(raw))) >= 0

    def encode(self, value: str) -> int:
        return int(self.encode_many([value])[0])

    def encode_many(self, values: Sequence[str]) -> List[int]:
        buf, offsets = _flat_utf8(values)
        ids = self.encode_flat(buf, offsets)
        if ids is None:
            raise OverflowError(
                f"element dictionary full (capacity {self.capacity}); "
                "grow() and re-pack")
        return [int(i) for i in ids]

    def encode_flat(self, buf: bytes,
                    offsets: np.ndarray) -> Optional[np.ndarray]:
        """Batch-encode a flat utf-8 buffer; returns ids or None on
        capacity overflow."""
        n = len(offsets) - 1
        out = np.empty(n, np.int64)
        rc = self._lib.ed_encode_batch(
            self._h, buf,
            np.ascontiguousarray(offsets).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64)),
            n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rc < 0:
            return None
        return out

    def decode(self, eid: int) -> str:
        return self.decode_many([eid])[0]

    def decode_many(self, ids: Sequence[int]) -> List[str]:
        arr = np.ascontiguousarray(ids, dtype=np.int64)
        idp = arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        size = self._lib.ed_decode_size(self._h, idp, len(arr))
        if size < 0:
            raise IndexError("unknown element id in batch")
        out = ctypes.create_string_buffer(max(int(size), 1))
        offsets = np.empty(len(arr) + 1, np.int64)
        rc = self._lib.ed_decode_batch(
            self._h, idp, len(arr), out, size,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rc < 0:
            raise IndexError("unknown element id in batch")
        raw = out.raw[:size]
        return [raw[offsets[i]:offsets[i + 1]].decode("utf-8")
                for i in range(len(arr))]

    def grow(self, factor: int = 2) -> None:
        self._lib.ed_set_capacity(self._h, self.capacity * factor)

    def state_dict(self) -> dict:
        return {"capacity": self.capacity,
                "values": self.decode_many(list(range(len(self))))}

    @classmethod
    def from_state_dict(cls, d: dict) -> "NativeElementDict":
        return cls(capacity=d["capacity"], values=d["values"])


def make_element_dict(capacity: int = 16,
                      values: Optional[Iterable[str]] = None,
                      prefer_native: bool = True):
    """Factory: native interner when the toolchain allows, else the
    pure-Python ElementDict — identical observable behavior."""
    if prefer_native and available():
        return NativeElementDict(capacity=capacity, values=values)
    from go_crdt_playground_tpu.utils.codec import ElementDict

    return ElementDict(capacity=capacity, values=values)
