// Native host runtime: element-dictionary interning + delta wire codec.
//
// The reference keeps its element universe as Go map keys (awset.go:58)
// and ships delta payloads as in-memory maps computed against the
// receiver's version vector (awset-delta_test.go:79-105).  In the TPU
// framework the host-side runtime around the XLA compute path owns two
// byte-level jobs:
//
//   1. interning element strings to dense ids 0..E-1 (SURVEY §7.1) when
//      packing/unpacking states, where inputs arrive as flat utf-8
//      buffers (wire/disk), and
//   2. serializing masked delta payloads into a compact wire format
//      (bitmask + varint dot pairs) for DCN shipping and persistence —
//      the dense-mask-to-sparse-bytes step XLA cannot do.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the
// image); go_crdt_playground_tpu/native/__init__.py builds this file
// with g++ on first use and falls back to the pure-Python codec when a
// toolchain is unavailable.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------
// Element dictionary
// ---------------------------------------------------------------------

struct ElementDict {
  std::unordered_map<std::string, int64_t> to_id;
  std::vector<std::string> to_str;
  int64_t capacity;
};

void* ed_new(int64_t capacity) {
  auto* d = new ElementDict();
  d->capacity = capacity;
  return d;
}

void ed_free(void* h) { delete static_cast<ElementDict*>(h); }

int64_t ed_len(void* h) {
  return static_cast<int64_t>(static_cast<ElementDict*>(h)->to_str.size());
}

int64_t ed_capacity(void* h) {
  return static_cast<ElementDict*>(h)->capacity;
}

void ed_set_capacity(void* h, int64_t capacity) {
  static_cast<ElementDict*>(h)->capacity = capacity;
}

// Non-mutating lookup: id of the string, or -1 if not interned.
int64_t ed_lookup(void* h, const char* buf, int64_t len) {
  auto* d = static_cast<ElementDict*>(h);
  auto it = d->to_id.find(std::string(buf, static_cast<size_t>(len)));
  return it == d->to_id.end() ? -1 : it->second;
}

// Encode n strings given as a concatenated utf-8 buffer with
// offsets[n+1] (string i = buf[offsets[i] .. offsets[i+1])).
// Fills out_ids[n].  Returns n on success, or -(i+1) if string i found
// the dictionary full (ids before i are assigned; i.. untouched) — the
// grow-and-repack overflow policy surfaces exactly like the Python
// codec's OverflowError.
int64_t ed_encode_batch(void* h, const char* buf, const int64_t* offsets,
                        int64_t n, int64_t* out_ids) {
  auto* d = static_cast<ElementDict*>(h);
  for (int64_t i = 0; i < n; ++i) {
    std::string s(buf + offsets[i],
                  static_cast<size_t>(offsets[i + 1] - offsets[i]));
    auto it = d->to_id.find(s);
    if (it != d->to_id.end()) {
      out_ids[i] = it->second;
      continue;
    }
    if (static_cast<int64_t>(d->to_str.size()) >= d->capacity) {
      return -(i + 1);
    }
    int64_t id = static_cast<int64_t>(d->to_str.size());
    d->to_id.emplace(std::move(s), id);
    d->to_str.push_back(
        std::string(buf + offsets[i],
                    static_cast<size_t>(offsets[i + 1] - offsets[i])));
    out_ids[i] = id;
  }
  return n;
}

// Total bytes of the concatenated decode of ids[n]; -1 on unknown id.
int64_t ed_decode_size(void* h, const int64_t* ids, int64_t n) {
  auto* d = static_cast<ElementDict*>(h);
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (ids[i] < 0 || ids[i] >= static_cast<int64_t>(d->to_str.size()))
      return -1;
    total += static_cast<int64_t>(d->to_str[ids[i]].size());
  }
  return total;
}

// Decode ids[n] into out (concatenated) + out_offsets[n+1].  Returns
// bytes written, or -1 if out_cap is too small / id unknown.
int64_t ed_decode_batch(void* h, const int64_t* ids, int64_t n, char* out,
                        int64_t out_cap, int64_t* out_offsets) {
  auto* d = static_cast<ElementDict*>(h);
  int64_t pos = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (ids[i] < 0 || ids[i] >= static_cast<int64_t>(d->to_str.size()))
      return -1;
    const std::string& s = d->to_str[ids[i]];
    if (pos + static_cast<int64_t>(s.size()) > out_cap) return -1;
    out_offsets[i] = pos;
    std::memcpy(out + pos, s.data(), s.size());
    pos += static_cast<int64_t>(s.size());
  }
  out_offsets[n] = pos;
  return pos;
}

// ---------------------------------------------------------------------
// Delta wire codec: bitmask + varint dot pairs
//
// Row format (one replica's changed or deleted payload over universe E):
//   varint E, varint n_set,
//   ceil(E/8) bitmask bytes (LSB-first within each byte),
//   then per set lane in ascending id order: varint dot_actor,
//   varint dot_counter.
// ---------------------------------------------------------------------

static inline int64_t put_varint(uint8_t* out, int64_t cap, int64_t pos,
                                 uint64_t v) {
  while (true) {
    if (pos >= cap) return -1;
    if (v < 0x80) {
      out[pos++] = static_cast<uint8_t>(v);
      return pos;
    }
    out[pos++] = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
}

static inline int64_t get_varint(const uint8_t* in, int64_t size,
                                 int64_t pos, uint64_t* v) {
  uint64_t out = 0;
  int shift = 0;
  while (true) {
    if (pos >= size || shift > 63) return -1;
    uint8_t b = in[pos++];
    out |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  *v = out;
  return pos;
}

// Worst case: header + bitmask + 2 x 5-byte varints per lane.
int64_t delta_encode_bound(int64_t e) { return 20 + (e + 7) / 8 + 10 * e; }

// mask: uint8[E] (0/1), da/dc: uint32[E].  Returns bytes written or -1.
int64_t delta_encode(const uint8_t* mask, const uint32_t* da,
                     const uint32_t* dc, int64_t e, uint8_t* out,
                     int64_t cap) {
  int64_t n_set = 0;
  for (int64_t i = 0; i < e; ++i) n_set += mask[i] != 0;
  int64_t pos = put_varint(out, cap, 0, static_cast<uint64_t>(e));
  if (pos < 0) return -1;
  pos = put_varint(out, cap, pos, static_cast<uint64_t>(n_set));
  if (pos < 0) return -1;
  int64_t nbytes = (e + 7) / 8;
  if (pos + nbytes > cap) return -1;
  std::memset(out + pos, 0, static_cast<size_t>(nbytes));
  for (int64_t i = 0; i < e; ++i)
    if (mask[i]) out[pos + (i >> 3)] |= static_cast<uint8_t>(1u << (i & 7));
  pos += nbytes;
  for (int64_t i = 0; i < e; ++i) {
    if (!mask[i]) continue;
    pos = put_varint(out, cap, pos, da[i]);
    if (pos < 0) return -1;
    pos = put_varint(out, cap, pos, dc[i]);
    if (pos < 0) return -1;
  }
  return pos;
}

// Inverse.  mask/da/dc are caller buffers of length E (E must match the
// encoded universe).  Unset lanes are zeroed.  Returns bytes consumed
// or -1 on malformed input / size mismatch.
int64_t delta_decode(const uint8_t* in, int64_t size, int64_t e,
                     uint8_t* mask, uint32_t* da, uint32_t* dc) {
  uint64_t enc_e = 0, n_set = 0;
  int64_t pos = get_varint(in, size, 0, &enc_e);
  if (pos < 0 || static_cast<int64_t>(enc_e) != e) return -1;
  pos = get_varint(in, size, pos, &n_set);
  if (pos < 0 || n_set > enc_e) return -1;
  int64_t nbytes = (e + 7) / 8;
  if (pos + nbytes > size) return -1;
  const uint8_t* bits = in + pos;
  pos += nbytes;
  int64_t seen = 0;
  for (int64_t i = 0; i < e; ++i) {
    bool set = (bits[i >> 3] >> (i & 7)) & 1;
    mask[i] = set ? 1 : 0;
    if (set) {
      uint64_t a = 0, c = 0;
      pos = get_varint(in, size, pos, &a);
      if (pos < 0 || a > 0xFFFFFFFFull) return -1;
      pos = get_varint(in, size, pos, &c);
      if (pos < 0 || c > 0xFFFFFFFFull) return -1;
      da[i] = static_cast<uint32_t>(a);
      dc[i] = static_cast<uint32_t>(c);
      ++seen;
    } else {
      da[i] = 0;
      dc[i] = 0;
    }
  }
  if (seen != static_cast<int64_t>(n_set)) return -1;
  return pos;
}

// Version-vector row: varint A then A varint counters.
int64_t vv_encode_bound(int64_t a) { return 10 + 5 * a; }

int64_t vv_encode(const uint32_t* vv, int64_t a, uint8_t* out, int64_t cap) {
  int64_t pos = put_varint(out, cap, 0, static_cast<uint64_t>(a));
  if (pos < 0) return -1;
  for (int64_t i = 0; i < a; ++i) {
    pos = put_varint(out, cap, pos, vv[i]);
    if (pos < 0) return -1;
  }
  return pos;
}

int64_t vv_decode(const uint8_t* in, int64_t size, int64_t a, uint32_t* vv) {
  uint64_t enc_a = 0;
  int64_t pos = get_varint(in, size, 0, &enc_a);
  if (pos < 0 || static_cast<int64_t>(enc_a) != a) return -1;
  for (int64_t i = 0; i < a; ++i) {
    uint64_t v = 0;
    pos = get_varint(in, size, pos, &v);
    if (pos < 0 || v > 0xFFFFFFFFull) return -1;
    vv[i] = static_cast<uint32_t>(v);
  }
  return pos;
}

}  // extern "C"
