"""M001 metrics contract (DESIGN.md §15): the names the soaks
adjudicate and the docs promise must be names the code actually emits.

Three name sets, all derived mechanically:

* **emitted** — every literal (or f-string pattern) passed to an
  ``obs.Recorder`` emission call in the package: ``count`` /
  ``count_many`` (dict-literal keys) / ``observe`` / ``set_gauge``,
  plus the ``_count`` wrapper convention every subsystem uses.
  F-string segments become ``*`` wildcards (``sync.failures.{cls}`` →
  ``sync.failures.*``), so classified counters stay checkable.
* **referenced** — dotted metric-shaped string literals in
  ``tools/*_soak.py``, the adjudication layer.  A referenced name no
  emission site can produce is an ERROR: the soak would adjudicate a
  counter that is always zero/absent — the "phantom metric" failure
  mode where a rename quietly turns an assertion into a no-op.
* **documented** — backtick-quoted metric-shaped names in DESIGN.md
  (``<placeholder>`` segments become wildcards).  An emitted name no
  documentation covers is a WARNING-severity finding: dashboards are
  written from the docs, so an undocumented counter is invisible
  operational surface.  (The gate fails on errors only, but the
  committed report must be clean — document new names in the
  DESIGN.md catalog as they land.)

Entry points take explicit file lists so tests can plant a phantom
reference or an undocumented emission.
"""

from __future__ import annotations

import ast
import fnmatch
import glob
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (METRICS_CONTRACT,
                                                    SEVERITY_ERROR,
                                                    SEVERITY_WARNING,
                                                    Finding)

# a metric name: dotted lowercase segments (underscores ok); segments
# may be (or contain) ``*`` wildcard stubs from f-string holes — a
# leading hole (the ConnHost counter-prefix convention) included
_NAME_RE = re.compile(r"^([a-z][a-z0-9_]*|\*)(\.[a-z0-9_*:]+)+\*?$")
# path-ish literals that match the dotted shape but are not metrics
_NOT_METRIC_RE = re.compile(
    r"\.(json|jsonl|py|sh|log|md|txt|ckpt|tmp|wal|proto|cpp|go|toml)$|/")

_EMIT_METHODS = {"count", "observe", "set_gauge", "_count"}


def _patterns_of(node: ast.AST) -> List[str]:
    """Every metric-name pattern inside an expression: string literals
    (whole), f-strings (holes become ``*``), and the strings inside
    conditional expressions (``"a.x" if c else "a.y"``).  A plain
    variable yields nothing — the builder-dict convention is handled
    by the function-scoped ``count_many`` sweep below."""
    out: List[str] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append(sub.value)
        elif isinstance(sub, ast.JoinedStr):
            parts = []
            for v in sub.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                else:
                    parts.append("*")
            out.append("".join(parts))
    # JoinedStr's inner Constants were also walked; drop fragments that
    # are substrings of a collected f-string pattern
    joined = [p for p in out if "*" in p]
    return [p for p in out
            if "*" in p or not any(p in j for j in joined)]


def emitted_patterns(paths: Iterable[str],
                     loader: Optional[SourceLoader] = None
                     ) -> Dict[str, List[str]]:
    loader = ensure_loader(loader)
    """pattern -> [path:line, ...] of every Recorder emission site.

    Two collection scopes: the direct argument of an emission call,
    and — for ``count_many``, whose dict is conventionally built up a
    few lines above the call — every metric-shaped string in a
    function that calls ``count_many`` (the ``_record`` builder
    shape: nothing but metric names lives in those functions)."""
    out: Dict[str, List[str]] = {}

    def record(pats: List[str], path: str, lineno: int) -> None:
        for p in pats:
            if _NAME_RE.match(p) and not _NOT_METRIC_RE.search(p):
                out.setdefault(p, []).append(f"{path}:{lineno}")

    for path in paths:
        tree = loader.load(path).tree
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                calls_count_many = any(
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "count_many"
                    for sub in ast.walk(node))
                if calls_count_many:
                    record(_patterns_of(node), path, node.lineno)
                continue
            if not isinstance(node, ast.Call):
                continue
            fname = (node.func.attr
                     if isinstance(node.func, ast.Attribute)
                     else node.func.id
                     if isinstance(node.func, ast.Name) else None)
            if fname in _EMIT_METHODS and node.args:
                record(_patterns_of(node.args[0]), path, node.lineno)
            elif fname == "count_many" and node.args:
                record(_patterns_of(node.args[0]), path, node.lineno)
    return out


def referenced_names(paths: Iterable[str],
                     loader: Optional[SourceLoader] = None
                     ) -> Dict[str, List[str]]:
    loader = ensure_loader(loader)
    """name -> [path:line, ...] of every metric-shaped string literal
    in the adjudication tools."""
    out: Dict[str, List[str]] = {}
    for path in paths:
        tree = loader.load(path).tree
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _NAME_RE.match(node.value)
                    and not _NOT_METRIC_RE.search(node.value)):
                out.setdefault(node.value, []).append(
                    f"{path}:{node.lineno}")
    return out


_BACKTICK_RE = re.compile(r"`([^`\s]+)`")


def documented_patterns(doc_paths: Iterable[str]) -> Set[str]:
    """Backtick-quoted metric-shaped names in the docs;
    ``<placeholder>`` segments normalize to ``*``."""
    out: Set[str] = set()
    for path in doc_paths:
        with open(path) as f:
            text = f.read()
        for m in _BACKTICK_RE.finditer(text):
            name = re.sub(r"<[^>]*>", "*", m.group(1))
            if _NAME_RE.match(name) and not _NOT_METRIC_RE.search(name):
                out.add(name)
    return out


def _covers(pattern: str, name: str) -> bool:
    """Does an emitted/documented pattern cover a (possibly wildcarded)
    name?  Exact match, glob match of a literal name, or equal
    normalized wildcard shapes (``sync.failures.*`` covers the
    f-string pattern ``sync.failures.*``).  A literal reference that
    is itself a PREFIX probe (``breaker.to_``) matches via glob."""
    if pattern == name:
        return True
    if fnmatch.fnmatchcase(name, pattern):
        return True
    if "*" in name and fnmatch.fnmatchcase(pattern, name):
        return True
    return False


def check(package_files: Iterable[str], tool_files: Iterable[str],
          doc_files: Iterable[str],
          loader: Optional[SourceLoader] = None
          ) -> Tuple[List[Finding], Dict]:
    emitted = emitted_patterns(package_files, loader=loader)
    referenced = referenced_names(tool_files, loader=loader)
    documented = documented_patterns(doc_files)
    findings: List[Finding] = []
    for name, sites in sorted(referenced.items()):
        if not any(_covers(p, name) or _covers(name + "*", p)
                   for p in emitted):
            findings.append(Finding(
                analyzer="metrics_contract", code=METRICS_CONTRACT,
                severity=SEVERITY_ERROR, symbol=name,
                path=sites[0].rsplit(":", 1)[0],
                line=int(sites[0].rsplit(":", 1)[1]),
                message=f"soak adjudicates metric {name!r} but no "
                        "Recorder emission site produces it — the "
                        "assertion reads an always-absent counter "
                        "(phantom metric; renamed or never wired?)"))
    undocumented = []
    for pattern, sites in sorted(emitted.items()):
        if not any(_covers(doc, pattern) or _covers(pattern, doc)
                   for doc in documented):
            undocumented.append(pattern)
            findings.append(Finding(
                analyzer="metrics_contract", code=METRICS_CONTRACT,
                severity=SEVERITY_WARNING, symbol=pattern,
                path=sites[0].rsplit(":", 1)[0],
                line=int(sites[0].rsplit(":", 1)[1]),
                message=f"metric {pattern!r} is emitted but appears "
                        "nowhere in the DESIGN.md metric catalog — "
                        "dashboards are written from the docs; add it "
                        "to the §15 catalog"))
    return findings, {
        "emitted": len(emitted), "referenced": len(referenced),
        "documented": len(documented),
        "undocumented": sorted(undocumented),
    }


def analyze(root: str, loader: Optional[SourceLoader] = None
            ) -> Tuple[List[Finding], Dict]:
    """Default scopes: the package for emissions, ``tools/*_soak.py``
    for adjudication references, DESIGN.md for the catalog."""
    pkg_files = []
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if fn.endswith(".py"):
                pkg_files.append(os.path.join(dirpath, fn))
    repo = os.path.dirname(root)
    tool_files = sorted(glob.glob(os.path.join(repo, "tools",
                                               "*_soak.py")))
    doc_files = [p for p in (os.path.join(repo, "DESIGN.md"),)
                 if os.path.exists(p)]
    return check(sorted(pkg_files), tool_files, doc_files,
                 loader=loader)
