"""Exhaustive protocol model checking for the fenced-epoch ladder
(DESIGN.md §26; E003/E004).

The promotion/fencing protocols — router HA promotion (§22), shard
replication failover (§23), keyspace handoff (§18) — are exactly the
code whose bugs unit tests miss: the hazard lives in one interleaving
of promote vs resurrect vs crash that no scripted test schedules.
This module holds EXECUTABLE MODELS of the three protocols at small
scope (one primary, one standby, bounded epochs/ops/rounds) and an
explicit-state explorer that enumerates EVERY interleaving, crash
injection included, checking the protocol invariants on each
transition.  Small-scope exhaustiveness over large-scope sampling: the
bug classes here (persist/announce swapped, ack without standby
coverage, swap before the committed record) all bite within two
actors and two rounds.

Explorer.  A model is three methods: ``initial() -> dict`` (the start
state; values must be hashable), ``actions(state) -> [(label, next)]``
(every enabled transition — crash and restart are ordinary actions),
``invariants(prev, label, state) -> [violation strings]``.  The
explorer runs breadth-first with state-hash dedup, keeps parent
pointers for shortest-trace reconstruction, and reports complete=True
iff the frontier drained below the state cap — a cap hit is reported,
never silently truncated into "verified".

Each model also takes a ``bug=`` constructor flag that re-introduces a
real bug class (the swapped persist/announce twin, the gate-less ack,
the swap-before-persist commit).  Those are not dead weight: the
planted-violation tests promote them to proof that the checker can
still FAIL — a gate that cannot fail proves nothing.

Deliberate abstractions (checked elsewhere or out of scope): the
semi-sync degrade window (its async acks are typed non-covered, so
they are outside the zero-acked-op-loss contract), WAL truncation
byte-level catch-up, and the false-positive-promotion write window on
an undeposed primary (those writes can never semi-sync ack — the gate
blocks without a tailing standby — so they shed typed, §23).

E003 keeps the models honest: every model pins the source segments it
mirrors (MODEL_MIRRORS, F001-style short hashes).  Editing a mirrored
protocol function without re-verifying the model fails the gate with
MODEL_STALE; ``python -m go_crdt_playground_tpu.analysis.protomodel``
prints the refreshed table to paste after re-verification.
"""

from __future__ import annotations

import hashlib
import os
from collections import deque
from typing import (Callable, Dict, Iterable, List, NamedTuple, Optional,
                    Sequence, Tuple)

from go_crdt_playground_tpu.analysis.epoch_order import _find_function
from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (MODEL_STALE,
                                                    MODEL_VIOLATION,
                                                    SEVERITY_ERROR, Finding)

# -- the explorer -----------------------------------------------------------


class Violation(NamedTuple):
    message: str
    trace: Tuple[str, ...]   # action labels, initial state to violation


class Result(NamedTuple):
    states: int
    transitions: int
    violations: Tuple[Violation, ...]
    complete: bool           # False iff the state cap cut exploration


def _freeze(state: Dict) -> Tuple:
    return tuple(sorted(state.items()))


def explore(model, max_states: int = 100000,
            max_violations: int = 8) -> Result:
    """Exhaust the model's state graph.  Invariants run on the initial
    state and on every TRANSITION (prev, label, next) — including
    re-entries to already-seen states, so transition-shaped invariants
    (e.g. monotonicity) see every edge, deduped by message."""
    init = dict(model.initial())
    f0 = _freeze(init)
    parents: Dict[Tuple, Tuple[Optional[Tuple], Optional[str]]] = {
        f0: (None, None)}
    seen = {f0}
    queue = deque([init])
    violations: List[Violation] = []
    reported = set()

    def _trace(fz: Tuple, last: Optional[str]) -> Tuple[str, ...]:
        labels: List[str] = [] if last is None else [last]
        while fz in parents:
            fz, label = parents[fz]
            if label is None:
                break
            labels.append(label)
        return tuple(reversed(labels))

    for msg in model.invariants(None, None, init):
        if msg not in reported:
            reported.add(msg)
            violations.append(Violation(msg, ()))
    transitions = 0
    complete = True
    while queue:
        state = queue.popleft()
        fz = _freeze(state)
        for label, nxt in model.actions(state):
            transitions += 1
            nfz = _freeze(nxt)
            if nfz not in seen:
                if len(seen) >= max_states:
                    complete = False
                    continue
                seen.add(nfz)
                parents[nfz] = (fz, label)
                queue.append(nxt)
            for msg in model.invariants(state, label, nxt):
                if (msg not in reported
                        and len(violations) < max_violations):
                    reported.add(msg)
                    base = _trace(fz, None)
                    violations.append(Violation(msg, base + (label,)))
    return Result(len(seen), transitions, tuple(violations), complete)


# -- model 1: router HA promotion (§22) -------------------------------------


class RouterHAModel:
    """RouterStandby promotion vs primary resurrection, one durable
    shard as the adjudication tier.  Mirrors ``shard/ha.py``'s
    ``_promote_locked`` spine: claim epoch = max(tailed, disk)+1,
    persist it, announce to the shard (which adjudicates the max and
    thereafter refuses lower-epoch routers), best-effort RING_SYNC
    deposition of the old primary (optional — network blip or dead
    primary skips it), then serve.  A resurrected primary probes the
    shard and self-fences iff a higher epoch was adjudicated
    (``ShardRouter.deposed`` / serve()-time announce).

    bug="announce_before_persist" reorders steps 1 and 3: the claimed
    epoch reaches the shard before it is durable, so a crash between
    the two re-promotes at the SAME epoch — the E001 bug class,
    demonstrated here as an actual two-incarnations-one-epoch run."""

    name = "router_ha"
    MAX_ROUNDS = 2

    def __init__(self, bug: Optional[str] = None) -> None:
        assert bug in (None, "announce_before_persist"), bug
        self.bug = bug

    def initial(self) -> Dict:
        return {"shard": 0,        # adjudicated router epoch (durable)
                "disk": 0,         # standby state_dir epoch (durable)
                "p": "up", "p_epoch": 0,
                "s": "idle", "s_epoch": 0,
                "rounds": 0,
                # (round, epoch) pairs that reached the announce step
                "claims": frozenset()}

    def actions(self, st: Dict) -> List[Tuple[str, Dict]]:
        out: List[Tuple[str, Dict]] = []

        def step(label: str, **upd) -> None:
            nxt = dict(st)
            nxt.update(upd)
            out.append((label, nxt))

        s, p = st["s"], st["p"]
        if s == "idle" and st["rounds"] < self.MAX_ROUNDS:
            epoch = max(st["p_epoch"], st["disk"]) + 1
            step("s:claim", s="claimed", s_epoch=epoch,
                 rounds=st["rounds"] + 1)
        announced = {"shard": max(st["shard"], st["s_epoch"]),
                     "claims": st["claims"]
                     | {(st["rounds"], st["s_epoch"])}}
        if self.bug == "announce_before_persist":
            if s == "claimed":
                step("s:announce", s="announced", **announced)
            if s == "announced":
                step("s:persist", s="ready", disk=st["s_epoch"])
        else:
            if s == "claimed":
                step("s:persist", s="persisted", disk=st["s_epoch"])
            if s == "persisted":
                step("s:announce", s="ready", **announced)
        if s == "ready":
            if p == "up":
                # best-effort RING_SYNC deposition (3b) — serve below
                # stays enabled without it (blip / dead primary)
                step("s:notice", p="fenced")
            step("s:serve", s="serving")
        if s in ("claimed", "persisted", "announced", "ready"):
            step("s:crash", s="crashed")
        if s == "crashed":
            step("s:restart", s="idle")
        if p == "up":
            step("p:crash", p="crashed")
        if p == "crashed":
            # restart probe: the shards remember the adjudicated epoch
            step("p:restart",
                 p="fenced" if st["shard"] > st["p_epoch"] else "up")
        return out

    def invariants(self, prev: Optional[Dict], label: Optional[str],
                   st: Dict) -> List[str]:
        out: List[str] = []
        if (st["p"] == "up" and st["p_epoch"] >= st["shard"]
                and st["s"] == "serving"
                and st["s_epoch"] >= st["shard"]):
            out.append("single-writer: primary and promoted standby "
                       "can both commit through the shard tier")
        epochs = [e for _, e in st["claims"]]
        if len(set(epochs)) < len(epochs):
            out.append("epoch-uniqueness: two promotion incarnations "
                       "announced the same router epoch (a crash "
                       "between announce and persist resurrects the "
                       "epoch)")
        if prev is not None and st["shard"] < prev["shard"]:
            out.append("epoch-monotonicity: the shard-adjudicated "
                       "router epoch went backwards")
        return out


# -- model 2: shard replication failover (§23) ------------------------------


class ShardReplModel:
    """Semi-sync replication plus standby failover: the contract is
    ZERO ACKED-OP LOSS — every op acked under the semi-sync gate is on
    the member the router reads after any crash/failover sequence.
    Mirrors ``ReplicationPublisher.gate`` (ack only once the standby
    cursor covers the WAL tail), ``ShardStandby._promote_locked``
    (persist shard epoch, announce to the router, serve), and
    ``ShardRouter.failover_shard`` (adjudicate max epoch, depose
    lower-epoch resurrections via the stale check).

    bug="ack_without_coverage" drops the gate's coverage condition —
    the crash-then-promote run then serves with acked records missing,
    which is precisely the loss the gate exists to prevent."""

    name = "shard_repl"
    MAX_WAL = 2
    MAX_ROUNDS = 2

    def __init__(self, bug: Optional[str] = None) -> None:
        assert bug in (None, "ack_without_coverage"), bug
        self.bug = bug

    def initial(self) -> Dict:
        return {"wal": 0,      # primary WAL length
                "acked": 0,    # semi-sync acked prefix
                "cursor": 0,   # standby's replicated prefix (durable)
                "p": "up", "s": "idle", "s_epoch": 0,
                "disk": 0,     # standby durable shard epoch
                "adjud": 0,    # router-adjudicated shard epoch
                "rounds": 0}

    def actions(self, st: Dict) -> List[Tuple[str, Dict]]:
        out: List[Tuple[str, Dict]] = []

        def step(label: str, **upd) -> None:
            nxt = dict(st)
            nxt.update(upd)
            out.append((label, nxt))

        if st["p"] == "up":
            if st["wal"] < self.MAX_WAL:
                step("client:op", wal=st["wal"] + 1)
            if st["s"] == "idle" and st["cursor"] < st["wal"]:
                # WAL shipping: the standby tails while unpromoted
                step("repl:ship", cursor=st["cursor"] + 1)
            if st["acked"] < st["wal"] and (
                    self.bug == "ack_without_coverage"
                    or st["cursor"] >= st["wal"]):
                step("p:ack", acked=st["wal"])
            step("p:crash", p="crashed")
        if st["p"] == "crashed":
            # resurrection announce: the router's stale check deposes
            # a member below the adjudicated epoch
            step("p:restart",
                 p="deposed" if st["adjud"] > 0 else "up")
        if st["s"] == "idle" and st["rounds"] < self.MAX_ROUNDS:
            epoch = st["disk"] + 1
            step("s:promote_persist", s="persisted", s_epoch=epoch,
                 disk=epoch, rounds=st["rounds"] + 1)
        if st["s"] == "persisted":
            step("s:announce", s="announced",
                 adjud=max(st["adjud"], st["s_epoch"]))
        if st["s"] == "announced":
            if st["p"] == "up":
                # best-effort WAL_SYNC deposition; serving never
                # waits on it
                step("s:notice", p="deposed")
            step("s:serve", s="serving")
        if st["s"] in ("persisted", "announced"):
            step("s:crash", s="crashed")
        if st["s"] == "crashed":
            step("s:restart", s="idle")
        return out

    def invariants(self, prev: Optional[Dict], label: Optional[str],
                   st: Dict) -> List[str]:
        out: List[str] = []
        if st["s"] == "serving" and st["cursor"] < st["acked"]:
            out.append("acked-op-loss: the promoted standby serves "
                       "without records the primary acked under the "
                       "semi-sync gate")
        if prev is not None and st["adjud"] < prev["adjud"]:
            out.append("epoch-monotonicity: the router-adjudicated "
                       "shard epoch went backwards")
        return out


# -- model 3: keyspace handoff commit (§18) ---------------------------------


class HandoffModel:
    """The FENCED -> COMMITTED | ABORTED spine of
    ``HandoffCoordinator._run`` with a SIGKILL available at every
    transition: stage, fence, drain, transfer, persist the COMMITTED
    record, then the atomic in-memory route swap
    (``ShardRouter.commit_route``); every pre-commit failure funnels
    through clear_fence + ABORTED.  A crash loses all in-memory state;
    restart recovery adopts the durable record (committed -> new ring,
    anything else -> old ring, fence gone either way).

    Invariants: the in-memory ring never swaps before the COMMITTED
    record is durable; an ABORTED record is only ever written while
    the old ring is provably the active route; the fence never blocks
    reads; recovery lands on the ring the durable record names.

    bug="swap_before_persist" commits in-memory first — the persist
    failure then funnels to the abort arm AFTER the irreversible swap,
    the exact hazard the ordering comment in ``_run`` documents.
    bug="fence_blocks_reads" makes the fence reject reads, violating
    the fences-never-block-reads contract (the fence covers moved-
    element WRITES only)."""

    name = "handoff"

    def __init__(self, bug: Optional[str] = None) -> None:
        assert bug in (None, "swap_before_persist",
                       "fence_blocks_reads"), bug
        self.bug = bug

    def initial(self) -> Dict:
        return {"phase": "idle", "durable": "none", "route": "old",
                "fence": False, "reads_blocked": False}

    def actions(self, st: Dict) -> List[Tuple[str, Dict]]:
        out: List[Tuple[str, Dict]] = []

        def step(label: str, **upd) -> None:
            nxt = dict(st)
            nxt.update(upd)
            out.append((label, nxt))

        ph = st["phase"]
        if ph == "idle":
            step("c:stage", phase="staged", durable="staged")
        if ph == "staged":
            step("c:fence", phase="fenced", fence=True,
                 reads_blocked=(self.bug == "fence_blocks_reads"))
        if ph == "fenced":
            step("c:drain", phase="drained")
        if ph == "drained":
            step("c:transfer", phase="transferred")
        if self.bug == "swap_before_persist":
            if ph == "transferred":
                step("c:swap", phase="swapped", route="new",
                     fence=False, reads_blocked=False)
            if ph == "swapped":
                step("c:persist_committed", phase="done",
                     durable="committed")
        else:
            if ph == "transferred":
                step("c:persist_committed", phase="committed",
                     durable="committed")
            if ph == "committed":
                step("c:swap", phase="done", route="new",
                     fence=False, reads_blocked=False)
        abortable = ("staged", "fenced", "drained", "transferred")
        if self.bug == "swap_before_persist":
            # the persist failure now lands AFTER the swap and still
            # funnels through the abort arm — the modeled hazard
            abortable += ("swapped",)
        if ph in abortable:
            step("c:fail", phase="aborting", fence=False,
                 reads_blocked=False)
        if ph == "aborting":
            step("c:persist_aborted", phase="aborted",
                 durable="aborted")
        if ph not in ("crashed", "recovered"):
            # SIGKILL: in-memory fence state dies with the process
            step("crash", phase="crashed", fence=False,
                 reads_blocked=False)
        if ph == "crashed":
            step("restart", phase="recovered",
                 route=("new" if st["durable"] == "committed"
                        else "old"))
        return out

    def invariants(self, prev: Optional[Dict], label: Optional[str],
                   st: Dict) -> List[str]:
        out: List[str] = []
        if st["reads_blocked"]:
            out.append("fence-blocks-reads: the handoff fence rejected "
                       "a read (it covers moved-element writes only)")
        if (st["route"] == "new" and st["durable"] != "committed"
                and st["phase"] != "crashed"):
            out.append("swap-before-durable: the in-memory ring "
                       "swapped before the COMMITTED record persisted "
                       "(a crash or abort here misreports the active "
                       "ring)")
        if st["durable"] == "aborted" and st["route"] == "new":
            out.append("abort-inconsistency: an ABORTED record was "
                       "written while the new ring is the active "
                       "route — 'aborted' must prove the old ring "
                       "serves")
        if (st["phase"] == "recovered"
                and (st["durable"] == "committed")
                != (st["route"] == "new")):
            out.append("recovery-mismatch: restart landed on a ring "
                       "the durable record does not name")
        return out


# factories, not instances: every exploration starts from a fresh
# bug-free model
MODELS: Tuple[Tuple[str, Callable[[], object]], ...] = (
    ("router_ha", RouterHAModel),
    ("shard_repl", ShardReplModel),
    ("handoff", HandoffModel),
)


# -- E003: model freshness --------------------------------------------------


class MirrorSpec(NamedTuple):
    model: str
    path: str        # package-relative file
    qualname: str    # "Class.method"
    sha: str         # 16-hex sha256 prefix of the pinned segment


def _segment_hash(source: str, node) -> str:
    lines = source.splitlines()[node.lineno - 1:node.end_lineno]
    blob = "\n".join(ln.rstrip() for ln in lines)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# THE mirror table: each model pins the source segments it abstracts.
# Refresh (after re-verifying the model against the changed protocol):
#     python -m go_crdt_playground_tpu.analysis.protomodel
MODEL_MIRRORS: Tuple[MirrorSpec, ...] = (
    MirrorSpec('router_ha', 'shard/ha.py',
               'RouterStandby._promote_locked', '46007f3587f2c09e'),
    MirrorSpec('router_ha', 'shard/router.py',
               'ShardRouter.deposed', 'bd8bfc7a7ef5a869'),
    MirrorSpec('router_ha', 'serve/frontend.py',
               'ServeFrontend._handle_ring_sync', '408822a46b360102'),
    MirrorSpec('shard_repl', 'shard/replica.py',
               'ShardStandby._promote_locked', '3abc8ce07f071876'),
    MirrorSpec('shard_repl', 'shard/replica.py',
               'ReplicationPublisher.gate', '869653ab50148e17'),
    MirrorSpec('shard_repl', 'shard/router.py',
               'ShardRouter.failover_shard', '107054f3de950252'),
    MirrorSpec('shard_repl', 'serve/frontend.py',
               'ServeFrontend._handle_wal_sync', '5e44af2c0dfb6262'),
    MirrorSpec('handoff', 'shard/handoff.py',
               'HandoffCoordinator._run', '66c8fe8ced76e461'),
    MirrorSpec('handoff', 'shard/router.py',
               'ShardRouter.commit_route', '8319007e8f48365f'),
    MirrorSpec('handoff', 'shard/router.py',
               'ShardRouter.set_fence', '9a008dfe56ffd536'),
)


def check_freshness(root: str,
                    mirrors: Sequence[MirrorSpec] = MODEL_MIRRORS,
                    loader: Optional[SourceLoader] = None
                    ) -> Tuple[List[Finding], Dict]:
    loader = ensure_loader(loader)
    findings: List[Finding] = []
    fresh = 0
    for spec in mirrors:
        path = os.path.join(root, spec.path)
        pf = loader.load(path)
        fn = _find_function(pf.tree, spec.qualname)
        if fn is None:
            findings.append(Finding(
                analyzer="protomodel", code=MODEL_STALE,
                severity=SEVERITY_ERROR, path=path,
                symbol=spec.qualname,
                message=(f"model {spec.model!r} mirrors "
                         f"{spec.qualname}, which no longer exists in "
                         f"{spec.path} — re-verify the model against "
                         "the refactored protocol and re-pin the "
                         "mirror (python -m go_crdt_playground_tpu."
                         "analysis.protomodel prints the table)")))
            continue
        cur = _segment_hash(pf.source, fn)
        if cur != spec.sha:
            findings.append(Finding(
                analyzer="protomodel", code=MODEL_STALE,
                severity=SEVERITY_ERROR, path=path, line=fn.lineno,
                symbol=spec.qualname,
                message=(f"model {spec.model!r} is stale against "
                         f"{spec.qualname} ({spec.path}): pinned "
                         f"segment {spec.sha}, current {cur} — the "
                         "protocol changed under the model; re-verify "
                         "the model's transitions, then refresh the "
                         "pin (python -m go_crdt_playground_tpu."
                         "analysis.protomodel)")))
        else:
            fresh += 1
    return findings, {"mirrored_symbols": len(mirrors), "fresh": fresh}


# -- the gate pass ----------------------------------------------------------


def analyze(root: str,
            models: Iterable[Tuple[str, Callable[[], object]]] = MODELS,
            mirrors: Sequence[MirrorSpec] = MODEL_MIRRORS,
            loader: Optional[SourceLoader] = None,
            max_states: int = 100000) -> Tuple[List[Finding], Dict]:
    """Freshness first, then exhaust each model.  ``models`` is
    injectable so tests can run the gate over a bug-flagged twin and
    prove E004 fires."""
    findings, stats = check_freshness(root, mirrors, loader)
    model_stats: Dict[str, Dict] = {}
    total_states = 0
    for name, factory in models:
        res = explore(factory(), max_states=max_states)
        total_states += res.states
        model_stats[name] = {"states": res.states,
                             "transitions": res.transitions,
                             "complete": res.complete,
                             "violations": len(res.violations)}
        if not res.complete:
            findings.append(Finding(
                analyzer="protomodel", code=MODEL_VIOLATION,
                severity=SEVERITY_ERROR, symbol=name,
                message=(f"model {name!r} hit the {max_states}-state "
                         "cap before draining: the scope grew past "
                         "exhaustiveness — shrink the model bounds "
                         "(a sampled 'verified' is not verified)")))
        for v in res.violations:
            trace = " -> ".join(v.trace) or "<initial>"
            findings.append(Finding(
                analyzer="protomodel", code=MODEL_VIOLATION,
                severity=SEVERITY_ERROR, symbol=name,
                message=(f"model {name!r} violates [{v.message}] via: "
                         f"{trace}")))
    stats.update({"models": model_stats, "total_states": total_states})
    return findings, stats


def _print_mirror_table(root: str) -> None:
    loader = ensure_loader(None)
    print("MODEL_MIRRORS: Tuple[MirrorSpec, ...] = (")
    for spec in MODEL_MIRRORS:
        pf = loader.load(os.path.join(root, spec.path))
        fn = _find_function(pf.tree, spec.qualname)
        sha = "<MISSING>" if fn is None else _segment_hash(pf.source, fn)
        print(f"    MirrorSpec({spec.model!r}, {spec.path!r},\n"
              f"               {spec.qualname!r}, {sha!r}),")
    print(")")


if __name__ == "__main__":
    _print_mirror_table(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
