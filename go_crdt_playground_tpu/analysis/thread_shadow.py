"""T001: Thread-subclass attribute shadowing (DESIGN.md §15).

PR 12's soak debugging lost an afternoon to one line: a
``threading.Thread`` subclass named its stop flag ``self._stop`` —
which silently REPLACED ``Thread._stop`` (the method the runtime calls
to mark the thread finished), so ``join()`` hung forever on an exited
thread.  Nothing crashes at assignment time; CPython's Thread keeps
its internals as plain attributes with no protection.  The failure is
invisible until a teardown path deadlocks, usually in a soak.

This pass makes the trap gate-time: every class in the tree whose base
list names ``Thread`` (``threading.Thread`` or an imported ``Thread``)
is checked for

* **instance-attribute assignments** ``self.<name> = ...`` where
  ``<name>`` collides with a ``threading.Thread`` internal (method or
  state slot).  ``daemon`` and ``name`` are excluded — they are
  PROPERTIES whose setters exist exactly for this; assigning them is
  the documented API.
* **method definitions** overriding a Thread internal other than
  ``run`` (the documented override point) — ``def _stop(self)`` is the
  same bug wearing a def.

The blocklist is derived from the RUNNING interpreter's
``threading.Thread`` (non-dunder attributes), so a CPython that grows
a new internal is covered without a code change here.

Scope: the package, ``tools/``, and ``tests/`` — the PR-12 offender
lived in a tool, and a test harness thread that cannot ``join()``
wedges CI just as hard as a runtime one.
"""

from __future__ import annotations

import ast
import os
import threading
from typing import Dict, List, Optional, Tuple

from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (SEVERITY_ERROR,
                                                    THREAD_SHADOW, Finding)

# assignable-by-design properties on threading.Thread: setting them is
# the documented API, never a shadow
_PROPERTY_NAMES = frozenset(
    name for name in dir(threading.Thread)
    if isinstance(getattr(threading.Thread, name, None), property))

# the documented override point — subclassing Thread to define run()
# is the whole point of subclassing Thread
_OVERRIDE_OK = frozenset({"run"})


def thread_internal_names() -> frozenset:
    """Every non-dunder attribute of the running interpreter's
    ``threading.Thread`` that is NOT an assignable property: methods
    (``_stop``, ``start``, ``join``, ``is_alive`` ...) and state slots
    — assigning any of these on an instance shadows the runtime's."""
    return frozenset(
        name for name in dir(threading.Thread)
        if not (name.startswith("__") and name.endswith("__"))
        and name not in _PROPERTY_NAMES)


def _is_thread_base(base: ast.expr) -> bool:
    """``class X(Thread)`` / ``class X(threading.Thread)``."""
    if isinstance(base, ast.Name):
        return base.id == "Thread"
    if isinstance(base, ast.Attribute):
        return base.attr == "Thread"
    return False


def check_file(path: str, internals: frozenset,
               loader: Optional[SourceLoader] = None
               ) -> Tuple[List[Finding], int]:
    """Returns (findings, thread_subclass_count) from ONE parse."""
    try:
        tree = ensure_loader(loader).load(path).tree
    except SyntaxError as e:
        return [Finding(
            analyzer="thread_shadow", code=THREAD_SHADOW,
            severity=SEVERITY_ERROR, path=path, line=e.lineno,
            message=f"unparseable file: {e.msg}")], 0
    findings: List[Finding] = []
    n_subclasses = 0
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if not any(_is_thread_base(b) for b in node.bases):
            continue
        n_subclasses += 1
        # method definitions shadowing a Thread internal (run is the
        # documented override point; dunders like __init__ are not
        # in the internals set by construction)
        for sub in node.body:
            if (isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and sub.name in internals
                    and sub.name not in _OVERRIDE_OK):
                findings.append(Finding(
                    analyzer="thread_shadow", code=THREAD_SHADOW,
                    severity=SEVERITY_ERROR, path=path, line=sub.lineno,
                    symbol=f"{node.name}.{sub.name}",
                    message=(f"Thread subclass {node.name} defines "
                             f"{sub.name}() — it overrides "
                             f"threading.Thread.{sub.name} (an "
                             "internal the runtime calls); rename it "
                             "(only run() is a documented override)")))
        # self.<name> = ... assignments anywhere in the class body
        for meth in [n for n in node.body
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]:
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Assign):
                    targets = sub.targets
                elif isinstance(sub, ast.AnnAssign):
                    targets = [sub.target]
                else:
                    continue
                for tgt in targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and tgt.attr in internals):
                        findings.append(Finding(
                            analyzer="thread_shadow", code=THREAD_SHADOW,
                            severity=SEVERITY_ERROR, path=path,
                            line=sub.lineno,
                            symbol=f"{node.name}.{tgt.attr}",
                            message=(
                                f"Thread subclass {node.name} assigns "
                                f"self.{tgt.attr} — it shadows "
                                f"threading.Thread.{tgt.attr} and "
                                "silently breaks the thread runtime "
                                "(the PR-12 _stop-breaks-join() bug "
                                "class); rename the attribute")))
    return findings, n_subclasses


def analyze(root: str,
            extra_dirs: Tuple[str, ...] = ("tools", "tests"),
            loader: Optional[SourceLoader] = None
            ) -> Tuple[List[Finding], Dict]:
    loader = ensure_loader(loader)
    """Sweep the package at ``root`` plus the repo's ``tools/`` and
    ``tests/`` siblings (explicit args so tests can plant violations
    in a tmp tree)."""
    internals = thread_internal_names()
    paths: List[str] = []
    scan_roots = [root] + [os.path.join(os.path.dirname(root), d)
                           for d in extra_dirs]
    for scan in scan_roots:
        if not os.path.isdir(scan):
            continue
        for dirpath, _dirnames, filenames in os.walk(scan):
            if "__pycache__" in dirpath:
                continue
            for fn in filenames:
                if fn.endswith(".py"):
                    paths.append(os.path.join(dirpath, fn))
    paths.sort()
    findings: List[Finding] = []
    n_subclasses = 0
    for path in paths:
        file_findings, n = check_file(path, internals, loader=loader)
        findings.extend(file_findings)
        n_subclasses += n
    return findings, {"files_scanned": len(paths),
                      "thread_subclasses": n_subclasses,
                      "internals_checked": len(internals)}
