"""The analysis gate CLI (DESIGN.md §15, §26).

    python -m go_crdt_playground_tpu.analysis            # full gate
    python -m go_crdt_playground_tpu.analysis --fast     # tier-1 budget
    python -m go_crdt_playground_tpu.analysis --out P    # report path
    python -m go_crdt_playground_tpu.analysis --json     # machine summary

Runs every registered pass and writes ``ANALYSIS_REPORT.json``:

1. lock-discipline lint (``# guarded-by:`` + lock-order cycles) over
   the threaded runtime files;
2. a short in-process lockset race-detector exercise (instrumented
   Node + DeltaWal driven from racing threads) so the runtime pass is
   covered on every gate run, not only under the opt-in soaks;
3. durability-ordering lint over the WAL/checkpoint modules and the
   JAX-purity lint over ``ops/``;
4. lattice-law property checks of every registered join (each family's
   declared law subset — non-idempotent merge strategies like the
   model-merging mean register fewer laws, never zero checks);
5. the wire-contract suite: W001 dispatch exhaustiveness + W002
   reject-code discipline + W004 frame-cap discipline
   (``protocol_contract``), W003 codec symmetry with the seeded
   roundtrip/truncation/garble harness (``codec_symmetry``), and the
   M001 metrics contract (``metrics_contract``);
6. the protocol verification ladder (§26): E001 persist-before-
   announce ordering over the registered promotion paths
   (``epoch_order``), E002 fence coverage of every write-verb
   dispatcher arm (``fence_coverage``), D002 blocking device
   transfers under held locks (``transfer_lock``), and the
   ``protomodel`` explorer — exhaustive interleaving+crash
   enumeration of the router-HA / shard-replication / handoff models
   with E003 freshness pins against the mirrored source;
7. report freshness: the COMMITTED ``ANALYSIS_REPORT.json``'s pass
   list must match the registered passes — a new pass cannot land
   while the committed artifact silently claims full coverage.

All source-reading passes share one parse cache (``loader.py``); its
hit counts and the gate wall time land in the report's ``meta`` block
(tier-1 asserts ``--fast`` stays under ``FAST_BUDGET_S``).

Exit status: 0 iff no ERROR finding.  ``--fast`` trims the lattice
seeds, the codec sample counts, and the lockset exercise, not the
pass list — every pass runs in every mode (tier-1 wires ``--fast`` in
as a non-slow test).  ``--json`` prints one machine-readable summary
line instead of per-finding lines; the exit contract is identical.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
from typing import List, Optional

# the lattice/lockset passes touch jax; the gate is defined as a CPU
# tool (seeded, accelerator-independent), so pin the platform before
# any jax import unless the caller already chose one
os.environ.setdefault("JAX_PLATFORMS", "cpu")

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# pass targets, package-relative (DESIGN.md §15 pass catalog; the
# serve/ files are the PR-5 serving frontend — its admission queue,
# session writers, batcher, and client are all multi-threaded shared
# state, so the guarded-by sweep covers them like the sync runtime;
# the shard/ files are the router tier — its per-shard links, relay
# fan-in, and fleet runner cross as many threads as the frontend does)
LOCK_TARGETS = ["net/peer.py", "net/antientropy.py", "net/digestsync.py",
                "utils/wal.py",
                "serve/admission.py", "serve/session.py",
                "serve/batcher.py", "serve/frontend.py",
                "serve/client.py", "serve/host.py", "serve/compaction.py",
                "obs/metrics.py",
                "shard/ring.py", "shard/router.py", "shard/fleet.py",
                "shard/handoff.py",
                # the mesh replica tier (ISSUE 10): a Node subclass
                # whose compiled-program caches and re-pin paths run
                # under the node lock like every other state mutation
                "parallel/meshtarget.py",
                # the 2-D dp×mp tier (ISSUE 15): its striping planner
                # and chunked apply loop run under the node lock; the
                # stripe/program caches follow the 1-D discipline
                "parallel/meshtarget2d.py",
                # the fleet autopilot (ISSUE 12): the controller loop
                # thread owns most state (race-ok-annotated), but the
                # signal poller, standby pool and actuator cross the
                # loop thread with start/stop owners and post-stop
                # readers — swept like every other runtime tier
                "control/signals.py", "control/policy.py",
                "control/actuator.py", "control/controller.py",
                # the router HA tier (ISSUE 13): the standby's tail
                # loop thread, the promotion path, and await/observer
                # readers all cross on the standby lock
                "shard/ha.py",
                # the shard replication tier (ISSUE 14): the
                # publisher's condition crosses WAL_SYNC reader
                # threads with the batcher's ack gate, and the shard
                # standby's tail loop crosses promote()/observers —
                # plus the shared degrade-window latch both serving
                # ladders poll cross-thread
                "shard/replica.py", "utils/degrade.py",
                # the conflict-aware admission scheduler (ISSUE 18):
                # owned by the batcher loop thread, race-ok-annotated
                # read-only config — swept so the annotations stay
                # honest as the scheduler grows state
                "serve/scheduler.py"]
# extra files that participate in the lock-ORDER graph (their locks can
# nest under the runtime's)
LOCK_ORDER_EXTRA = ["utils/checkpoint.py"]
DURABILITY_TARGETS = ["utils/wal.py", "utils/checkpoint.py",
                      "utils/checkpoint_sharded.py", "utils/fsutil.py",
                      "shard/handoff.py"]
PURITY_TARGETS = ["ops/merge.py", "ops/delta.py", "ops/lattices.py",
                  "ops/vv.py", "ops/compact.py", "ops/pallas_merge.py",
                  "ops/pallas_delta.py", "ops/ingest.py",
                  "ops/pallas_ingest.py", "ops/digest.py",
                  "ops/pallas_digest.py", "parallel/meshtarget.py",
                  "parallel/meshtarget2d.py",
                  # the scheduler's planning core (key_runs/plan_emit)
                  # is pure host-side combinatorics: no I/O, no
                  # hidden state — hold it to the kernel bar
                  "serve/scheduler.py"]
# attribute-name -> class hints for cross-class lock-order edges
ATTR_CLASSES = {"wal": "DeltaWal", "node": "Node",
                "recorder": "Recorder", "_store": "CheckpointStore",
                "breaker": "CircuitBreaker", "queue": "AdmissionQueue",
                "session": "Session", "batcher": "MicroBatcher",
                "supervisor": "SyncSupervisor", "target": "Node",
                "ring": "HashRing", "router": "ShardRouter",
                "relay": "_Relay", "_client": "ServeClient",
                "host": "ConnHost", "handoff": "HandoffCoordinator",
                "_route": "RouteState",
                "compactor": "CompactionScheduler",
                "_negotiator": "DigestNegotiator",
                "_group_adapter": "AdaptiveGroupSize",
                "policy": "AutopilotPolicy",
                "actuator": "ReshardActuator",
                "signals": "FleetSignals",
                "pool": "StandbyPool",
                "pilot": "FleetAutopilot",
                "standby": "RouterStandby",
                "repl": "ReplicationPublisher",
                "window": "DegradeWindow",
                "_storage": "DegradeWindow",
                "scheduler": "ConflictScheduler"}

# the D002 sweep: every lock-swept runtime file plus the framing
# module (its WAL-record encoder runs under the node lock by call,
# not by lexical with-block — the fixpoint finds it)
TRANSFER_TARGETS = LOCK_TARGETS + ["net/framing.py"]

# the full pass list (report keys): the report-freshness lint pins the
# COMMITTED artifact's pass list to this — landing a new pass without
# regenerating ANALYSIS_REPORT.json fails the gate instead of letting
# the committed artifact silently claim full coverage
REGISTERED_PASSES = ("lockdiscipline", "locksets", "durability",
                     "purity", "lattice_laws", "protocol_contract",
                     "codec_symmetry", "metrics_contract",
                     "report_freshness", "thread_shadow",
                     "epoch_order", "fence_coverage", "transfer_lock",
                     "protomodel")

# the --fast wall-time envelope (meta.fast_budget_s): generous against
# the measured ~7s so CI jitter never flakes, tight enough that a
# pass going quadratic (or a model scope exploding) fails tier-1
FAST_BUDGET_S = 60.0


def _paths(rel: List[str], root: str) -> List[str]:
    return [os.path.join(root, p) for p in rel]


def run_lockset_exercise(report, *, rounds: int = 200) -> None:
    """A small deliberately-contended workload under the instrumented
    classes: two threads mutate one Node (adds/deletes vs members/vv
    reads) while two more hammer one DeltaWal.  Everything shared is
    lock-guarded in the current tree, so a clean run reports zero races
    — and the pass is exercised end-to-end on every gate run."""
    import tempfile

    from go_crdt_playground_tpu.analysis.locksets import RaceDetector
    from go_crdt_playground_tpu.net.peer import Node
    from go_crdt_playground_tpu.utils.wal import DeltaWal

    det = RaceDetector()
    with tempfile.TemporaryDirectory(prefix="analysis-locksets-") as d:
        node = Node(0, 32, 4)
        wal = DeltaWal(os.path.join(d, "wal"), fsync=False)
        det.instrument(node, label="Node#gate")
        det.instrument(wal, label="DeltaWal#gate")
        try:
            stop = threading.Event()

            def mutate() -> None:
                i = 0
                while not stop.is_set():
                    node.add(i % 32)
                    if i % 3 == 0:
                        node.delete((i + 1) % 32)
                    i += 1

            def observe() -> None:
                while not stop.is_set():
                    node.members()
                    node.vv()

            def log(tag: bytes) -> None:
                i = 0
                while not stop.is_set():
                    wal.append(tag + str(i).encode())
                    i += 1

            threads = [threading.Thread(target=t, args=a, daemon=True)
                       for t, a in ((mutate, ()), (observe, ()),
                                    (log, (b"a",)), (log, (b"b",)))]
            for t in threads:
                t.start()
            # bound by work, not wall time: wait until the WAL saw
            # enough appends (or a short timeout on pathologic hosts)
            deadline = rounds
            import time as _time

            t0 = _time.monotonic()
            while _time.monotonic() - t0 < 10.0:
                if wal.record_count() >= deadline:
                    break
                _time.sleep(0.01)
            stop.set()
            for t in threads:
                t.join(timeout=5.0)
        finally:
            stats = det.stats()
            det.uninstall(node)
            det.uninstall(wal)
            wal.close()
    report.extend(det.findings)
    report.add_stats("locksets", mode="gate-exercise", **stats)


def check_report_freshness(report, committed_path: str,
                           out_path: Optional[str] = None) -> None:
    """The committed artifact's pass list must match the registered
    passes (F001) — stale coverage claims fail the gate; regenerating
    the artifact with the full gate is the documented fix.  When THIS
    run's ``--out`` is the committed path itself, the run IS the
    regeneration: the pre-run file is about to be superseded, so it is
    recorded (mode=regenerating), never flagged — without this, the
    documented fix command would exit 1 on its own first run and embed
    a spurious stale-against-itself finding in the fresh artifact.
    CI and the tier-1 test write to a separate --out, so staleness of
    the committed file stays enforced where it matters."""
    import json

    from go_crdt_playground_tpu.analysis.report import (REPORT_STALE,
                                                        SEVERITY_ERROR,
                                                        Finding)

    stats = {"registered": sorted(REGISTERED_PASSES),
             "committed_path": committed_path}
    if (out_path is not None and os.path.abspath(out_path)
            == os.path.abspath(committed_path)):
        report.add_stats("report_freshness", mode="regenerating",
                         **stats)
        return
    if not os.path.exists(committed_path):
        # a fresh clone mid-regeneration: the write at the end of this
        # very run creates it — absence is not a stale claim
        report.add_stats("report_freshness", committed=None, **stats)
        return
    try:
        with open(committed_path) as f:
            committed = sorted(json.load(f).get("passes", {}))
    except (ValueError, OSError) as e:
        report.extend([Finding(
            analyzer="report_freshness", code=REPORT_STALE,
            severity=SEVERITY_ERROR, path=committed_path,
            message=f"committed ANALYSIS_REPORT.json unreadable: {e}")])
        report.add_stats("report_freshness", committed=None, **stats)
        return
    report.add_stats("report_freshness", committed=committed, **stats)
    if set(committed) != set(REGISTERED_PASSES):
        missing = sorted(set(REGISTERED_PASSES) - set(committed))
        extra = sorted(set(committed) - set(REGISTERED_PASSES))
        report.extend([Finding(
            analyzer="report_freshness", code=REPORT_STALE,
            severity=SEVERITY_ERROR, path=committed_path,
            message=(f"committed report's pass list is stale "
                     f"(missing {missing}, extra {extra}) — "
                     "regenerate it with the full gate: "
                     "python -m go_crdt_playground_tpu.analysis"))])


def build_report(fast: bool, root: str = PKG_ROOT,
                 skip_runtime: bool = False,
                 committed_report: Optional[str] = None,
                 out_path: Optional[str] = None):
    import time

    from go_crdt_playground_tpu.analysis import (codec_symmetry,
                                                 durability, epoch_order,
                                                 fence_coverage,
                                                 lattice_laws,
                                                 lockdiscipline,
                                                 metrics_contract,
                                                 protocol_contract,
                                                 protomodel, purity,
                                                 thread_shadow,
                                                 transfer_lock)
    from go_crdt_playground_tpu.analysis.loader import SourceLoader
    from go_crdt_playground_tpu.analysis.report import Report

    t0 = time.monotonic()
    report = Report()
    # ONE parse per file per gate run: every source-reading pass below
    # shares this cache (meta.parse_cache records the dedup)
    loader = SourceLoader()

    findings, stats = lockdiscipline.analyze_files(
        _paths(LOCK_TARGETS + LOCK_ORDER_EXTRA, root),
        attr_classes=ATTR_CLASSES, loader=loader)
    # the extra files join the lock-order graph only; their guarded-by
    # coverage is (deliberately) not yet swept, so restrict L001/L003 to
    # the ISSUE-targeted runtime files
    targeted = {os.path.abspath(p) for p in _paths(LOCK_TARGETS, root)}
    findings = [f for f in findings
                if f.code == "L002" or f.path is None
                or os.path.abspath(f.path) in targeted]
    report.extend(findings)
    report.add_stats("lockdiscipline", **stats)

    f2, s2 = durability.analyze_files(_paths(DURABILITY_TARGETS, root),
                                      loader=loader)
    report.extend(f2)
    report.add_stats("durability", **s2)

    f3, s3 = purity.analyze_files(_paths(PURITY_TARGETS, root),
                                  loader=loader)
    report.extend(f3)
    report.add_stats("purity", **s3)

    seeds = (11,) if fast else (11, 12, 13)
    n_ops = 24 if fast else 40
    f4, s4 = lattice_laws.check_registry(seeds, n_ops=n_ops)
    report.extend(f4)
    report.add_stats("lattice_laws", **s4)

    # the wire-contract suite (DESIGN.md §15 W001-W004 + M001)
    f5, s5 = protocol_contract.analyze(root, loader=loader)
    report.extend(f5)
    report.add_stats("protocol_contract", **s5)

    f6, s6 = codec_symmetry.analyze(root, fast=fast, loader=loader)
    report.extend(f6)
    report.add_stats("codec_symmetry", **s6)

    f7, s7 = metrics_contract.analyze(root, loader=loader)
    report.extend(f7)
    report.add_stats("metrics_contract", **s7)

    # T001 Thread-subclass attribute shadowing (the PR-12
    # _stop-breaks-join() bug class, now gate-time)
    f8, s8 = thread_shadow.analyze(root, loader=loader)
    report.extend(f8)
    report.add_stats("thread_shadow", **s8)

    # the protocol verification ladder (DESIGN.md §26): ordering lint,
    # fence coverage, transfer-under-lock, and the model checker
    f9, s9 = epoch_order.analyze(root, loader=loader)
    report.extend(f9)
    report.add_stats("epoch_order", **s9)

    f10, s10 = fence_coverage.analyze(root, loader=loader)
    report.extend(f10)
    report.add_stats("fence_coverage", **s10)

    f11, s11 = transfer_lock.analyze(root, TRANSFER_TARGETS,
                                     loader=loader)
    report.extend(f11)
    report.add_stats("transfer_lock", **s11)

    f12, s12 = protomodel.analyze(root, loader=loader)
    report.extend(f12)
    report.add_stats("protomodel", **s12)

    if committed_report is None:
        committed_report = os.path.join(os.path.dirname(root),
                                        "ANALYSIS_REPORT.json")
    check_report_freshness(report, committed_report, out_path)

    if skip_runtime:
        report.add_stats("locksets", mode="skipped")
    else:
        run_lockset_exercise(report, rounds=60 if fast else 200)

    # meta is top-level report metadata, deliberately NOT a pass: the
    # F001 pass-list comparison and the census tests key on "passes"
    report.meta.update({
        "wall_time_s": round(time.monotonic() - t0, 3),
        "fast": bool(fast),
        "fast_budget_s": FAST_BUDGET_S,
        "parse_cache": loader.stats(),
    })
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m go_crdt_playground_tpu.analysis",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 budget: fewer lattice seeds, shorter "
                         "lockset exercise (every pass still runs)")
    ap.add_argument("--out", default="ANALYSIS_REPORT.json",
                    help="report path (default: ./ANALYSIS_REPORT.json)")
    ap.add_argument("--root", default=PKG_ROOT,
                    help="package root to analyze (default: the "
                         "installed go_crdt_playground_tpu)")
    ap.add_argument("--skip-runtime", action="store_true",
                    help="skip the in-process lockset exercise (pass is "
                         "reported as skipped, not covered)")
    ap.add_argument("--committed-report", default=None,
                    help="committed ANALYSIS_REPORT.json the freshness "
                         "lint checks (default: <repo>/"
                         "ANALYSIS_REPORT.json next to the package)")
    ap.add_argument("--json", action="store_true",
                    help="print one machine-readable JSON summary line "
                         "instead of per-finding lines (same exit "
                         "contract: 0 iff no ERROR finding)")
    args = ap.parse_args(argv)

    report = build_report(args.fast, root=args.root,
                          skip_runtime=args.skip_runtime,
                          committed_report=args.committed_report,
                          out_path=args.out)
    report.write_json(args.out)
    n_err = len(report.errors())
    if args.json:
        import json

        summary = {
            "ok": report.ok(),
            "findings": len(report.findings),
            "errors": n_err,
            "passes": sorted(report.stats),
            "wall_time_s": report.meta.get("wall_time_s"),
            "model_states": report.stats.get(
                "protomodel", {}).get("total_states"),
            "out": args.out,
        }
        print(json.dumps(summary, sort_keys=True))
        return 0 if report.ok() else 1
    for f in report.findings:
        print(f.render())
    print(f"wrote {args.out}: {len(report.findings)} findings, "
          f"{n_err} errors, passes: "
          + ", ".join(sorted(report.stats)))
    return 0 if report.ok() else 1


if __name__ == "__main__":
    sys.exit(main())
