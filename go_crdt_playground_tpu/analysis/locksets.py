"""Eraser-style runtime lockset race detector (Savage et al., 1997).

The static lint (``analysis.lockdiscipline``) proves what it can see
lexically; this detector catches the dynamic residue: aliased objects,
locks handed across threads, and fields nobody thought to annotate.  It
is OPT-IN instrumentation — ``instrument(obj)`` swaps the object onto a
tracing subclass and wraps its mutex attributes — wired into the chaos
and crash soaks behind ``--detect-races`` and exercised briefly by the
analysis CLI gate so ANALYSIS_REPORT.json always covers the pass.

Algorithm, per (object, field):

    virgin -> exclusive(t)    first access; single-thread warm-up is free
    exclusive(t) -> shared            second thread READS
    exclusive(t) -> shared_modified   second thread WRITES
    shared -> shared_modified         any later WRITE

From the first second-thread access on, the field's candidate lockset
``C(v)`` (initially "every lock") is intersected with the locks the
accessing thread holds; an empty ``C(v)`` while shared_modified is a
race report (R001): some write to the field is ordered only by luck.

Two project-specific twists:

* **container reads count as writes.**  ``self._done.add(x)`` mutates
  through a field READ — attribute tracing cannot see the mutation, so
  reads that yield a set/dict/list are treated as writes.  Guard your
  single-owner containers with ``# race-ok:`` if that is too strict.
* **annotation-aware.**  Fields annotated ``# race-ok: <reason>`` in the
  class source are excluded (the annotation grammar is shared with the
  static lint), as are the lock fields themselves and anything in
  ``_ALWAYS_IGNORE``.

``instrument`` refuses a second installation on the same object via
``utils.guards.SHIM_GUARD`` — a doubled shim would intersect locksets
against phantom wrappers and report nonsense.
"""

from __future__ import annotations

import ast
import inspect
import threading
import weakref
from typing import Any, Dict, List, Optional, Set, Tuple

from go_crdt_playground_tpu.analysis.annotations import KIND_RACE_OK
from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (RACE_EMPTY_LOCKSET,
                                                    SEVERITY_ERROR, Finding)
from go_crdt_playground_tpu.utils.guards import SHIM_GUARD

_VIRGIN = 0
_EXCLUSIVE = 1
_SHARED = 2
_SHARED_MODIFIED = 3

_STATE_NAMES = {_VIRGIN: "virgin", _EXCLUSIVE: "exclusive",
                _SHARED: "shared", _SHARED_MODIFIED: "shared_modified"}

# interpreter/bookkeeping names never worth tracking
_ALWAYS_IGNORE = {"__dict__", "__class__", "__weakref__"}

_MUTEX_TYPES = (type(threading.Lock()), type(threading.RLock()))


def _release_shim_key(key) -> None:
    """weakref.finalize callback: return a shim key whose object died
    uninstalled (tolerant — an explicit uninstall already released it)."""
    if SHIM_GUARD.installed(key):
        SHIM_GUARD.uninstall(key)


class TrackedLock:
    """Wraps a mutex; registers itself in the owning detector's
    per-thread held set while held.  Duck-compatible with the
    ``threading.Lock`` surface the codebase uses (acquire / release /
    context manager)."""

    def __init__(self, detector: "RaceDetector", name: str, inner):
        self._detector = detector
        self._name = name
        self._inner = inner

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._detector._held_set().add(id(self))
        return got

    def release(self) -> None:
        self._detector._held_set().discard(id(self))
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TrackedLock {self._name}>"


class _FieldState:
    __slots__ = ("state", "owner", "lockset", "reported", "last_writer")

    def __init__(self) -> None:
        self.state = _VIRGIN
        self.owner: Optional[int] = None
        self.lockset: Optional[Set[int]] = None  # None = every lock (⊤)
        self.reported = False
        self.last_writer: Optional[str] = None


_RACE_OK_CACHE: Dict[type, Set[str]] = {}


def _race_ok_fields(cls: type,
                    loader: Optional[SourceLoader] = None) -> Set[str]:
    """``# race-ok:``-annotated fields of ``cls`` (and bases), read from
    source via the shared annotation grammar; unreadable source (REPL,
    frozen) degrades to no exclusions.  Cached per class — a soak
    instruments dozens of same-class objects and the source never
    changes under it.  The file parse rides the gate's shared loader
    (one parse per file per run, not per instrumented class)."""
    cached = _RACE_OK_CACHE.get(cls)
    if cached is not None:
        return set(cached)
    loader = ensure_loader(loader)
    out: Set[str] = set()
    for klass in cls.__mro__:
        if klass is object:
            continue
        try:
            path = inspect.getfile(klass)
            pf = loader.load(path)
        except (OSError, TypeError, SyntaxError):
            continue
        # every ClassDef matching the runtime name (nested classes in
        # test files included) — a same-named sibling merely widens the
        # exclusion set, the conservative direction for a detector
        name = getattr(klass, "__name__", None)
        for cnode in ast.walk(pf.tree):
            if not (isinstance(cnode, ast.ClassDef)
                    and cnode.name == name):
                continue
            for node in ast.walk(cnode):
                # both plain and TYPE-ANNOTATED assignments carry
                # contracts (``self.x: Optional[T] = None
                # # race-ok: ...`` is an ast.AnnAssign, not Assign)
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                else:
                    continue
                end = getattr(node, "end_lineno", node.lineno)
                a = pf.annotations.on_lines(node.lineno, end,
                                            KIND_RACE_OK)
                if a is None:
                    continue
                for tgt in targets:
                    if isinstance(tgt, ast.Attribute):
                        out.add(tgt.attr)
    _RACE_OK_CACHE[cls] = set(out)
    return out


class RaceDetector:
    """One detector instance owns the traced objects, the lock registry,
    and the findings.  Thread-safe; meant to be shared by a whole fleet
    (one detector per soak process)."""

    def __init__(self, loader: Optional[SourceLoader] = None) -> None:
        self._loader = ensure_loader(loader)
        self._tls = threading.local()
        self._next_tid = iter(range(1, 1 << 62))
        self._mu = threading.Lock()
        self._fields: Dict[Tuple[int, str], _FieldState] = {}
        self._objects: Dict[int, Any] = {}   # strong refs: id() stability
        self._labels: Dict[int, str] = {}
        self._excluded: Dict[int, Set[str]] = {}
        self._traced_classes: Dict[type, type] = {}
        self._finalizers: Dict[int, "weakref.finalize"] = {}
        self.findings: List[Finding] = []

    # -- lock plumbing ------------------------------------------------------

    def _held_set(self) -> Set[int]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = set()
        return held

    def _thread_id(self) -> int:
        """A thread id NEVER reused across the detector's lifetime.
        ``threading.get_ident()`` recycles pthread ids the moment a
        thread exits, which aliases a dead thread's accesses onto a live
        one and silently keeps fields in the exclusive state — the
        classic Eraser implementation trap."""
        tid = getattr(self._tls, "tid", None)
        if tid is None:
            with self._mu:
                tid = self._tls.tid = next(self._next_tid)
        return tid

    # -- instrumentation ----------------------------------------------------

    def instrument(self, obj: Any, label: Optional[str] = None,
                   extra_exclude: Tuple[str, ...] = ()) -> Any:
        """Start tracing ``obj``: wrap its mutex attributes and swap it
        onto a tracing subclass.  Returns ``obj``.  Raises
        ``utils.guards.AlreadyInstalledError`` when ``obj`` is already
        instrumented (by this or any detector)."""
        SHIM_GUARD.install(("race-detector", id(obj)),
                           owner=type(obj).__name__)
        cls = type(obj)
        excl = set(_ALWAYS_IGNORE) | _race_ok_fields(cls, self._loader) \
            | set(extra_exclude)
        lock_names = []
        for name, value in list(obj.__dict__.items()):
            if isinstance(value, _MUTEX_TYPES):
                wrapped = TrackedLock(self, f"{cls.__name__}.{name}",
                                      value)
                object.__setattr__(obj, name, wrapped)
                excl.add(name)
                lock_names.append(name)
            elif isinstance(value, TrackedLock):
                excl.add(name)
        with self._mu:
            self._objects[id(obj)] = obj
            self._labels[id(obj)] = label or f"{cls.__name__}#{id(obj):x}"
            self._excluded[id(obj)] = excl
        traced = self._traced_class(cls)
        object.__setattr__(obj, "__class__", traced)
        # a detector dropped WITHOUT uninstall() must not pin the shim
        # key forever: id() values are recycled, so a leaked key would
        # make instrument() spuriously refuse an unrelated later object.
        # The finalizer fires when obj is collected (which implies this
        # detector released its strong ref) and returns the key.
        self._finalizers[id(obj)] = weakref.finalize(
            obj, _release_shim_key, ("race-detector", id(obj)))
        return obj

    def uninstall(self, obj: Any) -> None:
        """Stop tracing ``obj``: restore its class and raw locks.
        Refuses (KeyError, side-effect free) objects this detector never
        instrumented — demoting a live object's class first and raising
        after would corrupt it."""
        with self._mu:
            if id(obj) not in self._objects:
                raise KeyError(
                    f"{type(obj).__name__} object is not instrumented by "
                    "this detector (unbalanced uninstall)")
        traced = type(obj)
        base = traced.__bases__[0]
        object.__setattr__(obj, "__class__", base)
        for name, value in list(obj.__dict__.items()):
            if isinstance(value, TrackedLock):
                object.__setattr__(obj, name, value._inner)
        with self._mu:
            self._objects.pop(id(obj), None)
            self._excluded.pop(id(obj), None)
            fin = self._finalizers.pop(id(obj), None)
        if fin is not None:
            fin.detach()
        SHIM_GUARD.uninstall(("race-detector", id(obj)))

    def _traced_class(self, cls: type) -> type:
        cached = self._traced_classes.get(cls)
        if cached is not None:
            return cached
        detector = self

        class Traced(cls):  # type: ignore[misc, valid-type]
            def __getattribute__(self, name):
                value = object.__getattribute__(self, name)
                # trace INSTANCE fields only: a property/descriptor
                # resolves at class level and hands back a fresh value
                # AFTER its getter released any lock it took — tracing
                # that result would misread a correctly-locked property
                # returning a container as an unlocked shared write
                if name in object.__getattribute__(self, "__dict__"):
                    detector._on_access(self, name, value, is_write=False)
                return value

            def __setattr__(self, name, value):
                object.__setattr__(self, name, value)
                detector._on_access(self, name, value, is_write=True)

        Traced.__name__ = f"Traced{cls.__name__}"
        Traced.__qualname__ = Traced.__name__
        self._traced_classes[cls] = Traced
        return Traced

    # -- the Eraser state machine -------------------------------------------

    def _on_access(self, obj: Any, name: str, value: Any,
                   is_write: bool) -> None:
        if name.startswith("__") or callable(value) \
                or isinstance(value, TrackedLock):
            return
        oid = id(obj)
        excl = self._excluded.get(oid)
        if excl is None or name in excl:
            return
        # container mutation is invisible to attribute tracing: a read
        # that hands back a mutable container counts as a write
        if not is_write and isinstance(value, (set, dict, list)):
            is_write = True
        tid = self._thread_id()
        held = frozenset(self._held_set())
        with self._mu:
            fs = self._fields.setdefault((oid, name), _FieldState())
            if fs.state == _VIRGIN:
                fs.state, fs.owner = _EXCLUSIVE, tid
                return
            if fs.state == _EXCLUSIVE:
                if fs.owner == tid:
                    return
                fs.state = _SHARED_MODIFIED if is_write else _SHARED
                fs.lockset = set(held)
            else:
                if is_write and fs.state == _SHARED:
                    fs.state = _SHARED_MODIFIED
                fs.lockset = (set(held) if fs.lockset is None
                              else fs.lockset & held)
            if is_write:
                fs.last_writer = f"thread-{tid}"
            if (fs.state == _SHARED_MODIFIED and not fs.lockset
                    and not fs.reported):
                fs.reported = True
                label = self._labels.get(oid, "?")
                self.findings.append(Finding(
                    analyzer="locksets", code=RACE_EMPTY_LOCKSET,
                    severity=SEVERITY_ERROR,
                    symbol=f"{type(obj).__bases__[0].__name__}.{name}",
                    message=(f"empty lockset on shared field {name!r} of "
                             f"{label}: a write is ordered by no common "
                             "lock (guard it, or annotate '# race-ok: "
                             "<reason>' with the safety argument)")))

    # -- results ------------------------------------------------------------

    def stats(self) -> Dict:
        with self._mu:
            states: Dict[str, int] = {}
            for fs in self._fields.values():
                key = _STATE_NAMES[fs.state]
                states[key] = states.get(key, 0) + 1
            return {
                "objects_traced": len(self._objects),
                "fields_tracked": len(self._fields),
                "field_states": states,
                "races": len(self.findings),
            }

    def race_summaries(self) -> List[str]:
        return [f.render() for f in self.findings]
