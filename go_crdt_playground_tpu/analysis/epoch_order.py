"""E001 — epoch-persist-before-announce ordering lint (DESIGN.md §26).

Every fenced-epoch protocol in the tree (router HA §22, shard
replication §23, keyspace handoff §18) rests on the same two-step
contract: the claimed epoch is DURABLE before any other member can
hear it.  Persist-then-announce is what makes a crash mid-promotion
re-promote at an equal-or-higher epoch instead of resurrecting a
lower one; swapping the two steps is precisely the bug class that
cost the PR-13/14 hand-review rounds (and that the protomodel
explorer demonstrates ends in two writers on one epoch).

This pass extends ``durability.py``'s source-order dominance machinery
from fsync/rename pairs to REGISTERED ordered call pairs: for each
``OrderSpec``, every call to an ``after`` name inside the named
function must be preceded — earlier source line, same function — by a
call to a ``before`` name.  The approximation is the same one D001
documents: these promotion paths are straight-line persist-then-act
sequences where source order and execution order agree; exotic control
flow belongs in review (and in the model checker), not in this lint.

A registered function that has disappeared (renamed, refactored away)
is itself an E001 finding — an ordering contract silently un-checked
is exactly the drift this ladder exists to catch.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (EPOCH_ORDER,
                                                    SEVERITY_ERROR, Finding)


class OrderSpec(NamedTuple):
    """One ordered-call-pair contract: inside ``path``:``qualname``,
    every call to a name in ``after`` must be dominated (earlier
    source line) by a call to a name in ``before``."""

    name: str                 # short label for findings/stats
    path: str                 # package-relative file
    qualname: str             # "Class.method" or module-level "fn"
    before: Tuple[str, ...]   # trailing callee names that persist
    after: Tuple[str, ...]    # trailing callee names that announce/act


# THE registry (DESIGN.md §26): the persist→announce spine of each
# fenced-epoch protocol.  ``before`` names are trailing callee names
# (``persist_router_epoch(...)`` however it is imported), so a rename
# of the persistence helper fails loud (function-missing arm) rather
# than silently matching nothing.
ORDER_SPECS: Tuple[OrderSpec, ...] = (
    # router HA promotion (§22): durable router epoch before the
    # announce fan-out, the deposition notice, and the listener bind
    OrderSpec("router-ha-promote", "shard/ha.py",
              "RouterStandby._promote_locked",
              before=("persist_router_epoch",),
              after=("announce_epoch", "ring_sync", "serve")),
    # shard replication failover (§23): durable shard epoch before the
    # frontend claim, the router announce, the old-primary deposition,
    # and serving
    OrderSpec("shard-repl-promote", "shard/replica.py",
              "ShardStandby._promote_locked",
              before=("persist_shard_epoch",),
              after=("claim_shard_epoch", "_announce_router", "wal_sync",
                     "serve")),
    # the router's adjudication half of the same protocol: the epoch
    # map persists before the link swap and the roster rewrite
    OrderSpec("router-failover-adjudicate", "shard/router.py",
              "ShardRouter.failover_shard",
              before=("persist_shard_epochs",),
              after=("_new_link", "_persist_addr_roster")),
    # keyspace handoff (§18): the COMMITTED record persists before the
    # atomic in-memory route swap (a crash between the two restarts
    # onto the persisted new ring; swapping them can report "aborted"
    # for a ring that irreversibly swapped)
    OrderSpec("handoff-commit", "shard/handoff.py",
              "HandoffCoordinator._run",
              before=("_persist",),
              after=("commit_route",)),
)


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


def _find_function(tree: ast.Module, qualname: str
                   ) -> Optional[ast.FunctionDef]:
    if "." in qualname:
        cls_name, meth = qualname.split(".", 1)
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls_name:
                for sub in node.body:
                    if (isinstance(sub, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                            and sub.name == meth):
                        return sub
        return None
    for node in tree.body:
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == qualname):
            return node
    return None


def check_spec(spec: OrderSpec, tree: ast.Module, path: str
               ) -> Tuple[List[Finding], int]:
    """Findings plus the number of dominance points checked."""
    findings: List[Finding] = []
    fn = _find_function(tree, spec.qualname)
    if fn is None:
        findings.append(Finding(
            analyzer="epoch_order", code=EPOCH_ORDER,
            severity=SEVERITY_ERROR, path=path, symbol=spec.qualname,
            message=(f"registered ordering contract {spec.name!r} names "
                     f"{spec.qualname}, which no longer exists in "
                     f"{spec.path} — re-register the contract on the "
                     "renamed promotion path (an un-checked persist→"
                     "announce ordering is silent drift)")))
        return findings, 0
    persist_lines: List[int] = []
    act_sites: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in spec.before:
            persist_lines.append(node.lineno)
        elif name in spec.after:
            act_sites.append((node.lineno, name))
    if not persist_lines:
        findings.append(Finding(
            analyzer="epoch_order", code=EPOCH_ORDER,
            severity=SEVERITY_ERROR, path=path, line=fn.lineno,
            symbol=spec.qualname,
            message=(f"{spec.qualname} contains no call to any of "
                     f"{sorted(spec.before)} — the {spec.name} protocol "
                     "acts on an epoch that was never persisted")))
    checked = 0
    for line, name in sorted(act_sites):
        checked += 1
        if not any(p < line for p in persist_lines):
            findings.append(Finding(
                analyzer="epoch_order", code=EPOCH_ORDER,
                severity=SEVERITY_ERROR, path=path, line=line,
                symbol=f"{spec.qualname}:{name}",
                message=(f"{name}() at line {line} is not dominated by "
                         f"any of {sorted(spec.before)} in "
                         f"{spec.qualname}: the {spec.name} protocol "
                         "announces/acts on an epoch before it is "
                         "durable — a crash here resurrects a lower "
                         "epoch and two writers can share one "
                         "adjudicated epoch")))
    return findings, checked


def analyze(root: str,
            specs: Sequence[OrderSpec] = ORDER_SPECS,
            loader: Optional[SourceLoader] = None,
            sources: Optional[Dict[str, str]] = None
            ) -> Tuple[List[Finding], Dict]:
    """Check every registered ordering contract.  ``specs`` and
    ``sources`` (path -> planted text) are injectable so tests can
    plant a swapped persist/announce twin — a gate that cannot fail
    proves nothing."""
    loader = ensure_loader(loader)
    findings: List[Finding] = []
    checked = 0
    for spec in specs:
        path = os.path.join(root, spec.path)
        planted = (sources or {}).get(spec.path)
        tree = loader.load(path, planted).tree
        f, n = check_spec(spec, tree, path)
        findings.extend(f)
        checked += n
    stats = {"specs": len(specs), "ordered_points": checked,
             "spec_names": sorted(s.name for s in specs)}
    return findings, stats
