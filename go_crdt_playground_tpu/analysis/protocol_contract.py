"""Wire-contract passes W001/W002/W004 (DESIGN.md §15).

The serve dialect grew by hand across five PRs — msg types 16..33, six
reject codes, per-verb frame caps — and none of it was machine-checked:
a missed dispatch arm, an unregistered reject code, or a bare
``recv_frame`` only ever surfaced (if ever) in a slow soak.  These
passes make the contract gate-time:

* **W001 dispatch exhaustiveness** — every ``MSG_*`` constant of a
  dialect module must have a handler arm in every registered server
  dispatcher, or carry an explicit ``# protocol-ignore`` annotation
  (definition-scoped ``reply``/``internal`` direction, or a
  dispatcher-scoped exclusion with the constant's name).  Constants
  marked ``reply`` must instead have an arm in the registered CLIENT
  reader — the reciprocal check, so a new reply verb cannot land
  half-wired.  Each dispatcher must also keep its typed unknown-frame
  fallthrough (the ``MSG_ERROR`` reply / ``ProtocolError`` close).
* **W002 reject-code discipline** — ``REJECT_EXCEPTIONS`` and
  ``REJECT_CODES`` must be exact inverses over distinct typed
  ``ServeError`` subclasses, every ``REJECT_*`` integer constant must
  be registered, every ``ServeError`` subclass must be mapped (a typed
  exception no code can produce is dead wire surface), and no
  ``encode_reject`` call site may pass a bare numeric literal — named
  registered constants only (dynamic relay variables are allowed; the
  encoder's own ``ValueError`` is the runtime backstop).
* **W004 frame-cap discipline** — every ``framing.recv_frame`` call
  site in the package must pass an explicit ``max_body`` (the 1MB DoS
  bound PR 7 made per-verb; a bare read silently inherits the 1GB
  peer-payload ceiling).  Call-site resolution is import-aware, so
  ``bridge/service.py``'s own struct-framed ``recv_frame`` is not
  confused with the armored one.

All entry points take explicit file/dispatcher arguments so the tests
can plant violations (a gate that cannot fail proves nothing).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

from go_crdt_playground_tpu.analysis.annotations import \
    KIND_PROTOCOL_IGNORE
from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (DISPATCH_HOLE,
                                                    FRAME_CAP_MISSING,
                                                    REJECT_UNDISCIPLINED,
                                                    SEVERITY_ERROR, Finding)

# definition-scoped direction keywords (# protocol-ignore: <kw> — why)
DIR_REPLY = "reply"        # client-inbound: armed in the client reader
DIR_INTERNAL = "internal"  # consumed below dispatch (e.g. MSG_ERROR)


class DispatcherSpec(NamedTuple):
    """One registered frame dispatcher.

    ``path`` is package-relative; ``qualname`` is ``Class.method``;
    ``dialects`` the package-relative wire modules whose ``MSG_*``
    constants this dispatcher must cover; ``role`` is ``server``
    (covers non-ignored constants) or ``client`` (covers the
    ``reply``-annotated ones); ``fallthrough`` names the symbol the
    typed unknown-frame path must reference (``MSG_ERROR`` for servers,
    ``ProtocolError`` for the client reader)."""

    name: str
    path: str
    qualname: str
    dialects: Tuple[str, ...]
    role: str
    fallthrough: str


# THE registry (DESIGN.md §15): every serve/peer-dialect frame reader.
DISPATCHERS: Tuple[DispatcherSpec, ...] = (
    DispatcherSpec("frontend", "serve/frontend.py",
                   "ServeFrontend._dispatch", ("serve/protocol.py",),
                   "server", "MSG_ERROR"),
    DispatcherSpec("router", "shard/router.py",
                   "ShardRouter._dispatch", ("serve/protocol.py",),
                   "server", "MSG_ERROR"),
    DispatcherSpec("peer", "net/peer.py",
                   "Node._serve_conn", ("net/framing.py",),
                   "server", "MSG_ERROR"),
    DispatcherSpec("serve-client", "serve/client.py",
                   "ServeClient._read_loop", ("serve/protocol.py",),
                   "client", "ProtocolError"),
)


# ---------------------------------------------------------------------------
# W001: dispatch exhaustiveness
# ---------------------------------------------------------------------------


class _DialectInfo(NamedTuple):
    constants: Dict[str, int]            # MSG_* name -> def line
    ignored: Dict[str, Tuple[str, str]]  # name -> (direction, reason)
    malformed: List[str]


def _load_dialect(path: str, loader: Optional[SourceLoader] = None
                  ) -> _DialectInfo:
    pf = ensure_loader(loader).load(path)
    tree = pf.tree
    consts: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.startswith("MSG_"):
                    consts[t.id] = node.lineno
    anns = pf.annotations
    ignored: Dict[str, Tuple[str, str]] = {}
    malformed = list(anns.malformed)
    for ann in anns.every:
        if ann.kind != KIND_PROTOCOL_IGNORE:
            continue
        owners = [n for n, ln in consts.items() if ln == ann.line]
        if not owners:
            continue  # an in-function annotation; dispatcher-scoped
        parts = (ann.arg or "").split(None, 1)
        direction = parts[0].rstrip(":—-") if parts else ""
        reason = parts[1].strip(" —-:") if len(parts) > 1 else ""
        if direction not in (DIR_REPLY, DIR_INTERNAL) or not reason:
            malformed.append(
                f"{path}:{ann.line}: definition-scoped protocol-ignore "
                f"must read '# protocol-ignore: reply|internal — "
                f"<reason>', got {ann.arg!r}")
            continue
        for name in owners:
            ignored[name] = (direction, reason)
    return _DialectInfo(consts, ignored, malformed)


def _find_function(tree: ast.Module, qualname: str
                   ) -> Optional[ast.FunctionDef]:
    cls_name, meth = qualname.split(".", 1)
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for sub in node.body:
                if (isinstance(sub, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))
                        and sub.name == meth):
                    return sub
    return None


def _compared_msg_names(fn: ast.AST) -> set:
    """MSG_* names that appear inside a comparison in ``fn`` — the
    dispatcher's handler arms (``msg_type == protocol.MSG_OP``,
    ``msg_type != MSG_HELLO``, membership tests)."""
    out: set = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id.startswith("MSG_"):
                out.add(sub.id)
            elif (isinstance(sub, ast.Attribute)
                  and sub.attr.startswith("MSG_")):
                out.add(sub.attr)
    return out


def _references_symbol(fn: ast.AST, symbol: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == symbol:
            return True
        if isinstance(node, ast.Attribute) and node.attr == symbol:
            return True
    return False


def check_dispatchers(root: str,
                      dispatchers: Iterable[DispatcherSpec] = DISPATCHERS,
                      loader: Optional[SourceLoader] = None
                      ) -> Tuple[List[Finding], Dict]:
    loader = ensure_loader(loader)
    findings: List[Finding] = []
    stats: Dict = {"dispatchers": {}}
    dialect_cache: Dict[str, _DialectInfo] = {}

    def dialect(rel: str) -> _DialectInfo:
        if rel not in dialect_cache:
            dialect_cache[rel] = _load_dialect(os.path.join(root, rel),
                                               loader)
        return dialect_cache[rel]

    for spec in dispatchers:
        path = os.path.join(root, spec.path)
        pf = loader.load(path)
        tree = pf.tree
        fn = _find_function(tree, spec.qualname)
        if fn is None:
            findings.append(Finding(
                analyzer="protocol_contract", code=DISPATCH_HOLE,
                severity=SEVERITY_ERROR, path=path,
                symbol=spec.qualname,
                message=f"registered dispatcher {spec.qualname} not "
                        f"found in {spec.path}"))
            continue
        handled = _compared_msg_names(fn)
        # dispatcher-scoped ignores: protocol-ignore annotations whose
        # line falls inside the function span, first token = MSG_*
        anns = pf.annotations
        local_ignored: Dict[str, str] = {}
        constants: Dict[str, int] = {}
        ignored_global: Dict[str, Tuple[str, str]] = {}
        malformed: List[str] = []
        for rel in spec.dialects:
            info = dialect(rel)
            constants.update(info.constants)
            ignored_global.update(info.ignored)
            malformed.extend(info.malformed)
        for ann in anns.every:
            if (ann.kind != KIND_PROTOCOL_IGNORE
                    or not fn.lineno <= ann.line <= fn.end_lineno):
                continue
            parts = (ann.arg or "").split(None, 1)
            name = parts[0].rstrip(":—-") if parts else ""
            reason = parts[1].strip(" —-:") if len(parts) > 1 else ""
            if name not in constants or not reason:
                findings.append(Finding(
                    analyzer="protocol_contract", code=DISPATCH_HOLE,
                    severity=SEVERITY_ERROR, path=path, line=ann.line,
                    symbol=spec.name,
                    message=f"dispatcher protocol-ignore must name a "
                            f"dialect MSG_* constant with a reason, "
                            f"got {ann.arg!r}"))
                continue
            if name in handled:
                findings.append(Finding(
                    analyzer="protocol_contract", code=DISPATCH_HOLE,
                    severity=SEVERITY_ERROR, path=path, line=ann.line,
                    symbol=spec.name,
                    message=f"stale protocol-ignore: {name} HAS a "
                            f"handler arm in {spec.qualname} — drop "
                            "the annotation or the arm"))
                continue
            local_ignored[name] = reason
        if spec.role == "server":
            required = [n for n in constants if n not in ignored_global
                        and n not in local_ignored]
        else:
            required = [n for n, (d, _) in ignored_global.items()
                        if d == DIR_REPLY and n not in local_ignored]
        missing = sorted(n for n in required if n not in handled)
        for name in missing:
            findings.append(Finding(
                analyzer="protocol_contract", code=DISPATCH_HOLE,
                severity=SEVERITY_ERROR, path=path, line=fn.lineno,
                symbol=f"{spec.name}:{name}",
                message=f"{spec.qualname} has no handler arm for "
                        f"{name} and no protocol-ignore annotation — "
                        "a frame of this type hits the unknown-frame "
                        "fallthrough (or worse, a stale arm)"))
        if not _references_symbol(fn, spec.fallthrough):
            findings.append(Finding(
                analyzer="protocol_contract", code=DISPATCH_HOLE,
                severity=SEVERITY_ERROR, path=path, line=fn.lineno,
                symbol=spec.name,
                message=f"{spec.qualname} lost its typed unknown-frame "
                        f"fallthrough (no {spec.fallthrough} "
                        "reference): an unexpected frame must be "
                        "answered typed, never silently dropped"))
        stats["dispatchers"][spec.name] = {
            "role": spec.role,
            "required": sorted(required),
            "handled": sorted(handled & set(constants)),
            "ignored": sorted(local_ignored),
        }
        for msg in malformed:
            findings.append(Finding(
                analyzer="protocol_contract", code=DISPATCH_HOLE,
                severity=SEVERITY_ERROR, message=msg))
        # malformed dialect annotations are reported once per gate run
        for rel in spec.dialects:
            dialect_cache[rel] = dialect_cache[rel]._replace(malformed=[])
    # NOT "constants": check_reject_registry's stats carry an integer
    # count under that name, and analyze() merges both dicts
    stats["dialect_constants"] = {
        rel: sorted(info.constants) for rel, info in dialect_cache.items()}
    return findings, stats


# ---------------------------------------------------------------------------
# W002: reject-code discipline
# ---------------------------------------------------------------------------


def check_reject_registry() -> Tuple[List[Finding], Dict]:
    """Runtime half: the REJECT_EXCEPTIONS/REJECT_CODES bijection over
    distinct typed ServeError subclasses, with every REJECT_* integer
    constant registered and every ServeError subclass mapped."""
    import inspect

    from go_crdt_playground_tpu.serve import protocol

    findings: List[Finding] = []
    path = inspect.getfile(protocol)

    def err(msg: str, symbol: Optional[str] = None) -> None:
        findings.append(Finding(
            analyzer="protocol_contract", code=REJECT_UNDISCIPLINED,
            severity=SEVERITY_ERROR, path=path, symbol=symbol,
            message=msg))

    exc_map = protocol.REJECT_EXCEPTIONS
    seen_excs = set()
    for code, exc in exc_map.items():
        if not isinstance(code, int):
            err(f"REJECT_EXCEPTIONS key {code!r} is not an int")
            continue
        if not (isinstance(exc, type)
                and issubclass(exc, protocol.ServeError)):
            err(f"REJECT_EXCEPTIONS[{code}] = {exc!r} is not a typed "
                "ServeError subclass", symbol=str(code))
            continue
        if exc in seen_excs:
            err(f"exception {exc.__name__} mapped by two reject codes "
                "— the client cannot classify the shed", exc.__name__)
        seen_excs.add(exc)
    inverse = {exc: code for code, exc in exc_map.items()}
    if protocol.REJECT_CODES != inverse:
        err("REJECT_CODES is not the exact inverse of "
            "REJECT_EXCEPTIONS — the router's relay direction would "
            "re-encode a different code than the shard sent")
    n_consts = 0
    for name in dir(protocol):
        if not name.startswith("REJECT_") or name in (
                "REJECT_EXCEPTIONS", "REJECT_CODES"):
            continue
        val = getattr(protocol, name)
        if isinstance(val, int):
            n_consts += 1
            if val not in exc_map:
                err(f"reject code {name}={val} is not registered in "
                    "REJECT_EXCEPTIONS — a frontend can send a code "
                    "the client decodes as a protocol error", name)
    n_subclasses = 0
    for name in dir(protocol):
        obj = getattr(protocol, name)
        if (isinstance(obj, type) and issubclass(obj, protocol.ServeError)
                and obj is not protocol.ServeError):
            n_subclasses += 1
            if obj not in inverse:
                err(f"typed exception {name} has no reject code — no "
                    "wire frame can ever produce it", name)
    return findings, {"codes": len(exc_map), "constants": n_consts,
                      "exception_classes": n_subclasses}


def check_reject_call_sites(paths: Iterable[str],
                            loader: Optional[SourceLoader] = None
                            ) -> Tuple[List[Finding], Dict]:
    """Static half: every ``encode_reject`` call site passes a NAMED
    registered code (bare numeric literals drift silently when codes
    renumber; unknown ``REJECT_*`` names are typos the encoder would
    only catch at serve time)."""
    from go_crdt_playground_tpu.serve import protocol

    registered = {name for name in dir(protocol)
                  if name.startswith("REJECT_")
                  and isinstance(getattr(protocol, name), int)}
    loader = ensure_loader(loader)
    findings: List[Finding] = []
    n_sites = 0
    for path in paths:
        tree = loader.load(path).tree
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = (node.func.attr if isinstance(node.func, ast.Attribute)
                     else node.func.id if isinstance(node.func, ast.Name)
                     else None)
            if fname != "encode_reject":
                continue
            n_sites += 1
            # the code may ride positionally or as code=... — a
            # keyword-form literal must not slip past the lint
            code_arg = (node.args[1] if len(node.args) >= 2
                        else next((kw.value for kw in node.keywords
                                   if kw.arg == "code"), None))
            if code_arg is None:
                continue
            if isinstance(code_arg, ast.Constant):
                findings.append(Finding(
                    analyzer="protocol_contract",
                    code=REJECT_UNDISCIPLINED, severity=SEVERITY_ERROR,
                    path=path, line=node.lineno,
                    message=f"encode_reject called with bare literal "
                            f"{code_arg.value!r} — use a registered "
                            "REJECT_* constant"))
            else:
                name = (code_arg.attr
                        if isinstance(code_arg, ast.Attribute)
                        else code_arg.id
                        if isinstance(code_arg, ast.Name) else None)
                if (name is not None and name.startswith("REJECT_")
                        and name not in registered):
                    findings.append(Finding(
                        analyzer="protocol_contract",
                        code=REJECT_UNDISCIPLINED,
                        severity=SEVERITY_ERROR, path=path,
                        line=node.lineno,
                        message=f"encode_reject called with "
                                f"unregistered code name {name}"))
    return findings, {"reject_sites": n_sites}


# ---------------------------------------------------------------------------
# W004: frame-cap discipline
# ---------------------------------------------------------------------------


def _framing_recv_aliases(tree: ast.Module) -> Tuple[set, set]:
    """(module_aliases, direct_names) under which this file can reach
    ``net.framing.recv_frame`` — import-aware so a module defining its
    OWN recv_frame (bridge/service.py) is never misattributed.
    Relative forms count too (``from ..net import framing``,
    ``from .framing import recv_frame``): the match is on the LAST
    module-path segment, so a refactor to relative imports cannot
    silently exempt a file from the pass."""
    mod_aliases: set = set()
    direct: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            last = (node.module or "").split(".")[-1]
            if last == "framing":
                for a in node.names:
                    if a.name == "recv_frame":
                        direct.add(a.asname or a.name)
            elif last == "net":
                for a in node.names:
                    if a.name == "framing":
                        mod_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("net.framing"):
                    mod_aliases.add((a.asname or a.name).split(".")[0])
    return mod_aliases, direct


def check_frame_caps(paths: Iterable[str],
                     loader: Optional[SourceLoader] = None
                     ) -> Tuple[List[Finding], Dict]:
    loader = ensure_loader(loader)
    findings: List[Finding] = []
    n_sites = 0
    for path in paths:
        tree = loader.load(path).tree
        mod_aliases, direct = _framing_recv_aliases(tree)
        if not mod_aliases and not direct:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            is_target = False
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "recv_frame"):
                base = node.func.value
                if isinstance(base, ast.Name) and base.id in mod_aliases:
                    is_target = True
                elif (isinstance(base, ast.Attribute)
                      and base.attr == "framing"):
                    # fully-dotted chain (pkg.net.framing.recv_frame)
                    is_target = True
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in direct):
                is_target = True
            if not is_target:
                continue
            n_sites += 1
            explicit = (len(node.args) >= 3
                        or any(kw.arg == "max_body"
                               for kw in node.keywords))
            if not explicit:
                findings.append(Finding(
                    analyzer="protocol_contract", code=FRAME_CAP_MISSING,
                    severity=SEVERITY_ERROR, path=path, line=node.lineno,
                    message="recv_frame without an explicit max_body "
                            "inherits the 1GB peer-payload ceiling — "
                            "pass the dialect's cap (the per-verb DoS "
                            "bound, DESIGN.md §16/§18)"))
    return findings, {"recv_frame_sites": n_sites}


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def analyze(root: str, loader: Optional[SourceLoader] = None
            ) -> Tuple[List[Finding], Dict]:
    """Run all three passes over the installed package at ``root``."""
    loader = ensure_loader(loader)
    findings, stats = check_dispatchers(root, loader=loader)
    py_files = []
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if fn.endswith(".py"):
                py_files.append(os.path.join(dirpath, fn))
    py_files.sort()
    f2, s2 = check_reject_registry()
    findings.extend(f2)
    f3, s3 = check_reject_call_sites(py_files, loader=loader)
    findings.extend(f3)
    f4, s4 = check_frame_caps(py_files, loader=loader)
    findings.extend(f4)
    stats.update(s2)
    stats.update(s3)
    stats.update(s4)
    stats["files_scanned"] = len(py_files)
    return findings, stats
