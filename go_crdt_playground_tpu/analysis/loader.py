"""Shared source/AST/annotation cache for the analysis gate.

Before this module, every pass opened, tokenized, and ``ast.parse``d
its target files independently — the runtime files the gate cares most
about (``serve/frontend.py``, ``shard/router.py``, ``net/peer.py``) are
each parsed by four to six passes per run.  ``SourceLoader`` does each
parse ONCE per gate run and hands every pass the same ``ParsedFile``
(source text + module AST + the parsed annotation set); the gate
records the hit/miss counts in ``ANALYSIS_REPORT.json`` (``meta.
parse_cache``) so the win is adjudicated, not claimed.

Two deliberate properties:

* **Planted sources bypass the cache.**  Tests drive passes with
  ``analyze_file("<planted>", source=...)`` — same fake path, different
  source per test.  A ``load(path, source=...)`` call parses exactly
  what it was given and caches nothing, so a cached twin can never mask
  a planted violation.
* **The cache is per-run, not per-process.**  ``build_report`` creates
  one loader per gate run; a long-lived test process that edits files
  between runs never sees stale trees.  Passes called WITHOUT a loader
  (unit tests, ad-hoc use) construct a private one — the default is
  correctness, the shared instance is the optimization.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, NamedTuple, Optional

from go_crdt_playground_tpu.analysis.annotations import (AnnotationSet,
                                                         parse_annotations)


class ParsedFile(NamedTuple):
    path: str
    source: str
    tree: ast.Module
    annotations: AnnotationSet


class SourceLoader:
    """One gate run's parse cache, keyed by absolute path."""

    def __init__(self) -> None:
        self._cache: Dict[str, ParsedFile] = {}
        self.hits = 0
        self.misses = 0

    def load(self, path: str, source: Optional[str] = None) -> ParsedFile:
        """The parsed form of ``path``.  With ``source`` given, parse
        THAT text (planted-source test path) and skip the cache in both
        directions."""
        if source is not None:
            return ParsedFile(path, source,
                              ast.parse(source, filename=path),
                              parse_annotations(source, path))
        key = os.path.abspath(path)
        hit = self._cache.get(key)
        if hit is not None:
            self.hits += 1
            return hit
        self.misses += 1
        with open(path) as f:
            text = f.read()
        pf = ParsedFile(path, text, ast.parse(text, filename=path),
                        parse_annotations(text, path))
        self._cache[key] = pf
        return pf

    def stats(self) -> Dict[str, int]:
        return {"files": len(self._cache), "hits": self.hits,
                "misses": self.misses}


def ensure_loader(loader: Optional[SourceLoader]) -> SourceLoader:
    """The pass-side entry point: share the gate's loader when given
    one, else a private single-use cache (same semantics, no sharing)."""
    return loader if loader is not None else SourceLoader()
