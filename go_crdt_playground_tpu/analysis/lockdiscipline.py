"""AST lock-discipline lint: ``# guarded-by:`` enforcement + lock-order
cycles.

What it checks (grammar in ``analysis.annotations``; DESIGN.md §15):

* **L001 — unguarded access.**  A field annotated ``# guarded-by: L`` on
  its ``__init__`` assignment may only be read or written while ``L`` is
  held: lexically inside ``with self.L:`` (or ``with <var>.L:`` for the
  same object via another name), or inside a method annotated
  ``# requires-lock: L`` (whose call sites are then checked instead).
  ``__init__`` holds every lock implicitly — the object is not shared
  yet.  Writes through OTHER names (``node._state = ...`` in a
  classmethod constructor) are checked against a global registry of
  guarded fields, so alternate-constructor mutation is not a blind spot.
* **L002 — lock-order cycle.**  Every observed "holding A, acquire B"
  pair (lexical ``with`` nesting, plus calls into methods whose
  summaries say they acquire) becomes an edge ``A → B`` in a
  class-qualified lock graph; any cycle is an ERROR (two threads taking
  the locks in opposite orders can deadlock).
* **L003 — inconsistently locked.**  A field accessed at least once
  inside an explicit ``with``-lock block and at least once outside,
  with no ``guarded-by``/``race-ok`` annotation: either the annotation
  or one of the accesses is missing.

This is a LINT, not a verifier: aliasing beyond simple names, locks
passed across objects, and dynamic dispatch are out of scope — the
runtime lockset detector (``analysis.locksets``) covers the dynamic
residue.  Severities: L001/L002 error, L003 error (the tree is kept
clean; silence it per-field with ``# race-ok: <reason>``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from go_crdt_playground_tpu.analysis.annotations import (
    KIND_GUARDED_BY, KIND_RACE_OK, KIND_REQUIRES_LOCK, AnnotationSet)
from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (LOCK_ORDER_CYCLE,
                                                    SEVERITY_ERROR,
                                                    UNANNOTATED_SHARED,
                                                    UNGUARDED_ACCESS, Finding)

# threading constructors whose instance attributes count as locks
_LOCK_CTORS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore",
               "Condition"}
# lock types that provide mutual exclusion (semaphores with capacity > 1
# do not, but for guarded-by purposes holding the with-block is still
# the declared discipline, so all count here)


@dataclass
class ClassModel:
    """One class's lock contract, extracted from source + annotations."""

    name: str
    path: str
    locks: Set[str] = field(default_factory=set)
    guarded: Dict[str, str] = field(default_factory=dict)   # field -> lock
    race_ok: Set[str] = field(default_factory=set)
    requires: Dict[str, str] = field(default_factory=dict)  # method -> lock
    methods: Set[str] = field(default_factory=set)
    # method -> self-locks it may acquire (with-blocks, transitive
    # through same-class self-calls); feeds cross-class lock-order edges
    acquires: Dict[str, Set[str]] = field(default_factory=dict)


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``self.wal.seal`` -> ["self", "wal", "seal"]; None when the base
    is not a simple name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _with_lock_target(item: ast.withitem) -> Optional[Tuple[str, str]]:
    """``with <base>.<lock>:`` -> (base, lock)."""
    chain = _attr_chain(item.context_expr)
    if chain is not None and len(chain) == 2:
        return chain[0], chain[1]
    return None


def build_class_models(tree: ast.Module, annots: AnnotationSet,
                       path: str) -> Dict[str, ClassModel]:
    models: Dict[str, ClassModel] = {}
    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        model = ClassModel(name=cls.name, path=path)
        for meth in [n for n in cls.body
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]:
            model.methods.add(meth.name)
            req = annots.on_lines(meth.lineno, meth.body[0].lineno - 1,
                                  KIND_REQUIRES_LOCK)
            if req is not None:
                model.requires[meth.name] = req.arg
            for node in ast.walk(meth):
                # plain AND type-annotated assignments (``self.x: T = v``
                # is an ast.AnnAssign) both declare fields and carry
                # annotations
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign):
                    targets, value = [node.target], node.value
                else:
                    continue
                for tgt in targets:
                    chain = _attr_chain(tgt)
                    if chain is None or len(chain) != 2 \
                            or chain[0] != "self":
                        continue
                    fname = chain[1]
                    if meth.name == "__init__" \
                            and isinstance(value, ast.Call):
                        ctor = _attr_chain(value.func)
                        if ctor and ctor[-1] in _LOCK_CTORS:
                            model.locks.add(fname)
                    end = getattr(node, "end_lineno", node.lineno)
                    g = annots.on_lines(node.lineno, end, KIND_GUARDED_BY)
                    if g is not None:
                        model.guarded[fname] = g.arg
                    r = annots.on_lines(node.lineno, end, KIND_RACE_OK)
                    if r is not None:
                        model.race_ok.add(fname)
        # direct lock acquisitions per method, then one transitive pass
        # through same-class self-calls (depth is tiny in practice)
        direct: Dict[str, Set[str]] = {}
        calls: Dict[str, Set[str]] = {}
        for meth in [n for n in cls.body if isinstance(n, ast.FunctionDef)]:
            acq: Set[str] = set()
            callees: Set[str] = set()
            for node in ast.walk(meth):
                if isinstance(node, ast.With):
                    for item in node.items:
                        t = _with_lock_target(item)
                        if t and t[0] == "self" and t[1] in model.locks:
                            acq.add(t[1])
                if isinstance(node, ast.Call):
                    chain = _attr_chain(node.func)
                    if chain and len(chain) == 2 and chain[0] == "self":
                        callees.add(chain[1])
            direct[meth.name] = acq
            calls[meth.name] = callees
        changed = True
        while changed:
            changed = False
            for m, callees in calls.items():
                for c in callees:
                    extra = direct.get(c, set()) - direct[m]
                    if extra:
                        direct[m] |= extra
                        changed = True
        model.acquires = direct
        models[cls.name] = model
    return models


class _MethodLinter(ast.NodeVisitor):
    """Walks one method body tracking lexically-held locks."""

    def __init__(self, lint: "LockLint", model: ClassModel,
                 method: ast.FunctionDef):
        self.lint = lint
        self.model = model
        self.method = method
        # held locks as (base_name, lock_name); __init__ holds all of
        # self's locks implicitly (pre-sharing), and a requires-lock
        # method holds its declared lock.  Implicit holds satisfy
        # guarded-by checks but do NOT create lock-order edges — nothing
        # is actually acquired.
        self.held: Set[Tuple[str, str]] = set()
        self.implicit: Set[Tuple[str, str]] = set()
        if method.name == "__init__":
            self.implicit |= {("self", lk) for lk in model.locks}
        req = model.requires.get(method.name)
        if req is not None:
            self.implicit.add(("self", req))
        self.held |= self.implicit

    # -- lock tracking -----------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        entered: List[Tuple[str, str]] = []
        for item in node.items:
            t = _with_lock_target(item)
            if t is not None and self.lint.is_lock_name(t[1]):
                self.lint.note_acquisition(self.model,
                                           self.held - self.implicit, t)
                if t not in self.held:
                    self.held.add(t)
                    entered.append(t)
            # non-lock with-items (files, sockets) still get visited
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for t in entered:
            self.held.discard(t)

    # -- access checking ---------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        chain = _attr_chain(node)
        if chain is not None and len(chain) >= 2:
            base, fname = chain[0], chain[1]
            is_store = isinstance(node.ctx, (ast.Store, ast.Del))
            if base == "self":
                self._check_self_access(node, fname, is_store)
            elif is_store:
                self._check_foreign_store(node, base, fname)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func)
        if chain is not None and len(chain) == 2 and chain[0] == "self":
            callee = chain[1]
            req = self.model.requires.get(callee)
            if req is not None and ("self", req) not in self.held:
                self.lint.findings.append(Finding(
                    analyzer="lockdiscipline", code=UNGUARDED_ACCESS,
                    severity=SEVERITY_ERROR, path=self.model.path,
                    line=node.lineno,
                    symbol=f"{self.model.name}.{callee}",
                    message=(f"call to requires-lock method {callee!r} "
                             f"without holding self.{req} "
                             f"(in {self.method.name})")))
            # cross-class acquisition edges: self.<attr>.<meth>() — or
            # <localname>.<meth>() for hinted names — where the target
            # class summary says <meth> acquires
        explicit = self.held - self.implicit
        if chain is not None and explicit:
            if len(chain) == 3 and chain[0] == "self":
                self.lint.note_call_edges(self.model, explicit,
                                          chain[1], chain[2], node.lineno)
            elif len(chain) == 2 and chain[0] in self.lint.attr_classes:
                self.lint.note_call_edges(self.model, explicit,
                                          chain[0], chain[1], node.lineno)
        self.generic_visit(node)

    def _check_self_access(self, node: ast.Attribute, fname: str,
                           is_store: bool) -> None:
        lock = self.model.guarded.get(fname)
        if lock is not None:
            if ("self", lock) not in self.held:
                what = "write" if is_store else "read"
                self.lint.findings.append(Finding(
                    analyzer="lockdiscipline", code=UNGUARDED_ACCESS,
                    severity=SEVERITY_ERROR, path=self.model.path,
                    line=node.lineno,
                    symbol=f"{self.model.name}.{fname}",
                    message=(f"{what} of guarded field {fname!r} without "
                             f"holding self.{lock} "
                             f"(in {self.method.name})")))
            return
        if fname in self.model.race_ok or fname in self.model.locks \
                or fname in self.model.methods:
            return
        # evidence for the L003 inconsistent-locking heuristic; fields
        # never WRITTEN outside __init__ are immutable and cannot race,
        # so only mutated fields can fire (reads of config fields inside
        # a with-block are coincidence, not discipline)
        if self.method.name == "__init__":
            return
        key = (self.model.name, fname)
        if is_store:
            self.lint.mutated.add(key)
        inside = any(b == "self" and lk in self.model.locks
                     for (b, lk) in self.held)
        ev = self.lint.evidence.setdefault(
            key, {"inside": None, "outside": None})
        slot = "inside" if inside else "outside"
        if ev[slot] is None:
            ev[slot] = (self.model.path, node.lineno, self.method.name)

    def _check_foreign_store(self, node: ast.Attribute, base: str,
                             fname: str) -> None:
        """A write like ``node._state = ...``: check the global guarded
        registry (alternate constructors mutate through other names).
        The owner class is resolved via the ``attr_classes`` hint for
        ``base`` when available; with several same-named owners and no
        hint, the check runs only when they all agree on the lock name
        (ambiguity must not assert the WRONG class's contract)."""
        owners = self.lint.global_guarded.get(fname)
        if not owners:
            return
        hinted = self.lint.attr_classes.get(base)
        if hinted is not None:
            if hinted not in owners:
                return  # hinted class doesn't guard this field
            owner_cls, lock = hinted, owners[hinted]
        elif len(set(owners.values())) == 1:
            owner_cls, lock = next(iter(owners.items()))
        else:
            return  # ambiguous owners with differing locks: can't check
        if (base, lock) in self.held:
            return
        self.lint.findings.append(Finding(
            analyzer="lockdiscipline", code=UNGUARDED_ACCESS,
            severity=SEVERITY_ERROR, path=self.model.path,
            line=node.lineno, symbol=f"{owner_cls}.{fname}",
            message=(f"write of {owner_cls}-guarded field {fname!r} "
                     f"through name {base!r} without holding "
                     f"{base}.{lock} (in "
                     f"{self.model.name}.{self.method.name})")))


class LockLint:
    """Whole-run state: class models, lock-order graph, findings."""

    def __init__(self, attr_classes: Optional[Dict[str, str]] = None,
                 loader: Optional[SourceLoader] = None):
        # hints mapping attribute names to the class of the object they
        # hold, for cross-class acquisition edges (self.wal.seal())
        self.attr_classes = attr_classes or {}
        self.loader = ensure_loader(loader)
        self.models: Dict[str, ClassModel] = {}
        # field name -> {owner class: lock}: same-named guarded fields
        # in different classes must not clobber each other's contract
        self.global_guarded: Dict[str, Dict[str, str]] = {}
        self.findings: List[Finding] = []
        # (class, field) -> {"inside": loc|None, "outside": loc|None}
        self.evidence: Dict = {}
        # (class, field) written outside __init__ — L003 candidates
        self.mutated: Set[Tuple[str, str]] = set()
        # lock-order edges: (qualified_from, qualified_to) -> first loc
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self._lock_names: Set[str] = set()
        # files loaded but not yet linted (run() is two-phase so the
        # cross-file guarded registry is complete before checking)
        self._pending: List[Tuple[str, ast.Module]] = []

    def is_lock_name(self, name: str) -> bool:
        return name in self._lock_names

    def qualify(self, model: ClassModel, base: str, lock: str) -> str:
        """Class-qualified lock node name for the order graph."""
        if base == "self":
            return f"{model.name}.{lock}"
        cls = self.attr_classes.get(base)
        return f"{cls}.{lock}" if cls else f"?{base}.{lock}"

    def note_acquisition(self, model: ClassModel,
                         held: Set[Tuple[str, str]],
                         new: Tuple[str, str]) -> None:
        tgt = self.qualify(model, new[0], new[1])
        for b, lk in held:
            src = self.qualify(model, b, lk)
            if src != tgt:
                self.edges.setdefault((src, tgt), (model.path, 0))

    def note_call_edges(self, model: ClassModel,
                        held: Set[Tuple[str, str]], attr: str, meth: str,
                        line: int) -> None:
        """``self.<attr>.<meth>()`` (or ``<attr>.<meth>()`` for a hinted
        local name) while holding locks: if <attr>'s hinted class
        summary says <meth> acquires, add edges."""
        cls_name = self.attr_classes.get(attr)
        target = self.models.get(cls_name) if cls_name else None
        if target is None:
            return
        for lk in target.acquires.get(meth, set()):
            tgt = f"{target.name}.{lk}"
            for b, hlk in held:
                src = self.qualify(model, b, hlk)
                if src != tgt:
                    self.edges.setdefault((src, tgt), (model.path, line))

    # -- driving -----------------------------------------------------------

    def load_file(self, path: str, source: Optional[str] = None) -> None:
        pf = self.loader.load(path, source)
        tree, annots = pf.tree, pf.annotations
        for msg in annots.malformed:
            self.findings.append(Finding(
                analyzer="lockdiscipline", code=UNGUARDED_ACCESS,
                severity=SEVERITY_ERROR, path=path,
                message=f"malformed annotation: {msg}"))
        models = build_class_models(tree, annots, path)
        self.models.update(models)
        for m in models.values():
            self._lock_names |= m.locks
            for fname, lock in m.guarded.items():
                self.global_guarded.setdefault(fname, {})[m.name] = lock
        self._pending.append((path, tree))

    def run(self) -> List[Finding]:
        """Lint every loaded file (two-phase so cross-file guarded
        fields and acquisition summaries are complete before checking)."""
        for path, tree in self._pending:
            for cls in [n for n in tree.body
                        if isinstance(n, ast.ClassDef)]:
                model = self.models[cls.name]
                for meth in [n for n in cls.body
                             if isinstance(n, ast.FunctionDef)]:
                    _MethodLinter(self, model, meth).visit(meth)
        for (cname, fname), ev in sorted(self.evidence.items()):
            if (cname, fname) not in self.mutated:
                continue
            if ev["inside"] and ev["outside"]:
                path, line, meth = ev["outside"]
                self.findings.append(Finding(
                    analyzer="lockdiscipline", code=UNANNOTATED_SHARED,
                    severity=SEVERITY_ERROR, path=path, line=line,
                    symbol=f"{cname}.{fname}",
                    message=(f"field {fname!r} is accessed under a lock "
                             f"elsewhere but bare in {meth!r}; annotate "
                             "it '# guarded-by: <lock>' (and fix the "
                             "bare accesses) or '# race-ok: <reason>'")))
        self.findings.extend(self._check_cycles())
        return self.findings

    def _check_cycles(self) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        out: List[Finding] = []
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(v: str) -> Optional[List[str]]:
            color[v] = 1
            stack.append(v)
            for w in sorted(graph[v]):
                if color.get(w, 0) == 1:
                    return stack[stack.index(w):] + [w]
                if color.get(w, 0) == 0:
                    cyc = dfs(w)
                    if cyc:
                        return cyc
            stack.pop()
            color[v] = 2
            return None

        for v in sorted(graph):
            if color.get(v, 0) == 0:
                cyc = dfs(v)
                if cyc:
                    path, line = self.edges.get(
                        (cyc[0], cyc[1]), (None, None))
                    out.append(Finding(
                        analyzer="lockdiscipline", code=LOCK_ORDER_CYCLE,
                        severity=SEVERITY_ERROR, path=path,
                        line=line or None,
                        message=("lock acquisition cycle: "
                                 + " -> ".join(cyc))))
                    break
        return out

    def stats(self) -> Dict:
        return {
            "classes": len(self.models),
            "classes_by_name": sorted(self.models),
            "locks": sorted(self._lock_names),
            "guarded_fields": sum(len(m.guarded)
                                  for m in self.models.values()),
            "requires_lock_methods": sum(len(m.requires)
                                         for m in self.models.values()),
            "lock_order_edges": sorted(f"{a} -> {b}"
                                       for a, b in self.edges),
        }


def analyze_files(paths: List[str],
                  attr_classes: Optional[Dict[str, str]] = None,
                  loader: Optional[SourceLoader] = None
                  ) -> Tuple[List[Finding], Dict]:
    lint = LockLint(attr_classes=attr_classes, loader=loader)
    for p in paths:
        lint.load_file(p)
    findings = lint.run()
    return findings, lint.stats()
