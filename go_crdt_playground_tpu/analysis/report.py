"""Finding/report plumbing shared by every analyzer pass.

A ``Finding`` is one diagnostic: pass name, severity, location, message,
and an optional stable ``code`` (the grep-able contract — tests pin
codes, not message prose).  ``Report`` aggregates per-pass findings plus
pass-level stats into the ``ANALYSIS_REPORT.json`` shape documented in
DESIGN.md §15.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

SEVERITY_ERROR = "error"      # gate-failing: the invariant is violated
SEVERITY_WARNING = "warning"  # suspicious but not gate-failing

# Stable finding codes (tests and DESIGN.md §15 pin these):
UNGUARDED_ACCESS = "L001"       # guarded field touched without its lock
LOCK_ORDER_CYCLE = "L002"       # lock acquisition graph has a cycle
UNANNOTATED_SHARED = "L003"     # field locked sometimes, annotated never
RACE_EMPTY_LOCKSET = "R001"     # runtime: shared write, empty lockset
FSYNC_MISSING = "D001"          # ack/rename not dominated by fsync
PURITY_VIOLATION = "P001"       # jit/Pallas-reachable host side effect
LAW_COMMUTATIVITY = "J001"
LAW_ASSOCIATIVITY = "J002"
LAW_IDEMPOTENCE = "J003"
LAW_DECLARATION = "J004"        # JoinSpec.laws empty or unknown
# wire-contract passes (analysis/protocol_contract.py,
# analysis/codec_symmetry.py, analysis/metrics_contract.py):
DISPATCH_HOLE = "W001"          # MSG_* constant with no dispatcher arm
REJECT_UNDISCIPLINED = "W002"   # reject code/exception registry drift
CODEC_ASYMMETRY = "W003"        # encode/decode pair broke its contract
FRAME_CAP_MISSING = "W004"      # recv_frame call site without max_body
METRICS_CONTRACT = "M001"       # metric name referenced/emitted drift
REPORT_STALE = "F001"           # committed report's pass list is stale
THREAD_SHADOW = "T001"          # Thread subclass shadows a Thread internal
# protocol-verification ladder (analysis/protomodel.py,
# analysis/epoch_order.py, analysis/fence_coverage.py,
# analysis/transfer_lock.py — DESIGN.md §26):
EPOCH_ORDER = "E001"            # persist does not dominate announce/bind
FENCE_UNCOVERED = "E002"        # write-verb arm consults no fence predicate
MODEL_STALE = "E003"            # protocol model drifted from its source
MODEL_VIOLATION = "E004"        # explorer found an invariant-violating run
TRANSFER_UNDER_LOCK = "D002"    # blocking device transfer while lock held


@dataclass
class Finding:
    analyzer: str                 # "lockdiscipline" | "locksets" | ...
    code: str
    severity: str
    message: str
    path: Optional[str] = None
    line: Optional[int] = None
    symbol: Optional[str] = None  # class.field / function / join name

    def location(self) -> str:
        loc = self.path or "<runtime>"
        if self.line is not None:
            loc += f":{self.line}"
        return loc

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.severity.upper()} {self.code} "
                f"{self.location()}{sym}: {self.message}")


@dataclass
class Report:
    """One gate run: per-pass findings + stats, JSON-serializable."""

    findings: List[Finding] = field(default_factory=list)
    stats: Dict[str, Dict] = field(default_factory=dict)
    # run-level metadata (wall time, parse-cache hit rates, budgets) —
    # serialized top-level, NOT as a pass: the report-freshness lint
    # compares pass lists, and meta must never read as coverage
    meta: Dict = field(default_factory=dict)

    def extend(self, findings: List[Finding]) -> None:
        self.findings.extend(findings)

    def add_stats(self, analyzer: str, **stats) -> None:
        self.stats.setdefault(analyzer, {}).update(stats)

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEVERITY_ERROR]

    def ok(self) -> bool:
        return not self.errors()

    def to_dict(self) -> Dict:
        by_pass: Dict[str, List[Dict]] = {}
        for f in self.findings:
            by_pass.setdefault(f.analyzer, []).append(asdict(f))
        return {
            "ok": self.ok(),
            "n_findings": len(self.findings),
            "n_errors": len(self.errors()),
            "meta": dict(self.meta),
            "passes": {
                name: {
                    "stats": self.stats.get(name, {}),
                    "findings": by_pass.get(name, []),
                }
                # every pass appears even when clean — "covered and
                # found nothing" must be distinguishable from "not run"
                for name in sorted(set(self.stats) | set(by_pass))
            },
        }

    def write_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
