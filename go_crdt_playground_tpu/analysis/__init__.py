"""Invariant analyzer suite: the correctness tooling the runtime grew to
need (DESIGN.md §15).

Three invariant classes in this stack were, until this package, probed
only dynamically by soaks:

* **lock discipline** in the threaded anti-entropy runtime (the Node
  lock serializing state/WAL, the ``_conn_slots`` semaphore, supervisor
  threads) — the PR-1/PR-2 code carries ``# guarded-by:`` contracts in
  comments;
* **durability ordering** in the WAL/checkpoint layer — fsync must
  dominate every ack/rename, or "durable on return" is a lie the next
  power cut exposes;
* **lattice laws** — commutativity, associativity, idempotence are what
  make the vmapped merge a join at all (Almeida et al.,
  arXiv:1410.2803; Enes et al., arXiv:1803.02750); a non-commutative
  "join" converges only on the schedules the tests happened to run.

Four passes, one gate:

    python -m go_crdt_playground_tpu.analysis          # full gate
    python -m go_crdt_playground_tpu.analysis --fast   # tier-1 budget

``lockdiscipline``  AST lint over ``# guarded-by:`` / ``# requires-lock:``
                    annotations plus a lock-order cycle check.
``locksets``        Eraser-style runtime lockset race detector
                    (instrumented locks + attribute tracing); opt-in
                    under the soaks via ``--detect-races`` and embedded
                    as a short exercise in the CLI gate.
``durability``      fsync-dominates-ack/rename lint + JAX-purity lint
                    for jit/Pallas-reachable functions (``purity``).
``lattice_laws``    randomized, seeded property checks of every join in
                    the ``ops.lattices`` registry.

Each pass returns a list of ``report.Finding``; the CLI aggregates them
into ``ANALYSIS_REPORT.json`` and exits non-zero on any ERROR finding.
"""

from go_crdt_playground_tpu.analysis.report import (Finding, Report,
                                                    SEVERITY_ERROR,
                                                    SEVERITY_WARNING)

__all__ = [
    "Finding",
    "Report",
    "SEVERITY_ERROR",
    "SEVERITY_WARNING",
]
