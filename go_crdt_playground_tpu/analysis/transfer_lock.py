"""D002 — blocking device→host transfer while a lock is held (§26).

``jax.device_get`` and ``.block_until_ready()`` synchronize with the
device: milliseconds of stall when the transfer is one bounded pull,
tens of milliseconds when a loop sneaks per-field pulls onto a hot
path.  Under the node lock that stall is SERIALIZED against every
reader and writer — the PR-8 review-round-4 bug class was exactly ~10
sequential per-field pulls under the node lock in the fused ingest
path.  This pass makes that class gate-time: a blocking transfer that
executes while a lock is held must carry a ``# transfer-ok: <reason>``
annotation stating why it is one sanctioned bounded pull.

"While a lock is held" is computed three ways, compounding:

1. lexically inside a ``with <...>.<lock>:`` block (any context
   manager whose trailing attribute name contains ``lock`` or
   ``cond`` — the repo's mutex naming discipline);
2. anywhere in a function annotated ``# requires-lock: <lock>`` (the
   caller holds the lock for the whole body);
3. anywhere in a function REACHABLE from (1) or (2) through the swept
   files' call graph, matched by trailing callee name (one fixpoint —
   how ``framing.encode_delta_wal_record``'s single compact pull,
   called under the node lock from ``Node._append_delta_record``, is
   found in a different module from any ``with`` block).

The trailing-name propagation over-approximates (any same-named
function anywhere in the sweep joins the lock context), which is the
conservative direction for this lint: blocking transfers are rare and
deliberate, so a false lock-context attribution costs one honest
annotation, while a missed one hides a hot-path stall.  A transfer-ok
on a site the propagation does NOT currently reach is allowed and
counted (``annotated_unflagged``, not a finding): it documents a pull
whose callers hold locks beyond the swept graph — there is no stale-
annotation check here because "no swept caller holds a lock today"
does not prove no caller ever does.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

from go_crdt_playground_tpu.analysis.annotations import (KIND_REQUIRES_LOCK,
                                                         KIND_TRANSFER_OK)
from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (SEVERITY_ERROR,
                                                    TRANSFER_UNDER_LOCK,
                                                    Finding)

_TRANSFER_NAMES = {"device_get", "block_until_ready"}


def _trailing(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_lockish(expr: ast.expr) -> bool:
    name = _trailing(expr)
    if name is None and isinstance(expr, ast.Call):
        name = _trailing(expr.func)
    if name is None:
        return False
    low = name.lower()
    return "lock" in low or "cond" in low


class _FnScan(NamedTuple):
    qual: str
    path: str
    fn: ast.AST
    requires_lock: bool
    transfers: List[Tuple[int, int, str, bool]]  # (line,end,name,in_with)
    calls_in_lock: Set[str]       # trailing names called under a with-lock
    calls_all: Set[str]           # every trailing callee name


def _scan_function(fn, qual: str, path: str, annots) -> _FnScan:
    requires = annots.on_lines(fn.lineno, fn.body[0].lineno - 1,
                               KIND_REQUIRES_LOCK) is not None
    transfers: List[Tuple[int, int, str, bool]] = []
    calls_in_lock: Set[str] = set()
    calls_all: Set[str] = set()

    def walk(node: ast.AST, in_lock: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_lock = in_lock
            if isinstance(child, (ast.With, ast.AsyncWith)):
                if any(_is_lockish(item.context_expr)
                       for item in child.items):
                    child_lock = True
            if isinstance(child, ast.Call):
                name = _trailing(child.func)
                if name is not None:
                    calls_all.add(name)
                    if child_lock:
                        calls_in_lock.add(name)
                    if name in _TRANSFER_NAMES:
                        end = getattr(child, "end_lineno", child.lineno)
                        transfers.append((child.lineno, end, name,
                                          child_lock))
            walk(child, child_lock)

    walk(fn, False)
    return _FnScan(qual, path, fn, requires, transfers, calls_in_lock,
                   calls_all)


def _scan_file(pf) -> List[_FnScan]:
    out: List[_FnScan] = []
    for node in pf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(_scan_function(node, node.name, pf.path,
                                      pf.annotations))
        elif isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append(_scan_function(
                        m, f"{node.name}.{m.name}", pf.path,
                        pf.annotations))
    return out


def analyze_paths(paths: Sequence[str],
                  loader: Optional[SourceLoader] = None,
                  sources: Optional[Dict[str, str]] = None
                  ) -> Tuple[List[Finding], Dict]:
    """Sweep ``paths`` as one call graph.  ``sources`` (abs or given
    path -> planted text) lets tests plant a locked transfer."""
    loader = ensure_loader(loader)
    scans: List[_FnScan] = []
    annot_sets = {}
    for p in paths:
        pf = loader.load(p, (sources or {}).get(p))
        annot_sets[pf.path] = pf.annotations
        scans.extend(_scan_file(pf))

    # lock-context fixpoint over trailing names: seeds are requires-lock
    # bodies and with-lock regions; closure follows every call a
    # lock-context function makes (its whole body may run under the
    # caller's lock)
    by_name: Dict[str, List[_FnScan]] = {}
    for sc in scans:
        by_name.setdefault(sc.qual.rsplit(".", 1)[-1], []).append(sc)
    lock_ctx: Set[str] = {sc.qual.rsplit(".", 1)[-1] for sc in scans
                          if sc.requires_lock}
    pending: Set[str] = set(lock_ctx)
    for sc in scans:
        for callee in sc.calls_in_lock:
            if callee in by_name and callee not in lock_ctx:
                lock_ctx.add(callee)
                pending.add(callee)
    while pending:
        name = pending.pop()
        for sc in by_name.get(name, ()):
            for callee in sc.calls_all:
                if callee in by_name and callee not in lock_ctx:
                    lock_ctx.add(callee)
                    pending.add(callee)

    findings: List[Finding] = []
    n_transfers = n_locked = n_ok = n_ok_unflagged = 0
    for sc in scans:
        fn_locked = sc.qual.rsplit(".", 1)[-1] in lock_ctx
        for line, end, name, in_with in sc.transfers:
            n_transfers += 1
            ann = annot_sets[sc.path].on_lines(line, end,
                                               KIND_TRANSFER_OK)
            if not (in_with or fn_locked):
                if ann is not None:
                    n_ok_unflagged += 1
                continue
            n_locked += 1
            if ann is not None:
                n_ok += 1
                continue
            how = ("inside a with-lock block" if in_with else
                   "in a lock-context function (requires-lock or "
                   "called under a lock)")
            findings.append(Finding(
                analyzer="transfer_lock", code=TRANSFER_UNDER_LOCK,
                severity=SEVERITY_ERROR, path=sc.path, line=line,
                symbol=sc.qual,
                message=(f"blocking {name}() {how}: the device stall "
                         "serializes every reader/writer on that lock "
                         "(the PR-8 fused-hot-path bug class) — hoist "
                         "the pull outside the lock, or annotate the "
                         "statement '# transfer-ok: <reason>' if it is "
                         "one sanctioned bounded pull")))
    stats = {"files": len(paths), "functions": len(scans),
             "transfer_calls": n_transfers, "lock_held": n_locked,
             "transfer_ok": n_ok, "annotated_unflagged": n_ok_unflagged,
             "lock_context_fns": len(lock_ctx)}
    return findings, stats


def analyze(root: str, rel_paths: Sequence[str],
            loader: Optional[SourceLoader] = None
            ) -> Tuple[List[Finding], Dict]:
    return analyze_paths([os.path.join(root, p) for p in rel_paths],
                         loader=loader)
