"""W003 codec symmetry (DESIGN.md §15): every encode has a decode that
inverts it, and every decode fails TYPED on hostile bytes.

The wire surface spans four modules — ``serve/protocol.py``,
``net/framing.py``, ``net/digestsync.py``, ``utils/wire.py`` — and
until now only a handful of hand-written tests pinned individual
codecs (``TruncatedFrame``, a few roundtrips).  This pass declares THE
registry of encode/decode pairs and property-checks each one with
seeded inputs:

* **roundtrip identity** — ``decode(encode(*args))`` must equal the
  declared oracle projection of ``args``;
* **truncation** — every strict prefix of an encoded body must raise
  the module's TYPED error class (``ProtocolError`` for frame
  dialects, ``ValueError`` for the wire layer).  Codecs whose body
  ends in free-form bytes (utf-8 reason, JSON, opaque payload) may
  legitimately decode a truncated tail — for those, prefixes must
  decode-or-raise-typed, never raise untyped;
* **garble** — seeded byte corruption must decode-or-raise-typed.
  The contract under attack is the ERROR TYPE: an ``IndexError`` /
  ``OverflowError`` / ``UnicodeDecodeError`` escaping a decoder
  bypasses the dialect's typed-error mapping and kills the reader
  thread that called it.

Registry completeness is itself checked: every public ``encode_*`` /
``decode_*`` name in the four modules must be covered by some spec —
a codec registered nowhere is a codec whose decode can drift from its
encode without any gate noticing (exactly how ``decode_members``
shipped without the uint32 range check every sibling had).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from go_crdt_playground_tpu.analysis.report import (CODEC_ASYMMETRY,
                                                    SEVERITY_ERROR, Finding)

# property-harness universe: small so all-prefix truncation stays cheap
E = 16
A = 4

# the four wire modules whose public codec surface must be covered
WIRE_MODULES = ("serve/protocol.py", "net/framing.py",
                "net/digestsync.py", "utils/wire.py")


class CodecSpec(NamedTuple):
    """One encode/decode pair under property check.

    ``gen(rng)`` returns encoder args; ``encode(*args) -> bytes``;
    ``decode(body)`` is closed over the harness dimensions;
    ``expected(args)`` is the decoded-value oracle; ``compare``
    defaults to recursive equality with array support.
    ``self_delimiting=False`` marks bodies with free-form tails
    (truncation may legally decode).  ``covers`` lists the public
    module functions this spec exercises, for the completeness
    check."""

    name: str
    encode: Callable[..., bytes]
    decode: Callable[[bytes], Any]
    gen: Callable[[np.random.Generator], tuple]
    expected: Callable[[tuple], Any]
    typed_errors: Tuple[type, ...]
    covers: Tuple[str, ...]
    self_delimiting: bool = True
    compare: Optional[Callable[[Any, Any], bool]] = None


def _eq(a: Any, b: Any) -> bool:
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (np.asarray(a).shape == np.asarray(b).shape
                and bool(np.array_equal(np.asarray(a), np.asarray(b))))
    if type(a).__name__ == "ArrayImpl" or type(b).__name__ == "ArrayImpl":
        return _eq(np.asarray(a), np.asarray(b))
    if isinstance(a, (tuple, list)) and isinstance(b, (tuple, list)):
        return (len(a) == len(b)
                and all(_eq(x, y) for x, y in zip(a, b)))
    if isinstance(a, dict) and isinstance(b, dict):
        return (set(a) == set(b)
                and all(_eq(a[k], b[k]) for k in a))
    return bool(a == b)


def _payload_eq(got, want) -> bool:
    """DeltaPayload comparison on every shipped field (src_processed
    rides out-of-band for some codecs — the oracle sets what the codec
    promises)."""
    for f in ("src_vv", "changed", "ch_da", "ch_dc", "deleted",
              "del_da", "del_dc", "src_actor", "src_processed"):
        if not _eq(getattr(got, f), getattr(want, f)):
            return False
    return True


# ---------------------------------------------------------------------------
# Seeded generators
# ---------------------------------------------------------------------------


def _rid(rng) -> int:
    return int(rng.integers(0, 1 << 20))


def _vv(rng) -> np.ndarray:
    return rng.integers(0, 50, A).astype(np.uint32)


def _elements(rng, lo: int = 1, hi: int = 5) -> List[int]:
    k = int(rng.integers(lo, hi))
    return [int(e) for e in rng.choice(E, size=k, replace=False)]


def _canonical_payload(rng, *, fresh_deletions_vs=None):
    """A DeltaPayload whose unmasked lanes are zero (the wire form
    round-trips masked lanes only, scattering zeros elsewhere — the
    generator bakes that canonicalization in so equality is exact).
    With ``fresh_deletions_vs`` (a guard vv), some deletion dots are
    deliberately placed BELOW the guard to exercise the WAL record
    deletion filter."""
    import jax.numpy as jnp

    from go_crdt_playground_tpu.ops.delta import DeltaPayload

    changed = rng.random(E) < 0.3
    deleted = (rng.random(E) < 0.3) & ~changed
    ch_da = np.where(changed, rng.integers(0, A, E), 0).astype(np.uint32)
    ch_dc = np.where(changed, rng.integers(1, 60, E), 0).astype(np.uint32)
    del_da = np.where(deleted, rng.integers(0, A, E), 0).astype(np.uint32)
    if fresh_deletions_vs is not None:
        # straddle the guard: ~half fresh (> guard), ~half stale
        guard = np.take(np.asarray(fresh_deletions_vs, np.uint32),
                        del_da.astype(np.int64), mode="clip")
        fresh = rng.random(E) < 0.5
        dc = np.where(fresh, guard + 1 + rng.integers(0, 5, E),
                      np.maximum(guard, 1) - rng.integers(0, 1, E))
        del_dc = np.where(deleted, dc, 0).astype(np.uint32)
    else:
        del_dc = np.where(deleted, rng.integers(1, 60, E),
                          0).astype(np.uint32)
    return DeltaPayload(
        src_vv=jnp.asarray(_vv(rng)),
        changed=jnp.asarray(changed),
        ch_da=jnp.asarray(ch_da), ch_dc=jnp.asarray(ch_dc),
        deleted=jnp.asarray(deleted),
        del_da=jnp.asarray(del_da), del_dc=jnp.asarray(del_dc),
        src_actor=jnp.uint32(int(rng.integers(0, A))),
        src_processed=jnp.asarray(_vv(rng)))


# ---------------------------------------------------------------------------
# The registry
# ---------------------------------------------------------------------------


def build_codecs() -> List[CodecSpec]:
    import jax.numpy as jnp

    from go_crdt_playground_tpu.net import digestsync, framing
    from go_crdt_playground_tpu.net.framing import ProtocolError
    from go_crdt_playground_tpu.ops.digest import num_groups
    from go_crdt_playground_tpu.serve import protocol as p
    from go_crdt_playground_tpu.utils import wire

    P = (ProtocolError,)
    V = (ValueError,)
    specs: List[CodecSpec] = []

    def add(*args, **kw):
        specs.append(CodecSpec(*args, **kw))

    # -- serve/protocol.py ---------------------------------------------------
    add("op", p.encode_op, p.decode_op,
        lambda rng: (_rid(rng), int(rng.integers(0, 2)), _elements(rng),
                     int(rng.integers(0, 1 << 20))),
        lambda a: (a[0], a[1], list(a[2]), a[3]), P,
        ("encode_op", "decode_op"))
    add("ack", p.encode_ack, p.decode_ack,
        lambda rng: (_rid(rng),), lambda a: a[0], P,
        ("encode_ack", "decode_ack"))
    add("reject", p.encode_reject, p.decode_reject,
        lambda rng: (_rid(rng),
                     int(rng.choice(sorted(p.REJECT_EXCEPTIONS))),
                     "reason-" + str(int(rng.integers(100)))),
        lambda a: (a[0], a[1], a[2]), P,
        ("encode_reject", "decode_reject"), self_delimiting=False)
    add("query", p.encode_query, p.decode_query,
        lambda rng: (_rid(rng),), lambda a: a[0], P,
        ("encode_query", "decode_query"))
    add("stats", p.encode_stats, p.decode_stats,
        lambda rng: (_rid(rng),), lambda a: a[0], P,
        ("encode_stats", "decode_stats"))
    add("stats_reply", p.encode_stats_reply, p.decode_stats_reply,
        lambda rng: (_rid(rng),
                     {"counters": {"x": int(rng.integers(100))}}),
        lambda a: (a[0], a[1]), P,
        ("encode_stats_reply", "decode_stats_reply"),
        self_delimiting=False)
    add("members", p.encode_members,
        lambda body: p.decode_members(body),
        lambda rng: (_rid(rng), _elements(rng, 0, 5), _vv(rng)),
        lambda a: (a[0], list(a[1]), np.asarray(a[2], np.uint32)), P,
        ("encode_members", "decode_members"))
    add("reshard", p.encode_reshard, p.decode_reshard,
        lambda rng: ((_rid(rng), p.RESHARD_JOIN, "s1",
                      ("127.0.0.1", int(rng.integers(1, 1 << 16))))
                     if rng.random() < 0.5
                     else (_rid(rng), p.RESHARD_LEAVE, "s2", None)),
        lambda a: a, P, ("encode_reshard", "decode_reshard"))
    add("reshard_reply", p.encode_reshard_reply, p.decode_reshard_reply,
        lambda rng: (_rid(rng), bool(rng.integers(0, 2)),
                     {"moved": int(rng.integers(100))}),
        lambda a: a, P,
        ("encode_reshard_reply", "decode_reshard_reply"),
        self_delimiting=False)
    add("slice_pull", p.encode_slice_pull, p.decode_slice_pull,
        lambda rng: (_rid(rng), _elements(rng)),
        lambda a: (a[0], list(a[1])), P,
        ("encode_slice_pull", "decode_slice_pull"))
    add("slice_state", p.encode_slice_state, p.decode_slice_state,
        lambda rng: (_rid(rng),
                     rng.integers(0, 256, int(rng.integers(1, 40)))
                     .astype(np.uint8).tobytes()),
        lambda a: a, P, ("encode_slice_state", "decode_slice_state"),
        self_delimiting=False)
    add("slice_push", p.encode_slice_push, p.decode_slice_push,
        lambda rng: (_rid(rng),
                     rng.integers(0, 256, int(rng.integers(1, 40)))
                     .astype(np.uint8).tobytes()),
        lambda a: a, P, ("encode_slice_push", "decode_slice_push"),
        self_delimiting=False)
    add("frontier", p.encode_frontier, p.decode_frontier,
        lambda rng: (_rid(rng),), lambda a: a[0], P,
        ("encode_frontier", "decode_frontier"))
    add("frontier_reply", p.encode_frontier_reply,
        p.decode_frontier_reply,
        lambda rng: (_rid(rng), _vv(rng), _vv(rng),
                     bool(rng.integers(0, 2))),
        lambda a: (a[0], np.asarray(a[1], np.uint32),
                   np.asarray(a[2], np.uint32), a[3]), P,
        ("encode_frontier_reply", "decode_frontier_reply"))
    add("gc", p.encode_gc, p.decode_gc,
        lambda rng: (_rid(rng), _vv(rng)),
        lambda a: (a[0], np.asarray(a[1], np.uint32)), P,
        ("encode_gc", "decode_gc"))
    add("gc_reply", p.encode_gc_reply, p.decode_gc_reply,
        lambda rng: (_rid(rng), int(rng.integers(100)),
                     int(rng.integers(100))),
        lambda a: a, P, ("encode_gc_reply", "decode_gc_reply"))
    add("dsum", p.encode_dsum, p.decode_dsum,
        lambda rng: (_rid(rng),), lambda a: a[0], P,
        ("encode_dsum", "decode_dsum"))
    add("dsum_reply", p.encode_dsum_reply, p.decode_dsum_reply,
        lambda rng: (_rid(rng),
                     rng.integers(0, 256, int(rng.integers(1, 40)))
                     .astype(np.uint8).tobytes()),
        lambda a: a, P, ("encode_dsum_reply", "decode_dsum_reply"),
        self_delimiting=False)
    add("ring_sync", p.encode_ring_sync, p.decode_ring_sync,
        lambda rng: (_rid(rng), int(rng.integers(0, 1 << 16)),
                     "router-" + str(int(rng.integers(100)))),
        lambda a: a, P, ("encode_ring_sync", "decode_ring_sync"))
    add("ring_sync_reply", p.encode_ring_sync_reply,
        p.decode_ring_sync_reply,
        lambda rng: (_rid(rng),
                     {"router_epoch": int(rng.integers(0, 1 << 16)),
                      "generation": int(rng.integers(100))}),
        lambda a: a, P,
        ("encode_ring_sync_reply", "decode_ring_sync_reply"),
        self_delimiting=False)

    def gen_wal_sync(rng):
        catchup = rng.random() < 0.4
        summary = (rng.integers(0, 256, int(rng.integers(1, 60)))
                   .astype(np.uint8).tobytes() if catchup else None)
        return (_rid(rng), int(rng.integers(1, 1 << 20)),
                int(rng.integers(0, 1 << 12)),
                "sb-" + str(int(rng.integers(100))),
                int(rng.integers(0, 2000)), int(rng.integers(0, 512)),
                summary)

    add("wal_sync", p.encode_wal_sync, p.decode_wal_sync,
        gen_wal_sync,
        lambda a: (a[0], a[2], a[3], a[1], a[4], a[5], a[6]), P,
        ("encode_wal_sync", "decode_wal_sync"),
        self_delimiting=False)

    def gen_wal_sync_reply(rng):
        catchup = rng.random() < 0.3
        if catchup:
            records = ()
            payload = (rng.integers(0, 256, int(rng.integers(1, 60)))
                       .astype(np.uint8).tobytes())
        else:
            records = tuple(
                rng.integers(0, 256, int(rng.integers(0, 30)))
                .astype(np.uint8).tobytes()
                for _ in range(int(rng.integers(0, 5))))
            payload = None
        first = int(rng.integers(1, 1 << 16))
        return (_rid(rng), int(rng.integers(0, 2)),
                int(rng.integers(0, 1 << 12)),
                "s" + str(int(rng.integers(10))),
                "%08x" % int(rng.integers(1 << 31)),
                first, first + len(records), first, records, payload)

    def cmp_wal_sync_reply(got, want) -> bool:
        # the encoder ORs WAL_CATCHUP_PAYLOAD into flags when a
        # payload rides along; compare modulo that bit, everything
        # else exactly
        exp_flags = want[1] | (p.WAL_CATCHUP_PAYLOAD
                               if want[9] is not None else 0)
        return (got.req_id, got.flags, got.shard_epoch, got.shard_id,
                got.nonce, got.min_seq, got.next_seq, got.first_seq,
                tuple(got.records), got.payload) == (
            want[0], exp_flags, want[2], want[3], want[4], want[5],
            want[6], want[7], tuple(want[8]), want[9])

    add("wal_sync_reply", p.encode_wal_sync_reply,
        p.decode_wal_sync_reply, gen_wal_sync_reply,
        lambda a: a, P,
        ("encode_wal_sync_reply", "decode_wal_sync_reply"),
        self_delimiting=False, compare=cmp_wal_sync_reply)

    add("shard_failover", p.encode_shard_failover,
        p.decode_shard_failover,
        lambda rng: (_rid(rng), int(rng.integers(1, 1 << 12)),
                     "s" + str(int(rng.integers(10))),
                     "sb-" + str(int(rng.integers(100))),
                     ("127.0.0.1", int(rng.integers(1, 1 << 16)))),
        lambda a: a, P,
        ("encode_shard_failover", "decode_shard_failover"))
    add("shard_failover_reply", p.encode_shard_failover_reply,
        p.decode_shard_failover_reply,
        lambda rng: (_rid(rng),
                     {"sid": "s1", "shard_epoch": int(rng.integers(100)),
                      "swapped": bool(rng.integers(0, 2))}),
        lambda a: a, P,
        ("encode_shard_failover_reply", "decode_shard_failover_reply"),
        self_delimiting=False)

    # -- net/framing.py ------------------------------------------------------
    add("hello", framing.encode_hello,
        lambda body: framing.decode_hello(body, E, A),
        lambda rng: (int(rng.integers(0, A)), E, _vv(rng)),
        lambda a: (a[0], np.asarray(a[2], np.uint32)), P,
        ("encode_hello", "decode_hello"))

    def gen_payload_msg(rng):
        mode = int(rng.choice((framing.MODE_DELTA, framing.MODE_FULL,
                               framing.MODE_SLICE, framing.MODE_DIGEST)))
        payload = _canonical_payload(rng)
        return (mode, int(np.uint32(payload.src_actor)),
                np.asarray(payload.src_processed, np.uint32), payload)

    def cmp_payload_msg(got, want) -> bool:
        return got[0] == want[0] and _payload_eq(got[1], want[1])

    add("payload_msg", framing.encode_payload_msg,
        lambda body: framing.decode_payload_msg(body, E, A),
        gen_payload_msg,
        lambda a: (a[0], a[3]._replace()), P,
        ("encode_payload_msg", "decode_payload_msg"),
        compare=cmp_payload_msg)

    def gen_wal_record(rng):
        pre_vv = _vv(rng)
        payload = _canonical_payload(rng, fresh_deletions_vs=pre_vv)
        return (pre_vv, int(np.uint32(payload.src_actor)), payload,
                None, bool(rng.integers(0, 2)))

    def enc_wal_record(pre_vv, src_actor, payload, compact,
                       compact_records) -> bytes:
        body, _ = framing.encode_delta_wal_record(
            pre_vv, src_actor, payload, compact,
            compact_records=compact_records)
        return body

    def dec_wal_record(body: bytes):
        # the replay-side dispatch (net/peer.Node.replay form): a 0x00
        # lead byte can never open a dense record, so it tags compact
        if body[:1] == bytes([wire.WAL_COMPACT_TAG]):
            return wire.decode_compact_wal_body(body, E, A)
        guard, pos = wire._decode_vv_py(body, 0, A)
        _mode, payload = framing.decode_payload_msg(body[pos:], E, A)
        return guard, payload

    def exp_wal_record(a):
        import jax.numpy as jnp

        pre_vv, _src_actor, payload, _c, _cr = a
        # the record contract: deletion dots covered by the guard are
        # filtered (they replay from earlier records), masked-out
        # lanes scatter back as zeros
        deleted = np.asarray(payload.deleted) & (
            np.asarray(payload.del_dc)
            > np.take(pre_vv, np.asarray(payload.del_da, np.int64),
                      mode="clip"))
        want = payload._replace(
            deleted=jnp.asarray(deleted),
            del_da=jnp.asarray(
                np.where(deleted, np.asarray(payload.del_da), 0)
                .astype(np.uint32)),
            del_dc=jnp.asarray(
                np.where(deleted, np.asarray(payload.del_dc), 0)
                .astype(np.uint32)))
        return np.asarray(pre_vv, np.uint32), want

    add("delta_wal_record", enc_wal_record, dec_wal_record,
        gen_wal_record, exp_wal_record, P + V,
        ("encode_delta_wal_record",),
        compare=lambda got, want: (_eq(got[0], want[0])
                                   and _payload_eq(got[1], want[1])))

    # -- utils/wire.py -------------------------------------------------------
    def gen_payload(rng):
        return (_canonical_payload(rng),)

    def exp_payload(a):
        import jax.numpy as jnp

        # src_processed/src_actor ride out-of-band: decode zeroes them
        return a[0]._replace(src_actor=jnp.uint32(0),
                             src_processed=jnp.zeros(A, jnp.uint32))

    add("payload", wire.encode_payload,
        lambda body: wire.decode_payload(body, E, A),
        gen_payload, exp_payload, V,
        ("encode_payload", "decode_payload", "payload_nbytes_wire"),
        compare=_payload_eq)
    add("payload_lanes",
        lambda payload: wire.encode_payload_lanes(payload, E),
        lambda body: wire.decode_payload_lanes(body, E, A),
        gen_payload, exp_payload, V,
        ("encode_payload_lanes", "decode_payload_lanes"),
        compare=_payload_eq)

    def gen_compact_wal(rng):
        payload = _canonical_payload(rng)
        ch = np.nonzero(np.asarray(payload.changed))[0]
        dl = np.nonzero(np.asarray(payload.deleted))[0]
        return (_vv(rng), int(np.uint32(payload.src_actor)),
                np.asarray(payload.src_processed, np.uint32),
                np.asarray(payload.src_vv, np.uint32),
                ch, np.asarray(payload.ch_da)[ch],
                np.asarray(payload.ch_dc)[ch],
                dl, np.asarray(payload.del_da)[dl],
                np.asarray(payload.del_dc)[dl], E, payload)

    add("compact_wal_body",
        lambda *a: wire.encode_compact_wal_body(*a[:11]),
        lambda body: wire.decode_compact_wal_body(body, E, A),
        gen_compact_wal,
        lambda a: (np.asarray(a[0], np.uint32), a[11]), V,
        ("encode_compact_wal_body", "decode_compact_wal_body"),
        compare=lambda got, want: (_eq(got[0], want[0])
                                   and _payload_eq(got[1], want[1])))

    # -- net/digestsync.py ---------------------------------------------------
    GS = 4

    def gen_summary(rng):
        g = num_groups(E, GS)
        return (int(rng.integers(0, A)), E, GS, _vv(rng), _vv(rng),
                rng.integers(0, 1 << 32, g).astype(np.uint32))

    add("summary", digestsync.encode_summary,
        lambda body: digestsync.decode_summary(body, E, A),
        gen_summary,
        lambda a: (a[0], a[2], np.asarray(a[3], np.uint32),
                   np.asarray(a[4], np.uint32),
                   np.asarray(a[5], np.uint32)), P,
        ("encode_summary", "decode_summary"))
    return specs


# ---------------------------------------------------------------------------
# The property harness
# ---------------------------------------------------------------------------

# error types that may NEVER escape a decoder: they bypass the typed
# mapping and kill the reader thread that called it
_MAX_TRUNC_POSITIONS = 192


def check_codec(spec: CodecSpec, rng: np.random.Generator, *,
                n_samples: int, n_garbles: int) -> List[Finding]:
    findings: List[Finding] = []
    compare = spec.compare if spec.compare is not None else _eq

    def err(msg: str) -> None:
        findings.append(Finding(
            analyzer="codec_symmetry", code=CODEC_ASYMMETRY,
            severity=SEVERITY_ERROR, symbol=spec.name, message=msg))

    for i in range(n_samples):
        args = spec.gen(rng)
        try:
            body = spec.encode(*args)
        except Exception as e:  # noqa: BLE001 — an encoder refusing
            err(f"encode raised on generated args (sample {i}): "
                f"{type(e).__name__}: {e}")
            continue
        # 1. roundtrip identity
        try:
            got = spec.decode(body)
        except Exception as e:  # noqa: BLE001
            err(f"decode raised on its own encode (sample {i}): "
                f"{type(e).__name__}: {e}")
            continue
        if not compare(got, spec.expected(args)):
            err(f"roundtrip mismatch (sample {i}): decode(encode(...)) "
                "differs from the declared oracle — the decode drifted "
                "from its encode")
            continue
        # 2. truncation at every boundary (sampled when the body is
        # large): typed error, or — free-form-tail codecs only — a
        # successful decode of the shorter tail
        if len(body) <= _MAX_TRUNC_POSITIONS:
            cuts = range(len(body))
        else:
            cuts = sorted({0, 1, len(body) - 1} | {
                int(c) for c in rng.integers(
                    0, len(body), _MAX_TRUNC_POSITIONS - 3)})
        for cut in cuts:
            try:
                spec.decode(body[:cut])
            except spec.typed_errors:
                continue
            except Exception as e:  # noqa: BLE001 — the finding
                err(f"UNTYPED {type(e).__name__} on truncation at byte "
                    f"{cut}/{len(body)} (sample {i}): hostile bytes "
                    "must map to the dialect's typed error, not kill "
                    f"the reader thread ({e})")
                break
            else:
                if spec.self_delimiting:
                    err(f"truncated prefix ACCEPTED at byte "
                        f"{cut}/{len(body)} (sample {i}): a torn body "
                        "decoded as a complete frame")
                    break
        # 3. seeded garble: decode-or-typed, never untyped
        for g in range(n_garbles):
            pos = int(rng.integers(0, len(body)))
            flip = int(rng.integers(1, 256))
            garbled = (body[:pos] + bytes([body[pos] ^ flip])
                       + body[pos + 1:])
            try:
                spec.decode(garbled)
            except spec.typed_errors:
                continue
            except Exception as e:  # noqa: BLE001
                err(f"UNTYPED {type(e).__name__} on garbled byte "
                    f"{pos} (sample {i}, xor {flip:#x}): {e}")
                break
        # 4. varint inflation at every byte position: splice in a
        # 5-byte varint decoding to 2^32 — one past uint32 — so any
        # count/dot/clock field missing its range check converts to an
        # OverflowError (or a giant allocation) instead of the typed
        # reject.  Deterministic, because a random byte flip almost
        # never manufactures a >32-bit varint (how decode_members
        # shipped without the range check every sibling had).
        inflate = b"\x80\x80\x80\x80\x10"  # varint(2**32)
        positions = (range(len(body)) if len(body) <= _MAX_TRUNC_POSITIONS
                     else sorted({int(c) for c in rng.integers(
                         0, len(body), _MAX_TRUNC_POSITIONS)}))
        for pos in positions:
            inflated = body[:pos] + inflate + body[pos + 1:]
            try:
                spec.decode(inflated)
            except spec.typed_errors:
                continue
            except Exception as e:  # noqa: BLE001
                err(f"UNTYPED {type(e).__name__} on varint inflation "
                    f"at byte {pos} (sample {i}): a >uint32 field must "
                    f"map to the typed error, got: {e}")
                break
    return findings


def check_coverage(root: str, specs: List[CodecSpec],
                   loader=None) -> Tuple[List[Finding], Dict]:
    """Every public encode_*/decode_* in the wire modules must be
    covered by some spec."""
    import ast as _ast

    from go_crdt_playground_tpu.analysis.loader import ensure_loader
    loader = ensure_loader(loader)
    covered = {name for s in specs for name in s.covers}
    findings: List[Finding] = []
    per_module: Dict[str, List[str]] = {}
    for rel in WIRE_MODULES:
        path = os.path.join(root, rel)
        tree = loader.load(path).tree
        names = [n.name for n in tree.body
                 if isinstance(n, (_ast.FunctionDef,
                                   _ast.AsyncFunctionDef))
                 and (n.name.startswith("encode_")
                      or n.name.startswith("decode_"))]
        per_module[rel] = names
        for name in names:
            if name not in covered:
                findings.append(Finding(
                    analyzer="codec_symmetry", code=CODEC_ASYMMETRY,
                    severity=SEVERITY_ERROR, path=path, symbol=name,
                    message=f"codec function {name} is not covered by "
                            "any CodecSpec in analysis/codec_symmetry "
                            "— its symmetry is unverified (register "
                            "it, or fold it into an existing spec's "
                            "covers tuple)"))
    return findings, {"codec_functions": sum(len(v)
                                             for v in per_module.values())}


def analyze(root: str, *, fast: bool = False, seed: int = 7,
            loader=None) -> Tuple[List[Finding], Dict]:
    specs = build_codecs()
    findings, stats = check_coverage(root, specs, loader=loader)
    n_samples = 2 if fast else 5
    n_garbles = 8 if fast else 24
    rng = np.random.default_rng(seed)
    for spec in specs:
        findings.extend(check_codec(spec, rng, n_samples=n_samples,
                                    n_garbles=n_garbles))
    stats.update(codecs=len(specs), samples_per_codec=n_samples,
                 garbles_per_sample=n_garbles, seed=seed,
                 codec_names=sorted(s.name for s in specs))
    return findings, stats
