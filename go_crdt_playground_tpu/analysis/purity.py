"""JAX-purity lint: jit/Pallas-reachable code must be side-effect free.

A function traced by ``jax.jit`` (or compiled into a Pallas kernel) runs
its Python body ONCE, at trace time; any host side effect — wall-clock
reads, RNG draws, printing, file or socket I/O, global mutation — bakes
a single stale value into the compiled program or fires at compile time
instead of run time.  The merge kernels are the paper's hot path; a
``time.time()`` smuggled into one is a silent semantics bug, not a perf
nit.  This pass (P001):

* finds jit ROOTS in each ``ops/`` module: ``@jax.jit``-decorated
  functions, ``functools.partial(jax.jit, ...)`` decorations,
  ``x = jax.jit(f)`` module-level wrappings, and any function that
  calls ``pl.pallas_call`` (its kernel closures trace on device);
* walks the same-module call graph from those roots (imported helpers
  are out of scope — they are linted when their module is scanned);
* flags calls to banned host APIs and ``global``/``nonlocal``
  declarations inside reachable functions.

Allowed by design: ``jax.debug.print`` / ``jax.debug.callback`` (the
sanctioned effect escape hatches) and trace-time ``import`` statements
(cached, idempotent).  ``numpy`` host math on STATIC values is legal at
trace time and not flagged — only the named effectful APIs are banned,
because distinguishing static-time numpy from traced-value numpy needs
type inference a lint does not have.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (PURITY_VIOLATION,
                                                    SEVERITY_ERROR, Finding)

# dotted-call prefixes that are host effects inside traced code
_BANNED_PREFIXES = (
    "time.", "datetime.", "random.", "np.random.", "numpy.random.",
    "os.", "sys.", "socket.", "subprocess.", "threading.",
)
_BANNED_NAMES = {"print", "open", "input", "exec", "eval"}
# sanctioned escape hatches
_ALLOWED_DOTTED = {"jax.debug.print", "jax.debug.callback",
                   "jax.debug.breakpoint"}


def _dotted(node: ast.expr) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_decorator(dec: ast.expr) -> bool:
    """``@jax.jit`` / ``@jit`` / ``@functools.partial(jax.jit, ...)`` /
    ``@partial(jax.jit, ...)``."""
    d = _dotted(dec)
    if d in ("jax.jit", "jit"):
        return True
    if isinstance(dec, ast.Call):
        f = _dotted(dec.func)
        if f in ("functools.partial", "partial") and dec.args:
            return _dotted(dec.args[0]) in ("jax.jit", "jit")
        if f in ("jax.jit", "jit"):
            return True
    return False


def _calls_pallas(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None and d.endswith("pallas_call"):
                return True
    return False


def _module_functions(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    """Top-level functions (the call-graph nodes).  Methods are included
    under ``Class.name`` AND bare name for same-module resolution."""
    out: Dict[str, ast.FunctionDef] = {}
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, ast.FunctionDef):
                    out.setdefault(m.name, m)
    return out


def _jit_roots(tree: ast.Module,
               fns: Dict[str, ast.FunctionDef]) -> Set[str]:
    roots: Set[str] = set()
    for name, fn in fns.items():
        if any(_is_jit_decorator(d) for d in fn.decorator_list):
            roots.add(name)
        if _calls_pallas(fn):
            roots.add(name)
    # module-level ``x = jax.jit(f, ...)`` wrappings
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            f = _dotted(node.value.func)
            if f in ("jax.jit", "jit") and node.value.args:
                target = node.value.args[0]
                if isinstance(target, ast.Name) and target.id in fns:
                    roots.add(target.id)
                elif isinstance(target, ast.Lambda):
                    pass  # lambdas scanned via their enclosing function
    return roots


def _local_calls(fn: ast.FunctionDef,
                 fns: Dict[str, ast.FunctionDef]) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None and d in fns:
                out.add(d)
        elif isinstance(node, ast.Name) and node.id in fns:
            # bare function references (vmap(f), partial(f, ...))
            out.add(node.id)
    return out


def _check_function(fn: ast.FunctionDef, qual: str, path: str
                    ) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            findings.append(Finding(
                analyzer="purity", code=PURITY_VIOLATION,
                severity=SEVERITY_ERROR, path=path, line=node.lineno,
                symbol=qual,
                message=(f"{type(node).__name__.lower()} declaration in "
                         "jit/Pallas-reachable code: host mutation bakes "
                         "trace-time state into the compiled program")))
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None:
            continue
        if d in _ALLOWED_DOTTED:
            continue
        if d in _BANNED_NAMES:
            findings.append(Finding(
                analyzer="purity", code=PURITY_VIOLATION,
                severity=SEVERITY_ERROR, path=path, line=node.lineno,
                symbol=qual,
                message=(f"call to {d}() in jit/Pallas-reachable code: "
                         "host I/O fires at trace time, not run time")))
            continue
        for prefix in _BANNED_PREFIXES:
            if d.startswith(prefix):
                findings.append(Finding(
                    analyzer="purity", code=PURITY_VIOLATION,
                    severity=SEVERITY_ERROR, path=path, line=node.lineno,
                    symbol=qual,
                    message=(f"call to {d} in jit/Pallas-reachable code: "
                             "wall-clock/RNG/OS state is frozen at trace "
                             "time (hoist it to the host caller)")))
                break
    return findings


def analyze_file(path: str, source: Optional[str] = None,
                 loader: Optional[SourceLoader] = None
                 ) -> Tuple[List[Finding], Dict]:
    tree = ensure_loader(loader).load(path, source).tree
    fns = _module_functions(tree)
    roots = _jit_roots(tree, fns)
    # reachability over the same-module call graph
    reachable: Set[str] = set()
    frontier = list(roots)
    while frontier:
        name = frontier.pop()
        if name in reachable:
            continue
        reachable.add(name)
        for callee in _local_calls(fns[name], fns):
            if callee not in reachable:
                frontier.append(callee)
    findings: List[Finding] = []
    for name in sorted(reachable):
        findings.extend(_check_function(fns[name], name, path))
    stats = {"jit_roots": sorted(roots),
             "reachable_checked": len(reachable)}
    return findings, stats


def analyze_files(paths: List[str],
                  loader: Optional[SourceLoader] = None
                  ) -> Tuple[List[Finding], Dict]:
    loader = ensure_loader(loader)
    findings: List[Finding] = []
    stats: Dict = {"files": len(paths), "jit_roots": 0,
                   "reachable_checked": 0}
    for p in paths:
        f, s = analyze_file(p, loader=loader)
        findings.extend(f)
        stats["jit_roots"] += len(s["jit_roots"])
        stats["reachable_checked"] += s["reachable_checked"]
    return findings, stats
