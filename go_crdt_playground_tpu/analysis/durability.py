"""Durability-ordering lint: fsync must dominate every ack/rename.

The WAL/checkpoint contract (DESIGN.md §14) is "durable on return": a
record is fsync'd before the mutation it describes is acknowledged, and
an ``os.replace`` publishing a checkpoint must land only after the data
it renames into place is on disk.  This pass makes the ordering a lint
(D001) over the durability modules:

* every ``os.replace`` / ``os.rename`` call must be preceded — in the
  same function — by an fsync-ish call (``os.fsync``, any callee whose
  name contains ``fsync``, e.g. ``fsync_dir``/``_fsync_dir``);
* every function annotated ``# durable-on-return`` must contain an
  fsync-ish call (its plain return IS the ack);
* a conditional fsync counts ONLY when its guard is the documented
  opt-out toggle (``if self.fsync:`` / ``if <x>.fsync:``) — that switch
  exists for tests and benchmarks, and the lint must not force it away.

Approximation, stated plainly: domination is checked by SOURCE ORDER
within the function (an fsync on an earlier line dominates a later
target).  These modules are straight-line write-then-publish code, where
source order and execution order agree; exotic control flow would need
the real CFG, and belongs in review, not in this lint.  Calls into
helpers that fsync internally (``save_checkpoint``) are credited via the
``fsync``-in-name rule plus a per-run set of locally-defined functions
known to fsync (one transitive pass).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from go_crdt_playground_tpu.analysis.annotations import \
    KIND_DURABLE_ON_RETURN
from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (FSYNC_MISSING,
                                                    SEVERITY_ERROR, Finding)

_RENAME_FUNCS = {"replace", "rename"}


def _call_name(node: ast.Call) -> Optional[str]:
    """Trailing name of the callee: ``os.fsync`` -> "fsync"."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


def _is_rename(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in _RENAME_FUNCS
            and isinstance(f.value, ast.Name) and f.value.id == "os")


def _is_fsync_call(node: ast.Call, known_fsyncers: Set[str]) -> bool:
    name = _call_name(node)
    if name is None:
        return False
    return "fsync" in name or name in known_fsyncers


class _FunctionScan:
    """Source-ordered fsync/target events of one function."""

    def __init__(self, fn: ast.FunctionDef, known_fsyncers: Set[str]):
        self.fn = fn
        self.fsync_lines: List[int] = []
        self.targets: List[Tuple[int, str]] = []  # (line, what)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _is_fsync_call(node, known_fsyncers):
                # an fsync gated on the ``if self.fsync:`` toggle still
                # counts — that switch is the documented test/bench
                # opt-out, not a missing-durability bug
                self.fsync_lines.append(node.lineno)
            elif _is_rename(node):
                self.targets.append((node.lineno,
                                     f"os.{node.func.attr}"))

    def first_fsync_before(self, line: int) -> Optional[int]:
        prior = [ln for ln in self.fsync_lines if ln < line]
        return max(prior) if prior else None


def _local_fsyncers(tree: ast.Module) -> Set[str]:
    """Module functions that (transitively, one fixpoint) fsync —
    credited at their call sites in the same module."""
    fns = {n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}
    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        for m in cls.body:
            if isinstance(m, ast.FunctionDef):
                fns.setdefault(m.name, m)
    known: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, fn in fns.items():
            if name in known:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and _is_fsync_call(node, known):
                    known.add(name)
                    changed = True
                    break
    return known


def analyze_file(path: str, source: Optional[str] = None,
                 loader: Optional[SourceLoader] = None
                 ) -> Tuple[List[Finding], Dict]:
    pf = ensure_loader(loader).load(path, source)
    tree, annots = pf.tree, pf.annotations
    known = _local_fsyncers(tree)
    findings: List[Finding] = []
    n_fns = n_targets = 0

    def scan_function(fn: ast.FunctionDef, qual: str) -> None:
        nonlocal n_fns, n_targets
        n_fns += 1
        scan = _FunctionScan(fn, known)
        durable = annots.on_lines(fn.lineno, fn.body[0].lineno - 1,
                                  KIND_DURABLE_ON_RETURN) is not None
        for line, what in scan.targets:
            n_targets += 1
            if scan.first_fsync_before(line) is None:
                findings.append(Finding(
                    analyzer="durability", code=FSYNC_MISSING,
                    severity=SEVERITY_ERROR, path=path, line=line,
                    symbol=qual,
                    message=(f"{what} at line {line} is not dominated by "
                             "an fsync in this function: the rename can "
                             "publish data the disk never received")))
        if durable:
            n_targets += 1
            if not scan.fsync_lines:
                findings.append(Finding(
                    analyzer="durability", code=FSYNC_MISSING,
                    severity=SEVERITY_ERROR, path=path, line=fn.lineno,
                    symbol=qual,
                    message=("function is annotated durable-on-return "
                             "but contains no fsync: its ack is a lie "
                             "under power loss")))

    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            scan_function(node, node.name)
        elif isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, ast.FunctionDef):
                    scan_function(m, f"{node.name}.{m.name}")
    stats = {"functions": n_fns, "checked_points": n_targets,
             "local_fsyncers": sorted(known)}
    return findings, stats


def analyze_files(paths: List[str],
                  loader: Optional[SourceLoader] = None
                  ) -> Tuple[List[Finding], Dict]:
    loader = ensure_loader(loader)
    findings: List[Finding] = []
    stats: Dict = {"files": len(paths), "functions": 0,
                   "checked_points": 0}
    for p in paths:
        f, s = analyze_file(p, loader=loader)
        findings.extend(f)
        stats["functions"] += s["functions"]
        stats["checked_points"] += s["checked_points"]
    return findings, stats
