"""The annotation grammar shared by every analyzer pass.

Annotations are structured comments — the code already carried the
contracts in prose ("Caller holds the lock."); this makes them machine-
checkable without changing any runtime behavior.  Grammar (DESIGN.md
§15; one annotation per line, attached to the statement that spans the
comment's line):

    # guarded-by: <lock>        field annotation, on the ``self.f = ...``
                                line (usually in ``__init__``): every
                                later access to ``f`` must hold ``<lock>``
                                (a ``with self.<lock>:`` block or a
                                ``requires-lock`` method).
    # requires-lock: <lock>     method annotation, on the ``def`` line:
                                the CALLER must hold ``<lock>``; the body
                                may then touch guarded fields freely, and
                                every call site of the method is checked
                                instead.
    # race-ok: <reason>         field annotation: excluded from both the
                                static lint and the runtime lockset
                                detector, with the reason on record
                                (benign flags, owner-thread-only fields).
    # durable-on-return         function annotation: the durability lint
                                requires an fsync to dominate the end of
                                this function (its return IS the ack).
    # protocol-ignore: <what> — <reason>
                                wire-contract annotation (W001, analysis/
                                protocol_contract.py).  On a ``MSG_*``
                                constant's definition line, ``<what>`` is
                                a direction keyword: ``reply`` (client-
                                inbound — must have an arm in the client
                                reader instead of the servers) or
                                ``internal`` (consumed below dispatch,
                                e.g. MSG_ERROR raised inside recv_frame).
                                Inside a dispatcher function, ``<what>``
                                names the MSG_* constant this dispatcher
                                deliberately does not serve.  The reason
                                is required either way — an unexplained
                                hole in dispatch coverage is exactly the
                                drift the pass exists to catch.
    # fence-ok: <reason>        handler annotation (E002, analysis/
                                fence_coverage.py), on the handler's
                                ``def`` line: this write-verb handler
                                deliberately serves without consulting
                                the fence predicate — legitimate only
                                for the epoch-adjudication verbs that
                                ARE the fence mechanism (RING_SYNC /
                                WAL_SYNC persist-then-adopt).  The
                                reason is on record; an unexplained
                                unfenced write verb fails the gate.
    # transfer-ok: <reason>     statement annotation (D002, analysis/
                                transfer_lock.py): this blocking
                                device→host transfer (``jax.device_get``
                                / ``block_until_ready``) is sanctioned
                                under (or reachable from) a held lock —
                                the reason states why it is one bounded
                                pull, not the PR-8 per-field sweep.

``<lock>`` names an attribute of the same object (``_lock``,
``_conn_slots``).  Parsing is tokenize-based so annotations survive any
formatting; attachment is by line coverage of the enclosing statement
(multi-line statements carry the annotation on any of their lines,
conventionally the first).  A STANDALONE annotation comment (its own
line) attaches to the next STATEMENT line — continuation comment lines
and blank lines below it are skipped — so long reasons can wrap without
fighting the line-length limit.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_ANNOT_RE = re.compile(
    r"#\s*(guarded-by|requires-lock|race-ok|durable-on-return"
    r"|protocol-ignore|fence-ok|transfer-ok)\s*"
    r"(?::\s*(?P<arg>\S[^#]*?))?\s*$")

KIND_GUARDED_BY = "guarded-by"
KIND_REQUIRES_LOCK = "requires-lock"
KIND_RACE_OK = "race-ok"
KIND_DURABLE_ON_RETURN = "durable-on-return"
KIND_PROTOCOL_IGNORE = "protocol-ignore"
KIND_FENCE_OK = "fence-ok"
KIND_TRANSFER_OK = "transfer-ok"

_ARG_REQUIRED = {KIND_GUARDED_BY, KIND_REQUIRES_LOCK, KIND_RACE_OK,
                 KIND_PROTOCOL_IGNORE, KIND_FENCE_OK, KIND_TRANSFER_OK}


@dataclass
class Annotation:
    kind: str
    arg: Optional[str]   # lock name / reason; None for durable-on-return
    line: int


@dataclass
class AnnotationSet:
    """All annotations of one source file, indexed by line.

    ``every`` keeps all annotations in source order and is what
    ``on_lines`` searches — a statement can carry annotations of
    different kinds (a guarded-by plus a trailing protocol-ignore),
    and the wire-contract pass reads stacked ``protocol-ignore``
    comments that attach to the same statement.  ``by_line`` keeps the
    LAST annotation per line, retained for diagnostics only."""

    by_line: Dict[int, Annotation] = field(default_factory=dict)
    every: List[Annotation] = field(default_factory=list)
    malformed: List[str] = field(default_factory=list)

    def on_lines(self, first: int, last: int,
                 kind: Optional[str] = None) -> Optional[Annotation]:
        """The annotation attached to a statement spanning [first, last]
        (earliest line wins; statements conventionally annotate their
        first line).  Searches ``every``, not the single-slot
        ``by_line``: a statement can legitimately carry annotations of
        DIFFERENT kinds (a guarded-by above it plus a trailing
        protocol-ignore), and a kind-filtered lookup must never be
        shadowed by the other kind landing on the same line."""
        best: Optional[Annotation] = None
        for a in self.every:
            if (first <= a.line <= last
                    and (kind is None or a.kind == kind)
                    and (best is None or a.line < best.line)):
                best = a
        return best


def parse_annotations(source: str, path: str = "<string>") -> AnnotationSet:
    """Extract every analyzer annotation from ``source``.  Unknown
    comment shapes are ignored (they are just comments); a RECOGNIZED
    keyword with a missing required argument is recorded as malformed so
    the lint can surface the typo instead of silently skipping the
    contract."""
    out = AnnotationSet()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(t.start[0], t.start[1], t.line, t.string)
                    for t in tokens if t.type == tokenize.COMMENT]
    except tokenize.TokenError:
        return out
    standalone_lines = {ln for ln, col, srcline, _ in comments
                        if srcline[:col].strip() == ""}
    src_lines = source.splitlines()

    def _skippable(ln: int) -> bool:
        # comment-only continuation lines and blank separator lines sit
        # between a standalone annotation and the statement it means
        return (ln in standalone_lines
                or (ln - 1 < len(src_lines)
                    and not src_lines[ln - 1].strip()))

    for line, col, srcline, text in comments:
        # a standalone comment line annotates the statement BELOW it —
        # skipping further comment-only and blank lines first, so an
        # annotation whose reason wraps (or that sits a blank line
        # above its statement) still lands on the statement
        if srcline[:col].strip() == "":
            line += 1
            while line <= len(src_lines) and _skippable(line):
                line += 1
        m = _ANNOT_RE.search(text)
        if not m:
            # a comment that STARTS with an annotation keyword but fails
            # the strict grammar (missing colon, empty argument) is a
            # typo'd contract — silent skip would un-check the very
            # invariant the author tried to state.  Prose merely
            # mentioning a keyword mid-comment is left alone.
            if re.match(r"#\s*(guarded-by|requires-lock|race-ok"
                        r"|protocol-ignore|fence-ok|transfer-ok)\b",
                        text):
                out.malformed.append(
                    f"{path}:{line}: malformed annotation {text.strip()!r}"
                    " (expected '# <kind>: <arg>')")
            continue
        kind = m.group(1)
        arg = m.group("arg")
        arg = arg.strip() if arg else None
        if kind in _ARG_REQUIRED and not arg:
            out.malformed.append(
                f"{path}:{line}: annotation '# {kind}:' needs an argument")
            continue
        ann = Annotation(kind=kind, arg=arg, line=line)
        out.by_line[line] = ann
        out.every.append(ann)
    return out
