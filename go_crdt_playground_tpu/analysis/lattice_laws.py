"""Lattice-law checker: the joins must actually be joins.

Commutativity, associativity, and idempotence are the load-bearing
assumptions of the whole stack: they are why a lost exchange is only
delayed convergence (SURVEY §5.3), why WAL replay after a crash is
harmless double-merge (DESIGN.md §14), and why the δ-CRDT literature can
ship fragments instead of states (Almeida et al., arXiv:1410.2803).  A
"join" that quietly violates one converges only on the schedules the
tests happened to run — the worst kind of latent bug.

This pass enumerates ``ops.lattices.JOIN_REGISTRY`` (which
``ops.merge`` extends with the AWSet kernel) and, per family:

* samples batched REACHABLE states with the family's seeded sampler
  (random ops + gossip mixing — the laws are promised over reachable
  states, not arbitrary bit patterns);
* builds row-wise triples (a, b, c) via seeded row permutations of the
  sample (so operands share causal history, the interesting regime);
* checks, on the family's observable projection:
      commutativity   join(a, b) == join(b, a)
      associativity   join(join(a, b), c) == join(a, join(b, c))
      idempotence     join(a, a) == a
* reports the first counterexample row per (family, law, seed) with the
  differing field (J001/J002/J003, gate-failing).

Everything is seeded and CPU-sized (rows ~9, ops ~40 per seed); the
``--fast`` gate trims seeds, not families — every registered join is
checked on every run.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from go_crdt_playground_tpu.analysis.report import (LAW_ASSOCIATIVITY,
                                                    LAW_COMMUTATIVITY,
                                                    LAW_DECLARATION,
                                                    LAW_IDEMPOTENCE,
                                                    SEVERITY_ERROR, Finding)

_LAW_CODES = {
    "commutativity": LAW_COMMUTATIVITY,
    "associativity": LAW_ASSOCIATIVITY,
    "idempotence": LAW_IDEMPOTENCE,
}


def _diff_rows(pa: Dict[str, np.ndarray],
               pb: Dict[str, np.ndarray],
               atol: float = 0.0) -> Optional[Tuple[int, str]]:
    """(row, field) of the first mismatch between two projections.
    ``atol`` > 0 compares float fields with an absolute tolerance —
    for joins whose declared laws hold only up to IEEE rounding (the
    weighted-mean accumulator's associativity); integer/bool fields
    stay exact either way."""
    for field in pa:
        a, b = pa[field], pb[field]
        if a.shape != b.shape:
            return 0, field
        if atol > 0 and np.issubdtype(a.dtype, np.floating):
            neq = ~np.isclose(a, b, rtol=0.0, atol=atol)
        else:
            neq = a != b
        if neq.ndim > 1:
            neq = neq.reshape(neq.shape[0], -1).any(axis=1)
        if neq.any():
            return int(np.argmax(neq)), field
    return None


def _permuted(state, rng: np.random.Generator):
    import jax

    n = int(state[0].shape[0])
    perm = np.asarray(rng.permutation(n))
    return jax.tree.map(lambda x: x[np.asarray(perm)], state)


def check_join_spec(spec, seeds: Sequence[int], *, n_rows: int = 9,
                    n_ops: int = 40) -> Tuple[List[Finding], Dict]:
    """Property-check one JoinSpec over its DECLARED law subset
    (``JoinSpec.laws`` — the model-merging strategies claim fewer laws
    than a lattice join, with the why on record in ops/lattices.py);
    returns (findings, stats).  A spec claiming no laws at all is an
    error, not a skip — "registered but unchecked" must be
    impossible."""
    findings: List[Finding] = []
    checked = 0
    laws = tuple(getattr(spec, "laws", tuple(_LAW_CODES)))
    atol = float(getattr(spec, "atol", 0.0))
    unknown = [law for law in laws if law not in _LAW_CODES]
    if unknown or not laws:
        findings.append(Finding(
            analyzer="lattice_laws", code=LAW_DECLARATION,
            severity=SEVERITY_ERROR, symbol=spec.name,
            message=(f"join {spec.name!r} declares an invalid law "
                     f"subset {laws!r} (unknown: {unknown}) — every "
                     "registered join must claim at least one known "
                     "law")))
        return findings, {"seeds": list(seeds), "laws_checked": 0,
                          "laws": list(laws), "n_rows": n_rows,
                          "n_ops": n_ops}
    for seed in seeds:
        rng = np.random.default_rng(seed)
        base = spec.sample(rng, n_rows, n_ops)
        a = base
        b = _permuted(base, rng)
        c = _permuted(base, rng)
        join, project = spec.join, spec.project

        cases = (
            ("commutativity", lambda: (join(a, b), join(b, a))),
            ("associativity", lambda: (join(join(a, b), c),
                                       join(a, join(b, c)))),
            ("idempotence", lambda: (join(a, a), a)),
        )
        for law, make in cases:
            if law not in laws:
                continue
            lhs, rhs = make()
            checked += 1
            # commutativity is checked on the SYMMETRIC part of the
            # projection: fields the join defines as dst-anchored
            # (none today) would be excluded by the spec's project()
            diff = _diff_rows(project(lhs), project(rhs), atol)
            if diff is not None:
                row, field = diff
                findings.append(Finding(
                    analyzer="lattice_laws", code=_LAW_CODES[law],
                    severity=SEVERITY_ERROR, symbol=spec.name,
                    message=(f"{law} counterexample for join "
                             f"{spec.name!r}: field {field!r} differs at "
                             f"row {row} (seed {seed}, n_rows {n_rows}, "
                             f"n_ops {n_ops}) — this join does not "
                             "satisfy its declared laws over reachable "
                             "states")))
                break  # further laws on a broken join add noise
    return findings, {"seeds": list(seeds), "laws_checked": checked,
                      "laws": list(laws), "n_rows": n_rows,
                      "n_ops": n_ops}


def check_registry(seeds: Sequence[int] = (11, 12, 13), *,
                   n_rows: int = 9, n_ops: int = 40,
                   registry: Optional[Dict] = None
                   ) -> Tuple[List[Finding], Dict]:
    """Check every registered join (importing ops.merge first so its
    registration has run)."""
    from go_crdt_playground_tpu.ops import lattices
    from go_crdt_playground_tpu.ops import merge  # noqa: F401  (registers)

    reg = lattices.JOIN_REGISTRY if registry is None else registry
    findings: List[Finding] = []
    stats: Dict = {"families": sorted(reg), "per_family": {},
                   "laws_by_family": {}}
    for name in sorted(reg):
        f, s = check_join_spec(reg[name], seeds, n_rows=n_rows,
                               n_ops=n_ops)
        findings.extend(f)
        stats["per_family"][name] = s["laws_checked"]
        stats["laws_by_family"][name] = s["laws"]
    return findings, stats
