"""E002 — fence-coverage lint: no write verb ships unfenced (§26).

The serving tiers self-fence on two predicates: the ROUTER refuses to
act once a shard adjudicates a higher router epoch (``deposed``, plus
the per-key handoff fence ``RouteState.fenced``), and the FRONTEND
refuses once its keyspace's shard epoch moved past it
(``shard_deposed``) or a router-epoch fence is armed
(``_epoch_fenced``).  Every dispatcher arm that can MUTATE state —
accept an op, push a slice, run GC, swap a ring — must consult one of
those predicates before acting, or a resurrected deposed member
silently accepts writes the surviving fleet never sees (the
acked-writes-stranded hazard of DESIGN.md §22/§23).

This pass walks each registered dispatcher, resolves every write-verb
arm to its handler method(s), and requires the handler (or the arm
itself) to reference a fence predicate symbol.  The two legitimate
exceptions — RING_SYNC and WAL_SYNC, the epoch-adjudication verbs that
ARE the fence mechanism (persist-then-adopt; they must answer even on
a deposed member so it can learn its own deposition) — carry a
``# fence-ok: <reason>`` annotation on their handler's ``def`` line.
A fence-ok on a handler that DOES consult the predicate is stale and
fails the gate: an annotation that can never matter proves nothing.

New write verbs hit this pass by registration: the verb lists below
are part of the contract, and ``test_gate_fast`` pins their census so
a verb added to the dialect without a fence decision fails tier-1.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

from go_crdt_playground_tpu.analysis.annotations import KIND_FENCE_OK
from go_crdt_playground_tpu.analysis.loader import SourceLoader, ensure_loader
from go_crdt_playground_tpu.analysis.report import (FENCE_UNCOVERED,
                                                    SEVERITY_ERROR, Finding)


class FenceSpec(NamedTuple):
    """One dispatcher's fence contract: each verb in ``write_verbs``
    must resolve to a handler that references one of ``predicates`` or
    carries a fence-ok annotation."""

    name: str
    path: str
    qualname: str                 # "Class._dispatch"
    write_verbs: Tuple[str, ...]  # MSG_* constants that mutate state
    predicates: Tuple[str, ...]   # fence predicate attribute names


# THE registry (DESIGN.md §26).  Read verbs (QUERY/STATS/DSUM) are
# deliberately absent: fences must never block reads — that invariant
# is the model checker's, not this lint's.
FENCE_SPECS: Tuple[FenceSpec, ...] = (
    FenceSpec("frontend", "serve/frontend.py", "ServeFrontend._dispatch",
              write_verbs=("MSG_OP", "MSG_SLICE_PUSH", "MSG_GC",
                           "MSG_RING_SYNC", "MSG_WAL_SYNC"),
              predicates=("_epoch_fenced", "shard_deposed")),
    FenceSpec("router", "shard/router.py", "ShardRouter._dispatch",
              write_verbs=("MSG_OP", "MSG_RESHARD", "MSG_RING_SYNC",
                           "MSG_SHARD_FAILOVER"),
              predicates=("deposed", "fenced")),
)


def _find_method(tree: ast.Module, cls_name: str, meth: str
                 ) -> Optional[ast.FunctionDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for sub in node.body:
                if (isinstance(sub, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))
                        and sub.name == meth):
                    return sub
    return None


def _references_any(fn: ast.AST, symbols: Sequence[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in symbols:
            return True
        if isinstance(node, ast.Attribute) and node.attr in symbols:
            return True
    return False


def _arm_for_verb(dispatch: ast.FunctionDef, verb: str
                  ) -> Optional[ast.If]:
    """The ``if msg_type == protocol.MSG_X:`` arm comparing to
    ``verb`` (by trailing attribute or bare name)."""
    for node in ast.walk(dispatch):
        if not isinstance(node, ast.If):
            continue
        for sub in ast.walk(node.test):
            if ((isinstance(sub, ast.Name) and sub.id == verb)
                    or (isinstance(sub, ast.Attribute)
                        and sub.attr == verb)):
                return node
    return None


def _handlers_called(arm_body: List[ast.stmt]) -> List[str]:
    """``self._handle_*``-shaped method names called in the arm body."""
    out: List[str] = []
    for stmt in arm_body:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                out.append(node.func.attr)
    return out


def check_spec(spec: FenceSpec, tree: ast.Module, annots, path: str
               ) -> Tuple[List[Finding], Dict]:
    findings: List[Finding] = []
    covered = 0
    annotated = 0
    cls_name = spec.qualname.split(".", 1)[0]
    dispatch = _find_method(tree, cls_name,
                            spec.qualname.split(".", 1)[1])
    if dispatch is None:
        findings.append(Finding(
            analyzer="fence_coverage", code=FENCE_UNCOVERED,
            severity=SEVERITY_ERROR, path=path, symbol=spec.qualname,
            message=f"registered dispatcher {spec.qualname} not found "
                    f"in {spec.path}"))
        return findings, {"verbs": 0, "covered": 0, "fence_ok": 0}
    for verb in spec.write_verbs:
        arm = _arm_for_verb(dispatch, verb)
        if arm is None:
            findings.append(Finding(
                analyzer="fence_coverage", code=FENCE_UNCOVERED,
                severity=SEVERITY_ERROR, path=path, line=dispatch.lineno,
                symbol=f"{spec.name}:{verb}",
                message=(f"registered write verb {verb} has no arm in "
                         f"{spec.qualname} — if the verb left the "
                         "dialect, drop it from FENCE_SPECS; an "
                         "unresolvable registration checks nothing")))
            continue
        handlers = _handlers_called(arm.body)
        handler_fns = [(h, _find_method(tree, cls_name, h))
                       for h in handlers]
        handler_fns = [(h, f) for h, f in handler_fns if f is not None]
        # the arm may fence inline (rare) or in any called handler
        fenced = _references_any(arm, spec.predicates) or any(
            _references_any(f, spec.predicates) for _, f in handler_fns)
        ann = None
        for _, f in handler_fns:
            ann = annots.on_lines(f.lineno, f.body[0].lineno - 1,
                                  KIND_FENCE_OK)
            if ann is not None:
                break
        if fenced and ann is not None:
            findings.append(Finding(
                analyzer="fence_coverage", code=FENCE_UNCOVERED,
                severity=SEVERITY_ERROR, path=path, line=ann.line,
                symbol=f"{spec.name}:{verb}",
                message=(f"stale fence-ok: the {verb} handler DOES "
                         f"reference a fence predicate "
                         f"({'/'.join(spec.predicates)}) — drop the "
                         "annotation so the lint keeps checking it")))
            continue
        if fenced:
            covered += 1
            continue
        if ann is not None:
            annotated += 1
            continue
        handler_names = ", ".join(h for h, _ in handler_fns) or "<inline>"
        findings.append(Finding(
            analyzer="fence_coverage", code=FENCE_UNCOVERED,
            severity=SEVERITY_ERROR, path=path, line=arm.lineno,
            symbol=f"{spec.name}:{verb}",
            message=(f"write verb {verb} ({handler_names}) consults no "
                     f"fence predicate ({'/'.join(spec.predicates)}) "
                     "and carries no fence-ok annotation: a deposed "
                     "member would accept this mutation after the "
                     "fleet moved on — fence it or annotate the "
                     "handler's def line with the reason")))
    return findings, {"verbs": len(spec.write_verbs), "covered": covered,
                      "fence_ok": annotated}


def analyze(root: str,
            specs: Sequence[FenceSpec] = FENCE_SPECS,
            loader: Optional[SourceLoader] = None,
            sources: Optional[Dict[str, str]] = None
            ) -> Tuple[List[Finding], Dict]:
    """``specs``/``sources`` are injectable for planted-violation
    tests, protocol_contract-style."""
    loader = ensure_loader(loader)
    findings: List[Finding] = []
    stats: Dict = {"dispatchers": {}, "write_verbs": 0, "covered": 0,
                   "fence_ok": 0}
    for spec in specs:
        path = os.path.join(root, spec.path)
        planted = (sources or {}).get(spec.path)
        pf = loader.load(path, planted)
        f, s = check_spec(spec, pf.tree, pf.annotations, path)
        findings.extend(f)
        stats["dispatchers"][spec.name] = s
        stats["write_verbs"] += s["verbs"]
        stats["covered"] += s["covered"]
        stats["fence_ok"] += s["fence_ok"]
    return findings, stats
