"""Sharded serving fleet (DESIGN.md §17 "Sharded fleet").

The step from one replica to a fleet: a seeded consistent-hash ring
partitions the element universe across N `serve/` ingest frontends
(each an ordinary durable `net/peer.Node` replica on its own actor
lane), and a thin router tier speaks the EXISTING serve dialect on both
sides — clients dial the router with an unmodified ``ServeClient``, the
router forwards each OP to the owning shard over pipelined downstream
clients, relays typed ACK/REJECT back preserving req_ids, and fans
QUERY/MEMBERS/STATS out across the fleet.  Per-shard anti-entropy and
durability payloads stay O(shard), not O(universe) — the precondition
for the O(diff) digest rounds of PAPERS.md arxiv 1803.02750.

A dead shard degrades, never silently drops: ops owned by its keyspace
get a typed ``ShardUnavailable`` reject (gated by the existing
circuit-breaker/backoff machinery), while every surviving shard's
keyspace keeps serving.
"""

from go_crdt_playground_tpu.shard.fleet import (FleetSpec,  # noqa: F401
                                                RouterProc, ShardFleet,
                                                ShardProc)
from go_crdt_playground_tpu.shard.ha import RouterStandby  # noqa: F401
from go_crdt_playground_tpu.shard.handoff import (HandoffCoordinator,  # noqa: F401
                                                  HandoffError, RouteState)
from go_crdt_playground_tpu.shard.ring import HashRing  # noqa: F401
from go_crdt_playground_tpu.shard.router import ShardRouter  # noqa: F401
