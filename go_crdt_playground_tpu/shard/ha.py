"""Router high availability: a warm standby with fenced promotion
(DESIGN.md §22).

Every durability ladder below the routing tier survives SIGKILL with
zero acked-op loss — but the ``ShardRouter`` itself was one process: a
dead router took the whole fleet dark even though every shard beneath
it kept serving.  ``RouterStandby`` closes that hole with the cheapest
correct shape the existing machinery allows:

* **tail** — the standby polls the primary's ``RING_SYNC`` record (the
  committed ``RouteState``: generation, owner-map digest, shard map
  WITH addresses, the handoff-epoch counter, the primary's router
  epoch) and persists it into its own ``state_dir`` in the exact
  ``ring.json`` shape ``shard/handoff.py`` commits — so promotion is
  literally the router-restart path: ``ShardRouter(state_dir=...)``
  adopts the last ring the primary COMMITTED, never a staged or
  half-transferred one (a kill mid-handoff reads as aborted, same as a
  primary restart).
* **health-check** — the same poll is the health probe: N consecutive
  transport failures (connection refused/torn/timeout) trip promotion.
  One wrong promotion is SAFE, not split-brain: the data plane through
  either router is idempotent CRDT traffic over the same committed
  ring, and the admin plane is epoch-fenced below.
* **promote** — the standby persists ``router_epoch =
  max(primary's, own) + 1`` (fsync-then-rename, BEFORE anything is
  announced or served), constructs a real ``ShardRouter`` over the
  tailed ring under that epoch, ANNOUNCES the epoch to every reachable
  shard (``announce_epoch`` fan-out — from each shard's fsync on, any
  admin verb under a lower epoch rejects typed ``StaleRouterEpoch``),
  then binds its pre-declared listen address.  Clients carrying the
  ordered address list (``ServeClient`` failover) rotate to it; their
  in-flight ops surfaced typed-ambiguous and resubmit idempotently.
* **deposed primary** — a resurrected primary still serves reads and
  idempotent OPs (harmless: same ring, CRDT join), but every admin
  action is contained: its links announce the OLD epoch per connection
  and the shards reject typed, so it can never commit a reshard
  transfer or force a GC drop; its own RESHARD verb also refuses once
  it HEARS the higher epoch (the router self-fence).

Counters: ``router.ha.polls`` / ``router.ha.poll_failures`` /
``router.ha.tail_records`` / ``router.ha.promotions``.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Dict, Mapping, Optional, Tuple

from go_crdt_playground_tpu.shard.handoff import (PHASE_COMMITTED,
                                                  RING_FILE,
                                                  load_router_epoch,
                                                  persist_router_epoch,
                                                  write_json_atomic)
from go_crdt_playground_tpu.shard.router import ShardRouter

Addr = Tuple[str, int]

# poll_once() verdicts (the state-machine seam tests drive directly)
POLL_TAILED = "tailed"       # primary answered; record tailed/persisted
POLL_FAILED = "failed"       # transport failure, below the threshold
POLL_PROMOTED = "promoted"   # threshold crossed: this poll promoted us


class RouterStandby:
    """Warm standby for one ``ShardRouter`` primary (module docstring).

    Single promotion per instance: after ``promote()`` the standby IS
    a serving router (``self.router``) and the tail loop exits.  The
    standby owns the router it creates until ``close()``.
    """

    def __init__(self, primary: Addr, shards: Mapping[str, Addr],
                 num_elements: int, *, seed: int = 0,
                 state_dir: Optional[str] = None,
                 standby_id: str = "router-standby",
                 listen_addr: Optional[Addr] = None,
                 poll_interval_s: float = 0.25,
                 failure_threshold: int = 3,
                 poll_timeout_s: float = 2.0,
                 recorder=None,
                 router_kwargs: Optional[dict] = None):
        from go_crdt_playground_tpu.obs import Recorder

        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        from go_crdt_playground_tpu.serve.client import normalize_addrs

        self.primary = (primary[0], int(primary[1]))
        # values may be single pairs or ordered replication-group
        # rosters (DESIGN.md §23); keep whatever shape arrives — the
        # promoted ShardRouter normalizes either
        norm = {sid: normalize_addrs(a) for sid, a in shards.items()}
        self.shards = {sid: (addrs[0] if len(addrs) == 1 else addrs)
                       for sid, addrs in norm.items()}
        self.num_elements = int(num_elements)
        self.seed = int(seed)
        self.state_dir = state_dir
        if state_dir is not None:
            import os

            os.makedirs(state_dir, exist_ok=True)
        self.standby_id = standby_id
        self.listen_addr = (None if listen_addr is None
                            else (listen_addr[0], int(listen_addr[1])))
        self.poll_interval_s = float(poll_interval_s)
        self.failure_threshold = int(failure_threshold)
        self.poll_timeout_s = float(poll_timeout_s)
        self.recorder = recorder if recorder is not None else Recorder()
        # extra ShardRouter kwargs the promotion passes through
        # (timeouts, breaker knobs) — race-ok: read-only after __init__
        self.router_kwargs = dict(router_kwargs or {})
        self._lock = threading.Lock()
        # serializes the WHOLE promotion sequence (epoch persist →
        # router build → announce → bind): the router-is-None check at
        # promote() entry alone would let a manual promote racing the
        # poll loop build two live routers — with listen_addr the loser
        # merely fails on bind, but embedded (listen_addr=None) both
        # would survive and one leaks its shard links and readers.
        # Never held while _lock is held the other way: the order is
        # _promote_lock -> _lock
        self._promote_lock = threading.Lock()
        self._client = None  # guarded-by: _lock
        self._failures = 0  # guarded-by: _lock
        self._last_record: Optional[Dict] = None  # guarded-by: _lock
        self._last_primary_epoch = load_router_epoch(
            state_dir)  # guarded-by: _lock
        self._persisted_generation: Optional[int] = None  # guarded-by: _lock
        self.router: Optional[ShardRouter] = None  # guarded-by: _lock
        self._promotion_s: Optional[float] = None  # guarded-by: _lock
        self._announce_results: Dict = {}  # guarded-by: _lock
        self._promote_reason: Optional[str] = None  # guarded-by: _lock
        self._warned_epoch_zero = False  # guarded-by: _lock
        self._promoted = threading.Event()
        self._stop_loop = threading.Event()
        # race-ok: start()/close() owner thread only
        self._thread: Optional[threading.Thread] = None

    # -- observers ----------------------------------------------------------

    @property
    def promoted(self) -> bool:
        return self._promoted.is_set()

    @property
    def last_record(self) -> Optional[Dict]:
        """The most recently tailed primary record (None before the
        first successful poll)."""
        with self._lock:
            return (None if self._last_record is None
                    else dict(self._last_record))

    @property
    def promotion_s(self) -> Optional[float]:
        """Wall seconds the promotion itself took (persist epoch →
        router constructed → fleet announced → listener bound)."""
        with self._lock:
            return self._promotion_s

    @property
    def announce_results(self) -> Dict:
        """sid -> True | failure string from the promotion announce."""
        with self._lock:
            return dict(self._announce_results)

    @property
    def promote_reason(self) -> Optional[str]:
        """Why this standby promoted (None before promotion)."""
        with self._lock:
            return self._promote_reason

    def await_promoted(self, timeout_s: float) -> bool:
        return self._promoted.wait(timeout_s)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("standby already running")
        self._stop_loop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="router-standby",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop the tail loop (the promoted router, if any, keeps
        serving — ``close()`` tears everything down)."""
        self._stop_loop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.poll_timeout_s
                   + self.poll_interval_s + 10.0)
        self._drop_client()

    def close(self) -> None:
        self.stop()
        # _promote_lock: a manual promote() mid-sequence finishes (or
        # unwinds) before the router is read — without it, close()
        # could observe router=None while the racing promote is between
        # construction and the store, leaking the router it builds
        # (shard links, reader threads, a bound listener)
        with self._promote_lock:
            with self._lock:
                router = self.router
        if router is not None:
            router.close()

    def __enter__(self) -> "RouterStandby":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _loop(self) -> None:
        while not self._stop_loop.wait(self.poll_interval_s):
            try:
                if self.poll_once() == POLL_PROMOTED:
                    return
            except Exception:  # noqa: BLE001 — the standby must
                # outlive any single bad poll; the next wake retries
                # (a promotion failure is retried the same way: the
                # failure count is still past threshold)
                self._count("router.ha.loop_errors")

    # -- the tail/health/promotion state machine ----------------------------

    def poll_once(self) -> str:
        """One tail/health probe (the loop body, exposed so tests
        drive the state machine without wall-clock waits).  Returns a
        ``POLL_*`` verdict."""
        import socket as socket_mod

        if self._promoted.is_set():
            return POLL_PROMOTED
        self._count("router.ha.polls")
        try:
            record = self._tail_client().ring_sync(0, self.standby_id)
        except (OSError, ConnectionError, socket_mod.timeout) as e:
            self._drop_client()
            self._count("router.ha.poll_failures")
            with self._lock:
                self._failures += 1
                failures = self._failures
                tailed = self._last_record is not None
            if failures >= self.failure_threshold:
                if not tailed and load_router_epoch(self.state_dir) == 0:
                    # NEVER tailed (and no prior epoch on disk): this
                    # standby holds neither the primary's committed
                    # ring nor its epoch — promoting would serve the
                    # possibly-stale FLAG ring under an epoch that can
                    # COLLIDE with the primary's own (equal epochs
                    # adjudicate as current: no fence).  Warm means
                    # tailed; keep polling and let the operator see
                    # the counter instead
                    self._count("router.ha.promote_blocked")
                    return POLL_FAILED
                self.promote(reason=f"{failures} consecutive poll "
                                    f"failures: {e}")
                return POLL_PROMOTED
            return POLL_FAILED
        self._ingest_record(record)
        return POLL_TAILED

    def _ingest_record(self, record: Dict) -> None:
        """Adopt one tailed primary record: reset the failure count,
        remember the primary's epoch, persist the committed ring in
        the restart-adoptable shape (only when the generation moved —
        tail polls are frequent and fsyncs are not free)."""
        generation = record.get("generation")
        warn_epoch_zero = False
        with self._lock:
            self._failures = 0
            self._last_record = dict(record)
            epoch = int(record.get("router_epoch", 0) or 0)
            if epoch == 0 and not self._warned_epoch_zero:
                self._warned_epoch_zero = True
                warn_epoch_zero = True
            persist_epoch = epoch > self._last_primary_epoch
            if persist_epoch:
                self._last_primary_epoch = epoch
            persist = (self.state_dir is not None
                       and record.get("shards")
                       and generation is not None
                       and generation != self._persisted_generation)
            if persist:
                self._persisted_generation = generation
        if warn_epoch_zero:
            # resurrection containment is only airtight when the
            # PRIMARY can rediscover the adjudicated epoch before
            # taking traffic again.  A state_dir primary probes the
            # shards at serve() regardless of its epoch, but one
            # started with neither --router-epoch >= 1 nor a state_dir
            # restarts blind after this standby promotes: deposed stays
            # False and it forwards ops over its stale ring — exactly
            # the acked-writes-stranded hazard the fence exists for.
            # Loud and counted, not fatal: epoch-0 primaries are every
            # pre-HA deployment, and the standby still contains the
            # admin plane either way.
            self._count("router.ha.primary_epoch_zero")
            warnings.warn(
                "RouterStandby is tailing a primary at router epoch 0; "
                "restart the primary with --router-epoch >= 1 (or a "
                "--state-dir) or a resurrected primary will not "
                "self-fence its data plane after a promotion",
                RuntimeWarning, stacklevel=2)
        if persist_epoch:
            # the tailed epoch is part of what makes this standby WARM:
            # without it on disk, a standby restart would read as
            # never-tailed and the promote guard would block forever
            # against a dead primary even though the committed ring IS
            # durable here (and promoting at tailed+1 can never collide)
            persist_router_epoch(self.state_dir, epoch,
                                 f"tailed:{record.get('router_id', '?')}")
        if persist:
            # the exact record shape HandoffCoordinator commits, so a
            # promotion (or a later restart of the promoted router)
            # adopts it through the unchanged load_ring_file path
            write_json_atomic(self.state_dir, RING_FILE, {
                "epoch": int(record.get("epoch", 0) or 0),
                "phase": PHASE_COMMITTED,
                "shards": {s: list(a)
                           for s, a in record["shards"].items()},
                "seed": int(record.get("seed", self.seed)),
                "elements": int(record.get("elements",
                                           self.num_elements)),
                "generation": int(generation),
                "digest": str(record.get("digest", "")),
                "tailed_from": record.get("router_id", "?"),
            })
            self._count("router.ha.tail_records")

    def promote(self, reason: str = "manual") -> ShardRouter:
        """The promotion sequence (module docstring): persist the
        bumped epoch FIRST, build the router over the tailed ring,
        announce the epoch fleet-wide, then bind the listener.
        Single-entry end to end (``_promote_lock``): a concurrent call
        blocks until the winner finishes, then returns the winner's
        router — never a second one."""
        t0 = time.monotonic()
        with self._promote_lock:
            return self._promote_locked(reason, t0)

    # requires-lock: _promote_lock
    def _promote_locked(self, reason: str, t0: float) -> ShardRouter:
        with self._lock:
            if self.router is not None:
                return self.router
            epoch = max(self._last_primary_epoch,
                        load_router_epoch(self.state_dir)) + 1
        # 1. the fence root: the claimed epoch is durable before any
        # shard can hear it (a standby crash mid-promotion re-promotes
        # at an equal-or-higher epoch, never a lower one)
        persist_router_epoch(self.state_dir, epoch, self.standby_id)
        # 2. the router: state_dir makes it adopt the tailed committed
        # ring over the constructor shard map (exactly the restart
        # path a SIGKILLed primary would take)
        router = ShardRouter(self.shards, self.num_elements,
                             seed=self.seed, state_dir=self.state_dir,
                             recorder=self.recorder,
                             router_epoch=epoch,
                             router_id=self.standby_id,
                             **self.router_kwargs)
        try:
            # 3. the fence fan-out: every reachable shard adjudicates
            # the new epoch now; unreachable ones learn it on first
            # admin contact (announce-per-connection in
            # _ShardLink._request)
            announce = router.announce_epoch()
            # 3b. best-effort deposition notice to the old primary: a
            # FALSE-POSITIVE promotion (network blip, not a death)
            # leaves it alive and forwarding — one RING_SYNC claim
            # flips its self-fence so it sheds typed instead of
            # forwarding over a ring this router may reshard past.  A
            # dead primary learns the same thing from the shards at
            # its own restart probe.
            try:
                from go_crdt_playground_tpu.serve.client import \
                    ServeClient

                with ServeClient(self.primary,
                                 timeout=self.poll_timeout_s,
                                 connect_timeout=1.0) as c:
                    c.ring_sync(epoch, self.standby_id)
            except (OSError, ConnectionError):
                pass  # dead primary: the normal case
            # 4. serve on the pre-declared address — clients holding
            # the ordered address list rotate here on their next try
            if self.listen_addr is not None:
                router.serve(self.listen_addr[0], self.listen_addr[1])
        except BaseException:
            # partial promotion (e.g. the listen port is taken): the
            # retry loop re-enters promote() next poll — the router
            # built THIS attempt must not leak its shard-link sockets
            # and reader threads each round
            router.close()
            raise
        self._count("router.ha.promotions")
        with self._lock:
            self.router = router
            self._announce_results = dict(announce)
            self._promotion_s = time.monotonic() - t0
            self._promote_reason = reason
        self._promoted.set()
        return router

    # -- plumbing -----------------------------------------------------------

    def _tail_client(self):
        from go_crdt_playground_tpu.serve.client import ServeClient

        with self._lock:
            client = self._client
        if client is not None and not client.closed:
            return client
        self._drop_client()
        client = ServeClient(self.primary, timeout=self.poll_timeout_s,
                             connect_timeout=self.poll_timeout_s)
        with self._lock:
            self._client = client
        return client

    def _drop_client(self) -> None:
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except OSError:
                pass

    def _count(self, name: str, n: int = 1) -> None:
        if self.recorder is not None:
            self.recorder.count(name, n)
