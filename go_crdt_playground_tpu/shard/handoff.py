"""Live resharding: dynamic ring membership with zero-loss keyspace
handoff (DESIGN.md §18).

The PR-6 fleet was frozen at birth: an immutable ``HashRing`` and a
static owner map meant restart-to-resize.  This module makes membership
change a ROUTER operation under live traffic.  Correctness is anchored
on three facts:

* HRW gives **exact minimal remap** (``ring.remap_fraction``): a
  join/leave moves only the forced slice, so the handoff is a bounded
  one-shot state transfer, not a rebalance;
* the CRDT join makes the transfer **unconditionally safe to retry or
  duplicate** (arxiv 1803.02750's framing: a state-based sync round) —
  a half-delivered slice is a lower bound, never corruption;
* so the only hard problem is ROBUSTNESS: no acked op may be lost and
  no keyspace may double-serve while the ring swaps, even when a donor
  or recipient is SIGKILLed mid-handoff.

State machine (one epoch per admin verb, ``HandoffCoordinator``):

    IDLE --stage--> FENCED --transfer--> COMMITTED (ring swapped)
                       \\--any failure--> ABORTED  (old ring serving)

**stage**: build the candidate ring (``with_shard``/``without_shard``),
derive the moved slice per (donor, recipient) pair
(``ring.handoff_plan``), persist the epoch record.  **fence**: ops
naming moved elements get the typed retryable ``REJECT_MOVING`` — the
chosen fence semantics is *reject-and-retry*, not dual-write: a
dual-write would need cross-shard atomicity the protocol doesn't have,
while a typed reject reuses the client's existing idempotent-resubmit
contract and bounds unavailability to the transfer window (measured as
``fence_s``, adjudicated by the fleet soak).  After fencing, the
coordinator waits for router-level op handlers to settle and for every
donor's in-flight moved-slice sub-ops to resolve — a donor ack is an
fsync'd op, so everything acked is in the slice snapshot that follows.
**transfer**: per plan pair, ``SLICE_PULL`` the donor's complete slice
state and ``SLICE_PUSH`` it to the recipient, which applies it through
its WAL-logged payload path and acks only once durable (the recipient
half of zero-loss rides the EXISTING §14 durability layer).  Pulls and
pushes retry on transient failure with seeded jittered backoff
(``utils/backoff``) through the links' circuit breakers, bounded by the
transfer deadline.  **commit**: swap the router's ``RouteState``
atomically (new ring + owner map + generation + digest, fence cleared)
and persist the committed ring; a leave's retired link is closed after
the swap.  **abort** (the main path under fault injection): clear the
fence, close a staged link, persist the abort — the old ring never
stopped being the active route, so a failed join/leave leaves the
prior ring fully serving by construction (its owner-map digest is what
STATS keeps reporting; the soak pins this).

Double-serve is prevented on the READ path: the router filters each
shard's QUERY reply by the active owner map, so a donor's stale copy
of a moved slice is invisible the moment the ring swaps (and a delete
at the new owner is never shadowed by the donor's old ``present``
lane).

Epoch persistence: with a ``state_dir`` the coordinator writes
``ring.json`` (epoch, phase, ring, digest) fsync-then-rename atomic; a
router restart adopts a COMMITTED ring over its CLI flags and treats a
staged-but-uncommitted epoch as aborted.  A router SIGKILL mid-handoff
therefore resumes serving the old ring; donors/recipients recover
their halves from their own WAL/checkpoints.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from go_crdt_playground_tpu.shard.ring import (HashRing, handoff_plan,
                                               remap_fraction)
from go_crdt_playground_tpu.utils.backoff import Backoff, BackoffPolicy
from go_crdt_playground_tpu.utils.fsutil import fsync_dir

Addr = Tuple[str, int]

PHASE_STAGED = "staged"
PHASE_COMMITTED = "committed"
PHASE_ABORTED = "aborted"

RING_FILE = "ring.json"    # the last COMMITTED ring (what a restart adopts)
EPOCH_FILE = "epoch.json"  # the last epoch's phase breadcrumb (post-mortems
                           # + the monotone epoch counter across restarts)
# the ROUTER-LEADERSHIP epoch (DESIGN.md §22) — monotone across the HA
# pair: a promoted standby persists primary_epoch + 1 here before it
# announces/serves, and every shard frontend persists the highest
# epoch it has adjudicated so a restart cannot forget the fence.
# Distinct from EPOCH_FILE on purpose: handoff epochs count ring
# CHANGES under one router; router epochs count which ROUTER may
# drive them.
ROUTER_EPOCH_FILE = "router_epoch.json"
# per-shard SHARD epochs the ROUTER has adjudicated (DESIGN.md §23):
# which replication-group member may serve each sid's keyspace.
# Monotone per sid; persisted fsync-then-rename BEFORE a failover swap
# acts, so a router restart can never hand a keyspace back to a
# deposed member.
SHARD_EPOCHS_FILE = "shard_epochs.json"


class HandoffError(RuntimeError):
    """A handoff aborted (reason in the message).  The old ring is
    still the active route — callers reply failure and keep serving."""


class RouteState:
    """One immutable routing snapshot: the ring, its precomputed owner
    map, a monotone swap generation, the owner-map digest, and the
    optional handoff fence.  The hot path reads ONE of these per op
    (``ShardRouter.route()``), so a ring swap is atomic by construction
    — there is no half-updated routing state to observe."""

    __slots__ = ("ring", "owner", "generation", "digest", "fence")

    def __init__(self, ring: HashRing, owner: np.ndarray, generation: int,
                 digest: str, fence: Optional[np.ndarray] = None):
        # race-ok: all fields are write-once at construction; every
        # reader got this object from a locked swap point
        self.ring = ring
        self.owner = owner
        self.generation = generation
        self.digest = digest
        self.fence = fence  # bool[E] moved-slice mask, None = no fence

    def owner_sid(self, element_id: int) -> str:
        return self.ring.shards[self.owner[element_id]]

    def fenced(self, elements: Sequence[int]) -> bool:
        if self.fence is None:
            return False
        return any(self.fence[e] for e in elements)

    def with_fence(self, fence: Optional[np.ndarray]) -> "RouteState":
        return RouteState(self.ring, self.owner, self.generation,
                          self.digest, fence)

    def info(self) -> Dict[str, object]:
        """The STATS/banner read-out: which ring this router is
        actually serving (the observability the soak's failed-handoff
        adjudication leans on)."""
        return {
            "generation": self.generation,
            "digest": self.digest,
            "shards": list(self.ring.shards),
            "seed": self.ring.seed,
            "fenced": (int(self.fence.sum())
                       if self.fence is not None else 0),
        }


def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_ring_file(state_dir: str) -> Optional[dict]:
    """Read the persisted COMMITTED-ring record; None when
    absent/unreadable (a torn write is indistinguishable from no record
    — both mean "trust the CLI flags", the pre-reshard configuration).
    Only commits ever write this file, so a kill during a staged or
    aborting handoff can never clobber the ring a restart adopts."""
    return _load_json(os.path.join(state_dir, RING_FILE))


def load_epoch_file(state_dir: str) -> Optional[dict]:
    """The last epoch breadcrumb (any phase) — post-mortem material and
    the restart seed for the monotone epoch counter."""
    return _load_json(os.path.join(state_dir, EPOCH_FILE))


def write_json_atomic(state_dir: str, filename: str, rec: dict) -> None:
    """fsync-then-rename atomic JSON record write — the persistence
    discipline every routing-state file in this module shares (a torn
    write must read as ABSENT, never as a half-record)."""
    path = os.path.join(state_dir, filename)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=2)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(state_dir)


def load_router_epoch(state_dir: Optional[str]) -> int:
    """The persisted router-leadership epoch (0 when absent/unreadable
    — the pre-HA configuration, fence dormant)."""
    if state_dir is None:
        return 0
    rec = _load_json(os.path.join(state_dir, ROUTER_EPOCH_FILE))
    if rec is None:
        return 0
    try:
        return max(0, int(rec.get("router_epoch", 0)))
    except (TypeError, ValueError):
        return 0


def load_shard_epochs(state_dir: Optional[str]) -> Dict[str, int]:
    """The router's adjudicated per-sid shard epochs (empty when
    absent/unreadable — every sid at its pre-HA epoch 0)."""
    if state_dir is None:
        return {}
    rec = _load_json(os.path.join(state_dir, SHARD_EPOCHS_FILE))
    if not isinstance(rec, dict):
        return {}
    out: Dict[str, int] = {}
    for sid, e in rec.get("epochs", {}).items():
        try:
            out[str(sid)] = max(0, int(e))
        except (TypeError, ValueError):
            continue
    return out


def persist_shard_epochs(state_dir: Optional[str],
                         epochs: Dict[str, int]) -> None:
    """Durably record the router's per-sid shard-epoch adjudications —
    fsync'd BEFORE the failover swap acts on them."""
    if state_dir is None:
        return
    os.makedirs(state_dir, exist_ok=True)
    write_json_atomic(state_dir, SHARD_EPOCHS_FILE,
                      {"epochs": {sid: int(e)
                                  for sid, e in epochs.items()}})


def persist_router_epoch(state_dir: Optional[str], epoch: int,
                         owner: str) -> None:
    """Durably record the highest router epoch this endpoint has seen
    (or, for a promoting standby, now CLAIMS) — fsync'd BEFORE the
    epoch is acted on, so a restart can never regress the fence."""
    if state_dir is None:
        return
    os.makedirs(state_dir, exist_ok=True)
    write_json_atomic(state_dir, ROUTER_EPOCH_FILE,
                      {"router_epoch": int(epoch), "owner": owner})


class HandoffCoordinator:
    """Drives one handoff epoch at a time against a ``ShardRouter``.

    Single concurrent handoff by design (``_active``): overlapping
    membership changes would need plan composition nothing requires —
    the admin verb replies a typed failure and the operator retries.
    """

    # pull/push retry gate (seeded, jittered — utils/backoff)
    DEFAULT_POLICY = BackoffPolicy(base_s=0.05, multiplier=2.0, cap_s=1.0,
                                   jitter=0.1, max_retries=6)

    def __init__(self, router, *, state_dir: Optional[str] = None,
                 recorder=None, fence_timeout_s: float = 10.0,
                 transfer_timeout_s: float = 30.0,
                 policy: Optional[BackoffPolicy] = None, seed: int = 0):
        self.router = router
        self.recorder = recorder
        self.state_dir = state_dir
        self.fence_timeout_s = fence_timeout_s
        self.transfer_timeout_s = transfer_timeout_s
        self.policy = policy if policy is not None else self.DEFAULT_POLICY
        self.seed = seed
        self._lock = threading.Lock()
        self._active = False  # guarded-by: _lock
        self._epoch = 0  # guarded-by: _lock
        if state_dir is not None:
            os.makedirs(state_dir, exist_ok=True)
            epoch = 0
            for rec in (load_ring_file(state_dir),
                        load_epoch_file(state_dir)):
                if rec is not None:
                    epoch = max(epoch, int(rec.get("epoch", 0)))
            with self._lock:
                self._epoch = epoch

    @property
    def epoch(self) -> int:
        """The monotone HANDOFF epoch (ring-change counter) — exposed
        so the RING_SYNC tail record can carry it and a promoting
        standby's coordinator resumes past it."""
        with self._lock:
            return self._epoch

    # -- the admin verb -----------------------------------------------------

    def reshard(self, mode: str, sid: str,
                addr: Optional[Addr] = None) -> dict:
        """Run one join/leave handoff end to end; returns the commit
        accounting.  Raises ``HandoffError`` on abort — the old ring is
        still serving and the router replies the reason typed."""
        with self._lock:
            if self._active:
                raise HandoffError("another handoff is in progress")
            self._active = True
            self._epoch += 1
            epoch = self._epoch
        try:
            return self._run(epoch, mode, sid, addr)
        finally:
            with self._lock:
                self._active = False

    def _run(self, epoch: int, mode: str, sid: str,
             addr: Optional[Addr]) -> dict:
        router = self.router
        t0 = time.monotonic()
        staged_link = None
        fenced = False
        try:
            rt = router.route()
            ring_after = self._candidate_ring(rt.ring, mode, sid, addr)
            owners_after = ring_after.owner_map(router.num_elements)
            remap = remap_fraction(rt.owner, owners_after,
                                   rt.ring.shards, ring_after.shards)
            plan = handoff_plan(rt.owner, owners_after,
                                rt.ring.shards, ring_after.shards)
            self._persist(epoch, PHASE_STAGED, rt.info(),
                          {"mode": mode, "sid": sid,
                           "moved": remap["moved"]})
            if mode == "join":
                # the recipient link exists STAGED-only until commit:
                # no client op can route to it, but the transfer rides
                # the same breaker/backoff machinery as live links
                staged_link = router.make_link(sid, addr)

            # fence: moved-slice ops now reject typed-retryable; wait
            # for handlers that pre-date the fence to finish
            # registering, then for every donor's in-flight moved
            # sub-ops to resolve (each resolution is a durable donor
            # ack or a typed reject — either way the slice snapshot
            # that follows contains everything ever acked)
            fence = np.zeros(router.num_elements, bool)
            for _, _, elems in plan:
                fence[elems] = True
            router.set_fence(fence)
            fenced = True
            t_fence = time.monotonic()
            self._count("router.reshard.fences")
            settle_deadline = t_fence + self.fence_timeout_s
            router.await_ops_settled(settle_deadline)
            self._await_donors_drained(plan, fence, settle_deadline)

            # transfer: pull each donor slice, push to its recipient
            transfer_deadline = time.monotonic() + self.transfer_timeout_s
            moved_transferred = 0
            for src_sid, dst_sid, elems in plan:
                src_link = router.link(src_sid)
                if src_link is None:
                    raise HandoffError(f"donor {src_sid} not in ring")
                if staged_link is not None and dst_sid == sid:
                    dst_link = staged_link
                else:
                    dst_link = router.link(dst_sid)
                    if dst_link is None:
                        raise HandoffError(
                            f"recipient {dst_sid} not in ring")
                payload = self._with_retries(
                    lambda: src_link.slice_pull(elems),
                    f"pull {len(elems)} elements from {src_sid}",
                    transfer_deadline, epoch)
                self._with_retries(
                    lambda: dst_link.slice_push(payload),
                    f"push {len(elems)} elements to {dst_sid}",
                    transfer_deadline, epoch)
                moved_transferred += len(elems)

            # commit, in two steps whose failure modes are both safe:
            # PERSIST the committed record FIRST (a failure here
            # funnels to the abort arm while the old ring genuinely is
            # still the active route — persisting after the swap could
            # report "aborted" for a ring that irreversibly swapped),
            # THEN the atomic in-memory RouteState swap.  A process
            # death between the two restarts onto the persisted NEW
            # ring, whose slices are already durable on their
            # recipients — routing-consistent either way.
            digest = ring_after.digest(router.num_elements, owners_after)
            generation = router.route().generation + 1  # single handoff
            committed_shards = {
                s: (staged_link.addr
                    if staged_link is not None and s == sid
                    else router.shard_roster(s))
                for s in ring_after.shards}
            fence_s = time.monotonic() - t_fence
            detail = {
                "epoch": epoch,
                "mode": mode,
                "sid": sid,
                "moved": remap["moved"],
                "moved_transferred": moved_transferred,
                "fraction": remap["fraction"],
                "gratuitous": len(remap["gratuitous"]),
                "pairs": [[s, d, len(e)] for s, d, e in plan],
                "fence_s": round(fence_s, 4),
                "elapsed_s": round(time.monotonic() - t0, 4),
                "generation": generation,
                "digest": digest,
                "shards": list(ring_after.shards),
            }
            new_info = {"generation": generation, "digest": digest,
                        "shards": list(ring_after.shards),
                        "seed": ring_after.seed, "fenced": 0}
            self._persist(epoch, PHASE_COMMITTED, new_info, detail,
                          shards_map=committed_shards)
            swapped_gen = router.commit_route(
                ring_after, owners_after, digest,
                add_sid=sid if mode == "join" else None,
                add_link=staged_link,
                drop_sid=sid if mode == "leave" else None)
            assert swapped_gen == generation, (swapped_gen, generation)
            staged_link = None  # the router owns it now
            fenced = False      # cleared by the swap
            self._count("router.reshard.commits")
            return detail
        except Exception as e:  # noqa: BLE001 — EVERY failure funnels
            # through the abort arm: the old ring must come back
            # serving no matter what broke mid-handoff
            if fenced:
                router.clear_fence()
            if staged_link is not None:
                staged_link.close()
            reason = (str(e) if isinstance(e, HandoffError)
                      else f"{type(e).__name__}: {e}")
            self._persist(epoch, PHASE_ABORTED, router.route().info(),
                          {"mode": mode, "sid": sid, "reason": reason})
            self._count("router.reshard.aborts")
            if isinstance(e, HandoffError):
                raise
            raise HandoffError(f"handoff aborted: {reason}") from e

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _candidate_ring(ring: HashRing, mode: str, sid: str,
                        addr: Optional[Addr]) -> HashRing:
        if mode == "join":
            if addr is None:
                raise HandoffError("join requires the shard's address")
            if sid in ring.shards:
                raise HandoffError(f"shard {sid!r} already in the ring")
            return ring.with_shard(sid)
        if mode == "leave":
            try:
                return ring.without_shard(sid)
            except ValueError as e:
                raise HandoffError(str(e)) from e
        raise HandoffError(f"unknown reshard mode {mode!r}")

    def _await_donors_drained(self, plan, fence: np.ndarray,
                              deadline: float) -> None:
        for src_sid in sorted({s for s, _, _ in plan}):
            link = self.router.link(src_sid)
            if link is None:
                raise HandoffError(f"donor {src_sid} not in ring")
            while link.pending_touching(fence) > 0:
                if time.monotonic() > deadline:
                    raise HandoffError(
                        f"in-flight ops on donor {src_sid} did not "
                        f"settle within {self.fence_timeout_s}s")
                time.sleep(0.005)

    def _with_retries(self, fn, what: str, deadline: float,
                      epoch: int):
        """Run one transfer step with jittered-backoff retries on
        TRANSIENT failure, bounded by the transfer deadline.  A
        deterministic reject (e.g. an incompatible payload) aborts
        immediately — retrying the same bytes cannot help."""
        from go_crdt_playground_tpu.serve import protocol
        from go_crdt_playground_tpu.shard import router as router_mod

        bo = Backoff(self.policy, seed=self.seed * 100003 + epoch)
        while True:
            try:
                return fn()
            except (router_mod._Unreachable, protocol.Overloaded,
                    protocol.Draining, ConnectionError, OSError) as e:
                self._count("router.reshard.transfer_retries")
                delay = bo.next_delay()
                if delay is None:
                    bo.reset()
                    delay = self.policy.cap_s
                if time.monotonic() + delay > deadline:
                    raise HandoffError(
                        f"transfer step failed past deadline "
                        f"({what}): {e}") from e
                time.sleep(delay)
            except protocol.ServeError as e:
                raise HandoffError(
                    f"transfer step refused ({what}): {e}") from e

    def _persist(self, epoch: int, phase: str, route_info: dict,
                 detail: dict,
                 shards_map: Optional[Dict[str, Addr]] = None) -> None:
        """fsync-then-rename atomic epoch records.  Every phase writes
        the EPOCH breadcrumb; only COMMITTED (which must pass
        ``shards_map``, the post-swap membership with addresses) also
        rewrites the ring record a restart adopts — so a kill during a
        staged/aborting handoff leaves the previously-committed ring
        intact on disk (restart = old ring serving, the
        abort-on-restart semantics)."""
        if self.state_dir is None:
            return
        rec = {"epoch": epoch, "phase": phase, "route": route_info,
               "detail": detail}
        write_json_atomic(self.state_dir, EPOCH_FILE, rec)
        if phase == PHASE_COMMITTED:
            # a restarted router rebuilds the ring from this
            rec = dict(rec)
            rec["shards"] = {s: list(a) for s, a in shards_map.items()}
            rec["seed"] = int(route_info["seed"])
            rec["elements"] = self.router.num_elements
            rec["generation"] = detail["generation"]
            rec["digest"] = detail["digest"]
            write_json_atomic(self.state_dir, RING_FILE, rec)

    def _count(self, name: str, n: int = 1) -> None:
        if self.recorder is not None:
            self.recorder.count(name, n)
