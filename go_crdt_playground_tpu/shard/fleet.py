"""Fleet runner: N shard frontends + one router, as real subprocesses.

The fleet soak (tools/fleet_serve_soak.py) and the slow-marked pytest
wrapper drive REAL ``python -m go_crdt_playground_tpu`` processes — the
same CLI an operator runs — never in-process imports: a shard SIGKILL
must kill a process with its own WAL fds, page cache, and JAX runtime,
or the zero-acked-op-loss adjudication proves nothing.

``ShardFleet`` owns the lifecycle: it pre-allocates every port (so a
killed shard RESTARTS on the address the router was configured with —
the router's links redial through their breakers and the keyspace comes
back without touching the router), launches all shards concurrently
(each costs a JAX import + warmup; serial launch would dominate the
soak), then the router, and tears everything down on ``close()``.

Address handshake: each process prints one ``... listening on H:P``
line on stdout; a pump thread per process keeps draining stdout
afterwards so drain summaries can never block the pipe (the
tools/serve_soak.py lesson).
"""

from __future__ import annotations

import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

Addr = Tuple[str, int]

_ADDR_RE = re.compile(rb"listening on ([\d.]+):(\d+)")


def format_addrs(a) -> str:
    """One ``--shard`` flag value: ``H:P`` for a single (host, port)
    pair, ``H:P,H:P`` for an ordered replication-group roster."""
    if a and not isinstance(a[0], str):
        return ",".join(f"{h}:{p}" for h, p in a)
    return f"{a[0]}:{a[1]}"


def free_port() -> int:
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Proc:
    """One CLI subprocess with the address-line handshake."""

    def __init__(self, argv: List[str], cwd: str, log_path: str,
                 env: Optional[Dict[str, str]] = None,
                 env_drop: Tuple[str, ...] = ()):
        full_env = dict(os.environ, JAX_PLATFORMS="cpu")
        for k in env_drop:
            full_env.pop(k, None)
        if env:
            full_env.update(env)
        self.log = open(log_path, "ab")
        self.proc = subprocess.Popen(
            argv, env=full_env, cwd=cwd, stdout=subprocess.PIPE,
            stderr=self.log)
        self._lines: "list[bytes]" = []
        self._line_cond = threading.Condition()
        threading.Thread(target=self._pump, daemon=True).start()
        self.addr: Optional[Addr] = None

    def _pump(self) -> None:
        while True:
            line = self.proc.stdout.readline()
            with self._line_cond:
                self._lines.append(line)
                self._line_cond.notify_all()
            if not line:
                return

    def await_match(self, regex, timeout_s: float = 120.0):
        """Wait for the first stdout line matching ``regex``; returns
        the match.  The ONE banner-handshake implementation — the
        address handshake and the autopilot soak's engagement banner
        both ride it, so the deadline discipline (enforced on
        NON-matching lines too: a subprocess spamming warnings without
        ever printing its banner must still time out, not pin the soak
        forever) lives once."""
        deadline = time.monotonic() + timeout_s
        seen = 0
        while True:
            with self._line_cond:
                while seen >= len(self._lines):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise RuntimeError(
                            f"no line matching {regex.pattern!r} "
                            f"within {timeout_s}s "
                            f"(argv={self.proc.args[:6]}...)")
                    self._line_cond.wait(timeout=remaining)
                line = self._lines[seen]
                seen += 1
            if not line:
                raise RuntimeError(
                    f"process exited before a line matching "
                    f"{regex.pattern!r} (rc={self.proc.poll()})")
            m = regex.search(line)
            if m:
                return m
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no line matching {regex.pattern!r} within "
                    f"{timeout_s}s; last output line: {line!r}")

    def await_address(self, timeout_s: float = 120.0) -> Addr:
        m = self.await_match(_ADDR_RE, timeout_s)
        self.addr = (m.group(1).decode(), int(m.group(2)))
        return self.addr

    def sigkill(self) -> None:
        if self.proc.poll() is None:
            os.kill(self.proc.pid, signal.SIGKILL)
        self.proc.wait()

    def terminate(self) -> int:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                return self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                return self.proc.wait()
        return self.proc.returncode

    def close(self) -> None:
        self.terminate()
        self.log.close()


@dataclass
class FleetSpec:
    """Shape of one fleet: N shards over a shared element universe."""

    n_shards: int
    elements: int
    actors: int = 0          # 0 = n_shards (one actor lane per shard)
    seed: int = 0
    queue_depth: int = 128
    max_batch: int = 32
    flush_ms: float = 2.0
    # extra `serve --ingest` CLI flags appended verbatim to every shard
    # (the serve soak's seed-comparison / compaction legs ride these)
    extra_args: Tuple[str, ...] = ()
    # extra env for every shard subprocess, as (key, value) pairs (the
    # mesh soak exports XLA_FLAGS=--xla_force_host_platform_device_
    # count=N — jax only honors it at process init, so it must ride
    # the worker env, not the CLI)
    extra_env: Tuple[Tuple[str, str], ...] = ()

    def __post_init__(self) -> None:
        if self.n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        if self.actors == 0:
            self.actors = max(self.n_shards, 1)
        if self.actors < self.n_shards:
            raise ValueError(
                f"actors={self.actors} < n_shards={self.n_shards}: each "
                "shard replica ticks its own actor lane")


class ShardProc(_Proc):
    """One ``serve --ingest`` shard frontend subprocess.
    ``extra_args`` appends PER-SHARD flags after the fleet-wide
    ``spec.extra_args`` (the replication soak passes ``--shard-id`` /
    ``--shard-epoch`` / ``--announce-to``, which differ per shard)."""

    def __init__(self, repo: str, dirpath: str, spec: FleetSpec,
                 index: int, port: int,
                 crash_after_batches: Optional[int] = None,
                 crash_on_slice: Optional[str] = None,
                 extra_args: Tuple[str, ...] = ()):
        self.index = index
        self.port = port
        self.dirpath = dirpath
        os.makedirs(dirpath, exist_ok=True)
        env = dict(spec.extra_env)
        if crash_after_batches is not None:
            env["CRDT_SERVE_CRASH_AFTER_BATCHES"] = str(crash_after_batches)
        if crash_on_slice is not None:
            # "pull" = die as handoff donor, "push" = die as recipient
            # (serve/frontend.py kill-mid-handoff hook)
            env["CRDT_SERVE_CRASH_ON_SLICE"] = crash_on_slice
        argv = [sys.executable, "-m", "go_crdt_playground_tpu", "serve",
                "--ingest", "--port", str(port),
                "--elements", str(spec.elements),
                "--actors", str(spec.actors), "--actor", str(index),
                "--durable-dir", os.path.join(dirpath, "state"),
                "--queue-depth", str(spec.queue_depth),
                "--max-batch", str(spec.max_batch),
                "--flush-ms", str(spec.flush_ms),
                "--checkpoint-every", "0"] + list(spec.extra_args) \
            + list(extra_args)
        super().__init__(argv, cwd=repo,
                         log_path=os.path.join(dirpath, "shard.log"),
                         env=env,
                         env_drop=("CRDT_SERVE_CRASH_AFTER_BATCHES",
                                   "CRDT_SERVE_CRASH_ON_SLICE"))


class RouterProc(_Proc):
    """One ``router --serve`` subprocess over a shard map (the INITIAL
    fleet — live resharding grows/shrinks it; with ``state_dir`` the
    committed ring survives router restarts).  ``extra_args`` appends
    verbatim CLI flags (the HA soak passes ``--router-epoch`` /
    ``--router-id``)."""

    def __init__(self, repo: str, dirpath: str, spec: FleetSpec,
                 shard_addrs: Dict[str, Addr], port: int,
                 state_dir: Optional[str] = None,
                 transfer_timeout_s: float = 10.0,
                 extra_args: Tuple[str, ...] = ()):
        os.makedirs(dirpath, exist_ok=True)
        argv = [sys.executable, "-m", "go_crdt_playground_tpu", "router",
                "--serve", "--port", str(port),
                "--elements", str(spec.elements),
                "--seed", str(spec.seed),
                "--transfer-timeout", str(transfer_timeout_s)]
        for sid in sorted(shard_addrs):
            argv += ["--shard", f"{sid}={format_addrs(shard_addrs[sid])}"]
        if state_dir is not None:
            argv += ["--state-dir", state_dir]
        argv += list(extra_args)
        super().__init__(argv, cwd=repo,
                         log_path=os.path.join(dirpath, "router.log"))


_STANDBY_RE = re.compile(rb"Router standby engaged")
_TAILING_RE = re.compile(rb"Router standby tailing primary ring")
_SHARD_STANDBY_RE = re.compile(rb"Shard standby engaged")
_SHARD_TAILING_RE = re.compile(rb"Shard standby tailing primary wal")


class StandbyShardProc(_Proc):
    """One ``serve --ingest --standby-of`` subprocess
    (shard/replica.py as a process): tails the primary shard's WAL,
    promotes on its death under a bumped fenced shard epoch, claims
    the keyspace at the router, and only THEN prints the standard
    ``listening on`` banner — so ``await_address`` doubles as the
    promotion handshake, exactly the router-standby discipline."""

    def __init__(self, repo: str, dirpath: str, spec: FleetSpec,
                 index: int, port: int, primary: Addr, sid: str,
                 announce_to: Optional[Addr] = None,
                 standby_id: Optional[str] = None,
                 poll_interval_s: float = 0.1,
                 failure_threshold: int = 5):
        self.index = index
        self.port = port
        self.dirpath = dirpath
        os.makedirs(dirpath, exist_ok=True)
        argv = [sys.executable, "-m", "go_crdt_playground_tpu", "serve",
                "--ingest", "--port", str(port),
                "--elements", str(spec.elements),
                "--actors", str(spec.actors), "--actor", str(index),
                "--durable-dir", os.path.join(dirpath, "state"),
                "--queue-depth", str(spec.queue_depth),
                "--max-batch", str(spec.max_batch),
                "--flush-ms", str(spec.flush_ms),
                "--checkpoint-every", "0",
                "--standby-of", f"{primary[0]}:{primary[1]}",
                "--shard-id", sid,
                "--standby-id", standby_id or f"{sid}-standby",
                "--ha-poll-interval", str(poll_interval_s),
                "--ha-failure-threshold", str(failure_threshold)]
        if announce_to is not None:
            argv += ["--announce-to", f"{announce_to[0]}:{announce_to[1]}"]
        argv += list(spec.extra_args)
        super().__init__(argv, cwd=repo,
                         log_path=os.path.join(dirpath, "standby.log"))

    def await_engaged(self, timeout_s: float = 120.0) -> None:
        self.await_match(_SHARD_STANDBY_RE, timeout_s)

    def await_tailed(self, timeout_s: float = 60.0) -> None:
        """Wait until the standby has tailed the primary at least once
        — only a tailed standby promotes (the epoch-collision /
        empty-replica guard), so a soak must not SIGKILL the primary
        before this handshake."""
        self.await_match(_SHARD_TAILING_RE, timeout_s)


class StandbyRouterProc(_Proc):
    """One ``router --serve --standby-of`` subprocess (shard/ha.py as
    a process): tails the primary, promotes on its death, and only
    THEN prints the standard ``listening on`` banner — so
    ``await_address`` doubles as the promotion handshake.
    ``await_engaged`` is the pre-promotion handshake (the standby is
    tailing)."""

    def __init__(self, repo: str, dirpath: str, spec: FleetSpec,
                 shard_addrs: Dict[str, Addr], port: int,
                 primary: Addr, state_dir: str,
                 standby_id: str = "router-b",
                 poll_interval_s: float = 0.25,
                 failure_threshold: int = 3,
                 transfer_timeout_s: float = 10.0):
        os.makedirs(dirpath, exist_ok=True)
        argv = [sys.executable, "-m", "go_crdt_playground_tpu", "router",
                "--serve", "--port", str(port),
                "--elements", str(spec.elements),
                "--seed", str(spec.seed),
                "--transfer-timeout", str(transfer_timeout_s),
                "--standby-of", f"{primary[0]}:{primary[1]}",
                "--router-id", standby_id,
                "--ha-poll-interval", str(poll_interval_s),
                "--ha-failure-threshold", str(failure_threshold),
                "--state-dir", state_dir]
        for sid in sorted(shard_addrs):
            argv += ["--shard", f"{sid}={format_addrs(shard_addrs[sid])}"]
        super().__init__(argv, cwd=repo,
                         log_path=os.path.join(dirpath, "standby.log"))

    def await_engaged(self, timeout_s: float = 120.0) -> None:
        self.await_match(_STANDBY_RE, timeout_s)

    def await_tailed(self, timeout_s: float = 60.0) -> None:
        """Wait until the standby has tailed the primary at least once
        — only a tailed standby will promote (shard/ha.py's
        epoch-collision guard), so a soak must not SIGKILL the primary
        before this handshake."""
        self.await_match(_TAILING_RE, timeout_s)


@dataclass
class ShardFleet:
    """N shard subprocesses behind one router subprocess.

    Single-owner object: the soak's main thread starts, kills,
    restarts and closes it — nothing here is touched concurrently.
    """

    repo: str
    root: str
    spec: FleetSpec
    shards: List[Optional[ShardProc]] = field(default_factory=list)
    shard_ports: List[int] = field(default_factory=list)
    router: Optional[RouterProc] = None
    # pass a directory to persist committed ring swaps (live resharding)
    router_state_dir: Optional[str] = None
    # extra `router --serve` CLI flags (the HA soak's --router-epoch)
    router_extra_args: Tuple[str, ...] = ()
    # the router's port, fixed at start() so kill/restart reuses it
    router_port: Optional[int] = None

    @staticmethod
    def sid(index: int) -> str:
        return f"s{index}"

    def start(self) -> Addr:
        """Launch every shard concurrently, then the router; returns
        the router's client address."""
        self.shard_ports = [free_port() for _ in range(self.spec.n_shards)]
        router_port = free_port()
        # append-as-launched (never a bulk comprehension): if shard k's
        # constructor raises, the caller's close() must still reach
        # shards 0..k-1 or they outlive the soak holding ports + cores
        self.shards = []
        for i in range(self.spec.n_shards):
            self.shards.append(
                ShardProc(self.repo, os.path.join(self.root, self.sid(i)),
                          self.spec, i, self.shard_ports[i]))
        for s in self.shards:
            s.await_address()
        addrs = {self.sid(i): ("127.0.0.1", self.shard_ports[i])
                 for i in range(self.spec.n_shards)}
        self.router_port = router_port
        self.router = RouterProc(self.repo, os.path.join(self.root, "router"),
                                 self.spec, addrs, router_port,
                                 state_dir=self.router_state_dir,
                                 extra_args=self.router_extra_args)
        return self.router.await_address()

    def shard_addr_map(self) -> Dict[str, Addr]:
        """sid -> address of every INITIAL shard (the router/standby
        launch configuration)."""
        return {self.sid(i): ("127.0.0.1", self.shard_ports[i])
                for i in range(self.spec.n_shards)}

    def kill_router(self) -> None:
        """SIGKILL the router subprocess (the HA soak's failover
        trigger); its port and state_dir stay reserved for a
        restart."""
        assert self.router is not None
        self.router.sigkill()
        self.router.log.close()
        self.router = None

    def restart_router(self,
                       extra_args: Optional[Tuple[str, ...]] = None
                       ) -> Addr:
        """Restart a killed router on ITS ORIGINAL port + state_dir —
        the resurrection leg: it adopts its persisted committed ring
        and its OLD persisted router epoch, so a promoted standby's
        fence must contain it."""
        assert self.router is None, "router still running"
        assert self.router_port is not None
        self.router = RouterProc(
            self.repo, os.path.join(self.root, "router"), self.spec,
            self.shard_addr_map(), self.router_port,
            state_dir=self.router_state_dir,
            extra_args=(self.router_extra_args if extra_args is None
                        else extra_args))
        return self.router.await_address()

    def kill_shard(self, index: int) -> None:
        """SIGKILL one shard; its keyspace degrades to typed rejects at
        the router until ``restart_shard``."""
        shard = self.shards[index]
        assert shard is not None
        shard.sigkill()
        shard.log.close()
        self.shards[index] = None

    def restart_shard(self, index: int,
                      crash_on_slice: Optional[str] = None) -> None:
        """Restart a killed shard on ITS ORIGINAL port and durable dir
        (``Node.restore_durable``: checkpoint ⊔ WAL tail) — the router
        config is static, so recovery is invisible to it beyond the
        breaker's probe.  ``crash_on_slice`` re-arms the kill-mid-
        handoff hook (the reshard soak's donor-death leg restarts an
        EXISTING shard armed to die on the next slice pull)."""
        assert self.shards[index] is None, "shard still running"
        self.shards[index] = ShardProc(
            self.repo, os.path.join(self.root, self.sid(index)),
            self.spec, index, self.shard_ports[index],
            crash_on_slice=crash_on_slice)
        self.shards[index].await_address()

    def launch_shard(self, index: int,
                     crash_on_slice: Optional[str] = None) -> Addr:
        """Launch a shard BEYOND the initial set (the reshard joiner):
        allocates its port/slot, starts the subprocess, returns its
        serve address.  It owns no keyspace until a RESHARD join
        commits; ``spec.actors`` must cover its actor lane."""
        if index < self.spec.n_shards:
            raise ValueError(f"shard {index} is part of the initial "
                             "fleet; use restart_shard")
        if index >= self.spec.actors:
            raise ValueError(f"shard {index} has no actor lane "
                             f"(actors={self.spec.actors})")
        while len(self.shard_ports) <= index:
            self.shard_ports.append(free_port())
        while len(self.shards) <= index:
            self.shards.append(None)
        assert self.shards[index] is None, "shard already running"
        self.shards[index] = ShardProc(
            self.repo, os.path.join(self.root, self.sid(index)),
            self.spec, index, self.shard_ports[index],
            crash_on_slice=crash_on_slice)
        return self.shards[index].await_address()

    def owned_elements(self, index: int) -> List[int]:
        """The element ids shard ``index`` owns under the fleet ring
        (client-side ledger for the kill leg)."""
        from go_crdt_playground_tpu.shard.ring import HashRing

        ring = HashRing([self.sid(i) for i in range(self.spec.n_shards)],
                        seed=self.spec.seed)
        owners = ring.owner_map(self.spec.elements)
        want = ring.shards.index(self.sid(index))
        return [int(e) for e in
                (owners == want).nonzero()[0]]

    def close(self) -> None:
        if self.router is not None:
            self.router.close()
            self.router = None
        for s in self.shards:
            if s is not None:
                s.close()
        self.shards = []
