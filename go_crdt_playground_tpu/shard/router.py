"""The router tier: one serve-dialect endpoint over N shard frontends.

``ShardRouter`` speaks ``serve/protocol.py`` on BOTH sides.  Upstream
it is indistinguishable from a ``ServeFrontend`` — an unmodified
``ServeClient`` dials it, pipelines OPs, and reads typed ACK/REJECT
back by req_id.  Downstream it holds one pipelined ``ServeClient`` per
shard frontend and forwards:

* **OP** — elements are grouped by the ACTIVE route's owner map (a
  ``shard/handoff.RouteState`` snapshot: ring + precomputed owner map +
  fence, swapped atomically by live resharding — the hot path reads one
  snapshot and one array lookup per element).  An op whose keys span
  shards fans out as one sub-op per owner; the upstream reply is ONE
  frame: ACK when every sub-op acked, else the first reject (relayed
  with the downstream's own code — the client sees what the shard
  said).  Sub-ops on reachable shards may have applied when another
  shard rejects; that is the protocol's at-least-once shape — CRDT ops
  are idempotent, the client resubmits the whole op.  An op naming a
  FENCED element (a slice mid-handoff) gets the typed retryable
  ``REJECT_MOVING`` — never applied anywhere, resubmit lands it on the
  post-swap owner.
* **QUERY** — fan-out to every shard; each shard's members are
  FILTERED BY OWNERSHIP before the union (a donor's stale copy of a
  moved slice must not shadow the new owner — the no-double-serve half
  of DESIGN.md §18), vv joined element-wise (shards tick disjoint actor
  lanes).  Unreachable shards are EXCLUDED and counted: the union is a
  correct CRDT lower bound (membership only inflates), not an error.
* **STATS** — fan-out; the JSON reply carries ``router`` (this tier's
  recorder), ``shards`` (per-shard snapshots, ``null`` for unreachable
  ones), ``aggregate`` (summed shard counters) and ``ring`` (the
  ACTIVE route's generation + owner-map digest + member list — how an
  operator or the fleet soak asserts which ring a router is actually
  serving; before this, a swapped ring was observationally invisible).
* **RESHARD** — the admin verb: stage a candidate ring, drive the
  keyspace handoff, swap atomically (``shard/handoff.py`` owns the
  state machine; a failed handoff replies typed failure with the old
  ring still serving).

**Degradation ladder** (the per-shard half of DESIGN.md §13's):
each shard link carries the EXISTING ``net/antientropy.CircuitBreaker``
and a seeded ``utils/backoff.BackoffPolicy``-jittered redial gate.  A
dead shard costs its keyspace a typed ``REJECT_UNAVAILABLE`` per op —
never a silent drop, never a stall — while every other shard's
keyspace keeps serving; the breaker's HALF_OPEN probe re-admits the
shard the moment it answers again.  Downstream ops in flight when a
shard dies resolve as connection errors and relay upstream as the same
typed reject, so THROUGH the router every submitted op resolves
ack-or-typed-reject even across a shard SIGKILL (the fleet soak's
``unresolved == 0`` adjudication).

The listener/reader/conn-slot plumbing is the shared ``serve/host.py``
``ConnHost`` (the frontend runs the identical stack); relay threads
write upstream through the per-session writer queues
(serve/session.py), so one read-stalled client never blocks a shard
link's reply stream.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from go_crdt_playground_tpu.net import framing
from go_crdt_playground_tpu.net.antientropy import CircuitBreaker
from go_crdt_playground_tpu.serve import protocol
from go_crdt_playground_tpu.serve.client import ServeClient, normalize_addrs
from go_crdt_playground_tpu.serve.host import ConnHost
from go_crdt_playground_tpu.serve.session import Session
from go_crdt_playground_tpu.shard.handoff import (PHASE_COMMITTED,
                                                  RING_FILE,
                                                  HandoffCoordinator,
                                                  HandoffError, RouteState,
                                                  load_ring_file,
                                                  load_router_epoch,
                                                  load_shard_epochs,
                                                  persist_router_epoch,
                                                  persist_shard_epochs,
                                                  write_json_atomic)
from go_crdt_playground_tpu.shard.ring import HashRing, load_stats
from go_crdt_playground_tpu.utils.backoff import Backoff, BackoffPolicy

Addr = Tuple[str, int]


class _Unreachable(Exception):
    """Internal: the link could not take the sub-op (breaker open, dial
    or forward failed).  Always surfaces upstream as the typed
    ``REJECT_UNAVAILABLE`` — callers never let it escape the frame
    handler."""


class _DsumUnsupported(Exception):
    """Internal: the shard ANSWERED the DSUM probe with the legacy
    unknown-frame ``MSG_ERROR`` reply (a ``framing.RemoteError`` — the
    server really said it, as opposed to a locally-synthesized
    desync/teardown message that merely CONTAINS the same text).  The
    caller pins the sid to the uncached path; every other probe
    failure is transient and must stay re-probeable."""


class _OpRateWindow:
    """Per-shard forwarded-op counts in coarse time buckets — the
    windowed op-rate the fleet autopilot reads from STATS (DESIGN.md
    §21).  One-second buckets, a bounded ring of them per sid; readers
    get ops/s over the last ``window_s`` whole buckets (the current
    partial bucket is excluded so a poll landing early in a second
    cannot read an artificially low rate).  Cheap enough for the OP
    hot path: one lock hold + one dict update per sub-op group."""

    BUCKET_S = 1.0
    KEEP = 32  # bounded history: > any sane window_s

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # sid -> {bucket_epoch: count}, pruned to the last KEEP buckets
        self._buckets: Dict[str, Dict[int, int]] = {}  # guarded-by: _lock

    def note(self, sid: str, n: int = 1,
             now: Optional[float] = None) -> None:
        t = time.monotonic() if now is None else now
        epoch = int(t / self.BUCKET_S)
        with self._lock:
            b = self._buckets.setdefault(sid, {})
            b[epoch] = b.get(epoch, 0) + n
            if len(b) > self.KEEP:
                for old in sorted(b)[:len(b) - self.KEEP]:
                    del b[old]

    def drop(self, sid: str) -> None:
        """A shard that left the ring must not linger in the read-out."""
        with self._lock:
            self._buckets.pop(sid, None)

    def rates(self, window_s: float = 5.0,
              now: Optional[float] = None) -> Dict[str, float]:
        """sid -> forwarded ops/s over the last ``window_s`` COMPLETE
        buckets."""
        t = time.monotonic() if now is None else now
        current = int(t / self.BUCKET_S)
        n_buckets = max(1, int(window_s / self.BUCKET_S))
        lo = current - n_buckets
        with self._lock:
            return {
                sid: sum(c for ep, c in b.items()
                         if lo <= ep < current) / (n_buckets
                                                   * self.BUCKET_S)
                for sid, b in self._buckets.items()}


class _Relay:
    """One upstream OP's fan-out accounting: ack upstream only when
    every sub-op acked; the FIRST reject wins otherwise (deterministic
    for the common one-shard case; for spanning ops any reject means
    "resubmit", so which one the client sees is immaterial)."""

    __slots__ = ("_lock", "session", "req_id", "_remaining", "_reject")

    def __init__(self, session: Session, req_id: int, n_subops: int):
        self._lock = threading.Lock()
        self.session = session
        self.req_id = req_id
        self._remaining = n_subops  # guarded-by: _lock
        self._reject: Optional[Tuple[int, str]] = None  # guarded-by: _lock

    def resolve_one(self, reject: Optional[Tuple[int, str]]
                    ) -> Optional[Optional[Tuple[int, str]]]:
        """Record one sub-op outcome (None = acked).  Returns the final
        verdict — None-the-ack or (code, reason) — once ALL sub-ops
        resolved, else the not-done-yet sentinel ``None`` is NOT
        returned: the caller distinguishes via the wrapped tuple."""
        with self._lock:
            if reject is not None and self._reject is None:
                self._reject = reject
            self._remaining -= 1
            if self._remaining > 0:
                return None
            return (self._reject,)  # wrapped: (None,) means "ack now"


class _ShardLink:
    """Router-side state for ONE shard frontend: a lazily-dialed
    pipelined ServeClient, the breaker/backoff gate, and the
    downstream-req-id -> (_Relay, elements) map (the element list rides
    along so a reshard fence can count in-flight sub-ops touching the
    moving slice)."""

    # bound on the DIAL alone: a blackholed shard (SYN silently
    # dropped, no RST) must cost its keyspace at most this per breaker
    # probe, not the full reply timeout, and the cost is paid at most
    # once per cooldown because the breaker opens on the failure
    DIAL_TIMEOUT_S = 1.0

    # admin-plane calls that must be fenced by the router epoch: the
    # link ANNOUNCES its router's epoch (one RING_SYNC per dialed
    # connection) before driving any of these, so the shard can
    # adjudicate staleness per DESIGN.md §22
    ADMIN_CALLS = frozenset(
        {"slice_pull", "slice_push", "gc", "frontier"})

    def __init__(self, sid: str, addr, *, timeout_s: float,
                 breaker_threshold: int, breaker_cooldown_s: float,
                 policy: BackoffPolicy, seed: int, on_reply,
                 max_reply_body: Optional[int] = None,
                 router_epoch: int = 0, router_id: str = "",
                 on_deposed=None) -> None:
        self.sid = sid
        # ORDERED address list (DESIGN.md §23): the keyspace's active
        # member first, then its replication-group standbys.  Every
        # dial starts at the active member; the multi-address
        # ServeClient rotates on dial failure, so the keyspace comes
        # back through the promoted standby even before its
        # SHARD_FAILOVER announce lands (the announce then reorders
        # the roster durably).  race-ok: read-only after construction
        # (a failover swap builds a NEW link)
        self.addrs = normalize_addrs(addr)
        self.addr = self.addrs[0]
        self.timeout_s = timeout_s
        # the owning router's leadership epoch/id (0 = fence dormant,
        # pre-HA behavior).  race-ok: read-only after construction
        self.router_epoch = int(router_epoch)
        self.router_id = router_id
        # router._note_deposed (thread-safe): a shard adjudicated our
        # epoch stale — arm the router-wide self-fence
        self._on_deposed = on_deposed
        # reply-body cap for every client this link dials: the router
        # drives SLICE_PULL against shard frontends, and a donor slice
        # reply scales with the universe — the default 64MB ServeClient
        # ceiling would make a large-universe reshard permanently
        # impossible (every retry fails identically), so the router
        # sizes it from E like the frontend sizes its SLICE_PUSH cap
        self.max_reply_body = max_reply_body  # race-ok: read-only
        self._on_reply = on_reply  # router._relay_reply (thread-safe)
        self._lock = threading.Lock()
        self._client: Optional[ServeClient] = None  # guarded-by: _lock
        # latched by close(): a reader that raced past the router's
        # draining check must not redial a "closed" link (the leaked
        # client would outlive the router)
        self._closing = False  # guarded-by: _lock
        # req_ids are CONNECTION-scoped, so pending keys carry the dial
        # generation: a dead client's sweep can only ever resolve its
        # own generation's entries, never a successor's
        self._gen = 0  # guarded-by: _lock
        self._pending: Dict[Tuple[int, int],
                            Tuple[_Relay, Tuple[int, ...]]] = {}  # guarded-by: _lock
        # dial generation whose connection has ANNOUNCED the router
        # epoch (admin-plane fence): announce-once-per-connection, so
        # a redial re-announces and a deposed router's stale epoch is
        # re-adjudicated on every fresh connection
        self._announced_gen = 0  # guarded-by: _lock
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s)
        self._backoff = Backoff(policy, seed=seed)
        self._earliest_redial = 0.0  # guarded-by: _lock

    # -- dialing ------------------------------------------------------------

    # requires-lock: _lock
    def _sweep_dead_client_locked(self) -> Optional[ServeClient]:
        """Detach a client whose reader already exited (read-idle
        timeout on a quiet link, or the server went away between
        requests) so the caller redials instead of paying one doomed
        request to find out.  NOT a breaker failure: an idle-reaped
        connection says nothing about the shard's health.  The caller
        must close the returned client OUTSIDE the lock (its reader is
        already dead, but a racing death-sweep callback takes this
        lock)."""
        if self._client is not None and self._client.closed:
            client, self._client = self._client, None
            return client
        return None

    # requires-lock: _lock
    def _ensure_client_locked(self) -> ServeClient:
        if self._closing:
            raise _Unreachable(f"shard {self.sid} link closed")
        if self._client is not None:
            return self._client
        now = time.monotonic()
        if now < self._earliest_redial or not self.breaker.allow():
            raise _Unreachable(f"shard {self.sid} breaker open")
        gen = self._gen + 1
        try:
            client = ServeClient(
                self.addrs, timeout=self.timeout_s,
                connect_timeout=self.DIAL_TIMEOUT_S,
                max_reply_body=self.max_reply_body,
                on_result=lambda op: self._downstream_result(gen, op))
        except (OSError, ConnectionError) as e:
            self.breaker.record_failure()
            delay = self._backoff.next_delay()
            if delay is None:
                self._backoff.reset()
                delay = self._backoff.policy.cap_s
            self._earliest_redial = now + delay
            raise _Unreachable(
                f"shard {self.sid} dial failed: {e}") from e
        self.breaker.record_success()
        self._backoff.reset()
        self._earliest_redial = 0.0
        self._gen = gen
        self._client = client
        return client

    # requires-lock: _lock
    def _retire_client_locked(self, gen: int) -> Optional[ServeClient]:
        """Detach the current client if it is still generation ``gen``;
        the CALLER must close the returned client OUTSIDE the lock
        (close() joins the reader thread, and the reader takes this
        lock in the reply callback — closing under the lock would stall
        both sides on each other)."""
        if self._gen != gen or self._client is None:
            return None
        client, self._client = self._client, None
        self.breaker.record_failure()
        return client

    def submit(self, relay: _Relay, kind: int, elements: Sequence[int],
               deadline_s: Optional[float]) -> None:
        """Forward one sub-op; registers the relay BEFORE the reply can
        race back (submit + register share the lock the reply callback
        takes).  Raises ``_Unreachable`` — the caller owes the relay a
        typed resolve_one."""
        retired: List[Optional[ServeClient]] = []
        try:
            with self._lock:
                retired.append(self._sweep_dead_client_locked())
                client = self._ensure_client_locked()
                gen = self._gen
                try:
                    op = client.submit_async(kind, elements,
                                             deadline_s=deadline_s)
                except (OSError, ConnectionError) as e:
                    # forward failed: the connection is dead.  Retire it
                    # (closed below, outside the lock) so the next op
                    # redials through the breaker; its in-flight ops
                    # resolve via its own sweep -> _downstream_result.
                    retired.append(self._retire_client_locked(gen))
                    raise _Unreachable(
                        f"shard {self.sid} send failed: {e}") from e
                self._pending[(gen, op.req_id)] = (relay, tuple(elements))
        finally:
            for r in retired:
                if r is not None:
                    r.close()

    def pending_touching(self, mask: np.ndarray) -> int:
        """In-flight sub-ops naming any masked element — the reshard
        fence waits this to zero before snapshotting the donor slice
        (every resolution is a durable donor ack or a typed reject)."""
        with self._lock:
            return sum(1 for _, elems in self._pending.values()
                       if any(mask[e] for e in elems))

    # -- reply path (runs on the downstream client's reader thread) ---------

    def _downstream_result(self, gen: int, op) -> None:
        with self._lock:
            entry = self._pending.pop((gen, op.req_id), None)
            if op.error is not None and not isinstance(
                    op.error, protocol.ServeError):
                # transport death: every pending op on this client is
                # being swept (generation-fenced: a stale sweep cannot
                # retire a successor client).  No close() here — the
                # sweep IS the client's own teardown path.
                self._retire_client_locked(gen)
        if entry is None:
            return
        relay, _ = entry
        if op.error is None:
            reject = None
        elif isinstance(op.error, protocol.ServeError):
            # relay the shard's own verdict, code-for-code
            code = protocol.REJECT_CODES.get(
                type(op.error), protocol.REJECT_OVERLOADED)
            reject = (code, f"shard {self.sid}: {op.error}")
        else:
            reject = (protocol.REJECT_UNAVAILABLE,
                      f"shard {self.sid} went away (retry): {op.error}")
        self._on_reply(relay, reject)

    # -- fan-out reads + handoff transfer -----------------------------------

    def _request(self, call: str, *args):
        """One synchronous request/reply on the link's client with the
        drop-on-failure treatment members()/stats() pioneered.  Admin
        calls (``ADMIN_CALLS``) first announce the router epoch on
        this connection — once per dial generation — so the shard's
        fence adjudicates every admin verb; a typed
        ``StaleRouterEpoch`` from the announce or the call itself
        PROPAGATES (deterministic: the router is deposed; the handoff
        machinery aborts typed, never retries the same epoch)."""
        stale = None
        try:
            with self._lock:
                stale = self._sweep_dead_client_locked()
                client = self._ensure_client_locked()
                gen = self._gen
                announce = (self.router_epoch > 0
                            and call in self.ADMIN_CALLS
                            and self._announced_gen != gen)
        finally:
            if stale is not None:
                stale.close()
        if announce:
            try:
                client.ring_sync(self.router_epoch, self.router_id)
            except protocol.ServeError as e:
                if (isinstance(e, protocol.StaleRouterEpoch)
                        and self._on_deposed is not None):
                    # the shard adjudicated us deposed: arm the router
                    # self-fence too (RESHARD/fleet-GC/OP shed typed
                    # from here on), then propagate — the handoff
                    # machinery aborts typed on this
                    self._on_deposed()
                raise  # typed adjudication: deposed
            except (OSError, ConnectionError, socket.timeout,
                    framing.RemoteError) as e:
                self._drop_client(gen)
                raise _Unreachable(
                    f"shard {self.sid} epoch announce failed: {e}"
                ) from e
            with self._lock:
                if self._gen == gen:
                    self._announced_gen = gen
        try:
            result = getattr(client, call)(*args)
        except (OSError, ConnectionError, socket.timeout,
                framing.RemoteError) as e:
            # RemoteError too: a shard answering MSG_ERROR (e.g. a
            # --shard flag pointed at the wrong dialect's port) must
            # count as unreachable, not kill the calling thread
            self._drop_client(gen)
            raise _Unreachable(
                f"shard {self.sid} {call} failed: {e}") from e
        if call == "ring_sync":
            # an explicit announce (promotion fan-out) also satisfies
            # the once-per-connection announce contract
            with self._lock:
                if self._gen == gen:
                    self._announced_gen = gen
        return result

    def members(self) -> Tuple[List[int], np.ndarray]:
        return self._request("members")

    def digest_summary(self) -> bytes:
        return self._request("digest_summary")

    def digest_summary_probe(self) -> bytes:
        """First-ever DSUM against this shard, on a THROWAWAY dial: a
        pre-digest frontend answers an unknown frame by ENDING the
        connection (the ConnHost dispatch-False contract), which on
        the shared pipelined client would also tear down every
        in-flight OP and charge the breaker for a healthy shard — so
        classification pays its one possible failure on its own
        socket.  Never touches the breaker.  Classification is by
        exception TYPE: only a ``framing.RemoteError`` (the server's
        own MSG_ERROR reply) proves the shard is a pre-digest build —
        a torn/desynced reply surfaces as a locally-synthesized
        ``ConnectionError`` that may CONTAIN the same "unexpected
        frame type" text and must stay transient/re-probeable."""
        try:
            probe = ServeClient(self.addrs, timeout=self.timeout_s,
                                connect_timeout=self.DIAL_TIMEOUT_S,
                                max_reply_body=self.max_reply_body)
        except (OSError, ConnectionError) as e:
            raise _Unreachable(
                f"shard {self.sid} dsum probe dial failed: {e}") from e
        try:
            return probe.digest_summary()
        except framing.RemoteError as e:
            if "unexpected frame type" in str(e):
                raise _DsumUnsupported(
                    f"shard {self.sid} is pre-digest: {e}") from e
            raise _Unreachable(
                f"shard {self.sid} dsum probe: {e}") from e
        except Exception as e:  # noqa: BLE001 — transient
            raise _Unreachable(
                f"shard {self.sid} dsum probe: {e}") from e
        finally:
            probe.close()

    def stats(self) -> dict:
        return self._request("stats")

    def announce_epoch(self) -> dict:
        """Announce the owning router's epoch to this shard (the
        promotion fence fan-out); returns the shard's epoch record.
        Raises typed ``StaleRouterEpoch`` when this router is already
        deposed — the caller must stop acting, not retry."""
        return self._request("ring_sync", self.router_epoch,
                             self.router_id)

    def frontier(self) -> Tuple[np.ndarray, np.ndarray, bool]:
        return self._request("frontier")

    def gc(self, fleet_frontier: np.ndarray) -> Tuple[int, int]:
        return self._request("gc", fleet_frontier)

    def slice_pull(self, elements: Sequence[int]) -> bytes:
        """Handoff donor read (typed ServeError rejects propagate — the
        coordinator decides retry-vs-abort per class)."""
        return self._request("slice_pull", elements)

    def slice_push(self, payload: bytes) -> None:
        """Handoff recipient write; returns once the shard durably
        applied the slice."""
        self._request("slice_push", payload)

    def _drop_client(self, gen: int) -> None:
        """Retire after a fan-out failure and CLOSE the retired client
        (a timeout on a live-but-slow connection would otherwise leak
        its socket + reader thread every poll)."""
        with self._lock:
            retired = self._retire_client_locked(gen)
        if retired is not None:
            retired.close()

    def close(self) -> None:
        with self._lock:
            self._closing = True
            client, self._client = self._client, None
        if client is not None:
            client.close()


class ShardRouter:
    """Serve-dialect TCP router over a dynamic shard fleet.

    ``shards`` maps shard id -> (host, port) of a ``serve --ingest``
    frontend — the INITIAL fleet; live resharding (the RESHARD admin
    verb) grows and shrinks it at runtime.  ``num_elements`` is the
    fleet-wide element universe the owner map is built over (every
    shard runs the same E; each owns the active ring's slice of it).
    With ``state_dir``, committed ring swaps persist (fsync-then-rename
    ``ring.json``) and a restarted router adopts the last COMMITTED
    ring over its CLI flags — a kill mid-handoff therefore restarts on
    the old ring (staged-but-uncommitted epochs read as aborted).
    """

    IDLE_TIMEOUT_S = 60.0
    MAX_FRAME_BODY = 1 << 20
    MAX_CONNS = 256

    def __init__(self, shards: Mapping[str, Addr], num_elements: int, *,
                 seed: int = 0, recorder=None,
                 downstream_timeout_s: float = 10.0,
                 breaker_threshold: int = 1,
                 breaker_cooldown_s: float = 0.5,
                 backoff: Optional[BackoffPolicy] = None,
                 max_conns: Optional[int] = None,
                 state_dir: Optional[str] = None,
                 fence_timeout_s: float = 10.0,
                 transfer_timeout_s: float = 30.0,
                 fleet_gc_interval_s: float = 0.0,
                 router_epoch: int = 0,
                 router_id: Optional[str] = None):
        from go_crdt_playground_tpu.obs import Recorder

        if not shards:
            raise ValueError("a router needs at least one shard")
        self.recorder = recorder if recorder is not None else Recorder()
        self.num_elements = int(num_elements)
        # router-leadership epoch (DESIGN.md §22): monotone across the
        # HA pair, adjudicated by SHARDS on every admin-plane verb.  0
        # keeps the fence dormant (pre-HA deployments).  The persisted
        # record wins over a smaller flag so a restarted router can
        # never regress its own claim; a larger flag (a promotion)
        # persists before anything is announced or served.
        # race-ok: read-only after __init__ (a promotion constructs a
        # NEW router; nothing bumps a live router's own epoch)
        self.router_epoch = max(int(router_epoch),
                                load_router_epoch(state_dir))
        self.router_id = (router_id if router_id
                          else f"router-{os.getpid()}")
        self._state_dir = state_dir
        if state_dir is not None and self.router_epoch > 0:
            persist_router_epoch(state_dir, self.router_epoch,
                                 self.router_id)
        self._downstream_timeout_s = downstream_timeout_s
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self._policy = backoff if backoff is not None else BackoffPolicy(
            base_s=0.05, multiplier=2.0, cap_s=2.0, jitter=0.1,
            max_retries=4)
        self._seed = seed

        # values may be single (host, port) pairs or ORDERED address
        # lists (active member first, then replication-group standbys
        # — DESIGN.md §23); one normalization covers both shapes
        shard_map = {sid: normalize_addrs(a) for sid, a in shards.items()}
        generation = 0
        if state_dir is not None:
            rec = load_ring_file(state_dir)
            if rec is not None and rec.get("phase") == PHASE_COMMITTED:
                if (int(rec.get("elements", num_elements))
                        != int(num_elements)
                        or int(rec.get("seed", seed)) != int(seed)):
                    raise ValueError(
                        f"persisted ring in {state_dir!r} was committed "
                        f"under different (E, seed) than the flags — "
                        "delete ring.json to reset membership from flags")
                # the committed membership wins over the CLI flags: the
                # flags describe the fleet at FIRST launch, the record
                # describes it after every reshard AND every keyspace
                # failover since (the persisted order is active-first,
                # so a restart redials the promoted member)
                shard_map = {s: normalize_addrs(a)
                             for s, a in rec["shards"].items()}
                generation = int(rec.get("generation", 0))
                self._count("router.ring.restored")

        ring = HashRing(list(shard_map), seed=seed)
        owner = ring.owner_map(self.num_elements)
        self._lock = threading.Lock()
        # the hot path's atomic snapshot: ring + owner map + fence,
        # swapped whole by commit_route (immutable, so readers are
        # lock-free-consistent after one locked fetch)
        self._route = RouteState(  # guarded-by: _lock
            ring, owner, generation,
            ring.digest(self.num_elements, owner))
        self._links: Dict[str, _ShardLink] = {}  # guarded-by: _lock
        self._link_seq = 0  # guarded-by: _lock
        # the highest router epoch this router has ever HEARD claimed
        # (its own included): a RING_SYNC claim above our own means a
        # standby promoted past us — self-fence: refuse RESHARD and
        # fleet-GC rounds typed rather than drive admin verbs the
        # shards would reject one by one
        self._max_epoch_seen = self.router_epoch  # guarded-by: _lock
        # latched by announce_epoch(): serve() skips its startup probe
        # when the owner (the promotion path) already fanned it out
        # race-ok: single-writer latch, worst case one redundant probe
        self._announced_fleet = False
        # per-sid SHARD epochs (DESIGN.md §23): which replication-group
        # member the router has adjudicated as each keyspace's active
        # serving member.  Persisted fsync-then-rename BEFORE a
        # failover swap acts; a restart can never hand a keyspace back
        # to a deposed member.
        self._shard_epochs: Dict[str, int] = load_shard_epochs(
            state_dir)  # guarded-by: _lock
        # serializes whole failover adjudications (persist -> swap):
        # two racing claims for one sid must order their durable
        # records.  The order is _failover_lock -> _lock
        self._failover_lock = threading.Lock()
        with self._lock:
            for sid in ring.shards:
                self._links[sid] = self._new_link(sid, shard_map[sid])
        # op handlers between their fence check and their last submit,
        # counted PER FENCE EPOCH (set_fence bumps the epoch): the
        # reshard fence waits only for handlers that entered BEFORE it
        # went up — they might carry moved-slice ops it never rejected
        # — while post-fence handlers (which provably saw the fence)
        # can dial dead shards for seconds without wedging a handoff
        self._op_epoch = 0  # guarded-by: _lock
        self._inflight_by_epoch: Dict[int, int] = {}  # guarded-by: _lock
        # digest-guarded member cache (ROADMAP digest rung b): per
        # shard, the last MEMBERS reply keyed by the digest summary it
        # was fresh under.  QUERY fan-out fetches the O(E/16)-byte
        # summary first and re-pulls the O(membership) member set only
        # on mismatch — a quiescent fleet's repeated reads become
        # O(diff).  Safe because a replica's vv is monotone and rides
        # the summary: a stale summary key can never recur, so a
        # hit proves the cached reply is the one the shard would give
        # (to ops/digest.py's 2^-32-per-group collision bound).
        self._member_cache_lock = threading.Lock()
        self._member_cache: Dict[
            str, Tuple[bytes, List[int], np.ndarray]] = {}  # guarded-by: _member_cache_lock
        # bumped on every membership drop: a QUERY fan-out worker that
        # snapshotted its links BEFORE a reshard-leave can finish its
        # (seconds-long) members() pull AFTER the leave's eviction ran
        # — stores stamped with an older epoch are dropped, so a
        # departed sid can never be resurrected into the cache or the
        # DSUM classification (a rejoining sid may be a different
        # binary)
        self._member_cache_epoch = 0  # guarded-by: _member_cache_lock
        # DSUM classification, per sid until it leaves the ring:
        # supported sids ride the shared link client; sids that
        # answered the probe with the legacy "unexpected frame type"
        # error are queried uncached for good.  Unclassified sids
        # probe on a THROWAWAY dial (a legacy frontend ENDS the
        # connection on the unknown frame — on the shared client that
        # would tear down every in-flight OP).
        self._dsum_supported: set = set()  # guarded-by: _member_cache_lock
        self._dsum_unsupported: set = set()  # guarded-by: _member_cache_lock
        # per-shard windowed op-rate (the autopilot's imbalance signal,
        # exposed in STATS — no new wire verb); internally locked
        self._op_rates = _OpRateWindow()
        self._fleet_gc_interval_s = float(fleet_gc_interval_s)
        # race-ok: serve() owner thread only
        self._fleet_gc_thread: Optional[threading.Thread] = None
        self._closed = threading.Event()
        self.host = ConnHost(self._dispatch, recorder=self.recorder,
                             counter_prefix="router", thread_name="router",
                             max_conns=(self.MAX_CONNS if max_conns is None
                                        else max_conns),
                             idle_timeout_s=self.IDLE_TIMEOUT_S,
                             max_frame_body=self.MAX_FRAME_BODY)
        self.handoff = HandoffCoordinator(
            self, state_dir=state_dir, recorder=self.recorder,
            fence_timeout_s=fence_timeout_s,
            transfer_timeout_s=transfer_timeout_s, seed=seed)

    # -- route / link registry (the handoff seam) ---------------------------

    def route(self) -> RouteState:
        """The ACTIVE routing snapshot — take one per request and use
        it throughout; never mix fields from two takes."""
        with self._lock:
            return self._route

    @property
    def ring(self) -> HashRing:
        return self.route().ring

    @property
    def _owner(self) -> np.ndarray:
        # legacy read (tests/CLI banner): the active owner map
        return self.route().owner

    # requires-lock: _lock
    def _new_link(self, sid: str, addr: Addr) -> _ShardLink:
        self._link_seq += 1
        return _ShardLink(
            sid, addr, timeout_s=self._downstream_timeout_s,
            breaker_threshold=self._breaker_threshold,
            breaker_cooldown_s=self._breaker_cooldown_s,
            policy=self._policy, seed=self._seed * 1000 + self._link_seq,
            on_reply=self._relay_reply,
            router_epoch=self.router_epoch, router_id=self.router_id,
            on_deposed=self._note_deposed,
            # slice replies scale with the universe (the frontend's
            # SLICE_PUSH cap formula, §18); the 64MB floor keeps
            # MEMBERS/STATS bounded on small universes
            max_reply_body=max(ServeClient.MAX_REPLY_BODY,
                               16 * self.num_elements + 4096))

    def make_link(self, sid: str, addr: Addr) -> _ShardLink:
        """A STAGED link for a joining shard: full breaker/backoff
        machinery, but not in the routing registry — no client op can
        reach it until ``commit_route`` installs it."""
        with self._lock:
            return self._new_link(sid, addr)

    def link(self, sid: str) -> Optional[_ShardLink]:
        with self._lock:
            return self._links.get(sid)

    def links_snapshot(self) -> Dict[str, _ShardLink]:
        with self._lock:
            return dict(self._links)

    def shard_addr(self, sid: str) -> Addr:
        link = self.link(sid)
        if link is None:
            raise KeyError(sid)
        return link.addr

    def shard_roster(self, sid: str):
        """The sid's ordered address roster in the ring.json value
        shape: a legacy (host, port) pair when single, a list of
        pairs when the replication group has standbys — so a handoff
        commit's persisted record never silently drops a roster."""
        link = self.link(sid)
        if link is None:
            raise KeyError(sid)
        return (link.addrs[0] if len(link.addrs) == 1
                else [list(a) for a in link.addrs])

    def set_fence(self, fence: np.ndarray) -> None:
        with self._lock:
            self._route = self._route.with_fence(fence)
            # epoch bump under the SAME lock hold: any handler entering
            # after this observes the fenced route (one lock orders
            # its epoch read after ours and its route read after the
            # swap), so await_ops_settled need not wait for it
            self._op_epoch += 1

    def clear_fence(self) -> None:
        with self._lock:
            self._route = self._route.with_fence(None)

    def await_ops_settled(self, deadline: float) -> None:
        """Wait until every op handler that entered BEFORE the fence
        went up has left its fence-check-to-last-submit window — after
        this, every in-flight moved-slice sub-op is visible in some
        link's pending map, and every later op saw the fence.  Scoped
        to PRE-fence handlers on purpose: post-fence ops can be stuck
        a full DIAL_TIMEOUT_S against an unreachable (and unrelated)
        shard, and waiting for global quiescence would make resharding
        unavailable exactly when an operator is resizing around a
        failure."""
        with self._lock:
            fence_epoch = self._op_epoch
        while True:
            with self._lock:
                stale = sum(n for ep, n in self._inflight_by_epoch.items()
                            if ep < fence_epoch)
            if stale == 0:
                return
            if time.monotonic() > deadline:
                raise HandoffError(
                    f"{stale} pre-fence op handlers still in flight")
            time.sleep(0.002)

    def commit_route(self, ring: HashRing, owner: np.ndarray, digest: str,
                     *, add_sid: Optional[str] = None,
                     add_link: Optional[_ShardLink] = None,
                     drop_sid: Optional[str] = None) -> int:
        """The atomic swap: new ring + owner map under one lock hold,
        fence cleared, generation bumped; a leave's retired link closes
        OUTSIDE the lock (close joins its reader thread)."""
        retired = None
        with self._lock:
            if self._closed.is_set():
                # shutdown raced the commit: refuse rather than install
                # a live link into a swept registry.  The committed
                # ring record may already be persisted — harmless: a
                # restart adopts it, and its slices are already durable
                # on their recipients.
                raise HandoffError("router closed during commit")
            gen = self._route.generation + 1
            self._route = RouteState(ring, owner, gen, digest, None)
            if add_sid is not None and add_link is not None:
                self._links[add_sid] = add_link
            if drop_sid is not None:
                retired = self._links.pop(drop_sid, None)
        if drop_sid is not None:
            self._op_rates.drop(drop_sid)
            # a left shard's cached member set must not linger (its
            # link is gone, so nothing would ever refresh the entry),
            # and its DSUM classification resets with it — the sid
            # may rejoin as a different (upgraded or downgraded)
            # binary on the same id.  The epoch bump (same lock hold)
            # invalidates any in-flight fan-out worker's pending store
            # for the departed sid.
            with self._member_cache_lock:
                self._member_cache.pop(drop_sid, None)
                self._dsum_unsupported.discard(drop_sid)
                self._dsum_supported.discard(drop_sid)
                self._member_cache_epoch += 1
        if retired is not None:
            retired.close()
        return gen

    # -- lifecycle ----------------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        if not self._announced_fleet and (self.router_epoch > 0
                                          or self._state_dir is not None):
            # HA deployments: announce/probe BEFORE taking traffic —
            # a resurrected deposed primary discovers the promoted
            # epoch here (the shards remember it durably) and starts
            # life self-fenced: admin verbs refuse typed AND the data
            # plane sheds typed, because forwarding ops over a ring
            # the promoted router may have resharded past could strand
            # acked writes on handoff donors (read-filtered, invisible
            # to fleet reads — the one thing zero-acked-op-loss can
            # never tolerate).  Gated on state_dir as well as epoch:
            # a primary left at the DEFAULT epoch 0 never persists a
            # claim, so an epoch test alone would let its resurrection
            # skip straight to forwarding over a possibly-stale ring —
            # with epoch 0 the probe is a pure RING_SYNC read, and a
            # shard record carrying any adjudicated epoch > 0 arms the
            # self-fence (announce_epoch's reply check).  Skipped when
            # the owner already fanned the announce out (the promotion
            # path) — one fleet RTT, not two, on the SIGKILL-to-serving
            # critical path.
            self.announce_epoch()
        addr = self.host.listen(host, port)
        if self._fleet_gc_interval_s > 0:
            self._fleet_gc_thread = threading.Thread(
                target=self._fleet_gc_loop, name="router-fleet-gc",
                daemon=True)
            self._fleet_gc_thread.start()
        return addr

    def _fleet_gc_loop(self) -> None:
        while not self._closed.wait(self._fleet_gc_interval_s):
            try:
                self.run_fleet_gc()
            except Exception:  # noqa: BLE001 — maintenance must never
                # take the router down; the next wake retries
                self._count("router.fleet_gc.errors")

    def close(self) -> None:
        if self._closed.is_set():
            return
        # set FIRST (under the route lock): from here commit_route
        # refuses, so a handoff racing shutdown can never install a
        # live link into the registry this method is about to sweep
        with self._lock:
            self._closed.set()
        self.host.stop_accepting()
        # downstream first: closing a link resolves its in-flight ops as
        # connection errors, which relay typed rejects through sessions
        # that are still open
        for link in self.links_snapshot().values():
            link.close()
        # one SHARED flush window across all sessions (the frontend's
        # drain shape): stalled clients cost ~1s total, not each
        self.host.close_sessions(flush_timeout_s=1.0)
        if self._fleet_gc_thread is not None:
            self._fleet_gc_thread.join(timeout=5.0)

    def __enter__(self) -> "ShardRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request dispatch (runs on the host's reader threads) ---------------

    def _dispatch(self, session: Session, msg_type: int,
                  body: bytes) -> bool:
        if msg_type == protocol.MSG_OP:
            return self._handle_op(session, body)
        if msg_type == protocol.MSG_QUERY:
            self._handle_query(session, body)
            return True
        if msg_type == protocol.MSG_STATS:
            self._handle_stats(session, body)
            return True
        if msg_type == protocol.MSG_RESHARD:
            return self._handle_reshard(session, body)
        if msg_type == protocol.MSG_RING_SYNC:
            return self._handle_ring_sync(session, body)
        if msg_type == protocol.MSG_SHARD_FAILOVER:
            return self._handle_shard_failover(session, body)
        # The router DRIVES the verbs below against shard frontends; it
        # never serves them itself (W001 dispatcher-scoped exclusions):
        # protocol-ignore: MSG_SLICE_PULL — handoff donor read, driven
        # protocol-ignore: MSG_SLICE_PUSH — handoff recipient write, driven
        # protocol-ignore: MSG_FRONTIER — GC evidence read, driven
        # protocol-ignore: MSG_GC — fleet-frontier push, driven
        # protocol-ignore: MSG_DSUM — member-cache freshness read, driven
        # protocol-ignore: MSG_WAL_SYNC — shard-side replication tail
        # verb; standbys dial their primary SHARD, never the router
        session.send(framing.MSG_ERROR,
                     f"unexpected frame type {msg_type}".encode())
        return False

    # -- router HA: epoch record + self-fence (DESIGN.md §22) ---------------

    @property
    def deposed(self) -> bool:
        """True once a HIGHER router epoch than our own has been heard
        claimed: a standby promoted past this router.  The data plane
        (OP/QUERY/STATS) keeps serving — CRDT ops are safe through any
        correct ring holder — but admin actions refuse typed."""
        with self._lock:
            return self._max_epoch_seen > self.router_epoch

    def ring_record(self) -> Dict[str, object]:
        """The committed routing record a warm standby tails: ring
        generation/digest/membership WITH addresses, the handoff epoch
        counter, and this router's leadership epoch — everything a
        promotion needs to adopt the exact ring the primary last
        committed (shard/ha.py persists it in the ring.json shape a
        restarted/promoted router adopts)."""
        rt = self.route()
        links = self.links_snapshot()
        with self._lock:
            seen = self._max_epoch_seen
        return {
            "role": "router",
            "router_id": self.router_id,
            "router_epoch": self.router_epoch,
            "max_epoch_seen": seen,
            "generation": rt.generation,
            "digest": rt.digest,
            "seed": rt.ring.seed,
            "elements": self.num_elements,
            "epoch": self.handoff.epoch,
            # active member first; multi-member rosters ship as lists
            # of pairs (normalize_addrs reads both shapes back)
            "shards": {sid: (list(link.addrs[0])
                             if len(link.addrs) == 1
                             else [list(a) for a in link.addrs])
                       for sid, link in links.items()
                       if sid in rt.ring.shards},
        }

    # fence-ok: this verb IS the router-epoch fence mechanism — the
    # standby's tail read and the promotion's deposition notice both
    # ride it, and a deposed primary must keep answering so it can
    # learn (and persist) its own deposition
    def _handle_ring_sync(self, session: Session, body: bytes) -> bool:
        """Serve the tail read / adjudicate an epoch claim.  A claim
        above everything seen is NOTED (self-fence: this router stops
        admin actions) and acknowledged; a claim below the maximum is
        the deposed router itself — typed ``StaleRouterEpoch``."""
        try:
            req_id, epoch, router_id = protocol.decode_ring_sync(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        noted = False
        with self._lock:
            if epoch > self._max_epoch_seen:
                self._max_epoch_seen = epoch
                noted = True
            seen = self._max_epoch_seen
        if noted:
            self._count("router.epoch.noted")
        if 0 < epoch < seen:
            self._count("router.rejects.stale_epoch")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_STALE_EPOCH,
                f"router epoch {epoch} is stale: epoch {seen} "
                "already observed"))
            return True
        self._count("router.ring_syncs")
        session.send(protocol.MSG_RING_SYNC_REPLY,
                     protocol.encode_ring_sync_reply(
                         req_id, self.ring_record()))
        return True

    def _note_deposed(self) -> None:
        """A shard (or a RING_SYNC claimant) proved a HIGHER epoch
        exists: arm the self-fence.  The exact successor epoch is
        immaterial — ``deposed`` is a comparison, and our own epoch
        never changes on a live router."""
        with self._lock:
            if self._max_epoch_seen <= self.router_epoch:
                self._max_epoch_seen = self.router_epoch + 1
        self._count("router.epoch.noted")

    def _announce_one(self, sid: str, link: _ShardLink):
        try:
            return link.announce_epoch()
        except protocol.StaleRouterEpoch as e:
            # adjudicated deposed by this shard's durable fence (the
            # resurrection-discovery path: link._request only arms the
            # self-fence on the implicit admin-call announce, and this
            # was the EXPLICIT one)
            self._note_deposed()
            return e

    def announce_epoch(self) -> Dict[str, object]:
        """Fan this router's epoch out to every shard — the promotion
        fence, and the resurrection DISCOVERY probe: each shard either
        adopts/acks the epoch (its record rides back — a record
        carrying a higher adjudicated epoch arms our self-fence) or
        rejects it typed StaleRouterEpoch (we are deposed).  Returns
        sid -> True | the failure/verdict string.  An unreachable
        shard learns the epoch lazily on the first admin dial instead;
        promotion proceeds — the fence only needs to beat the deposed
        router to each shard, and the announce-per-connection
        discipline makes every later admin contact carry it."""
        results = self._fan_out_fn(self._announce_one)
        self._announced_fleet = True
        self._count("router.epoch.announces")
        out: Dict[str, object] = {}
        for sid, r in results.items():
            if isinstance(r, dict):
                if int(r.get("router_epoch", 0) or 0) > self.router_epoch:
                    self._note_deposed()
                out[sid] = True
            else:
                out[sid] = str(r)
        return out

    # -- shard replication: keyspace failover (DESIGN.md §23) ---------------

    def shard_epochs(self) -> Dict[str, int]:
        """The adjudicated per-sid shard epochs (STATS + tests)."""
        with self._lock:
            return dict(self._shard_epochs)

    def _handle_shard_failover(self, session: Session,
                               body: bytes) -> bool:
        """Adjudicate one keyspace-failover claim (or a restarting
        member's idempotent announce probe).  A deposed ROUTER refuses
        typed — its adjudications would desync from the promoted
        router's; the claimant's ordered router list retries there."""
        try:
            req_id, epoch, sid, owner_id, addr = \
                protocol.decode_shard_failover(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        if self.host.draining:
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_DRAINING, "router draining"))
            return True
        if self.deposed:
            self._count("router.shard_failover.deposed")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_STALE_EPOCH,
                "router deposed (stale router epoch) — claim the "
                "keyspace at the promoted router"))
            return True
        try:
            record = self.failover_shard(sid, epoch, addr,
                                         owner=owner_id)
        except KeyError:
            self._count("router.rejects.invalid")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_INVALID,
                f"unknown shard id {sid!r}"))
            return True
        except protocol.StaleShardEpoch as e:
            self._count("router.rejects.stale_shard_epoch")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_STALE_SHARD_EPOCH, str(e)))
            return True
        session.send(protocol.MSG_SHARD_FAILOVER_REPLY,
                     protocol.encode_shard_failover_reply(req_id, record))
        return True

    def failover_shard(self, sid: str, epoch: int, addr: Addr, *,
                       owner: str = "?") -> Dict[str, object]:
        """Adopt ``addr`` as shard ``sid``'s active downstream member
        under shard epoch ``epoch`` (module-level story: the promoted
        standby's claim).  Durable-before-act: the adjudicated epoch
        map persists first, then the link swaps (new ordered roster,
        claimed member first), then the active-first address order
        persists into the committed ring record so a router restart
        redials the promoted member.  Raises typed
        ``StaleShardEpoch`` for a claim at or below the adjudicated
        epoch from a different address (the resurrected old primary),
        ``KeyError`` for an unknown sid.  An echo of the adjudicated
        state is idempotent-ok (``swapped: False``)."""
        addr = (addr[0], int(addr[1]))
        with self._failover_lock:
            with self._lock:
                link = self._links.get(sid)
                if link is None:
                    raise KeyError(sid)
                cur = self._shard_epochs.get(sid, 0)
                active = link.addrs[0]
                roster = list(link.addrs)
            if epoch < cur or (epoch == cur and addr != active):
                raise protocol.StaleShardEpoch(
                    f"shard epoch {epoch} for {sid} is stale: epoch "
                    f"{cur} already adjudicated at "
                    f"{active[0]}:{active[1]} (a standby was promoted "
                    "past this member)")
            if epoch == cur and addr == active:
                # the active member's idempotent announce probe
                return {"sid": sid, "shard_epoch": cur,
                        "swapped": False, "addr": list(active)}
            # 1. durable adjudication BEFORE the swap: a crash between
            # the two leaves the fence armed and the swap re-claimable
            # (the standby's announce is idempotent)
            with self._lock:
                epochs = dict(self._shard_epochs)
            epochs[sid] = epoch
            persist_shard_epochs(self._state_dir, epochs)
            # 2. the swap: a NEW link whose roster leads with the
            # claimed member (the old roster rides behind it so a
            # later failover can rotate back)
            new_roster = [addr] + [a for a in roster if a != addr]
            retired = None
            with self._lock:
                if self._closed.is_set():
                    raise HandoffError("router closed during failover")
                self._shard_epochs[sid] = epoch
                new_link = self._new_link(sid, new_roster)
                retired = self._links.get(sid)
                self._links[sid] = new_link
            # the swapped member may be a different binary/replica:
            # its cached member set and DSUM classification must not
            # survive the swap (the drop_sid eviction discipline)
            with self._member_cache_lock:
                self._member_cache.pop(sid, None)
                self._dsum_unsupported.discard(sid)
                self._dsum_supported.discard(sid)
                self._member_cache_epoch += 1
            if retired is not None:
                retired.close()
            # 3. persist the active-first order for restarts
            self._persist_addr_roster()
            self._count("router.shard_failovers")
            return {"sid": sid, "shard_epoch": epoch, "swapped": True,
                    "addr": list(addr), "owner": owner}

    def _persist_addr_roster(self) -> None:
        """Write the committed ring record with the CURRENT active-
        first address rosters (the failover half of ring persistence —
        membership and generation unchanged).  Single-addr rosters
        persist in the legacy pair shape, so pre-HA records stay
        byte-compatible."""
        if self._state_dir is None:
            return
        rt = self.route()
        links = self.links_snapshot()
        shards = {}
        for sid in rt.ring.shards:
            link = links.get(sid)
            if link is None:
                continue
            shards[sid] = (list(link.addrs[0]) if len(link.addrs) == 1
                           else [list(a) for a in link.addrs])
        write_json_atomic(self._state_dir, RING_FILE, {
            "epoch": self.handoff.epoch,
            "phase": PHASE_COMMITTED,
            "shards": shards,
            "seed": rt.ring.seed,
            "elements": self.num_elements,
            "generation": rt.generation,
            "digest": rt.digest,
        })

    # -- OP forwarding ------------------------------------------------------

    def _handle_op(self, session: Session, body: bytes) -> bool:
        try:
            req_id, kind, elements, deadline_us = protocol.decode_op(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        E = self.num_elements
        if any(not 0 <= e < E for e in elements):
            self._count("router.rejects.invalid")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_INVALID,
                f"element id outside universe E={E}"))
            return True
        if len(set(elements)) != len(elements):
            self._count("router.rejects.invalid")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_INVALID,
                "duplicate element ids in one op"))
            return True
        if self.host.draining:
            self._count("router.shed.draining")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_DRAINING, "router draining"))
            return True
        if self.deposed:
            # a deposed router must not forward ops: its ring may be
            # STALE relative to the promoted router's reshards, and an
            # op applied on a handoff donor is acked-but-read-filtered
            # — invisible to fleet reads, a silent acked-op loss.  The
            # typed reject tells an HA client to rotate (ServeClient
            # arms its failover on this code).
            self._count("router.shed.deposed")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_STALE_EPOCH,
                "router deposed (stale router epoch) — dial the "
                "promoted router"))
            return True
        # the in-flight window the reshard fence synchronizes with:
        # from BEFORE the fence check to AFTER the last sub-op is
        # registered in its link's pending map — an op can never both
        # miss the fence and be invisible to the fence's drain.
        # Epoch-tagged: set_fence bumps the epoch, so the fence only
        # waits for handlers that entered before it existed.
        with self._lock:
            op_epoch = self._op_epoch
            self._inflight_by_epoch[op_epoch] = (
                self._inflight_by_epoch.get(op_epoch, 0) + 1)
        try:
            rt = self.route()
            if rt.fenced(elements):
                self._count("router.shed.moving")
                session.send(protocol.MSG_REJECT, protocol.encode_reject(
                    req_id, protocol.REJECT_MOVING,
                    "keyspace slice mid-handoff (retry)"))
                return True
            # group by owner, preserving client key order per group
            groups: Dict[str, List[int]] = {}
            for e in elements:
                groups.setdefault(rt.owner_sid(e), []).append(e)
            self._count("router.ops.forwarded")
            if len(groups) > 1:
                self._count("router.ops.split")
            # deadline: forward the client's remaining budget unchanged
            # — grouping costs microseconds, and the shard re-anchors
            # it at its own admission (propagation, not re-guessing)
            deadline_s = deadline_us / 1e6 if deadline_us > 0 else None
            relay = _Relay(session, req_id, len(groups))
            for sid, elems in groups.items():
                # imbalance signal: forwarded SUB-OPS per shard per
                # second (counted at forward, not ack — the autopilot
                # watches offered pressure, which exists even while a
                # saturated shard sheds)
                self._op_rates.note(sid)
                # per-group lookup, not a dict copy per op: the common
                # single-shard op pays one lock hold, no allocation
                link = self.link(sid)
                try:
                    if link is None:
                        # a ring/links transition blink (the snapshot
                        # straddled a commit): typed retry, the resubmit
                        # routes by the settled ring
                        raise _Unreachable(f"shard {sid} not linked")
                    link.submit(relay, kind, elems, deadline_s)
                except _Unreachable as e:
                    self._count("router.shed.unavailable")
                    self._relay_reply(
                        relay, (protocol.REJECT_UNAVAILABLE, str(e)))
        finally:
            with self._lock:
                n = self._inflight_by_epoch.get(op_epoch, 0) - 1
                if n <= 0:
                    self._inflight_by_epoch.pop(op_epoch, None)
                else:
                    self._inflight_by_epoch[op_epoch] = n
        return True

    def _relay_reply(self, relay: _Relay,
                     reject: Optional[Tuple[int, str]]) -> None:
        """One sub-op resolved; sends the upstream frame when the whole
        op has.  Runs on downstream reader threads AND the upstream
        reader thread (unreachable-at-submit) — the relay's own lock
        arbitrates."""
        verdict = relay.resolve_one(reject)
        if verdict is None:
            return  # sub-ops still outstanding
        final = verdict[0]
        if final is None:
            self._count("router.acks.relayed")
            relay.session.send(protocol.MSG_ACK,
                               protocol.encode_ack(relay.req_id))
        else:
            code, reason = final
            self._count("router.rejects.relayed")
            relay.session.send(protocol.MSG_REJECT,
                               protocol.encode_reject(relay.req_id, code,
                                                      reason))

    # -- fan-out reads ------------------------------------------------------

    def _fan_out(self, call: str, *args) -> Dict[str, object]:
        """Run ``link.<call>(*args)`` on every shard concurrently; returns
        sid -> result or the _Unreachable error.  Thread-per-shard per
        request is a deliberate control-plane tradeoff: QUERY/STATS are
        orders of magnitude rarer than OPs, and the alternative (async
        QUERY plumbing through ServeClient or long-lived fan-out
        workers) buys nothing until read fan-out is a measured cost —
        revisit if dashboards ever poll hot."""
        return self._fan_out_fn(
            lambda sid, link: getattr(link, call)(*args))

    def _fan_out_fn(self, fn) -> Dict[str, object]:
        """The fan-out engine behind ``_fan_out``: run ``fn(sid, link)``
        per shard concurrently (the member-cache read needs a two-step
        per-shard call, not a single link method)."""
        links = self.links_snapshot()
        # pre-seeded: a worker that dies unexpectedly or outlives the
        # join bound leaves its sentinel in place, so the shard reads
        # as unreachable-and-counted — NEVER silently absent from the
        # union (indistinguishable from a smaller healthy fleet)
        results: Dict[str, object] = {
            sid: _Unreachable(f"shard {sid} fan-out timed out")
            for sid in links}
        lock = threading.Lock()

        def one(sid: str, link: _ShardLink) -> None:
            try:
                r = fn(sid, link)
            except _Unreachable as e:
                r = e
            except Exception as e:  # noqa: BLE001 — any escape still
                # counts as unreachable rather than a vanished shard
                r = _Unreachable(f"shard {sid} fan-out raised: {e}")
            with lock:
                results[sid] = r

        threads = [threading.Thread(target=one, args=(sid, link),
                                    daemon=True)
                   for sid, link in links.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self._downstream_timeout_s + 5.0)
        with lock:
            return dict(results)

    def _members_cached(self, sid: str, link: _ShardLink):
        """One shard's QUERY read through the digest-guarded member
        cache: fetch the summary (cheap), serve the cached member set
        on a byte-identical key, re-pull MEMBERS only on mismatch.
        Counters: ``router.member_cache.hits`` / ``.refreshes``.  A
        shard that cannot answer DSUM (pre-digest build) is pinned to
        the uncached path so one legacy shard costs one failed probe,
        not a doomed extra round-trip per query."""
        with self._member_cache_lock:
            epoch0 = self._member_cache_epoch
            unsupported = sid in self._dsum_unsupported
            supported = sid in self._dsum_supported
        summ = None
        if not unsupported:
            try:
                if supported:
                    summ = link.digest_summary()
                else:
                    # unclassified: probe on a throwaway dial (a
                    # legacy frontend closes the connection on the
                    # unknown frame — never risk the shared client)
                    summ = link.digest_summary_probe()
                    with self._member_cache_lock:
                        if self._member_cache_epoch == epoch0:
                            self._dsum_supported.add(sid)
            except _DsumUnsupported:
                with self._member_cache_lock:
                    if self._member_cache_epoch == epoch0:
                        self._dsum_unsupported.add(sid)
            except _Unreachable:
                # transient (dead shard / torn link / desynced reply):
                # let members() classify it — both paths share the
                # breaker — and re-probe next query
                summ = None
        if summ is not None:
            with self._member_cache_lock:
                cached = self._member_cache.get(sid)
            if cached is not None and cached[0] == summ:
                self._count("router.member_cache.hits")
                return cached[1], cached[2]
        m, vv = link.members()
        if summ is not None:
            # keyed by the summary fetched BEFORE the member pull: if
            # the shard advanced in between, the stored key is stale
            # and the next query refreshes — never serves wrong data
            # (a replica's vv is monotone, so an old key cannot recur).
            # Epoch-guarded: if a reshard dropped membership while we
            # were pulling, this store would resurrect a dead entry.
            stored = False
            with self._member_cache_lock:
                if self._member_cache_epoch == epoch0:
                    self._member_cache[sid] = (summ, m, vv)
                    stored = True
            if stored:
                self._count("router.member_cache.refreshes")
        return m, vv

    def _handle_query(self, session: Session, body: bytes) -> None:
        try:
            req_id = protocol.decode_query(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return
        self._count("router.queries")
        # route snapshot BEFORE the fan-out: the filter must pair with
        # the ring the replies were served under — a commit landing
        # mid-fan-out would otherwise filter a donor's reply by the NEW
        # owner map while the recipient's reply predates its slice (one
        # query transiently missing the whole moved slice)
        rt = self.route()
        results = self._fan_out_fn(self._members_cached)
        # ownership filter (no-double-serve): each shard contributes
        # ONLY the elements the active ring assigns it — a donor's
        # stale copy of a moved slice must not shadow the new owner
        # (e.g. a post-handoff delete applied there)
        members: set = set()
        vvs: List[np.ndarray] = []
        unreachable = 0
        for sid, r in results.items():
            if isinstance(r, _Unreachable):
                unreachable += 1
                continue
            try:
                idx = rt.ring.shards.index(sid)
            except ValueError:
                # left the ring between fan-out and reply: its whole
                # keyspace is served by the post-swap owners
                continue
            m, vv = r
            members.update(
                int(e) for e in m
                if 0 <= e < self.num_elements and rt.owner[e] == idx)
            vvs.append(np.asarray(vv, np.uint32))
        if unreachable:
            # the union over reachable shards is a valid CRDT lower
            # bound (membership only inflates) — served, and counted,
            # not errored
            self._count("router.queries.partial", unreachable)
        if vvs:
            a = max(v.shape[0] for v in vvs)
            vv = np.zeros(a, np.uint32)
            for v in vvs:  # element-wise join; shards tick disjoint lanes
                vv[:v.shape[0]] = np.maximum(vv[:v.shape[0]], v)
        else:
            vv = np.zeros(0, np.uint32)
        session.send(protocol.MSG_MEMBERS, protocol.encode_members(
            req_id, sorted(int(e) for e in members), vv))

    def _handle_stats(self, session: Session, body: bytes) -> None:
        try:
            req_id = protocol.decode_stats(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return
        self._count("router.stats")
        results = self._fan_out("stats")
        shards: Dict[str, object] = {}
        aggregate: Dict[str, int] = {}
        for sid, r in results.items():
            if isinstance(r, _Unreachable):
                shards[sid] = None
                continue
            shards[sid] = r
            for name, v in r.get("counters", {}).items():
                aggregate[name] = aggregate.get(name, 0) + int(v)
        snap = self.recorder.snapshot()
        # top level is FRONTEND-shaped (counters/observations/gauges):
        # a stats reader written against one frontend reads the fleet
        # aggregate unmodified; the per-shard split rides alongside.
        # Aggregating shard-side latency PERCENTILES is statistically
        # meaningless, so observations stay router-local (empty today).
        counters = dict(aggregate)
        counters.update(snap.get("counters", {}))
        # the autopilot's observability surface (DESIGN.md §21): the
        # active ring's keyspace balance and the per-shard windowed
        # forwarded-op rate ride the EXISTING stats verb — imbalance is
        # observable with no new wire verb, by any dialect client
        rt = self.route()
        ring_info = rt.info()
        ring_info["load_stats"] = load_stats(rt.owner,
                                             len(rt.ring.shards))
        # which ROUTER is serving, not just which ring: the HA client
        # and the autopilot's decision log adjudicate failovers from
        # these (DESIGN.md §22)
        with self._lock:
            seen = self._max_epoch_seen
            shard_epochs = dict(self._shard_epochs)
        ring_info["router_epoch"] = self.router_epoch
        ring_info["router_id"] = self.router_id
        ring_info["max_epoch_seen"] = seen
        # the shard-replication observability half (DESIGN.md §23):
        # which member serves each keyspace (active-first rosters) and
        # under which adjudicated shard epoch — the failover soak and
        # the autopilot's decision records read these
        ring_info["shard_epochs"] = shard_epochs
        ring_info["shard_addrs"] = {
            sid: [list(a) for a in link.addrs]
            for sid, link in self.links_snapshot().items()
            if sid in rt.ring.shards}
        session.send(protocol.MSG_STATS_REPLY, protocol.encode_stats_reply(
            req_id, {"counters": counters,
                     "observations": {},
                     "gauges": snap.get("gauges", {}),
                     "router": snap,
                     "shards": shards,
                     "aggregate": {"counters": aggregate},
                     # which ring this router is ACTUALLY serving —
                     # generation + owner-map digest (the soak asserts
                     # a failed handoff left these untouched)
                     "ring": ring_info,
                     "autopilot": {
                         "op_rates": self._op_rates.rates(),
                         "op_rate_window_s": 5.0,
                     }}))

    # -- fleet-aware deletion-record GC (ROADMAP item c, DESIGN.md §17) -----

    def run_fleet_gc(self) -> dict:
        """One fleet GC round: collect every shard's GC evidence
        (FRONTIER), aggregate the true FLEET frontier, push it back
        (GC) for each shard to apply clamped to its own proof.

        Aggregation is a lane-wise MIN with one exclusion: a shard
        whose declared membership is the explicit isolated set AND
        whose applied vv is zero for lane ``a`` provably holds no
        lane-``a`` state anywhere in its deployment unit, so it is no
        constraint on lane ``a`` (without the exclusion, disjoint
        keyspaces would pin every foreign lane to zero forever and
        fleet GC would never drop anything).  A shard WITH declared
        replicas is always included — its own vv says nothing about
        what a replica may hold via transitive gossip, and a future
        reshard can hand that replica's cluster any element.  An
        UNREACHABLE shard blocks the whole round (its evidence is
        unknown, and unknown must read as zero everywhere).

        Returns the round's accounting; the periodic driver and the
        fleet soak read the same dict."""
        if self.deposed:
            # self-fence (DESIGN.md §22): a deposed router must never
            # push a GC frontier — its fleet view may be stale and the
            # shards would reject the verbs typed anyway
            self._count("router.fleet_gc.deposed")
            return {"pushed": False,
                    "reason": "router deposed (stale router epoch)"}
        results = self._fan_out("frontier")
        evidence = []
        for sid, r in sorted(results.items()):
            if isinstance(r, _Unreachable):
                self._count("router.fleet_gc.partial")
                return {"pushed": False,
                        "reason": f"shard {sid} unreachable"}
            evidence.append(r)
        a_max = max(f.shape[0] for f, _, _ in evidence)
        fleet = np.zeros(a_max, np.uint32)
        for lane in range(a_max):
            lanes = [int(f[lane]) if lane < f.shape[0] else 0
                     for f, proc, isolated in evidence
                     if not (isolated
                             and (lane >= proc.shape[0]
                                  or proc[lane] == 0))]
            if lanes:
                fleet[lane] = min(lanes)
        if not fleet.any():
            self._count("router.fleet_gc.noop")
            return {"pushed": False, "reason": "all-zeros fleet frontier",
                    "frontier": fleet}
        pushes = self._fan_out("gc", fleet)
        dropped = 0
        unreachable = 0
        for sid, r in pushes.items():
            if isinstance(r, _Unreachable):
                # GC is local compaction: a shard that missed this push
                # just keeps its records until a later round
                unreachable += 1
                continue
            dropped += int(r[0])
        self._count("router.fleet_gc.runs")
        if dropped:
            self._count("router.fleet_gc.dropped_lanes", dropped)
        if unreachable:
            self._count("router.fleet_gc.push_misses", unreachable)
        return {"pushed": True, "frontier": fleet, "dropped": dropped,
                "push_misses": unreachable}

    # -- the admin verb -----------------------------------------------------

    def _handle_reshard(self, session: Session, body: bytes) -> bool:
        """Run one live join/leave SYNCHRONOUSLY on this admin
        connection's reader thread (the handoff is seconds-scale and
        the admin client holds the connection open for the verdict);
        client ops ride other connections' readers, unaffected."""
        try:
            req_id, mode_code, sid, addr = protocol.decode_reshard(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        if self.host.draining:
            session.send(protocol.MSG_RESHARD_REPLY,
                         protocol.encode_reshard_reply(
                             req_id, False, {"reason": "router draining"}))
            return True
        if self.deposed:
            # self-fence: the typed refusal an operator (or autopilot)
            # gets from a deposed primary BEFORE any shard has to
            # reject a transfer verb — the reply names the reason so
            # the caller re-resolves the active router
            self._count("router.reshard.deposed")
            session.send(protocol.MSG_RESHARD_REPLY,
                         protocol.encode_reshard_reply(
                             req_id, False,
                             {"reason": "StaleRouterEpoch: router "
                                        "deposed — a standby promoted "
                                        "past this epoch"}))
            return True
        mode = ("join" if mode_code == protocol.RESHARD_JOIN else "leave")
        self._count("router.reshard.requests")
        try:
            detail = self.handoff.reshard(mode, sid, addr)
        except HandoffError as e:
            session.send(protocol.MSG_RESHARD_REPLY,
                         protocol.encode_reshard_reply(
                             req_id, False, {"reason": str(e)}))
            return True
        session.send(protocol.MSG_RESHARD_REPLY,
                     protocol.encode_reshard_reply(req_id, True, detail))
        return True

    def _count(self, name: str, n: int = 1) -> None:
        if self.recorder is not None:
            self.recorder.count(name, n)
