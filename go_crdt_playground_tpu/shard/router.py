"""The router tier: one serve-dialect endpoint over N shard frontends.

``ShardRouter`` speaks ``serve/protocol.py`` on BOTH sides.  Upstream
it is indistinguishable from a ``ServeFrontend`` — an unmodified
``ServeClient`` dials it, pipelines OPs, and reads typed ACK/REJECT
back by req_id.  Downstream it holds one pipelined ``ServeClient`` per
shard frontend and forwards:

* **OP** — elements are grouped by the ring's owner
  (``shard/ring.HashRing``; the owner map is precomputed once, so the
  hot path is one array lookup per element).  An op whose keys span
  shards fans out as one sub-op per owner; the upstream reply is ONE
  frame: ACK when every sub-op acked, else the first reject (relayed
  with the downstream's own code — the client sees what the shard
  said).  Sub-ops on reachable shards may have applied when another
  shard rejects; that is the protocol's at-least-once shape — CRDT ops
  are idempotent, the client resubmits the whole op.
* **QUERY** — fan-out to every shard, MEMBERS replies joined by set
  union and vv joined element-wise (shards tick disjoint actor lanes).
  Unreachable shards are EXCLUDED and counted: the union is a correct
  CRDT lower bound (membership only inflates), not an error.
* **STATS** — fan-out; the JSON reply carries ``router`` (this tier's
  recorder), ``shards`` (per-shard snapshots, ``null`` for unreachable
  ones) and ``aggregate`` (summed shard counters).

**Degradation ladder** (the per-shard half of DESIGN.md §13's):
each shard link carries the EXISTING ``net/antientropy.CircuitBreaker``
and a seeded ``utils/backoff.BackoffPolicy``-jittered redial gate.  A
dead shard costs its keyspace a typed ``REJECT_UNAVAILABLE`` per op —
never a silent drop, never a stall — while every other shard's
keyspace keeps serving; the breaker's HALF_OPEN probe re-admits the
shard the moment it answers again.  Downstream ops in flight when a
shard dies resolve as connection errors and relay upstream as the same
typed reject, so THROUGH the router every submitted op resolves
ack-or-typed-reject even across a shard SIGKILL (the fleet soak's
``unresolved == 0`` adjudication).

Relay threads write upstream through the per-session writer queues
(serve/session.py), so one read-stalled client never blocks a shard
link's reply stream.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from go_crdt_playground_tpu.net import framing
from go_crdt_playground_tpu.net.antientropy import CircuitBreaker
from go_crdt_playground_tpu.serve import protocol
from go_crdt_playground_tpu.serve.client import ServeClient
from go_crdt_playground_tpu.serve.session import Session
from go_crdt_playground_tpu.shard.ring import HashRing
from go_crdt_playground_tpu.utils.backoff import Backoff, BackoffPolicy

Addr = Tuple[str, int]


class _Unreachable(Exception):
    """Internal: the link could not take the sub-op (breaker open, dial
    or forward failed).  Always surfaces upstream as the typed
    ``REJECT_UNAVAILABLE`` — callers never let it escape the frame
    handler."""


class _Relay:
    """One upstream OP's fan-out accounting: ack upstream only when
    every sub-op acked; the FIRST reject wins otherwise (deterministic
    for the common one-shard case; for spanning ops any reject means
    "resubmit", so which one the client sees is immaterial)."""

    __slots__ = ("_lock", "session", "req_id", "_remaining", "_reject")

    def __init__(self, session: Session, req_id: int, n_subops: int):
        self._lock = threading.Lock()
        self.session = session
        self.req_id = req_id
        self._remaining = n_subops  # guarded-by: _lock
        self._reject: Optional[Tuple[int, str]] = None  # guarded-by: _lock

    def resolve_one(self, reject: Optional[Tuple[int, str]]
                    ) -> Optional[Optional[Tuple[int, str]]]:
        """Record one sub-op outcome (None = acked).  Returns the final
        verdict — None-the-ack or (code, reason) — once ALL sub-ops
        resolved, else the not-done-yet sentinel ``None`` is NOT
        returned: the caller distinguishes via the wrapped tuple."""
        with self._lock:
            if reject is not None and self._reject is None:
                self._reject = reject
            self._remaining -= 1
            if self._remaining > 0:
                return None
            return (self._reject,)  # wrapped: (None,) means "ack now"


class _ShardLink:
    """Router-side state for ONE shard frontend: a lazily-dialed
    pipelined ServeClient, the breaker/backoff gate, and the
    downstream-req-id -> _Relay map."""

    # bound on the DIAL alone: a blackholed shard (SYN silently
    # dropped, no RST) must cost its keyspace at most this per breaker
    # probe, not the full reply timeout, and the cost is paid at most
    # once per cooldown because the breaker opens on the failure
    DIAL_TIMEOUT_S = 1.0

    def __init__(self, sid: str, addr: Addr, *, timeout_s: float,
                 breaker_threshold: int, breaker_cooldown_s: float,
                 policy: BackoffPolicy, seed: int, on_reply) -> None:
        self.sid = sid
        self.addr = (addr[0], int(addr[1]))
        self.timeout_s = timeout_s
        self._on_reply = on_reply  # router._relay_reply (thread-safe)
        self._lock = threading.Lock()
        self._client: Optional[ServeClient] = None  # guarded-by: _lock
        # latched by close(): a reader that raced past the router's
        # draining check must not redial a "closed" link (the leaked
        # client would outlive the router)
        self._closing = False  # guarded-by: _lock
        # req_ids are CONNECTION-scoped, so pending keys carry the dial
        # generation: a dead client's sweep can only ever resolve its
        # own generation's entries, never a successor's
        self._gen = 0  # guarded-by: _lock
        self._pending: Dict[Tuple[int, int], _Relay] = {}  # guarded-by: _lock
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s)
        self._backoff = Backoff(policy, seed=seed)
        self._earliest_redial = 0.0  # guarded-by: _lock

    # -- dialing ------------------------------------------------------------

    # requires-lock: _lock
    def _ensure_client_locked(self) -> ServeClient:
        if self._closing:
            raise _Unreachable(f"shard {self.sid} link closed")
        if self._client is not None:
            return self._client
        now = time.monotonic()
        if now < self._earliest_redial or not self.breaker.allow():
            raise _Unreachable(f"shard {self.sid} breaker open")
        gen = self._gen + 1
        try:
            client = ServeClient(
                self.addr, timeout=self.timeout_s,
                connect_timeout=self.DIAL_TIMEOUT_S,
                on_result=lambda op: self._downstream_result(gen, op))
        except (OSError, ConnectionError) as e:
            self.breaker.record_failure()
            delay = self._backoff.next_delay()
            if delay is None:
                self._backoff.reset()
                delay = self._backoff.policy.cap_s
            self._earliest_redial = now + delay
            raise _Unreachable(
                f"shard {self.sid} dial failed: {e}") from e
        self.breaker.record_success()
        self._backoff.reset()
        self._earliest_redial = 0.0
        self._gen = gen
        self._client = client
        return client

    # requires-lock: _lock
    def _retire_client_locked(self, gen: int) -> Optional[ServeClient]:
        """Detach the current client if it is still generation ``gen``;
        the CALLER must close the returned client OUTSIDE the lock
        (close() joins the reader thread, and the reader takes this
        lock in the reply callback — closing under the lock would stall
        both sides on each other)."""
        if self._gen != gen or self._client is None:
            return None
        client, self._client = self._client, None
        self.breaker.record_failure()
        return client

    def submit(self, relay: _Relay, kind: int, elements: Sequence[int],
               deadline_s: Optional[float]) -> None:
        """Forward one sub-op; registers the relay BEFORE the reply can
        race back (submit + register share the lock the reply callback
        takes).  Raises ``_Unreachable`` — the caller owes the relay a
        typed resolve_one."""
        retired = None
        try:
            with self._lock:
                client = self._ensure_client_locked()
                gen = self._gen
                try:
                    op = client.submit_async(kind, elements,
                                             deadline_s=deadline_s)
                except (OSError, ConnectionError) as e:
                    # forward failed: the connection is dead.  Retire it
                    # (closed below, outside the lock) so the next op
                    # redials through the breaker; its in-flight ops
                    # resolve via its own sweep -> _downstream_result.
                    retired = self._retire_client_locked(gen)
                    raise _Unreachable(
                        f"shard {self.sid} send failed: {e}") from e
                self._pending[(gen, op.req_id)] = relay
        finally:
            if retired is not None:
                retired.close()

    # -- reply path (runs on the downstream client's reader thread) ---------

    def _downstream_result(self, gen: int, op) -> None:
        with self._lock:
            relay = self._pending.pop((gen, op.req_id), None)
            if op.error is not None and not isinstance(
                    op.error, protocol.ServeError):
                # transport death: every pending op on this client is
                # being swept (generation-fenced: a stale sweep cannot
                # retire a successor client).  No close() here — the
                # sweep IS the client's own teardown path.
                self._retire_client_locked(gen)
        if relay is None:
            return
        if op.error is None:
            reject = None
        elif isinstance(op.error, protocol.ServeError):
            # relay the shard's own verdict, code-for-code
            code = protocol.REJECT_CODES.get(
                type(op.error), protocol.REJECT_OVERLOADED)
            reject = (code, f"shard {self.sid}: {op.error}")
        else:
            reject = (protocol.REJECT_UNAVAILABLE,
                      f"shard {self.sid} went away (retry): {op.error}")
        self._on_reply(relay, reject)

    # -- fan-out reads ------------------------------------------------------

    def members(self) -> Tuple[List[int], np.ndarray]:
        with self._lock:
            client = self._ensure_client_locked()
            gen = self._gen
        try:
            return client.members()
        except (OSError, ConnectionError, socket.timeout,
                framing.RemoteError) as e:
            # RemoteError too: a shard answering MSG_ERROR (e.g. a
            # --shard flag pointed at the wrong dialect's port) must
            # count as unreachable, not kill the fan-out thread
            self._drop_client(gen)
            raise _Unreachable(
                f"shard {self.sid} members failed: {e}") from e

    def stats(self) -> dict:
        with self._lock:
            client = self._ensure_client_locked()
            gen = self._gen
        try:
            return client.stats()
        except (OSError, ConnectionError, socket.timeout,
                framing.RemoteError) as e:
            self._drop_client(gen)
            raise _Unreachable(
                f"shard {self.sid} stats failed: {e}") from e

    def _drop_client(self, gen: int) -> None:
        """Retire after a fan-out failure and CLOSE the retired client
        (a timeout on a live-but-slow connection would otherwise leak
        its socket + reader thread every poll)."""
        with self._lock:
            retired = self._retire_client_locked(gen)
        if retired is not None:
            retired.close()

    def close(self) -> None:
        with self._lock:
            self._closing = True
            client, self._client = self._client, None
        if client is not None:
            client.close()


class ShardRouter:
    """Serve-dialect TCP router over a static shard fleet.

    ``shards`` maps shard id -> (host, port) of a ``serve --ingest``
    frontend.  ``num_elements`` is the fleet-wide element universe the
    owner map is built over (every shard runs the same E; each owns the
    ring's slice of it).
    """

    IDLE_TIMEOUT_S = 60.0
    MAX_FRAME_BODY = 1 << 20
    MAX_CONNS = 256

    def __init__(self, shards: Mapping[str, Addr], num_elements: int, *,
                 seed: int = 0, recorder=None,
                 downstream_timeout_s: float = 10.0,
                 breaker_threshold: int = 1,
                 breaker_cooldown_s: float = 0.5,
                 backoff: Optional[BackoffPolicy] = None,
                 max_conns: Optional[int] = None):
        from go_crdt_playground_tpu.obs import Recorder

        if not shards:
            raise ValueError("a router needs at least one shard")
        self.recorder = recorder if recorder is not None else Recorder()
        self.num_elements = int(num_elements)
        self._downstream_timeout_s = downstream_timeout_s
        self.ring = HashRing(list(shards), seed=seed)
        # the hot path: element id -> owner index, one lookup per key
        self._owner = self.ring.owner_map(self.num_elements)
        policy = backoff if backoff is not None else BackoffPolicy(
            base_s=0.05, multiplier=2.0, cap_s=2.0, jitter=0.1,
            max_retries=4)
        self._links: Dict[str, _ShardLink] = {
            sid: _ShardLink(
                sid, shards[sid], timeout_s=downstream_timeout_s,
                breaker_threshold=breaker_threshold,
                breaker_cooldown_s=breaker_cooldown_s, policy=policy,
                seed=seed * 1000 + i, on_reply=self._relay_reply)
            for i, sid in enumerate(self.ring.shards)}
        self._conn_slots = threading.BoundedSemaphore(
            self.MAX_CONNS if max_conns is None else max_conns)
        self._lock = threading.Lock()
        self._sessions: set = set()  # guarded-by: _lock
        self._draining = threading.Event()
        self._closed = threading.Event()
        # race-ok: serve()/close() owner thread; accept loop snapshots
        self._listener: Optional[socket.socket] = None
        # race-ok: serve()/close() owner thread only
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        if self._listener is not None:
            raise RuntimeError("already serving")
        sock = socket.create_server((host, port))
        self._listener = sock
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="router-accept", daemon=True)
        self._accept_thread.start()
        return sock.getsockname()[:2]

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._draining.set()
        listener = self._listener
        if listener is not None:
            # shutdown BEFORE close: a bare close does not reliably
            # wake the blocked accept loop, and until it wakes the
            # kernel keeps completing new dials into the backlog
            try:
                listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                listener.close()
            except OSError:
                pass
            self._listener = None
        # downstream first: closing a link resolves its in-flight ops as
        # connection errors, which relay typed rejects through sessions
        # that are still open
        for link in self._links.values():
            link.close()
        with self._lock:
            sessions = list(self._sessions)
            self._sessions.clear()
        # one SHARED flush window across all sessions (the frontend's
        # drain shape): stalled clients cost ~1s total, not each
        flush_deadline = time.monotonic() + 1.0
        for s in sessions:
            s.close(flush_timeout_s=max(
                0.0, flush_deadline - time.monotonic()))
        self._closed.set()

    def __enter__(self) -> "ShardRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- accept / per-connection reader (the ServeFrontend shape) -----------

    def _accept_loop(self) -> None:
        sock = self._listener  # snapshot: close() may null the field
        assert sock is not None
        while not self._draining.is_set():
            try:
                conn, addr = sock.accept()
            except OSError:
                return  # listener closed
            if not self._conn_slots.acquire(blocking=False):
                self._count("router.shed.connections")
                conn.close()
                continue
            self._count("router.connections")
            session = Session(conn, peer=f"{addr[0]}:{addr[1]}")
            with self._lock:
                self._sessions.add(session)
            handed_off = False
            try:
                threading.Thread(
                    target=self._reader, args=(conn, session),
                    daemon=True).start()
                handed_off = True
            except RuntimeError:
                pass  # OS thread exhaustion: shed, keep accepting
            finally:
                if not handed_off:
                    with self._lock:
                        self._sessions.discard(session)
                    session.close()
                    self._conn_slots.release()

    def _reader(self, conn: socket.socket, session: Session) -> None:
        try:
            conn.settimeout(self.IDLE_TIMEOUT_S)
            while not session.closed:
                try:
                    msg_type, body = framing.recv_frame(
                        conn, timeout=self.IDLE_TIMEOUT_S,
                        max_body=self.MAX_FRAME_BODY)
                except (framing.ProtocolError, OSError):
                    return  # torn/idle/garbled connection: drop it
                if msg_type == protocol.MSG_OP:
                    if not self._handle_op(session, body):
                        return
                elif msg_type == protocol.MSG_QUERY:
                    self._handle_query(session, body)
                elif msg_type == protocol.MSG_STATS:
                    self._handle_stats(session, body)
                else:
                    session.send(framing.MSG_ERROR,
                                 f"unexpected frame type {msg_type}"
                                 .encode())
                    return
        finally:
            with self._lock:
                self._sessions.discard(session)
            session.close()
            self._conn_slots.release()

    # -- OP forwarding ------------------------------------------------------

    def _handle_op(self, session: Session, body: bytes) -> bool:
        try:
            req_id, kind, elements, deadline_us = protocol.decode_op(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return False
        E = self.num_elements
        if any(not 0 <= e < E for e in elements):
            self._count("router.rejects.invalid")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_INVALID,
                f"element id outside universe E={E}"))
            return True
        if len(set(elements)) != len(elements):
            self._count("router.rejects.invalid")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_INVALID,
                "duplicate element ids in one op"))
            return True
        if self._draining.is_set():
            self._count("router.shed.draining")
            session.send(protocol.MSG_REJECT, protocol.encode_reject(
                req_id, protocol.REJECT_DRAINING, "router draining"))
            return True
        # group by owner, preserving client key order within each group
        groups: Dict[str, List[int]] = {}
        for e in elements:
            sid = self.ring.shards[self._owner[e]]
            groups.setdefault(sid, []).append(e)
        self._count("router.ops.forwarded")
        if len(groups) > 1:
            self._count("router.ops.split")
        # deadline: forward the client's remaining budget unchanged —
        # grouping costs microseconds, and the shard re-anchors it at
        # its own admission (propagation, not re-guessing)
        deadline_s = deadline_us / 1e6 if deadline_us > 0 else None
        relay = _Relay(session, req_id, len(groups))
        for sid, elems in groups.items():
            try:
                self._links[sid].submit(relay, kind, elems, deadline_s)
            except _Unreachable as e:
                self._count("router.shed.unavailable")
                self._relay_reply(
                    relay, (protocol.REJECT_UNAVAILABLE, str(e)))
        return True

    def _relay_reply(self, relay: _Relay,
                     reject: Optional[Tuple[int, str]]) -> None:
        """One sub-op resolved; sends the upstream frame when the whole
        op has.  Runs on downstream reader threads AND the upstream
        reader thread (unreachable-at-submit) — the relay's own lock
        arbitrates."""
        verdict = relay.resolve_one(reject)
        if verdict is None:
            return  # sub-ops still outstanding
        final = verdict[0]
        if final is None:
            self._count("router.acks.relayed")
            relay.session.send(protocol.MSG_ACK,
                               protocol.encode_ack(relay.req_id))
        else:
            code, reason = final
            self._count("router.rejects.relayed")
            relay.session.send(protocol.MSG_REJECT,
                               protocol.encode_reject(relay.req_id, code,
                                                      reason))

    # -- fan-out reads ------------------------------------------------------

    def _fan_out(self, call: str) -> Dict[str, object]:
        """Run ``link.<call>()`` on every shard concurrently; returns
        sid -> result or the _Unreachable error.  Thread-per-shard per
        request is a deliberate control-plane tradeoff: QUERY/STATS are
        orders of magnitude rarer than OPs, and the alternative (async
        QUERY plumbing through ServeClient or long-lived fan-out
        workers) buys nothing until read fan-out is a measured cost —
        revisit if dashboards ever poll hot."""
        # pre-seeded: a worker that dies unexpectedly or outlives the
        # join bound leaves its sentinel in place, so the shard reads
        # as unreachable-and-counted — NEVER silently absent from the
        # union (indistinguishable from a smaller healthy fleet)
        results: Dict[str, object] = {
            sid: _Unreachable(f"shard {sid} fan-out timed out")
            for sid in self._links}
        lock = threading.Lock()

        def one(sid: str, link: _ShardLink) -> None:
            try:
                r = getattr(link, call)()
            except _Unreachable as e:
                r = e
            except Exception as e:  # noqa: BLE001 — any escape still
                # counts as unreachable rather than a vanished shard
                r = _Unreachable(f"shard {sid} {call} raised: {e}")
            with lock:
                results[sid] = r

        threads = [threading.Thread(target=one, args=(sid, link),
                                    daemon=True)
                   for sid, link in self._links.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self._downstream_timeout_s + 5.0)
        with lock:
            return dict(results)

    def _handle_query(self, session: Session, body: bytes) -> None:
        try:
            req_id = protocol.decode_query(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return
        self._count("router.queries")
        results = self._fan_out("members")
        members: set = set()
        vvs: List[np.ndarray] = []
        unreachable = 0
        for sid, r in results.items():
            if isinstance(r, _Unreachable):
                unreachable += 1
                continue
            m, vv = r
            members.update(m)
            vvs.append(np.asarray(vv, np.uint32))
        if unreachable:
            # the union over reachable shards is a valid CRDT lower
            # bound (membership only inflates) — served, and counted,
            # not errored
            self._count("router.queries.partial", unreachable)
        if vvs:
            a = max(v.shape[0] for v in vvs)
            vv = np.zeros(a, np.uint32)
            for v in vvs:  # element-wise join; shards tick disjoint lanes
                vv[:v.shape[0]] = np.maximum(vv[:v.shape[0]], v)
        else:
            vv = np.zeros(0, np.uint32)
        session.send(protocol.MSG_MEMBERS, protocol.encode_members(
            req_id, sorted(int(e) for e in members), vv))

    def _handle_stats(self, session: Session, body: bytes) -> None:
        try:
            req_id = protocol.decode_stats(body)
        except framing.ProtocolError as e:
            session.send(framing.MSG_ERROR, str(e).encode())
            return
        self._count("router.stats")
        results = self._fan_out("stats")
        shards: Dict[str, object] = {}
        aggregate: Dict[str, int] = {}
        for sid, r in results.items():
            if isinstance(r, _Unreachable):
                shards[sid] = None
                continue
            shards[sid] = r
            for name, v in r.get("counters", {}).items():
                aggregate[name] = aggregate.get(name, 0) + int(v)
        snap = self.recorder.snapshot()
        # top level is FRONTEND-shaped (counters/observations/gauges):
        # a stats reader written against one frontend reads the fleet
        # aggregate unmodified; the per-shard split rides alongside.
        # Aggregating shard-side latency PERCENTILES is statistically
        # meaningless, so observations stay router-local (empty today).
        counters = dict(aggregate)
        counters.update(snap.get("counters", {}))
        session.send(protocol.MSG_STATS_REPLY, protocol.encode_stats_reply(
            req_id, {"counters": counters,
                     "observations": {},
                     "gauges": snap.get("gauges", {}),
                     "router": snap,
                     "shards": shards,
                     "aggregate": {"counters": aggregate}}))

    def _count(self, name: str, n: int = 1) -> None:
        if self.recorder is not None:
            self.recorder.count(name, n)
