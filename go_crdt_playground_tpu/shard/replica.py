"""Shard replication groups: WAL-shipped warm standbys with fenced
shard epochs and keyspace failover (DESIGN.md §23).

PR 13 removed the router tier's single point of failure, but a
SIGKILLed SHARD still took its whole keyspace dark — typed
``ShardUnavailable`` until an operator restarted the process and
``restore_durable`` replayed its WAL.  The δ-state CRDT model makes
true shard HA cheap: the WAL already contains exactly the
δ-mutations a replica needs (arXiv:1410.2803 — the δ-groups ARE the
replication stream), and digest sync (arXiv:1803.02750) gives an
O(diff) catch-up for a standby that fell behind.  This module is the
``shard/ha.py`` tail/promote pattern applied to the DATA plane:

* **tail** — ``ShardStandby`` polls the primary's ``WAL_SYNC`` verb
  (serve/protocol.py): each reply ships a contiguous batch of
  committed WAL records by seq cursor, which the standby applies
  through ``Node.apply_wal_record`` — the records are WAL-logged
  VERBATIM on the standby and applied through the identical payload
  path, so the standby's state is bitwise-convergent with what a
  ``restore_durable`` restart of the primary would produce.  The
  cursor in the next poll IS the durable ack: everything below it is
  fsync'd on the standby.
* **semi-synchronous group commit** — the primary's batcher gates
  each batch's client acks on the standby's cursor covering the
  batch's last WAL record (``ReplicationPublisher.gate``), bounded by
  ``ack_timeout_s``.  A dead or slow standby degrades TYPED to async
  replication — a ``repl.degraded`` probe window, the exact
  ``storage_degraded()`` shape (utils/degrade.py) — so a standby can
  never take the primary's availability down with it.  The residual
  window is honest: records fsync'd on the primary whose ship the
  SIGKILL interrupts were never client-acked, so promotion loses no
  acked op even when it loses the unshipped tail.
* **catch-up** — a cursor below the primary's retained minimum (a
  checkpoint truncated the log) or a WAL-instance nonce change (the
  primary restarted and renumbered) surfaces typed, never as a
  silent gap; the standby then sends its own digest summary and the
  primary replies the O(diff) digest-sync payload
  (net/digestsync.build_reply_payload) plus a fresh cursor.
* **promote** — on N consecutive poll failures the standby persists
  ``shard_epoch = max(tailed primary epoch, own) + 1`` FIRST
  (fsync-then-rename), claims the keyspace at the ROUTER
  (``SHARD_FAILOVER``: the router adjudicates per-sid epochs durably
  and swaps the keyspace's downstream address under the existing
  RouteState machinery), best-effort deposes the old primary (a
  ``WAL_SYNC`` epoch claim — the false-positive-promotion
  containment), then binds its pre-declared serve port.  The
  standard listening banner doubles as the promotion handshake.
* **deposed primary** — a resurrected old primary announces its OWN
  (stale) epoch to the router at serve() time and learns the
  adjudicated one from the typed ``StaleShardEpoch`` reply: it boots
  self-fenced — writes shed typed, reads keep serving (a harmless
  CRDT lower bound) — exactly the PR-13 deposed-router containment,
  one tier down.

Counters/gauges (the §23 metric catalog): ``repl.polls`` /
``repl.records_shipped`` / ``repl.catchups_served`` on the primary's
serve side; ``repl.tail_records`` / ``repl.tail_polls`` /
``repl.poll_failures`` / ``repl.catchups`` / ``repl.apply_future`` /
``repl.promotions`` / ``repl.promote_blocked`` on the standby;
``repl.ship_errors`` / ``repl.degraded_windows`` / ``repl.heals`` and
the ``repl.lag_records`` / ``repl.lag_seconds`` gauges on the
publisher.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from go_crdt_playground_tpu.shard.handoff import write_json_atomic
from go_crdt_playground_tpu.utils.degrade import DegradeWindow

Addr = Tuple[str, int]

# the persisted SHARD epoch (DESIGN.md §23) — the data-plane sibling of
# handoff.ROUTER_EPOCH_FILE: a replication-group member's own claim to
# its keyspace, monotone across the group (a promoting standby persists
# max(tailed primary epoch, own) + 1 BEFORE announcing or serving).
# "seen" additionally records the highest epoch this member has ever
# ADJUDICATED (a live primary hearing its standby's deposition notice
# persists the fence so a restart cannot forget it).
SHARD_EPOCH_FILE = "shard_epoch.json"


def load_shard_epoch(state_dir: Optional[str]) -> int:
    """The persisted shard epoch (0 = absent/unreadable: the pre-HA
    configuration, fence dormant)."""
    rec = _load_epoch_rec(state_dir)
    try:
        return max(0, int(rec.get("shard_epoch", 0)))
    except (TypeError, ValueError):
        return 0


def load_shard_epoch_seen(state_dir: Optional[str]) -> int:
    """The highest shard epoch this member has durably adjudicated."""
    rec = _load_epoch_rec(state_dir)
    try:
        return max(0, int(rec.get("seen", 0)))
    except (TypeError, ValueError):
        return 0


def _load_epoch_rec(state_dir: Optional[str]) -> dict:
    import json

    if state_dir is None:
        return {}
    try:
        with open(os.path.join(state_dir, SHARD_EPOCH_FILE)) as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else {}
    except (OSError, ValueError):
        return {}


def persist_shard_epoch(state_dir: Optional[str], epoch: int,
                        owner: str, seen: Optional[int] = None) -> None:
    """Durably record this member's shard epoch (and the highest
    adjudicated one) — fsync'd BEFORE the epoch is acted on, so a
    restart can never regress the fence."""
    if state_dir is None:
        return
    os.makedirs(state_dir, exist_ok=True)
    write_json_atomic(state_dir, SHARD_EPOCH_FILE,
                      {"shard_epoch": int(epoch), "owner": owner,
                       "seen": int(max(epoch, seen if seen is not None
                                       else 0))})


class ReplicationPublisher:
    """Primary-side semi-synchronous replication state (module
    docstring): who is tailing, how far each standby's durable cursor
    has advanced, and the degrade window the ack gate rides when the
    standby is dead or slow.

    ``note_poll`` runs on WAL_SYNC reader threads; ``gate`` runs on
    the batcher thread; the condition serializes both.  The lag
    gauges are refreshed from both sides so STATS stays honest even
    when only one side is moving.
    """

    # a standby whose last poll is older than this no longer counts as
    # LIVE: the gate stops waiting on its cursor (the degrade window
    # already covers the transition, this just keeps a long-dead
    # standby from consuming a probe timeout per window forever)
    STALE_AFTER_S = 30.0

    def __init__(self, recorder=None, *, ack_timeout_s: float = 0.25,
                 degrade_retry_s: float = 1.0,
                 clock=time.monotonic):
        self.recorder = recorder
        self.ack_timeout_s = float(ack_timeout_s)
        self._clock = clock
        self.window = DegradeWindow(degrade_retry_s, clock)
        self._cond = threading.Condition()
        # standby_id -> (acked_seq, last_poll_t); acked_seq N means
        # "every record below N is durably applied over there"
        self._standbys: Dict[str, Tuple[int, float]] = {}  # guarded-by: _cond
        self._ever = False  # guarded-by: _cond
        # when the live-min cursor last covered the WAL tail (for the
        # lag_seconds gauge); None = currently caught up
        self._lagging_since: Optional[float] = None  # guarded-by: _cond

    def note_poll(self, standby_id: str, from_seq: int) -> None:
        """One WAL_SYNC tail poll arrived: ``from_seq`` acknowledges
        every record below it (the standby fsync'd them).  Wakes any
        gate waiting on the cursor.  An EMPTY standby id is an
        anonymous observability read — it must not enroll in the
        replication group (the gate waits on the slowest live member,
        and a one-off debugging poll would pin that minimum until it
        staled out)."""
        if not standby_id:
            self._count("repl.polls")
            return
        now = self._clock()
        with self._cond:
            prev = self._standbys.get(standby_id, (0, 0.0))[0]
            self._standbys[standby_id] = (max(prev, int(from_seq)), now)
            self._ever = True
            self._cond.notify_all()
        self._count("repl.polls")

    def has_standby(self) -> bool:
        with self._cond:
            return self._ever

    # requires-lock: _cond
    def _live_acked_locked(self, now: float) -> Optional[int]:
        """The min durable cursor across LIVE standbys (semi-sync must
        wait for the slowest live group member — the one that may be
        promoted); None when no standby is live."""
        live = [seq for seq, t in self._standbys.values()
                if now - t <= self.STALE_AFTER_S]
        return min(live) if live else None

    def lag_records(self, wal_next_seq: int) -> int:
        """Records committed on the primary but not yet acked by the
        slowest live standby (0 with no live standby reads as the
        degrade ladder's problem, not a lag of 0 — the gauges pair
        with ``repl.degraded_windows`` for that reason)."""
        with self._cond:
            acked = self._live_acked_locked(self._clock())
        if acked is None:
            return 0
        return max(0, int(wal_next_seq) - acked)

    def refresh_gauges(self, wal_next_seq: int) -> None:
        if self.recorder is None:
            return
        now = self._clock()
        with self._cond:
            acked = self._live_acked_locked(now)
            lag = (max(0, int(wal_next_seq) - acked)
                   if acked is not None else 0)
            if lag > 0:
                if self._lagging_since is None:
                    self._lagging_since = now
                lag_s = now - self._lagging_since
            else:
                self._lagging_since = None
                lag_s = 0.0
        if hasattr(self.recorder, "set_gauge"):
            self.recorder.set_gauge("repl.lag_records", lag)
            self.recorder.set_gauge("repl.lag_seconds", lag_s)

    def gate(self, wal) -> bool:
        """The semi-sync ack gate (module docstring): called by the
        batcher AFTER the group-commit fsync, BEFORE the acks.  Waits
        up to ``ack_timeout_s`` for the slowest live standby's cursor
        to cover the WAL tail; a timeout arms the degrade window
        (``repl.degraded_windows``) under which later gates return
        immediately — typed degradation to async — until the window
        expires and the next gate is the probe.  Returns True when
        the batch is standby-covered, False when it acked async."""
        if wal is None:
            return True
        target = int(wal.next_seq())  # cover every record below this
        with self._cond:
            if not self._ever:
                return True  # no replication group configured/tailed
        if self.window.active():
            # degraded: async acks until the window lapses (the next
            # gate after expiry probes the standby again)
            self.refresh_gauges(target)
            return False
        deadline = self._clock() + self.ack_timeout_s
        with self._cond:
            if self._live_acked_locked(self._clock()) is None:
                # no LIVE standby at all (decommissioned without
                # deregistering): waiting cannot succeed — go straight
                # to the degrade path instead of burning one
                # ack_timeout per probe forever (a returning standby
                # re-enrolls via note_poll and the next probe sees it)
                ok = False
            else:
                while True:
                    now = self._clock()
                    acked = self._live_acked_locked(now)
                    if acked is not None and acked >= target:
                        ok = True
                        break
                    remaining = deadline - now
                    if remaining <= 0:
                        ok = False
                        break
                    self._cond.wait(timeout=min(remaining, 0.05))
        self.refresh_gauges(target)
        if ok:
            if self.window.armed_ever():
                # the probe succeeded: the standby is back — semi-sync
                # resumes for every later batch
                self.window.clear()
                self._count("repl.heals")
            return True
        if self.window.arm():
            self._count("repl.degraded_windows")
        return False

    def snapshot(self) -> Dict[str, object]:
        """Observability read (tests + STATS debugging)."""
        now = self._clock()
        with self._cond:
            return {
                "standbys": {k: {"acked_seq": seq,
                                 "stale_s": round(now - t, 3)}
                             for k, (seq, t) in self._standbys.items()},
                "degraded": self.window.active(),
                "windows": self.window.windows,
            }

    def _count(self, name: str, n: int = 1) -> None:
        if self.recorder is not None:
            self.recorder.count(name, n)


# poll_once() verdicts (the state-machine seam tests drive directly —
# the shard/ha.py pattern)
POLL_TAILED = "tailed"       # primary answered; records applied
POLL_CAUGHT_UP = "caught_up"  # primary answered via digest catch-up
POLL_FAILED = "failed"       # transport failure, below the threshold
POLL_PROMOTED = "promoted"   # threshold crossed: this poll promoted us


class ShardStandby:
    """Warm standby for one shard frontend (module docstring).

    Owns a constructed-but-not-serving ``ServeFrontend`` whose node it
    feeds from the primary's WAL stream; ``promote()`` turns that
    frontend into the keyspace's serving member.  Single promotion per
    instance; the standby owns the frontend until ``close()``.
    """

    def __init__(self, primary, frontend, *, sid: str,
                 standby_id: str = "shard-standby",
                 listen_addr: Optional[Addr] = None,
                 announce_to=None,
                 poll_interval_s: float = 0.1,
                 failure_threshold: int = 5,
                 poll_timeout_s: float = 2.0,
                 wait_ms: int = 300,
                 max_records: int = 256):
        from go_crdt_playground_tpu.serve.client import normalize_addrs

        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if frontend.durable_dir is None:
            raise ValueError("a shard standby needs a durable frontend "
                             "(its replica and fenced epoch must "
                             "survive its own restart)")
        self.primary: List[Addr] = normalize_addrs(primary)
        self.frontend = frontend
        self.sid = sid
        self.standby_id = standby_id
        self.listen_addr = (None if listen_addr is None
                            else (listen_addr[0], int(listen_addr[1])))
        self.announce_to: Optional[List[Addr]] = (
            None if announce_to is None else normalize_addrs(announce_to))
        self.poll_interval_s = float(poll_interval_s)
        self.failure_threshold = int(failure_threshold)
        self.poll_timeout_s = float(poll_timeout_s)
        self.wait_ms = int(wait_ms)
        self.max_records = int(max_records)
        self.recorder = frontend.recorder
        self._lock = threading.Lock()
        # whole-promotion serialization, the shard/ha.py shape: the
        # order is _promote_lock -> _lock, never the reverse
        self._promote_lock = threading.Lock()
        self._client = None  # guarded-by: _lock
        self._failures = 0  # guarded-by: _lock
        self._cursor = 1  # guarded-by: _lock
        self._nonce: Optional[str] = None  # guarded-by: _lock
        self._need_catchup = False  # guarded-by: _lock
        self._tailed_ever = False  # guarded-by: _lock
        self._last_primary_epoch = load_shard_epoch(
            frontend.durable_dir)  # guarded-by: _lock
        self._promote_reason: Optional[str] = None  # guarded-by: _lock
        self._promotion_s: Optional[float] = None  # guarded-by: _lock
        self._announce_result: Optional[dict] = None  # guarded-by: _lock
        self._promoted = threading.Event()
        self._stop_loop = threading.Event()
        # race-ok: start()/close() owner thread only
        self._thread: Optional[threading.Thread] = None
        # pre-compile the whole serving path NOW: promotion must pay a
        # bind + announce, not a multi-second first-batch trace+compile
        # (the exact stall ServeFrontend._warmup exists to prevent —
        # here it would land inside the failover budget)
        frontend.warmup()

    # -- observers ----------------------------------------------------------

    @property
    def promoted(self) -> bool:
        return self._promoted.is_set()

    @property
    def tailed_ever(self) -> bool:
        with self._lock:
            return self._tailed_ever

    @property
    def cursor(self) -> int:
        with self._lock:
            return self._cursor

    @property
    def promote_reason(self) -> Optional[str]:
        with self._lock:
            return self._promote_reason

    @property
    def promotion_s(self) -> Optional[float]:
        with self._lock:
            return self._promotion_s

    @property
    def announce_result(self) -> Optional[dict]:
        with self._lock:
            return (None if self._announce_result is None
                    else dict(self._announce_result))

    def await_promoted(self, timeout_s: float) -> bool:
        return self._promoted.wait(timeout_s)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("standby already running")
        self._stop_loop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"shard-standby-{self.sid}",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_loop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.poll_timeout_s + self.wait_ms / 1e3
                   + self.poll_interval_s + 10.0)
        self._drop_client()

    def close(self) -> None:
        self.stop()
        # a racing manual promote() finishes (or unwinds) before the
        # frontend is torn down — the shard/ha.py close discipline
        with self._promote_lock:
            pass
        self.frontend.close()

    def __enter__(self) -> "ShardStandby":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _loop(self) -> None:
        while not self._stop_loop.wait(self.poll_interval_s):
            try:
                if self.poll_once() == POLL_PROMOTED:
                    return
            except Exception:  # noqa: BLE001 — the standby must outlive
                # any single bad poll; the next wake retries (and a
                # promotion failure retries the same way: the failure
                # count is still past threshold)
                self._count("repl.loop_errors")

    # -- the tail/health/promotion state machine ----------------------------

    def poll_once(self) -> str:
        """One tail/health probe (exposed so tests drive the state
        machine without wall-clock waits).  Returns a ``POLL_*``
        verdict."""
        import socket as socket_mod

        if self._promoted.is_set():
            return POLL_PROMOTED
        self._count("repl.tail_polls")
        with self._lock:
            cursor = self._cursor
            catchup = self._need_catchup
        try:
            if catchup:
                verdict = self._catch_up(cursor)
            else:
                verdict = self._tail(cursor)
        except (OSError, ConnectionError, socket_mod.timeout) as e:
            self._drop_client()
            self._count("repl.poll_failures")
            with self._lock:
                self._failures += 1
                failures = self._failures
                tailed = self._tailed_ever
            if failures >= self.failure_threshold:
                if not tailed and load_shard_epoch(
                        self.frontend.durable_dir) == 0:
                    # never tailed and no persisted epoch: this standby
                    # holds neither the primary's state nor its epoch —
                    # promoting would serve an EMPTY replica under an
                    # epoch that can collide with the primary's own.
                    # Warm means tailed; keep polling, let the operator
                    # see the counter
                    self._count("repl.promote_blocked")
                    return POLL_FAILED
                self.promote(reason=f"{failures} consecutive WAL_SYNC "
                                    f"poll failures: {e}")
                return POLL_PROMOTED
            return POLL_FAILED
        with self._lock:
            self._failures = 0
        return verdict

    def _tail(self, cursor: int) -> str:
        """One WAL_SYNC tail poll: apply the shipped records in order,
        advance the cursor (the NEXT poll's cursor is the durable
        ack)."""
        reply = self._tail_client().wal_sync(
            cursor, standby_id=self.standby_id, wait_ms=self.wait_ms,
            max_records=self.max_records)
        self._ingest_epoch(reply.shard_epoch)
        from go_crdt_playground_tpu.serve import protocol

        with self._lock:
            nonce_changed = (self._nonce is not None
                             and self._nonce != reply.nonce)
            self._nonce = reply.nonce
        if nonce_changed or (reply.flags & protocol.WAL_TRUNCATED):
            # the primary restarted (renumbered cursor space) or
            # checkpoint-truncated under our cursor: typed, never a
            # silent gap — catch up O(diff) next poll
            with self._lock:
                self._need_catchup = True
                self._cursor = max(1, int(reply.next_seq))
            self._count("repl.cursor_resets")
            return POLL_TAILED
        node = self.frontend.node
        applied = 0
        for i, body in enumerate(reply.records):
            seq = reply.first_seq + i
            if seq < cursor:
                continue  # overlap after a catch-up: idempotent skip
            verdict = node.apply_wal_record(body)
            if verdict == "future":
                # a gap (should be impossible on an in-order stream):
                # never skip past it — digest catch-up re-proves the
                # state instead
                self._count("repl.apply_future")
                with self._lock:
                    self._need_catchup = True
                break
            applied += 1
            with self._lock:
                self._cursor = seq + 1
                self._tailed_ever = True
        if applied:
            self._count("repl.tail_records", applied)
        with self._lock:
            if not self._tailed_ever and reply.next_seq <= 1:
                # an EMPTY primary log is still a successful tail: the
                # standby mirrors an empty replica (promoting it serves
                # exactly what a primary restart would)
                self._tailed_ever = True
        return POLL_TAILED

    def _catch_up(self, cursor: int) -> str:
        """O(diff) digest-sync catch-up (module docstring): ship our
        summary, apply the primary's mismatched-lane payload, resume
        tailing from the fresh cursor."""
        from go_crdt_playground_tpu.net import digestsync

        node = self.frontend.node
        summary = digestsync.node_summary(node)
        reply = self._tail_client().wal_sync(
            max(1, cursor), standby_id=self.standby_id,
            summary=summary)
        self._ingest_epoch(reply.shard_epoch)
        if reply.payload is not None:
            node.apply_payload_body(reply.payload)
        with self._lock:
            self._nonce = reply.nonce
            self._cursor = max(1, int(reply.next_seq))
            self._need_catchup = False
            self._tailed_ever = True
        self._count("repl.catchups")
        return POLL_CAUGHT_UP

    def _ingest_epoch(self, epoch: int) -> None:
        """Remember (and persist) the primary's shard epoch: the
        promotion bumps past it, and a persisted tailed epoch is what
        keeps a RESTARTED standby warm (the never-tailed promote guard
        would otherwise block it forever against a dead primary)."""
        epoch = int(epoch or 0)
        with self._lock:
            if epoch <= self._last_primary_epoch:
                return
            self._last_primary_epoch = epoch
        persist_shard_epoch(self.frontend.durable_dir, epoch,
                            f"tailed:{self.sid}")

    def promote(self, reason: str = "manual"):
        """The promotion sequence (module docstring): persist the
        bumped epoch FIRST, claim the keyspace at the router, depose
        the old primary best-effort, then serve.  Single-entry end to
        end; a concurrent call blocks, then returns with the winner's
        promotion standing."""
        t0 = time.monotonic()
        with self._promote_lock:
            return self._promote_locked(reason, t0)

    # requires-lock: _promote_lock
    def _promote_locked(self, reason: str, t0: float):
        from go_crdt_playground_tpu.serve.client import ServeClient

        if self._promoted.is_set():
            return self.frontend
        with self._lock:
            epoch = max(self._last_primary_epoch,
                        load_shard_epoch(self.frontend.durable_dir)) + 1
        # 1. the fence root: the claimed epoch is durable before anyone
        # can hear it (a standby crash mid-promotion re-promotes at an
        # equal-or-higher epoch, never lower)
        persist_shard_epoch(self.frontend.durable_dir, epoch,
                            self.standby_id)
        self.frontend.claim_shard_epoch(epoch)
        # 2. the keyspace claim: the router adjudicates the epoch
        # durably and swaps this sid's downstream address.  Bounded
        # retries — the router may itself be failing over (its HA pair
        # is an ordered list here) — but an unreachable router does NOT
        # block serving: the router's per-shard ordered address list
        # rotates to us on its next redial, and the fence completes at
        # the next successful announce (serve()-time re-announce).
        announce: Optional[dict] = None
        if self.announce_to is not None and self.listen_addr is not None:
            announce = self._announce_router(epoch)
        # 3. best-effort deposition notice to the old primary: a
        # false-positive promotion (network blip, not a death) leaves
        # it alive and acking — one WAL_SYNC epoch claim flips its
        # self-fence so its writes shed typed instead of landing on a
        # member the router no longer reads.  A dead primary learns
        # the same thing from its serve()-time router announce.
        try:
            with ServeClient(self.primary, timeout=self.poll_timeout_s,
                             connect_timeout=1.0) as c:
                c.wal_sync(1, epoch=epoch, standby_id=self.standby_id)
        except (OSError, ConnectionError):
            pass  # dead primary: the normal case
        # 4. serve on the pre-declared address — the router's swapped
        # link (and its ordered-list redial fallback) lands here
        if self.listen_addr is not None:
            self.frontend.serve(self.listen_addr[0], self.listen_addr[1])
        self._count("repl.promotions")
        with self._lock:
            self._promotion_s = time.monotonic() - t0
            self._promote_reason = reason
            self._announce_result = announce
        self._promoted.set()
        return self.frontend

    # requires-lock: _promote_lock
    def _announce_router(self, epoch: int) -> Optional[dict]:
        from go_crdt_playground_tpu.serve import protocol
        from go_crdt_playground_tpu.serve.client import ServeClient

        last: Optional[dict] = None
        for attempt in range(3):
            try:
                with ServeClient(self.announce_to,
                                 timeout=self.poll_timeout_s,
                                 connect_timeout=1.0) as c:
                    last = c.shard_failover(epoch, self.sid,
                                            self.standby_id,
                                            self.listen_addr)
                    return last
            except protocol.StaleShardEpoch:
                # a HIGHER epoch is already adjudicated: someone
                # promoted past us mid-promotion.  Serve anyway (the
                # router never routes here) but surface it loudly
                self._count("repl.promote_stale")
                return {"stale": True}
            except (OSError, ConnectionError, protocol.ServeError):
                time.sleep(0.2 * (attempt + 1))
        self._count("repl.announce_failures")
        return last

    # -- plumbing -----------------------------------------------------------

    def _tail_client(self):
        from go_crdt_playground_tpu.serve.client import ServeClient

        with self._lock:
            client = self._client
        if client is not None and not client.closed:
            return client
        self._drop_client()
        # reply timeout must cover the long-poll window
        client = ServeClient(
            self.primary,
            timeout=self.poll_timeout_s + self.wait_ms / 1e3,
            connect_timeout=self.poll_timeout_s,
            max_reply_body=max(ServeClient.MAX_REPLY_BODY,
                               32 * self.frontend.node.num_elements
                               + (1 << 20)))
        with self._lock:
            self._client = client
        return client

    def _drop_client(self) -> None:
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except OSError:
                pass

    def _count(self, name: str, n: int = 1) -> None:
        if self.recorder is not None:
            self.recorder.count(name, n)
