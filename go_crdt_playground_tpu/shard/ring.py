"""Seeded rendezvous (HRW) consistent hashing over element ids.

The ring decides ONE thing: which shard owns element ``e``.  Rendezvous
hashing (highest-random-weight) is used instead of a vnode ring because
its minimal-remap property is exact, not statistical: ``owner(e)`` is
the shard maximizing a keyed hash score of ``(seed, shard_id, e)``, so

* removing a shard moves ONLY the keys it owned (every other key's
  argmax is untouched), and
* adding a shard moves ONLY the keys the newcomer now wins — an
  expected ``1/(n+1)`` fraction, the information-theoretic floor.

Balance is multinomial: with ``E >> n`` the max/mean shard load
concentrates near 1 (bound pinned by tests/test_shard_ring.py).

Scores come from ``hashlib.blake2b`` over the raw ``(seed, shard_id,
element)`` bytes — never Python's ``hash()``, which is salted per
process: two processes building a ring from the same (shards, seed)
MUST route identically, or a router restart would strand keys on the
wrong replicas.  ``digest()`` condenses the whole owner map into one
hex string so that cross-process determinism is a one-line assertion
(the ``router`` CLI's dry-run mode prints it).

The ring is immutable; membership change is a NEW ring
(``with_shard``/``without_shard``) so a router swap is atomic by
construction — there is no half-updated routing state to lock.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class HashRing:
    """Immutable seeded rendezvous hash over a fixed shard set."""

    def __init__(self, shards: Sequence[str], seed: int = 0):
        if not shards:
            raise ValueError("a ring needs at least one shard")
        if len(set(shards)) != len(shards):
            raise ValueError(f"duplicate shard ids in {list(shards)!r}")
        for sid in shards:
            if not isinstance(sid, str) or not sid:
                raise ValueError(f"shard id must be a non-empty str, "
                                 f"got {sid!r}")
        # sorted: ownership must depend on the shard SET, not the order
        # the operator happened to list it in (two routers configured
        # with permuted --shard flags must agree)
        self.shards: Tuple[str, ...] = tuple(sorted(shards))
        self.seed = int(seed)

    # -- scores -------------------------------------------------------------

    def _score(self, sid: str, element_id: int) -> int:
        h = hashlib.blake2b(digest_size=8)
        h.update(struct.pack("<qQ", self.seed, int(element_id)))
        h.update(sid.encode("utf-8"))
        return int.from_bytes(h.digest(), "little")

    def owner(self, element_id: int) -> str:
        """The shard id owning ``element_id`` (ties broken by shard id,
        which blake2b makes a ~2^-64 event — the break just keeps the
        function total)."""
        return max(self.shards,
                   key=lambda sid: (self._score(sid, element_id), sid))

    def owner_index(self, element_id: int) -> int:
        """``owner()`` as an index into ``self.shards`` (what a router
        hot path caches)."""
        return self.shards.index(self.owner(element_id))

    # -- bulk views ---------------------------------------------------------

    def owner_map(self, num_elements: int) -> np.ndarray:
        """``(E,)`` int32 array of owner indices into ``self.shards`` —
        computed once at router start, then every OP routes by one array
        lookup."""
        if num_elements < 1:
            raise ValueError("num_elements must be >= 1")
        out = np.empty(num_elements, np.int32)
        for e in range(num_elements):
            out[e] = self.owner_index(e)
        return out

    def partition(self, num_elements: int) -> Dict[str, np.ndarray]:
        """shard id -> sorted element ids it owns (the fleet soak's
        keyspace ledger)."""
        owners = self.owner_map(num_elements)
        return {sid: np.nonzero(owners == i)[0]
                for i, sid in enumerate(self.shards)}

    def digest(self, num_elements: int,
               owners: Optional[np.ndarray] = None) -> str:
        """Hex digest of the full owner map: equal (shards, seed, E) ⇒
        equal digest in ANY process — the cross-process routing
        determinism probe.  Pass a precomputed ``owner_map`` result as
        ``owners`` to avoid hashing the universe twice."""
        if owners is None:
            owners = self.owner_map(num_elements)
        h = hashlib.blake2b(digest_size=16)
        h.update(("|".join(self.shards) + f"#{self.seed}").encode())
        h.update(np.ascontiguousarray(owners, np.int32).tobytes())
        return h.hexdigest()

    # -- membership change (new ring, old one untouched) --------------------

    def with_shard(self, sid: str) -> "HashRing":
        return HashRing(self.shards + (sid,), seed=self.seed)

    def without_shard(self, sid: str) -> "HashRing":
        if sid not in self.shards:
            raise ValueError(f"shard {sid!r} not in ring {self.shards}")
        if len(self.shards) == 1:
            raise ValueError("cannot remove the last shard")
        return HashRing([s for s in self.shards if s != sid],
                        seed=self.seed)

    def __repr__(self) -> str:
        return f"HashRing(shards={list(self.shards)}, seed={self.seed})"


def load_stats(owners: np.ndarray, num_shards: int) -> Dict[str, float]:
    """Balance summary of an owner map: per-shard loads plus the
    max/mean ratio the balance-bound test pins."""
    loads = np.bincount(owners, minlength=num_shards)
    mean = float(loads.mean())
    return {
        "loads": [int(x) for x in loads],
        "max_over_mean": float(loads.max()) / mean if mean else 0.0,
        "min_over_mean": float(loads.min()) / mean if mean else 0.0,
    }


def handoff_plan(before: np.ndarray, after: np.ndarray,
                 shards_before: Sequence[str],
                 shards_after: Sequence[str]
                 ) -> List[Tuple[str, str, List[int]]]:
    """The keyspace-handoff work list between two owner maps: one
    ``(donor_sid, recipient_sid, element_ids)`` entry per directed pair
    whose ownership changed — exactly the slices a live reshard must
    transfer before the ring swap (shard/handoff.py).  Sorted for
    deterministic transfer order; under HRW minimal remap a join's
    recipients are all the joiner and a leave's donors all the
    leaver."""
    pairs: Dict[Tuple[str, str], List[int]] = {}
    for e in range(len(before)):
        src = shards_before[before[e]]
        dst = shards_after[after[e]]
        if src != dst:
            pairs.setdefault((src, dst), []).append(e)
    return [(src, dst, elems)
            for (src, dst), elems in sorted(pairs.items())]


def remap_fraction(before: np.ndarray, after: np.ndarray,
                   shards_before: Sequence[str],
                   shards_after: Sequence[str]) -> Dict[str, object]:
    """How much of the keyspace moved between two owner maps, and
    whether every move was FORCED by the membership change (into a
    joining shard / out of a leaving one) — the minimal-remap property
    as data, adjudicated by tests/test_shard_ring.py."""
    before_ids = [shards_before[i] for i in before]
    after_ids = [shards_after[i] for i in after]
    moved = [e for e in range(len(before_ids))
             if before_ids[e] != after_ids[e]]
    joined = set(shards_after) - set(shards_before)
    left = set(shards_before) - set(shards_after)
    gratuitous: List[int] = [
        e for e in moved
        if after_ids[e] not in joined and before_ids[e] not in left]
    return {
        "moved": len(moved),
        "fraction": len(moved) / max(1, len(before_ids)),
        "gratuitous": gratuitous,  # MUST be [] — a move neither into a
                                   # joiner nor out of a leaver
    }
