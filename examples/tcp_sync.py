"""Two networked replicas converging over real TCP delta sync.

The reference simulates exchange as a direct method call
(awset_test.go:16-17); this is the same anti-entropy as an actual
protocol: each Node serves push-pull delta sync (net/peer.py), payloads
are the compact varint wire format, and convergence is checked with the
membership digest.

    python examples/tcp_sync.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")   # demo-sized: CPU is plenty


def main() -> int:
    import numpy as np

    from go_crdt_playground_tpu.net.peer import Node

    with Node(actor=0, num_elements=64, num_actors=2) as alice, \
            Node(actor=1, num_elements=64, num_actors=2) as bob:
        addr = bob.serve()
        alice.add(1, 2, 3)
        bob.add(3, 4)
        alice.delete(2)

        # ONE push-pull exchange converges both ends: the dialer ships
        # its delta against the peer's advertised VV and applies the
        # peer's delta back on the same connection.
        stats = alice.sync_with(addr)
        print(f"push-pull: sent {stats.bytes_sent}B "
              f"received {stats.bytes_received}B")

        members_a = set(alice.members().tolist())
        members_b = set(bob.members().tolist())
        print("alice members:", sorted(members_a))
        print("bob members:  ", sorted(members_b))
        assert members_a == members_b == {1, 3, 4}, "must converge"
        assert np.array_equal(alice.vv(), bob.vv()), "clocks must join"
        print("converged over TCP: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
