"""Quickstart: the reference's add-wins semantics, spec to TPU kernel.

Mirrors the switching user's first session: write the scenario from
TestAWSetConcurrentAddWinsOverDelete (reference awset_test.go:85-122)
against the executable spec, then run the SAME ops through the packed
tensor path — pack, jitted fused merge kernel, unpack, byte-equal
canonical rendering.

Run from the repo root:

    python examples/quickstart.py

Demo-sized, so it pins the CPU backend; drop the jax.config line below
to run on an ambient TPU.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")   # demo-sized: CPU is plenty


def main() -> int:
    from go_crdt_playground_tpu.models import awset
    from go_crdt_playground_tpu.models.spec import AWSet, VersionVector
    from go_crdt_playground_tpu.ops.merge import merge_one_into
    from go_crdt_playground_tpu.utils import codec

    # --- the reference scenario on the executable spec ------------------
    a = AWSet(actor=0, version_vector=VersionVector([0, 0]))
    b = AWSet(actor=1, version_vector=VersionVector([0, 0]))
    a.add("Anne", "Bob")
    b.merge(a)          # B observes both adds
    a.del_("Bob")       # A deletes Bob...
    b.add("Bob")        # ...while B concurrently re-adds him
    a.merge(b)
    b.merge(a)
    print("spec A:", a, sep="\n")
    assert a.sorted_values() == b.sorted_values() == ["Anne", "Bob"], \
        "concurrent add must win over delete"

    # --- the same ops through the packed tensor path --------------------
    dictionary = codec.ElementDict(capacity=4)
    state = awset.from_arrays(codec.pack_awsets([a, b], dictionary, 2))
    state, _ = merge_one_into(state, 0, state, 1)   # jitted fused kernel
    rendered = codec.render_packed(awset.to_arrays(state), dictionary)
    print("packed replica 0:", rendered[0], sep="\n")
    assert rendered[0] == str(a), "canonical renderings must be byte-equal"
    print("spec and kernel agree byte-for-byte: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
