"""Byte-stream conformance for the Go bridge client.

bridge/client/main.go cannot run in CI (no Go toolchain in this image,
SURVEY preamble), so this test IS its execution: a Python mirror of the
client's deterministic proto3 wire encoder produces the byte-identical
MergeRequest frames the Go program would send (pinned against protobuf's
own serializer), replays the same T1-T3 scenarios
(/root/reference/awset_test.go:10-122) and the δ scenario T6
(/root/reference/awset-delta_test.go:168-189) over a real TCP connection
to MergerServer, and checks the same membership + canonical-rendering
assertions the Go client makes.
"""

import socket
import struct

import pytest

from go_crdt_playground_tpu.bridge import service as bridge
from go_crdt_playground_tpu.bridge import merger_pb2 as pb
from go_crdt_playground_tpu.models.spec import (AWSet, AWSetDelta, Dot,
                                                VersionVector)

# ---------------------------------------------------------------------------
# Mirror of main.go's encoder: fields in tag order, entries sorted by key,
# proto3 zero values omitted, repeated uint64 packed.
# ---------------------------------------------------------------------------


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _enc_dot(d: Dot) -> bytes:
    out = b""
    if d.actor:
        out += _tag(1, 0) + _varint(d.actor)
    if d.counter:
        out += _tag(2, 0) + _varint(d.counter)
    return out


def _enc_entry(key: str, d: Dot) -> bytes:
    return _len_field(1, key.encode()) + _len_field(2, _enc_dot(d))


def _enc_replica(rep: AWSet) -> bytes:
    out = b""
    if rep.actor:
        out += _tag(1, 0) + _varint(rep.actor)
    vv = list(rep.version_vector)
    if vv:
        out += _len_field(2, b"".join(_varint(n) for n in vv))
    for k in sorted(rep.entries):
        out += _len_field(3, _enc_entry(k, rep.entries[k]))
    return out


def _enc_merge_request(dst: AWSet, src: AWSet) -> bytes:
    return _len_field(1, _enc_replica(dst)) + _len_field(2, _enc_replica(src))


def _enc_delta_replica(rep: AWSetDelta) -> bytes:
    out = _enc_replica(rep)
    for k in sorted(rep.deleted):  # Deleted log, field 4, sorted (main.go)
        out += _len_field(4, _enc_entry(k, rep.deleted[k]))
    return out


def _enc_delta_merge_request(dst: AWSetDelta, src: AWSetDelta) -> bytes:
    """main.go's encodeDeltaMergeRequest: delta=true, reference semantics,
    strict quirk on — the AWSetDelta.Merge dispatch
    (awset-delta_test.go:51-65)."""
    return (_len_field(1, _enc_delta_replica(dst))
            + _len_field(2, _enc_delta_replica(src))
            + _tag(3, 0) + _varint(1)
            + _len_field(4, b"reference")
            + _tag(5, 0) + _varint(1))


def test_wire_encoder_matches_protobuf_serializer():
    """The hand encoder (== main.go's) must produce byte-identical output
    to protobuf's canonical serializer, so the Go client's frames parse
    exactly as the server's merger_pb2 expects."""
    a = AWSet(actor=0, version_vector=VersionVector([0, 0]))
    b = AWSet(actor=1, version_vector=VersionVector([0, 0]))
    a.add("Anne", "Bob")
    b.add("Anne")
    a.del_("Bob")

    def to_pb(rep):
        msg = pb.ReplicaState(actor=rep.actor,
                              version_vector=list(rep.version_vector))
        for k in sorted(rep.entries):
            d = rep.entries[k]
            msg.entries.add(key=k,
                            dot=pb.Dot(actor=d.actor, counter=d.counter))
        return msg

    ref = pb.MergeRequest(dst=to_pb(a), src=to_pb(b)).SerializeToString()
    assert _enc_merge_request(a, b) == ref


def test_delta_wire_encoder_matches_protobuf_serializer():
    """The δ-request encoder (== main.go's encodeDeltaMergeRequest) must be
    byte-identical to protobuf's serializer, Deleted log included."""
    a = AWSetDelta(actor=0, version_vector=VersionVector([0, 0]))
    b = AWSetDelta(actor=1, version_vector=VersionVector([0, 0]))
    a.add("A", "B")
    b.add("A", "C")
    a.del_("B")

    def to_pb(rep):
        msg = pb.ReplicaState(actor=rep.actor,
                              version_vector=list(rep.version_vector))
        for k in sorted(rep.entries):
            d = rep.entries[k]
            msg.entries.add(key=k,
                            dot=pb.Dot(actor=d.actor, counter=d.counter))
        for k in sorted(rep.deleted):
            d = rep.deleted[k]
            msg.deleted.add(key=k,
                            dot=pb.Dot(actor=d.actor, counter=d.counter))
        return msg

    ref = pb.MergeRequest(
        dst=to_pb(a), src=to_pb(b), delta=True,
        delta_semantics="reference",
        strict_reference_semantics=True).SerializeToString()
    assert _enc_delta_merge_request(a, b) == ref


# ---------------------------------------------------------------------------
# Scenario replay over a live server — exactly main.go's driver.
# ---------------------------------------------------------------------------


class GoClientMirror:
    """Speaks main.go's exact byte stream to a MergerServer."""

    def __init__(self):
        self.server = bridge.MergerServer()
        host, port = self.server.serve()
        self.sock = socket.create_connection((host, port))

    def close(self):
        self.sock.close()
        self.server.close()

    def ping(self):
        self.sock.sendall(struct.pack(">BI", bridge.METHOD_PING, 0))
        method, length = struct.unpack(">BI", self._recv(5))
        assert method == bridge.METHOD_PING and length == 0

    def _recv(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            assert chunk, "server closed mid-frame"
            buf += chunk
        return buf

    def merge(self, dst: AWSet, src: AWSet) -> None:
        """dst.Merge(src) on the server; installs the merged state into
        dst and checks the cross-language canonical rendering, exactly as
        main.go's merge() does."""
        body = _enc_merge_request(dst, src)
        self.sock.sendall(struct.pack(">BI", bridge.METHOD_MERGE,
                                      len(body)) + body)
        method, length = struct.unpack(">BI", self._recv(5))
        assert method == bridge.METHOD_MERGE
        resp = pb.MergeResponse()
        resp.ParseFromString(self._recv(length))
        assert not resp.error, resp.error
        dst.version_vector = VersionVector(
            [int(n) for n in resp.merged.version_vector])
        dst.entries = {e.key: Dot(e.dot.actor, int(e.dot.counter))
                       for e in resp.merged.entries}
        assert str(dst) == resp.canonical, (str(dst), resp.canonical)
        assert resp.sorted_values == dst.sorted_values()

    def delta_merge(self, dst: AWSetDelta, src: AWSetDelta) -> None:
        """dst.Merge(src) via the server's δ dispatch, exactly as
        main.go's deltaMerge() does (state install + canonical parity)."""
        body = _enc_delta_merge_request(dst, src)
        self.sock.sendall(struct.pack(">BI", bridge.METHOD_MERGE,
                                      len(body)) + body)
        method, length = struct.unpack(">BI", self._recv(5))
        assert method == bridge.METHOD_MERGE
        resp = pb.MergeResponse()
        resp.ParseFromString(self._recv(length))
        assert not resp.error, resp.error
        dst.version_vector = VersionVector(
            [int(n) for n in resp.merged.version_vector])
        dst.entries = {e.key: Dot(e.dot.actor, int(e.dot.counter))
                       for e in resp.merged.entries}
        dst.deleted = {e.key: Dot(e.dot.actor, int(e.dot.counter))
                       for e in resp.merged.deleted}
        assert str(dst) == resp.canonical, (str(dst), resp.canonical)
        assert resp.sorted_values == dst.sorted_values()


@pytest.fixture()
def client():
    c = GoClientMirror()
    c.ping()
    yield c
    c.close()


def _fixture():
    """testAWSetInit (awset_test.go:156-174): A=Actor 0, B=Actor 1,
    pre-sized VV{0,0}."""
    return (AWSet(actor=0, version_vector=VersionVector([0, 0])),
            AWSet(actor=1, version_vector=VersionVector([0, 0])))


def _assert_entries(rep: AWSet, *expected: str):
    assert rep.sorted_values() == sorted(expected)


def test_t1_awset_xxx_replay(client):
    """awset_test.go:10-29 through the framework kernel."""
    A, B = _fixture()
    A.add("A", "B", "C")
    B.add("A", "B", "C")
    client.merge(A, B)
    client.merge(B, A)
    _assert_entries(A, "A", "B", "C")
    _assert_entries(B, "A", "B", "C")
    A.del_("B")
    B.add("B")
    client.merge(B, A)
    client.merge(A, B)
    _assert_entries(A, "A", "B", "C")
    _assert_entries(B, "A", "B", "C")  # concurrent writer wins


def test_t2_awset_replay(client):
    """awset_test.go:31-83 through the framework kernel."""
    A, B = _fixture()
    A.add("Shelly")
    client.merge(B, A)
    _assert_entries(B, "Shelly")
    B.add("Bob", "Phil", "Pete")
    client.merge(A, B)
    _assert_entries(A, "Shelly", "Bob", "Phil", "Pete")
    A.del_("Phil")
    A.add("Bob")
    A.add("Anna")
    client.merge(B, A)
    _assert_entries(A, "Shelly", "Bob", "Pete", "Anna")
    _assert_entries(B, "Shelly", "Bob", "Pete", "Anna")
    A.del_("Bob", "Pete")
    B.del_("Bob", "Shelly")
    client.merge(A, B)
    client.merge(B, A)
    _assert_entries(A, "Anna")
    _assert_entries(B, "Anna")
    A.add("A", "B", "C")
    A.del_("A")
    A.add("A")
    client.merge(B, A)
    _assert_entries(A, "Anna", "A", "B", "C")
    _assert_entries(B, "Anna", "A", "B", "C")


def test_t3_concurrent_add_wins_replay(client):
    """awset_test.go:85-122 through the framework kernel."""
    A, B = _fixture()
    A.add("Anne", "Bob")
    B.add("Anne")
    A2, B2 = A.clone(), B.clone()
    B2.add("Bob")
    A2.del_("Bob")
    client.merge(B2, A2)
    client.merge(A2, B2)
    _assert_entries(B2, "Anne", "Bob")  # writer wins
    _assert_entries(A2, "Anne", "Bob")
    B.add("Bob")
    client.merge(B, A)  # merge BEFORE delete: non-concurrent
    A.del_("Bob")
    client.merge(B, A)
    client.merge(A, B)
    _assert_entries(B, "Anne")
    _assert_entries(A, "Anne")


def test_t6_awset_delta_replay(client):
    """awset-delta_test.go:168-189 (T6) through the framework δ kernels:
    first contacts take the full-merge branch, later exchanges the
    δ extract/apply branch — all server-side."""
    A = AWSetDelta(actor=0, version_vector=VersionVector([0, 0]))
    B = AWSetDelta(actor=1, version_vector=VersionVector([0, 0]))
    A.add("A", "B")
    B.add("A", "C")
    client.delta_merge(A, B)
    client.delta_merge(B, A)
    _assert_entries(A, "A", "B", "C")
    _assert_entries(B, "A", "B", "C")

    A.del_("B")
    A.add("D", "E")
    B.add("E")
    client.delta_merge(B, A)
    _assert_entries(B, "A", "C", "D", "E")

    client.delta_merge(A, B)
    _assert_entries(A, "A", "C", "D", "E")

    # the strict-reference empty-δ quirk, live over the wire: the final
    # exchange ships no payload so A's VV is NOT joined
    # (awset-delta_test.go:60-64) — clocks stay divergent (SURVEY §3.3)
    assert list(A.version_vector) == [5, 2]
    assert list(B.version_vector) == [5, 3]
