"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding path
(parallel/) is exercised without TPU hardware, per the driver contract.
Real-TPU execution is covered by bench.py and __graft_entry__.entry().

This must run before anything imports jax, which pytest guarantees for a
root conftest.
"""

import os

# Tests run on CPU regardless of JAX_PLATFORMS: this image globally exports
# JAX_PLATFORMS=axon (the TPU tunnel), under which every host transfer costs
# ~100ms of network round-trip and the suite takes minutes instead of
# seconds.  A deliberate on-TPU test run opts in with
# CRDT_TPU_TEST_PLATFORM=axon pytest tests/.
_platform = os.environ.get("CRDT_TPU_TEST_PLATFORM", "cpu")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon plugin ignores the JAX_PLATFORMS env var; the config knob is
# authoritative and must be set before any device initialization.
import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)
