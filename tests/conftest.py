"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding path
(parallel/) is exercised without TPU hardware, per the driver contract.
Real-TPU execution is covered by bench.py and __graft_entry__.entry().

This must run before anything imports jax, which pytest guarantees for a
root conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
