"""Merger bridge service tests: the packed kernels driven through the
proto schema over the TCP transport, compared against the spec model —
the shape a Go conformance harness would take (SURVEY §7.3 step 1)."""

import random

import pytest

from go_crdt_playground_tpu.bridge import (MergerClient, MergerServer,
                                           convert, serve_grpc)
from go_crdt_playground_tpu.bridge import merger_pb2 as pb
from go_crdt_playground_tpu.models.spec import (AWSet, AWSetDelta,
                                                VersionVector)
from go_crdt_playground_tpu.utils.guards import UINT32_MAX


def _writer_pair(delta=False, **kw):
    cls = AWSetDelta if delta else AWSet
    a = cls(actor=0, version_vector=VersionVector([0, 0]), **kw)
    b = cls(actor=1, version_vector=VersionVector([0, 0]), **kw)
    return a, b


def test_proto_roundtrip_preserves_state():
    a, _ = _writer_pair(delta=True, delta_semantics="v2")
    a.add("Anne", "Bob")
    a.del_("Bob")
    msg = convert.replica_to_proto(a)
    back = convert.replica_from_proto(msg, delta=True, delta_semantics="v2")
    assert back.entries == a.entries
    assert back.deleted == a.deleted
    assert back.processed == a.processed
    assert list(back.version_vector.v) == list(a.version_vector.v)
    assert str(back) == str(a)


def test_tcp_merge_matches_spec_full_state():
    """The add-wins scenario (awset_test.go:85-122) through the service."""
    a, b = _writer_pair()
    a.add("Anne", "Bob")
    b.merge(a)          # local pre-merge: delete will be OBSERVED
    a.del_("Bob")
    with MergerServer() as srv:
        host, port = srv.serve()
        with MergerClient(host, port) as cli:
            assert cli.ping()
            merged = cli.merge(b, a)
    expected = b.clone()
    expected.merge(a)
    assert merged.sorted_values() == expected.sorted_values()
    assert str(merged) == str(expected)


def test_tcp_merge_randomized_conformance():
    rng = random.Random(41)
    with MergerServer() as srv:
        host, port = srv.serve()
        with MergerClient(host, port) as cli:
            for trial in range(10):
                a, b = _writer_pair()
                for _ in range(12):
                    rep = a if rng.random() < 0.5 else b
                    if rng.random() < 0.7:
                        rep.add(f"k{rng.randrange(8)}")
                    elif rep.entries:
                        rep.del_(rng.choice(sorted(rep.entries)))
                merged = cli.merge(a, b)
                expected = a.clone()
                expected.merge(b)
                assert str(merged) == str(expected), trial


def test_tcp_delta_merge_dispatch_and_quirk():
    """δ dispatch through the service, incl. the strict empty-δ VV quirk."""
    for strict in (True, False):
        a, b = _writer_pair(delta=True)
        a.strict_reference_semantics = strict
        b.strict_reference_semantics = strict
        a.add("x")
        b.merge(a)         # first contact: full branch
        a.del_("x")
        b.merge(a)         # δ branch ships the deletion
        with MergerServer() as srv:
            host, port = srv.serve()
            with MergerClient(host, port) as cli:
                merged = cli.merge(
                    b, a, delta=True,
                    strict_reference_semantics=strict)
        expected = b.clone()
        expected.merge(a)
        assert merged.sorted_values() == expected.sorted_values()
        assert list(merged.version_vector.v) == list(
            expected.version_vector.v), f"strict={strict}"


def test_service_rejects_uint64_overflow():
    a, b = _writer_pair()
    a.add("k")
    req = pb.MergeRequest(
        dst=convert.replica_to_proto(a),
        src=convert.replica_to_proto(b),
    )
    req.src.version_vector.append(UINT32_MAX + 1)
    with MergerServer() as srv:
        host, port = srv.serve()
        with MergerClient(host, port) as cli:
            resp = cli.merge_raw(req)
    assert "uint32" in resp.error


def test_grpc_adapter_gated():
    try:
        import grpc  # noqa: F401
        has_grpc = True
    except ImportError:
        has_grpc = False
    if has_grpc:
        server, port = serve_grpc()
        server.stop(0)
        assert port > 0
    else:
        with pytest.raises(ImportError):
            serve_grpc()


def test_unknown_method_reports_error():
    from go_crdt_playground_tpu.bridge import service as svc
    import socket
    with MergerServer() as srv:
        host, port = srv.serve()
        with socket.create_connection((host, port)) as sock:
            svc.send_frame(sock, 0x7F, b"")
            method, body = svc.recv_frame(sock)
    resp = pb.MergeResponse()
    resp.ParseFromString(body)
    assert method == 0x7F and "unknown method" in resp.error
