"""CI wrapper for the serve-frontend load soak (tools/serve_soak.py).

Mirrors the chaos/crash soak wrappers: the --quick sweep must complete
with the acceptance shape — goodput scaling below the admission limit,
typed Overloaded shedding (not silent drops, not latency collapse)
beyond it, and ZERO acked-op loss across both SIGKILL flavors (the
deterministic between-WAL-fsync-and-ack window hook, and a parent-timed
mid-load kill).  slow-marked: it spawns real `serve --ingest`
subprocesses and SIGKILLs them, so tier-1 runtime never pays for it.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


@pytest.mark.slow
def test_serve_soak_quick_mode(tmp_path):
    import serve_soak

    out = str(tmp_path / "SERVE_CURVE.json")
    rc = serve_soak.main(["--quick", "--out", out])
    assert rc == 0, "serve soak failed (goodput shape, unbounded p99, " \
                    "missing shed, or acked-op loss)"
    with open(out) as f:
        artifact = json.load(f)

    open_curve = artifact["open_loop"]
    assert len(open_curve) >= 3
    # (a) goodput scales with offered load until the admission limit
    assert open_curve[-1]["goodput"] > open_curve[0]["goodput"] * 1.5
    assert open_curve[0]["goodput"] >= \
        0.8 * open_curve[0]["achieved_offer_rate"]
    # (b) beyond it: typed Overloaded shedding, bounded SERVER-side p99
    top = open_curve[-1]
    assert top["shed_overloaded"] > 0, \
        "the overload leg never shed — admission control untested"
    assert top["server"]["ingest_p99_ms"] < 2000.0
    # sheds are TYPED, not silent: every submitted op is accounted for
    for leg in open_curve:
        accounted = (leg["acked"] + leg["shed_overloaded"]
                     + leg["shed_expired"] + leg["other_failures"])
        assert accounted == leg["submitted"], leg
        assert leg["unresolved"] == 0, leg

    # (b2) the throughput ladder: fused ingest runs ONE compiled
    # dispatch per batch (seed: two), compact WAL records cut
    # bytes-fsynced per acked op (the occupancy-independent metric —
    # per-batch bytes swing with disk weather), and goodput held at
    # the same offered load (latency pairs are reported, not asserted
    # — 9p fsync hiccups land in whichever worker they hit)
    ic = artifact["ingest_compare"]
    assert ic["fused"]["dispatches_per_batch"] == 1.0, ic
    assert ic["seed"]["dispatches_per_batch"] > 1.5, ic
    assert ic["fused"]["wal_bytes_per_acked_op"] < \
        0.7 * ic["seed"]["wal_bytes_per_acked_op"], ic
    assert ic["fused"]["wal_compact_records"] > 0
    assert ic["seed"]["wal_compact_records"] == 0
    assert ic["fused"]["goodput"] >= 0.8 * ic["seed"]["goodput"]
    assert ic["fused"]["unresolved"] == 0
    assert ic["seed"]["unresolved"] == 0

    # (b3) SLO-aware compaction: GC shrank deletion-lane occupancy
    # UNDER live traffic with a bounded server p99, and the saturating
    # phase provably pushed the scheduler into backoff
    comp = artifact["compaction"]
    assert comp["gc_dropped_lanes_under_traffic"] > 0, comp
    assert comp["light"]["server_p99_ms"] < 2000.0
    assert comp["backoffs_during_heavy"] > 0, \
        "compaction never backed off under saturation"
    assert comp["light"]["unresolved"] == 0
    assert comp["heavy"]["unresolved"] == 0

    # (c) the crash cycles: both kill flavors landed, nothing acked was
    # lost, nothing unsubmitted appeared (the ingest-window contract) —
    # with compact WAL records on (the default worker), so recovery
    # replayed the new record form
    crash = artifact["crash"]
    assert crash["record_modes"]["wal.replayed_compact"] > 0, crash
    assert crash["kills"]["window_hook"] >= 1, \
        "the between-WAL-fsync-and-ack window kill never landed"
    assert crash["kills"]["parent_sigkill"] >= 1
    assert crash["lost_acked_ops"] == []
    assert crash["phantom_members"] == []
    assert crash["unfinished"] == []
    assert crash["acked_ops"] == crash["elements"]

    # (d) the chaos leg: wire faults actually fired on the INGEST port
    # (torn OP frames / delayed acks / refused dials incl. the
    # partition window) and the durable-ack ledger held under them
    chaos = artifact["chaos"]
    pc = chaos["proxy_counters"]
    assert pc["dropped"] + pc["truncated"] >= 1, pc
    assert pc["delayed"] >= 1, pc
    assert pc["refused"] >= 1, "the partition window never refused a dial"
    assert chaos["lost_acked_ops"] == []
    assert chaos["phantom_members"] == []
    assert chaos["gave_up"] == []
    assert chaos["final_members"] == chaos["elements"]
