"""Shard replication groups (shard/replica.py, DESIGN.md §23):
WAL-shipped warm standbys, semi-synchronous group commit, fenced shard
epochs, keyspace failover at the router, deposed-member containment.

In-process, wall-clock-light: the state machines expose their seams
(``poll_once``, ``ReplicationPublisher.gate``, ``failover_shard``) so
the suite drives them directly; the real-subprocess acceptance rides
``tools/fleet_serve_soak.py --shard-repl`` (REPL_CURVE.json).
"""

import os
import threading
import time

import numpy as np
import pytest

from go_crdt_playground_tpu.serve import protocol
from go_crdt_playground_tpu.serve.client import ServeClient
from go_crdt_playground_tpu.serve.frontend import ServeFrontend
from go_crdt_playground_tpu.shard.replica import (POLL_CAUGHT_UP,
                                                  POLL_FAILED,
                                                  POLL_PROMOTED,
                                                  POLL_TAILED,
                                                  ReplicationPublisher,
                                                  ShardStandby,
                                                  load_shard_epoch,
                                                  load_shard_epoch_seen,
                                                  persist_shard_epoch)

E, A = 48, 4


def _frontend(dirpath, *, actor=0, sid="s0", epoch=0, announce=None):
    return ServeFrontend(E, A, actor=actor, durable_dir=str(dirpath),
                         max_batch=4, flush_ms=1.0, shard_id=sid,
                         shard_epoch=epoch, announce_to=announce,
                         repl_ack_timeout_ms=150.0)


def _full_slice(node) -> bytes:
    return node.extract_slice(np.ones(E, bool))


# -- shard-epoch persistence -------------------------------------------------


def test_shard_epoch_file_roundtrip(tmp_path):
    d = str(tmp_path)
    assert load_shard_epoch(d) == 0 and load_shard_epoch_seen(d) == 0
    assert load_shard_epoch(None) == 0
    persist_shard_epoch(d, 3, "s0-standby")
    assert load_shard_epoch(d) == 3
    assert load_shard_epoch_seen(d) == 3  # seen >= own always
    persist_shard_epoch(d, 3, "s0", seen=7)
    assert load_shard_epoch(d) == 3 and load_shard_epoch_seen(d) == 7
    # unreadable record reads as the pre-HA configuration
    with open(os.path.join(d, "shard_epoch.json"), "w") as f:
        f.write("not json")
    assert load_shard_epoch(d) == 0


# -- ReplicationPublisher: the semi-sync gate (no sockets, no jax) -----------


class _Wal:
    def __init__(self, n=1):
        self.n = n

    def next_seq(self):
        return self.n


def test_publisher_gate_no_standby_is_transparent():
    p = ReplicationPublisher(ack_timeout_s=0.05)
    assert p.gate(_Wal(5)) is True          # dormant: pre-HA ack path
    assert p.gate(None) is True             # non-durable target
    assert p.window.windows == 0


def test_publisher_anonymous_poll_not_enrolled():
    p = ReplicationPublisher(ack_timeout_s=0.05)
    p.note_poll("", 99)                     # observability read
    assert not p.has_standby()
    assert p.gate(_Wal(5)) is True


def test_publisher_gate_waits_for_cursor_then_degrades():
    from go_crdt_playground_tpu.obs import Recorder

    rec = Recorder()
    p = ReplicationPublisher(rec, ack_timeout_s=0.2,
                             degrade_retry_s=0.15)
    wal = _Wal(4)                           # records 1..3 committed
    p.note_poll("sb", 1)

    def late_ack():
        time.sleep(0.05)
        p.note_poll("sb", 4)                # covers the tail

    t = threading.Thread(target=late_ack)
    t.start()
    assert p.gate(wal) is True              # woken by the ack
    t.join()
    assert p.window.windows == 0
    # now the standby goes silent: the gate times out, arms the window
    wal.n = 9
    t0 = time.monotonic()
    assert p.gate(wal) is False
    assert time.monotonic() - t0 >= 0.15    # it really waited
    assert p.window.active()
    assert rec.snapshot()["counters"]["repl.degraded_windows"] == 1
    # degraded: the next gate is immediate (async acks)
    t0 = time.monotonic()
    assert p.gate(wal) is False
    assert time.monotonic() - t0 < 0.1
    # window lapses -> the next gate is the PROBE; the standby is back
    time.sleep(0.2)
    p.note_poll("sb", 9)
    assert p.gate(wal) is True              # probe succeeded
    assert not p.window.armed_ever()        # healed
    snap = rec.snapshot()["counters"]
    assert snap["repl.heals"] == 1
    assert snap["repl.degraded_windows"] == 1  # one EPISODE


def test_publisher_waits_for_slowest_live_standby(monkeypatch):
    p = ReplicationPublisher(ack_timeout_s=0.05)
    p.note_poll("sb1", 9)
    p.note_poll("sb2", 3)                   # the one that may promote
    wal = _Wal(9)
    assert p.lag_records(wal.next_seq()) == 6  # min over live cursors
    assert p.gate(wal) is False             # sb2 has not covered 8
    # sb2 goes stale: only live members gate acks (the degrade ladder
    # owns dead ones)
    monkeypatch.setattr(ReplicationPublisher, "STALE_AFTER_S", 0.0)
    p.window.clear()
    assert p.lag_records(wal.next_seq()) == 0
    snap = p.snapshot()
    assert set(snap["standbys"]) == {"sb1", "sb2"}


# -- the WAL_SYNC serve verb against a real frontend -------------------------


@pytest.fixture(scope="module")
def primary(tmp_path_factory):
    fe = _frontend(tmp_path_factory.mktemp("primary"), epoch=1)
    addr = fe.serve(port=0)
    client = ServeClient(addr, timeout=10.0)
    for e in range(10):
        client.add(e)
    client.delete(3)
    yield fe, addr, client
    client.close()
    fe.close()


def test_wal_sync_tail_serves_records_and_acks(primary):
    fe, addr, client = primary
    r = client.wal_sync(1, standby_id="t-ack")
    assert r.shard_epoch == 1 and r.shard_id == "s0"
    assert r.first_seq == 1 and len(r.records) >= 11
    assert r.next_seq == r.first_seq + len(r.records)
    assert r.min_seq == 1 and r.flags == 0 and r.payload is None
    # the poll enrolled the standby and its cursor IS the ack
    snap = fe.repl.snapshot()
    assert snap["standbys"]["t-ack"]["acked_seq"] == 1
    r2 = client.wal_sync(r.next_seq, standby_id="t-ack")
    assert r2.records == () and r2.next_seq == r.next_seq
    assert fe.repl.snapshot()["standbys"]["t-ack"]["acked_seq"] \
        == r.next_seq
    assert r2.nonce == r.nonce
    # a cursor beyond this instance's numbering is a typed reset
    r3 = client.wal_sync(r.next_seq + 1000, standby_id="t-ack")
    assert r3.flags & protocol.WAL_TRUNCATED
    assert r3.records == ()


def test_wal_sync_truncation_then_digest_catchup(primary):
    fe, addr, client = primary
    from go_crdt_playground_tpu.net import digestsync

    # checkpoint: seal + drop retires the tail under any old cursor
    fe.supervisor.checkpoint()
    r = client.wal_sync(1, standby_id="t-cu")
    assert r.flags & protocol.WAL_TRUNCATED
    assert r.min_seq > 1
    # catch-up: ship OUR (empty replica's) summary, get O(diff) payload
    import tempfile

    from go_crdt_playground_tpu.net.peer import Node

    scratch = Node(0, E, A)
    summary = digestsync.node_summary(scratch)
    rc = client.wal_sync(r.next_seq, standby_id="t-cu", summary=summary)
    assert rc.payload is not None
    assert rc.flags & protocol.WAL_CATCHUP_PAYLOAD
    scratch.apply_payload_body(rc.payload)
    # the caught-up replica mirrors the primary bitwise
    assert _full_slice(scratch) == _full_slice(fe.node)
    assert rc.next_seq >= r.min_seq


def test_wal_sync_epoch_claim_deposes_writes_not_reads(tmp_path):
    fe = _frontend(tmp_path / "dep", epoch=1)
    addr = fe.serve(port=0)
    with ServeClient(addr, timeout=10.0) as c:
        c.add(1, 2)
        assert not fe.shard_deposed
        # the promoting standby's deposition notice
        r = c.wal_sync(1, epoch=4, standby_id="sb")
        assert r.shard_epoch == 1
        assert fe.shard_deposed
        with pytest.raises(protocol.StaleShardEpoch):
            c.add(5)
        members, _vv = c.members()  # reads keep serving (lower bound)
        assert set(int(e) for e in members) == {1, 2}
        # a STALER claim than the adjudicated one is typed-rejected
        with pytest.raises(protocol.StaleShardEpoch):
            c.wal_sync(1, epoch=2, standby_id="older")
    fe.close()
    # the adjudication persisted: a restart boots fenced even with no
    # router reachable
    fe2 = _frontend(tmp_path / "dep", epoch=1)
    assert fe2.shard_deposed
    fe2.close()


# -- the standby state machine ----------------------------------------------


def test_standby_tail_mirror_promote_and_resurrection(tmp_path):
    """The full in-process failover story on one replication group
    behind a real router: tail to a bitwise mirror, quiesce, kill,
    promote (epoch bump + router keyspace swap), serve, restart the
    old primary and watch it boot self-fenced."""
    from go_crdt_playground_tpu.net.peer import Node
    from go_crdt_playground_tpu.shard.fleet import free_port
    from go_crdt_playground_tpu.shard.router import ShardRouter

    p_dir = tmp_path / "p0"
    fe = _frontend(p_dir, epoch=1)
    a0 = fe.serve(port=0)
    standby_port = free_port()
    router = ShardRouter({"s0": [a0, ("127.0.0.1", standby_port)]}, E,
                         state_dir=str(tmp_path / "router"))
    raddr = router.serve(port=0)
    client = ServeClient(raddr, timeout=10.0)
    for e in range(14):
        client.add(e)
    client.delete(2, 7)

    sfe = _frontend(tmp_path / "sb")
    sb = ShardStandby(a0, sfe, sid="s0", standby_id="s0-standby",
                      listen_addr=("127.0.0.1", standby_port),
                      announce_to=raddr, poll_interval_s=0.02,
                      failure_threshold=2, wait_ms=50)
    assert sb.poll_once() == POLL_TAILED
    assert sb.tailed_ever
    # quiesced: the standby is a BITWISE mirror
    assert _full_slice(sfe.node) == _full_slice(fe.node)

    # kill the primary; poll failures cross the threshold and promote
    fe.close()
    verdicts = [sb.poll_once(), sb.poll_once()]
    assert verdicts[-1] == POLL_PROMOTED, verdicts
    assert sb.promoted and sb.promote_reason
    assert sb.announce_result and sb.announce_result["swapped"]
    # the promoted member claims epoch tailed(1) + 1 and persists it
    assert load_shard_epoch(str(tmp_path / "sb")) == 2
    assert router.shard_epochs() == {"s0": 2}

    # the keyspace serves THROUGH THE ROUTER via the promoted standby,
    # with every pre-kill acked op present (zero acked-op loss) —
    # promotion equals what a restore_durable restart would have given
    restored = Node.restore_durable(str(p_dir))
    assert _full_slice(restored) == _full_slice(sfe.node)
    for e in range(14, 20):
        client.add(e)
    members, _vv = client.members()
    assert set(int(m) for m in members) == set(range(20)) - {2, 7}

    # resurrection: the old primary restarts on its old disk, announces
    # its stale epoch, and boots self-fenced — writes shed typed, the
    # promoted member untouched
    fe_old = _frontend(p_dir, epoch=1, announce=raddr)
    a_old = fe_old.serve(port=0)
    assert fe_old.shard_deposed
    with ServeClient(a_old, timeout=5.0) as c2:
        with pytest.raises(protocol.StaleShardEpoch):
            c2.add(40)
        m_old, _ = c2.members()  # reads serve the stale lower bound
        assert len(m_old) > 0
    assert router.shard_epochs() == {"s0": 2}

    client.close()
    fe_old.close()
    sb.close()
    router.close()


def test_standby_nonce_reset_catches_primary_restart(tmp_path):
    """A primary restart renumbers its WAL; the standby detects the
    instance-nonce change, resets its cursor TYPED (never a silent
    gap) and digest-catches-up to the restarted primary's state."""
    from go_crdt_playground_tpu.shard.fleet import free_port

    port = free_port()
    p_dir = tmp_path / "p"
    fe1 = _frontend(p_dir, epoch=1)
    fe1.serve(port=port)
    with ServeClient(("127.0.0.1", port), timeout=10.0) as c:
        for e in range(6):
            c.add(e)
    sfe = _frontend(tmp_path / "sb")
    sb = ShardStandby(("127.0.0.1", port), sfe, sid="s0",
                      poll_interval_s=0.02, failure_threshold=99,
                      wait_ms=20)
    assert sb.poll_once() == POLL_TAILED
    cursor_before = sb.cursor
    assert cursor_before > 1
    fe1.close()
    assert sb.poll_once() == POLL_FAILED
    # restart on the same port: fresh WAL numbering, fresh nonce; the
    # drain checkpoint truncated the log, so the record space is empty
    fe2 = _frontend(p_dir, epoch=1)
    fe2.serve(port=port)
    with ServeClient(("127.0.0.1", port), timeout=10.0) as c:
        c.add(40)
    v1 = sb.poll_once()          # detects the nonce change, resets
    v2 = sb.poll_once()          # ...and catches up O(diff)
    assert POLL_CAUGHT_UP in (v1, v2), (v1, v2)
    assert _full_slice(sfe.node) == _full_slice(fe2.node)
    sb.close()
    fe2.close()


def test_standby_never_tailed_blocks_promotion(tmp_path):
    """A standby that never tailed (and holds no persisted epoch) must
    NOT promote — it would serve an empty replica under a colliding
    epoch.  The counter records the refusal."""
    sfe = _frontend(tmp_path / "sb")
    dead = ("127.0.0.1", 1)  # nothing listens there
    sb = ShardStandby(dead, sfe, sid="s0", poll_interval_s=0.01,
                      failure_threshold=2, poll_timeout_s=0.2)
    assert sb.poll_once() == POLL_FAILED
    assert sb.poll_once() == POLL_FAILED  # threshold crossed, blocked
    assert not sb.promoted
    snap = sfe.recorder.snapshot()["counters"]
    assert snap["repl.promote_blocked"] >= 1
    sb.close()


# -- router-side failover adjudication (no shard processes) ------------------


def test_router_failover_adjudication_and_restart(tmp_path):
    from go_crdt_playground_tpu.shard.router import ShardRouter

    state = str(tmp_path / "router")
    p0, sb0 = ("127.0.0.1", 7001), ("127.0.0.1", 7002)
    router = ShardRouter({"s0": [p0, sb0], "s1": ("127.0.0.1", 7003)},
                         E, state_dir=state)
    try:
        # unknown sid
        with pytest.raises(KeyError):
            router.failover_shard("nope", 2, sb0)
        # the claim: adopt + swap (roster reorders, claimed first)
        rec = router.failover_shard("s0", 2, sb0, owner="s0-standby")
        assert rec["swapped"] and rec["shard_epoch"] == 2
        assert router.link("s0").addrs == [sb0, p0]
        assert router.shard_epochs() == {"s0": 2}
        # idempotent echo (the announce retry path)
        rec2 = router.failover_shard("s0", 2, sb0)
        assert not rec2["swapped"] and rec2["shard_epoch"] == 2
        # the deposed old primary's probe: typed, nothing swapped
        with pytest.raises(protocol.StaleShardEpoch):
            router.failover_shard("s0", 1, p0)
        assert router.link("s0").addrs == [sb0, p0]
        # equal epoch from a DIFFERENT address is stale too
        with pytest.raises(protocol.StaleShardEpoch):
            router.failover_shard("s0", 2, p0)
    finally:
        router.close()
    # a restarted router adopts the adjudicated epochs AND the
    # active-first roster order — it can never redial the deposed
    # member as the keyspace's active downstream
    router2 = ShardRouter({"s0": [p0, sb0], "s1": ("127.0.0.1", 7003)},
                          E, state_dir=state)
    try:
        assert router2.shard_epochs() == {"s0": 2}
        assert router2.link("s0").addrs == [sb0, p0]
        assert router2.link("s1").addrs == [("127.0.0.1", 7003)]
    finally:
        router2.close()


def test_batcher_gate_rides_live_standby(tmp_path):
    """End to end through the real batcher: a tailing standby's acks
    keep semi-sync satisfied — no degrade window opens while the
    standby follows the tail.  A dedicated frontend: the GROUP is the
    unit (the gate waits on the slowest live member, so any stale
    enrolled cursor from another test would rightly degrade it)."""
    fe = _frontend(tmp_path / "gate", epoch=1)
    addr = fe.serve(port=0)
    client = ServeClient(addr, timeout=10.0)
    stop = threading.Event()

    def tail():
        with ServeClient(addr, timeout=5.0) as tc:
            cursor = tc.wal_sync(1, standby_id="live-sb").next_seq
            while not stop.is_set():
                r = tc.wal_sync(cursor, standby_id="live-sb",
                                wait_ms=50)
                cursor = r.next_seq

    t = threading.Thread(target=tail, daemon=True)
    t.start()
    time.sleep(0.05)
    windows_before = fe.repl.window.windows
    for e in range(20, 30):
        client.add(e)
    stop.set()
    t.join(timeout=5.0)
    assert fe.repl.window.windows == windows_before
    snap = fe.repl.snapshot()
    assert snap["standbys"]["live-sb"]["acked_seq"] > 1
    client.close()
    fe.close()


def test_epoch_zero_primary_adopts_one_at_announce(tmp_path):
    """The review-found collision: an announce-configured primary left
    at the default epoch 0 must ADOPT (and persist) epoch 1 as its own
    claim — otherwise its boot announce registers epoch 1 at the
    router while its WAL_SYNC replies ship 0, its standby promotes at
    0+1 = 1, and the failover claim collides typed with the primary's
    own registration (equal epoch, different address): the keyspace
    could never swap."""
    from go_crdt_playground_tpu.shard.router import ShardRouter

    router = ShardRouter({"s0": ("127.0.0.1", 7009)}, E,
                         state_dir=str(tmp_path / "router"))
    raddr = router.serve(port=0)
    fe = _frontend(tmp_path / "p", epoch=0, announce=raddr)
    addr = fe.serve(port=0)
    try:
        # the member's own epoch is now 1, durably, and the replies
        # agree with what the router adjudicated
        assert load_shard_epoch(str(tmp_path / "p")) == 1
        with ServeClient(addr, timeout=5.0) as c:
            assert c.wal_sync(1, standby_id="probe-x").shard_epoch == 1
        assert router.shard_epochs().get("s0") == 1
        # a standby that tailed epoch 1 claims 2: the swap SUCCEEDS
        rec = router.failover_shard("s0", 2, ("127.0.0.1", 7010))
        assert rec["swapped"] and rec["shard_epoch"] == 2
    finally:
        fe.close()
        router.close()


def test_publisher_gate_skips_wait_with_no_live_standby(monkeypatch):
    """A decommissioned standby (enrolled once, long stale) must not
    cost one ack_timeout per probe forever: with zero LIVE members the
    gate goes straight to the degrade path."""
    p = ReplicationPublisher(ack_timeout_s=5.0, degrade_retry_s=0.05)
    p.note_poll("gone", 1)
    monkeypatch.setattr(ReplicationPublisher, "STALE_AFTER_S", 0.0)
    t0 = time.monotonic()
    assert p.gate(_Wal(9)) is False
    assert time.monotonic() - t0 < 1.0  # never waited the 5s budget
    assert p.window.armed_ever()
