"""The protocol model checker (analysis/protomodel.py, DESIGN.md §26).

Two halves, the gate suite's usual shape: the REAL models verify
clean over their exhaustive state graphs, and every bug-flagged twin
is caught with a concrete counterexample schedule — an explorer that
cannot find a planted two-writers run would prove nothing about the
absence of real ones.
"""

import os

from go_crdt_playground_tpu.analysis import protomodel
from go_crdt_playground_tpu.analysis.protomodel import (HandoffModel,
                                                        MirrorSpec,
                                                        RouterHAModel,
                                                        ShardReplModel,
                                                        explore)

PKG_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "go_crdt_playground_tpu")


# ---------------------------------------------------------------------------
# the explorer itself
# ---------------------------------------------------------------------------


class _Diamond:
    """Two commuting steps: four states, four edges, each state once."""

    def initial(self):
        return {"a": 0, "b": 0}

    def actions(self, st):
        out = []
        if st["a"] == 0:
            out.append(("a", {**st, "a": 1}))
        if st["b"] == 0:
            out.append(("b", {**st, "b": 1}))
        return out

    def invariants(self, prev, label, st):
        return []


def test_explorer_dedups_interleavings():
    r = explore(_Diamond())
    assert r.states == 4
    assert r.transitions == 4  # a;b and b;a converge, edges all walked
    assert r.complete and not r.violations


def test_explorer_reports_shortest_trace():
    class Line:
        def initial(self):
            return {"n": 0}

        def actions(self, st):
            return ([("inc", {"n": st["n"] + 1})]
                    if st["n"] < 5 else [])

        def invariants(self, prev, label, st):
            return ["boom"] if st["n"] == 3 else []

    r = explore(Line())
    assert len(r.violations) == 1
    assert r.violations[0].trace == ("inc", "inc", "inc")


def test_state_cap_is_loud_not_silent():
    """A capped exploration must say so — 'verified' may only mean
    exhausted."""
    r = explore(ShardReplModel(), max_states=20)
    assert not r.complete
    f, s = protomodel.analyze(
        PKG_ROOT, models=(("shard_repl", ShardReplModel),),
        mirrors=(), max_states=20)
    assert any(x.code == "E004" and "cap" in x.message for x in f)
    assert s["models"]["shard_repl"]["complete"] is False


# ---------------------------------------------------------------------------
# the real protocols verify clean, exhaustively
# ---------------------------------------------------------------------------


def test_real_models_exhaust_clean():
    for factory in (RouterHAModel, ShardReplModel, HandoffModel):
        r = explore(factory())
        assert r.complete, factory
        assert r.violations == (), (factory, r.violations)
        assert r.states >= 10 and r.transitions >= r.states, (factory, r)


# ---------------------------------------------------------------------------
# every bug twin is caught with a concrete schedule
# ---------------------------------------------------------------------------


def test_router_ha_announce_before_persist_caught():
    """The E001 bug class, end-to-end in the checker: announcing the
    epoch before it is durable lets a crash re-promote at the SAME
    epoch — two incarnations, one adjudicated epoch."""
    r = explore(RouterHAModel("announce_before_persist"))
    v = next(x for x in r.violations
             if "epoch-uniqueness" in x.message)
    # the counterexample is the real schedule: announce, die before
    # persist, re-promote
    assert "s:crash" in v.trace
    assert v.trace.index("s:announce") < v.trace.index("s:crash")
    assert v.trace.count("s:claim") == 2


def test_shard_repl_ack_without_coverage_caught():
    """Dropping the semi-sync gate's coverage condition loses acked
    ops across a crash+promote — the exact loss the gate prevents."""
    r = explore(ShardReplModel("ack_without_coverage"))
    v = next(x for x in r.violations if "acked-op-loss" in x.message)
    assert "p:ack" in v.trace and "s:serve" in v.trace


def test_handoff_swap_before_persist_caught():
    """Swapping the in-memory ring before the COMMITTED record is
    durable both breaks swap-durability and lets the abort arm write
    ABORTED for a ring that irreversibly swapped."""
    r = explore(HandoffModel("swap_before_persist"))
    heads = {v.message.split(":")[0] for v in r.violations}
    assert "swap-before-durable" in heads, heads
    assert "abort-inconsistency" in heads, heads


def test_handoff_fence_never_blocks_reads():
    r = explore(HandoffModel("fence_blocks_reads"))
    assert any("fence-blocks-reads" in v.message for v in r.violations)


def test_gate_pass_fails_on_buggy_model():
    """E004 through the gate surface (analyze), not just explore():
    the injectable models registry is how tests prove the pass can
    fail."""
    f, s = protomodel.analyze(
        PKG_ROOT,
        models=(("router_ha",
                 lambda: RouterHAModel("announce_before_persist")),),
        mirrors=())
    assert any(x.code == "E004" for x in f), f
    assert s["models"]["router_ha"]["violations"] >= 1


# ---------------------------------------------------------------------------
# E003: model freshness
# ---------------------------------------------------------------------------


def test_mirrors_fresh_against_tree():
    f, s = protomodel.check_freshness(PKG_ROOT)
    assert not f, [x.render() for x in f]
    assert s["fresh"] == s["mirrored_symbols"] >= 10


def test_stale_mirror_hash_detected():
    bad = (MirrorSpec("router_ha", "shard/ha.py",
                      "RouterStandby._promote_locked",
                      "deadbeefdeadbeef"),)
    f, _ = protomodel.check_freshness(PKG_ROOT, mirrors=bad)
    assert len(f) == 1 and f[0].code == "E003"
    assert "stale" in f[0].message


def test_vanished_mirror_symbol_detected():
    bad = (MirrorSpec("router_ha", "shard/ha.py",
                      "RouterStandby._promote_differently",
                      "deadbeefdeadbeef"),)
    f, _ = protomodel.check_freshness(PKG_ROOT, mirrors=bad)
    assert len(f) == 1 and f[0].code == "E003"
    assert "no longer exists" in f[0].message
