"""Named seeded workload generators (tools/workloads.py): determinism,
skew shape, flash-crowd scheduling — the distributions every soak leg
now declares in its artifact."""

import os
import sys
from collections import Counter

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import workloads  # noqa: E402


def _draw(picker, n, frac_of=None):
    return [picker.pick(i, (i / n) if frac_of is None else frac_of)
            for i in range(n)]


def test_cycle_is_the_historical_picker():
    p = workloads.CycleKeys(7)
    assert p.name == "uniform-cycle"
    assert _draw(p, 15) == [i % 7 for i in range(15)]


def test_seeded_pickers_replay():
    for make in (lambda s: workloads.UniformKeys(64, seed=s),
                 lambda s: workloads.ZipfKeys(64, s=1.1, seed=s),
                 lambda s: workloads.FlashCrowd(
                     workloads.ZipfKeys(64, seed=s), [1, 2, 3],
                     hot_prob=0.4, seed=s)):
        assert _draw(make(9), 200) == _draw(make(9), 200)
        assert _draw(make(9), 200) != _draw(make(10), 200)


def test_zipf_skew_and_rank_shuffle():
    z = workloads.ZipfKeys(256, s=1.0, seed=3)
    draws = Counter(_draw(z, 8000))
    top = z.hottest(1)[0]
    # rank 1 carries ~1/H(256) ≈ 16% of the mass; far above uniform
    assert draws[top] / 8000 > 0.08
    # the hot keys are a seed property, not always the low ids
    assert workloads.ZipfKeys(256, s=1.0, seed=3).hottest(5) != \
        workloads.ZipfKeys(256, s=1.0, seed=4).hottest(5)
    assert all(0 <= k < 256 for k in draws)


def test_flash_crowd_window():
    hot = [200, 201, 202]
    f = workloads.FlashCrowd(workloads.CycleKeys(64), hot,
                             start_frac=0.5, stop_frac=1.0,
                             hot_prob=1.0, seed=1)
    before = [f.pick(i, 0.2) for i in range(100)]
    during = [f.pick(i, 0.7) for i in range(100)]
    assert not any(k in hot for k in before)
    assert all(k in hot for k in during)
    assert "flash" in f.name and f.base.name in f.name
    with pytest.raises(ValueError):
        workloads.FlashCrowd(workloads.CycleKeys(4), [])


def test_shuffled_universe():
    a = workloads.shuffled_universe(50, 7)
    assert sorted(a) == list(range(50))
    assert a == workloads.shuffled_universe(50, 7)
    assert a != workloads.shuffled_universe(50, 8)
