"""CI wrapper for the sharded-fleet soak (tools/fleet_serve_soak.py).

Mirrors the serve/crash soak wrappers: the --quick sweep must complete
with the acceptance shape — every op through the router resolves
ack-or-typed-reject at every shard count, and the SIGKILL-one-shard leg
shows typed ``ShardUnavailable`` rejects for the dead keyspace,
survivor keyspaces still acking, and ZERO acked-op loss across the
restart.  slow-marked: it spawns N real ``serve --ingest`` subprocesses
plus a real ``router --serve`` subprocess and SIGKILLs one, so tier-1
runtime never pays for it.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


@pytest.mark.slow
def test_fleet_serve_soak_quick_mode(tmp_path):
    import fleet_serve_soak

    out = str(tmp_path / "SHARD_CURVE.json")
    rc = fleet_serve_soak.main(["--quick", "--out", out])
    assert rc == 0, "fleet soak failed (unresolved ops, missing typed " \
                    "rejects, dead survivors, or acked-op loss)"
    with open(out) as f:
        artifact = json.load(f)

    curve = artifact["shard_curve"]
    assert [leg["shards"] for leg in curve] == [1, 3]
    for leg in curve:
        # ack-or-typed-reject THROUGH the router, at every shard count
        assert leg["unresolved"] == 0, leg
        assert leg["goodput"] > 0, leg

    kill = artifact["kill_leg"]
    assert kill["shards"] >= 3
    # the outage was real and typed: the dead shard's keyspace rejected
    # ShardUnavailable while surviving keyspaces kept acking
    assert kill["outage"]["typed_unavailable"] > 0, kill
    assert kill["outage"]["acked_survivor"] > 0, kill
    assert kill["outage"]["unresolved"] == 0, kill
    # the ledger: acks on the victim BEFORE the SIGKILL all survived
    # its restore_durable restart; nothing phantom appeared; the whole
    # keyspace eventually landed
    assert kill["victim_acked_before_kill"] > 0
    assert kill["lost_acked_ops"] == []
    assert kill["phantom_members"] == []
    assert kill["unfinished"] == []
    assert kill["final_members"] == kill["elements"]

    # the live-resharding leg (DESIGN.md §18): kill-mid-handoff aborts
    # typed with the old ring (generation + owner-map digest) still
    # served, the committed join moves exactly the remap_fraction-
    # predicted slice inside a bounded fence window, the leave restores
    # the original digest, and across ALL of it: every op resolved
    # ack-or-typed-reject, zero acked-op loss, zero phantoms
    # the router↔shard chaos leg (DESIGN.md §22 satellite): the chaos
    # REALLY happened (proxy counters), the victim keyspace degraded
    # to typed ShardUnavailable while the survivor kept acking, and
    # the breaker re-admitted the healed link — ledger clean
    chaos = artifact["chaos_leg"]
    assert chaos["proxy"]["truncated"] > 0, chaos["proxy"]
    assert chaos["proxy"]["refused"] > 0, chaos["proxy"]
    assert chaos["outage"]["typed_unavailable"] > 0, chaos
    assert chaos["outage"]["acked_survivor_during_chaos"] > 0, chaos
    assert chaos["outage"]["unresolved"] == 0, chaos
    assert chaos["lost_acked_ops"] == []
    assert chaos["phantom_members"] == []
    assert chaos["unfinished"] == []

    reshard = artifact["reshard_leg"]
    events = {e["event"]: e for e in reshard["events"]}
    aborted = events["join_recipient_killed_mid_handoff"]
    assert not aborted["ok"] and aborted["joiner_died"], aborted
    assert aborted["ring_unchanged"], aborted
    joined = events["join_committed_via_cli"]
    assert joined["ok"] and joined["cli_rc"] == 0, joined
    assert joined["moved"] > 0 and joined["digest_changed"], joined
    assert joined["observed_fraction"] == pytest.approx(
        joined["predicted_fraction"]), joined
    assert joined["fence_s"] < 15.0, joined
    left = events["leave_committed"]
    assert left["ok"] and left["digest_restored"], left
    assert reshard["finished"] and reshard["unfinished"] == []
    assert reshard["traffic"]["unresolved"] == 0, reshard["traffic"]
    assert reshard["lost_acked_ops"] == []
    assert reshard["phantom_members"] == []
    assert reshard["final_members"] == reshard["elements"]


@pytest.mark.slow
def test_fleet_serve_soak_mesh_quick_mode(tmp_path):
    """The device-mesh soak (--mesh --quick, DESIGN.md §20/§24): real
    ``serve --mesh-devices`` workers (1-D AND the 2-D dp×mp ladder)
    through the router — every op resolves ack-or-typed-reject per
    mesh spec, lockstep bitwise parity vs a single-device worker AND
    vs the 1-D worker on the same op log, rows-per-commit scaling
    with dp, and zero acked-op loss across SIGKILL + restore_durable
    of both mesh flavors."""
    import fleet_serve_soak

    out = str(tmp_path / "MESH_CURVE.json")
    rc = fleet_serve_soak.main(["--mesh", "--quick", "--out", out])
    assert rc == 0, "mesh soak failed (unresolved ops, parity " \
                    "mismatch, or acked-op loss)"
    with open(out) as f:
        artifact = json.load(f)

    curve = artifact["serve_curve"]
    assert [leg["mesh_devices"] for leg in curve] == [1, 2]
    for leg in curve:
        assert leg["unresolved"] == 0, leg
        assert leg["goodput"] > 0, leg
        # the worker's own banner proves the subprocess really ran the
        # requested mesh width (a silently-single-device worker would
        # make every other assertion vacuous)
        assert leg["worker_banner_mesh"] == str(leg["mesh_devices"])

    curve_2d = artifact["serve_curve_2d"]
    assert [leg["mesh_devices"] for leg in curve_2d] == ["1x2", "2x2"]
    for leg in curve_2d:
        assert leg["unresolved"] == 0, leg
        assert leg["worker_banner_mesh"] == str(leg["mesh_devices"])
    # the dp mechanism engaged: rows per durable commit doubled from
    # the dp=1 leg to the dp=2 leg (each worker's own counters —
    # weather-proof, unlike cross-worker goodput ratios)
    rpd = [leg["server_mesh"]["rows_per_dispatch"] for leg in curve_2d]
    assert rpd[0] > 0 and rpd[-1] > 1.5 * rpd[0], rpd

    parity_2d = artifact["parity_2d"]
    assert parity_2d["bitwise_equal"], parity_2d
    assert parity_2d["vs"] == "2"  # the 2-D worker vs the 1-D worker
    crash_2d = artifact["crash_2d"]
    assert crash_2d["lost_acked_ops"] == []
    assert crash_2d["phantom_members"] == []

    parity = artifact["parity"]
    assert parity["bitwise_equal"], parity
    assert parity["mismatched_fields"] == []
    assert parity["ops"] > parity["elements"]  # deletes rode along

    crash = artifact["crash"]
    assert crash["outage"]["typed_unavailable"] > 0, crash
    assert crash["outage"]["unresolved"] == 0, crash
    assert crash["victim_acked_before_kill"] > 0
    assert crash["lost_acked_ops"] == []
    assert crash["phantom_members"] == []
    assert crash["unfinished"] == []
    assert crash["final_members"] == crash["elements"]


@pytest.mark.slow
def test_fleet_serve_soak_zipf_quick_mode(tmp_path):
    """The conflict-aware admission scheduling soak (--zipf --quick,
    DESIGN.md §25): scheduled dp-ladder legs under zipf hot-key skew
    through real ``serve --mesh-devices --sched on`` workers, an
    unscheduled (--sched off) baseline at the widest dp, and the
    SIGKILL replay-parity leg.  Adjudicates the tentpole acceptance:
    cuts-per-super-batch at dp=4/s=1.2 reduced ≥5× vs unscheduled,
    rows-per-dispatch ≥1.5× the dp=1 leg's, durable log replays
    bitwise-identically through the plain sequential Node and the 2-D
    mesh class, zero acked-op loss."""
    import fleet_serve_soak

    out = str(tmp_path / "MESH_CURVE.json")
    rc = fleet_serve_soak.main(["--zipf", "--quick", "--out", out])
    assert rc == 0, "zipf soak failed (cuts not reduced, rpd not " \
                    "scaled, replay mismatch, or acked-op loss)"
    with open(out) as f:
        artifact = json.load(f)

    curve = artifact["zipf_curve"]
    # 2 exponents x the quick dp ladder, every leg scheduler-on and
    # self-reporting it in the worker banner
    assert [(leg["zipf_s"], leg["mesh_devices"]) for leg in curve] == \
        [(0.99, "1x2"), (0.99, "4x2"), (1.2, "1x2"), (1.2, "4x2")]
    for leg in curve:
        assert leg["unresolved"] == 0, leg
        assert leg["goodput"] > 0, leg
        assert leg["worker_banner_mesh"] == leg["mesh_devices"]
        assert leg["worker_banner_sched"] == "on"
        assert leg["workload"].startswith("zipf("), leg["workload"]
        # the scheduler ran: key-runs were found and counted
        assert leg["server_mesh"]["sched"]["sched.keyruns"] > 0, leg

    baseline = artifact["zipf_baseline"]
    assert baseline["worker_banner_sched"] == "off"
    assert "sched.keyruns" not in baseline["server_mesh"]["sched"]
    # the tentpole ratios (each worker's own counters)
    deep = next(leg for leg in curve
                if leg["zipf_s"] == 1.2 and leg["mesh_devices"] == "4x2")
    dp1 = next(leg for leg in curve
               if leg["zipf_s"] == 1.2 and leg["mesh_devices"] == "1x2")
    base_cps = baseline["server_mesh"]["cuts_per_super_batch"]
    sched_cps = deep["server_mesh"]["cuts_per_super_batch"]
    assert base_cps > 0, baseline["server_mesh"]
    assert base_cps >= 5 * sched_cps, (base_cps, sched_cps)
    assert deep["server_mesh"]["rows_per_dispatch"] > \
        1.5 * dp1["server_mesh"]["rows_per_dispatch"]

    replay = artifact["zipf_replay"]
    assert replay["bitwise_equal"], replay["mismatched_fields"]
    assert replay["members_agree"], replay
    assert replay["acked_adds"] > 0
    assert replay["lost_acked_ops"] == []
    assert replay["phantom_members"] == []
    assert replay["worker_banner_sched"] == "on"


@pytest.mark.slow
def test_fleet_serve_soak_router_ha_quick_mode(tmp_path):
    """The router-HA soak (--router-ha --quick, DESIGN.md §22): a
    SIGKILLed primary router fails over to its warm standby inside the
    declared budget with the exact committed ring adopted under a
    bumped fenced epoch; ledgered traffic rides through with in-flight
    ops surfaced typed-ambiguous (zero unresolved, zero acked-op loss,
    zero phantoms); a real autopilot re-resolves the promoted router
    and commits a split with the epoch bump in its decision log; and a
    resurrected deposed primary is contained typed with the promoted
    ring untouched."""
    import fleet_serve_soak

    out = str(tmp_path / "HA_CURVE.json")
    rc = fleet_serve_soak.main(["--router-ha", "--quick", "--out", out])
    assert rc == 0, "router-HA soak failed (late promotion, stale-" \
                    "epoch fence breach, unresolved ops, or acked-op " \
                    "loss)"
    with open(out) as f:
        artifact = json.load(f)

    fo = artifact["legs"]["failover"]
    assert fo["promote_s"] <= fo["promote_budget_s"], fo
    assert fo["ring_after"]["router_epoch"] == \
        fo["ring_before"]["router_epoch"] + 1
    assert fo["ring_after"]["generation"] == \
        fo["ring_before"]["generation"]
    assert fo["ring_after"]["digest"] == fo["ring_before"]["digest"]
    assert fo["acked_before_kill"] > 0
    assert fo["acked_after_promotion"] > 0

    ap = artifact["legs"]["autopilot"]
    assert ap["split_committed"] and ap["split_sid"] in \
        ap["shards_after"], ap
    assert ap["resume_router_epoch"] == \
        fo["ring_after"]["router_epoch"]
    assert ap["decision_signals_router_epoch"] == \
        fo["ring_after"]["router_epoch"]
    assert ap["generation_after"] > fo["ring_after"]["generation"]

    rz = artifact["legs"]["resurrection"]
    assert rz["reshard_refused"], rz
    assert "StaleRouterEpoch" in rz["reshard_reason"], rz
    assert rz["op_shed_typed"] and rz["old_router_shed_deposed"] >= 1
    assert rz["old_router_deposed_noted"] >= 1
    assert rz["promoted_ring_unchanged"], rz

    assert artifact["traffic"]["unresolved"] == 0, artifact["traffic"]
    assert artifact["finished"] and artifact["unfinished"] == []
    assert artifact["lost_acked_ops"] == []
    assert artifact["phantom_members"] == []
    assert artifact["final_members"] == artifact["elements"]
    assert artifact["promoted_ha_counters"]["router.ha.promotions"] == 1


@pytest.mark.slow
def test_fleet_serve_soak_shard_repl_quick_mode(tmp_path):
    """The shard-replication soak (--shard-repl --quick, DESIGN.md
    §23): WAL-shipped warm shard standbys — the replication link
    survives deterministic chaos with typed degrade-to-async and
    digest catch-up on heal; a mid-stream primary SIGKILL with NO
    restart promotes the standby inside the declared budget and the
    router swaps the keyspace under a bumped fenced shard epoch; a
    quiesced kill proves the promoted replica byte-identical to the
    restore_durable restart path; and a resurrected old primary boots
    self-fenced.  Zero acked-op loss, zero phantoms, unresolved 0."""
    import fleet_serve_soak

    out = str(tmp_path / "REPL_CURVE.json")
    rc = fleet_serve_soak.main(["--shard-repl", "--quick",
                                "--out", out])
    assert rc == 0, "shard-replication soak failed (late promotion, " \
                    "bitwise drift vs the restart path, fence " \
                    "breach, unresolved ops, or acked-op loss)"
    with open(out) as f:
        artifact = json.load(f)

    ch = artifact["legs"]["chaos"]
    assert ch["proxy"]["truncated"] > 0 and ch["proxy"]["refused"] > 0
    assert ch["degraded_windows"] >= 1, ch
    assert ch["acked_s0_during_partition"] >= \
        ch["goodput_floor_ops_s"] * ch["partition_s"], ch
    assert ch["lag_records_after_heal"] == 0, ch
    assert ch["catchups_served"] >= 1, ch

    fo = artifact["legs"]["failover"]
    assert fo["promote_s"] <= fo["promote_budget_s"], fo
    assert fo["shard_epochs"]["s0"] == 2, fo
    assert fo["acked_s0_after_promotion"] >= 10, fo

    bw = artifact["legs"]["bitwise"]
    assert bw["slices_bitwise_equal"], bw
    assert bw["promote_s"] <= bw["promote_budget_s"], bw
    assert bw["shard_epochs"]["s1"] == 2, bw

    rz = artifact["legs"]["resurrection"]
    assert rz["write_shed_typed"] and rz["shed_counted"] >= 1, rz
    assert rz["router_shard_epochs"]["s0"] == 2, rz

    assert artifact["traffic"]["unresolved"] == 0, artifact["traffic"]
    assert artifact["finished"] and artifact["unfinished"] == []
    assert artifact["lost_acked_ops"] == []
    assert artifact["phantom_members"] == []


@pytest.mark.slow
def test_fleet_serve_soak_autopilot_quick_mode(tmp_path):
    """The fleet-autopilot soak (--autopilot --quick, DESIGN.md §21):
    a REAL ``autopilot`` CLI subprocess watching a real fleet must
    split a flash-crowded keyspace onto standby shards (convergence:
    windowed p99 + op-rate imbalance back inside the declared budgets),
    keep no fleet dependency on itself (SIGKILL leg), resume from the
    router's persisted committed ring, and drain cold — with zero
    acked-op loss, zero phantoms, and every committed action present
    in the decision log with its triggering signals."""
    import fleet_serve_soak

    out = str(tmp_path / "CONTROL_CURVE.json")
    rc = fleet_serve_soak.main(["--autopilot", "--quick", "--out", out])
    assert rc == 0, "autopilot soak failed (no split, no convergence, " \
                    "controller dependency, or acked-op loss)"
    with open(out) as f:
        artifact = json.load(f)

    # every leg: ack-or-typed-reject through the live handoffs
    for name, leg in artifact["legs"].items():
        assert leg["unresolved"] == 0, (name, leg)
        assert leg["goodput"] > 0, (name, leg)
    # the controller held at a healthy fleet, split under the crowd,
    # and the harness's own windowed timeline converged
    assert artifact["rings"]["after_baseline_generation"] == 0
    assert artifact["actions"]["splits_committed"] >= 1
    assert artifact["convergence"]["converged"], artifact["convergence"]
    # controller SIGKILL: the fleet is never a hostage
    ck = artifact["controller_kill"]
    assert ck["acked_during_outage"] > 0
    assert ck["unresolved_during_outage"] == 0
    assert ck["ring_generation_stable"]
    assert ck["resumed_generation_matches"]
    assert ck["adopted_nonempty"]
    # the restarted controller drained its predecessor's standby, and
    # the decision logs account for every generation bump with signals
    assert artifact["actions"]["merge_after_restart"]
    assert artifact["actions"]["committed_matches_generation"]
    assert artifact["actions"]["with_trigger_signals"] == \
        artifact["actions"]["committed_total"]
    assert artifact["lost_acked_ops"] == []
    assert artifact["phantom_members"] == []
